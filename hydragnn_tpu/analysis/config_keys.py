"""config_keys: the JSON config surface cannot drift between its three
authorities — config/lint.py's key census, docs/CONFIG.md's tables, and
the top-level sections the code actually reads.

The convention: every key the framework consumes is (a) listed in
``config/lint.py``'s ``_HANDLED`` set (so migration lint classifies it
"handled" instead of "unknown — likely a typo"), and (b) documented in
the matching ``docs/CONFIG.md`` section table. Both are hand-maintained;
PRs 6–14 each added a config section and at least one of them forgot one
side (the seed of this checker: a dozen ``_HANDLED`` keys with no docs
row, and docs rows for keys migration lint calls unknown).

Enforced contracts:

1. every ``_HANDLED`` leaf path whose section has a CONFIG.md table must
   appear in that table (backtick-quoted in the Key column);
2. every CONFIG.md table key under a linted section must be ``_HANDLED``
   (or inside an ``_OPAQUE`` subtree — those members are schema'd
   elsewhere by design);
3. every top-level section name the package reads via
   ``config["X"]`` / ``config.get("X")`` must be in ``_TOPLEVEL_SECTIONS``
   — a new section that migration lint would flag as unknown on every
   user config is a bug in lint, not in the user.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Checker, Finding, Repo, call_name, register, str_const, walk_calls

CHECKER_ID = "config_keys"

LINT_MODULE_SUFFIX = "config/lint.py"

# docs/CONFIG.md section headers that mirror lint sections 1:1
_DOC_SECTION_FOR = {
    "Verbosity": "Verbosity",
    "Dataset": "Dataset",
    "NeuralNetwork.Architecture": "NeuralNetwork.Architecture",
    "NeuralNetwork.Variables_of_interest": "NeuralNetwork.Variables_of_interest",
    "NeuralNetwork.Training": "NeuralNetwork.Training",
    "NeuralNetwork.Profile": "NeuralNetwork.Profile",
    "Visualization": "Visualization",
    "Serving": "Serving",
    "Telemetry": "Telemetry",
    "Mixture": "Mixture",
}

# the variables code reads top-level sections from (heuristic, kept tight:
# a `cfg["Dataset"]` on some unrelated dict must not fire)
_CONFIG_VARS = {"config", "cfg", "conf", "config_json"}

_KEY_CELL_RE = re.compile(r"`([A-Za-z_][A-Za-z0-9_.]*)`")


def _literal_str_set(node: ast.AST) -> Optional[Set[str]]:
    """The string members of a set/tuple/dict literal, or None."""
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for elt in node.elts:
            s = str_const(elt)
            if s is not None:
                out.add(s)
        return out
    if isinstance(node, ast.Dict):
        out = set()
        for k in node.keys:
            s = str_const(k) if k is not None else None
            if s is not None:
                out.add(s)
        return out
    return None


def lint_sets(repo: Repo) -> Tuple[Optional[str], Dict[str, Set[str]]]:
    """(lint.py relpath, {_HANDLED, _OPAQUE, _TOPLEVEL_SECTIONS, _LEGACY,
    _NOT_APPLICABLE}) parsed statically from config/lint.py."""
    target = None
    for rel in repo.python_files():
        if rel.replace("\\", "/").endswith(LINT_MODULE_SUFFIX):
            target = rel
            break
    sets: Dict[str, Set[str]] = {}
    if target is None:
        return None, sets
    tree = repo.source(target).tree
    if tree is None:
        return target, sets
    wanted = {"_HANDLED", "_OPAQUE", "_TOPLEVEL_SECTIONS", "_LEGACY", "_NOT_APPLICABLE"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id in wanted:
                vals = _literal_str_set(node.value)
                if vals is not None:
                    sets[t.id] = vals
    return target, sets


def doc_section_keys(repo: Repo) -> Dict[str, Dict[str, int]]:
    """CONFIG.md: section -> {leaf key path fragment -> line}. A table row
    may document several comma/backtick-separated keys; each backticked
    identifier in the first cell counts."""
    text = repo.read_text("docs/CONFIG.md")
    out: Dict[str, Dict[str, int]] = {}
    if text is None:
        return out
    section = None
    for i, line in enumerate(text.splitlines(), 1):
        if line.startswith("## "):
            title = line[3:].strip()
            section = title if title in _DOC_SECTION_FOR.values() else None
            continue
        if section is None or not line.strip().startswith("|"):
            continue
        cells = line.strip().strip("|").split("|")
        if not cells:
            continue
        first = cells[0]
        if set(first.strip()) <= {"-", " ", ":"}:  # separator row
            continue
        if first.strip() in ("Key", "Flag"):
            continue
        # the key cell may carry inline qualifiers — "`dropout` (default
        # `0.25`)" — whose backticked VALUES are not keys; strip every
        # parenthesized chunk before collecting key tokens
        bare = re.sub(r"\([^)]*\)", "", first)
        for key in _KEY_CELL_RE.findall(bare):
            out.setdefault(section, {})[key] = i
    return out


def _opaque_covers(path: str, opaque: Set[str]) -> bool:
    return any(path == o or path.startswith(o + ".") for o in opaque)


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    lint_rel, sets = lint_sets(repo)
    if lint_rel is None or "_HANDLED" not in sets:
        return findings  # fixture trees without a config lint: nothing to drift
    handled = sets["_HANDLED"]
    opaque = sets.get("_OPAQUE", set())
    toplevel = sets.get("_TOPLEVEL_SECTIONS", set())
    legacy = sets.get("_LEGACY", set()) | sets.get("_NOT_APPLICABLE", set())
    docs = doc_section_keys(repo)
    if docs:
        # contract 1: every handled leaf is documented in its section table
        for path in sorted(handled):
            section, _, leaf = path.rpartition(".")
            if not section:
                continue  # bare section entries ("NeuralNetwork.Profile" etc.)
            if section not in docs:
                continue  # section has no table (not a linted doc section)
            if leaf in docs[section]:
                continue
            if _opaque_covers(path, opaque):
                continue
            if path in {s + "." + k for s in docs for k in docs[s]}:
                continue
            findings.append(Finding(
                CHECKER_ID, lint_rel, 0,
                f"config key {path!r} is HANDLED by config lint but has no "
                f"docs/CONFIG.md row under '## {section}'",
                hint="document the key (or drop it from _HANDLED if it is "
                     "no longer consumed)",
            ))
        # contract 2: every documented key under a linted section is handled
        for section, keys in sorted(docs.items()):
            for leaf, line in sorted(keys.items()):
                path = f"{section}.{leaf}"
                if (
                    path in handled
                    or path in legacy
                    or leaf in toplevel
                    or _opaque_covers(path, opaque)
                    or any(  # key documented as a dotted sub-path of an opaque/handled parent
                        path.startswith(h + ".") for h in handled
                    )
                    or "." in leaf  # dotted doc keys (path.total) resolve below
                    and (
                        f"{section}.{leaf.split('.')[0]}" in handled
                        or _opaque_covers(f"{section}.{leaf.split('.')[0]}", opaque)
                    )
                ):
                    continue
                findings.append(Finding(
                    CHECKER_ID, "docs/CONFIG.md", line,
                    f"documented config key {path!r} is unknown to "
                    "config/lint.py — migration lint will call a user's "
                    "use of it a typo",
                    hint="add it to _HANDLED (if consumed) or fix the docs",
                ))
    # contract 3: top-level section reads are declared sections
    for rel in repo.python_files():
        if rel.replace("\\", "/").endswith(LINT_MODULE_SUFFIX):
            continue
        src = repo.source(rel)
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            key = None
            line = 0
            if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
                base = node.value
                if isinstance(base, ast.Name) and base.id in _CONFIG_VARS:
                    key, line = str_const(node.slice), node.lineno
            elif isinstance(node, ast.Call) and call_name(node).split(".")[-1] == "get":
                base = node.func.value if isinstance(node.func, ast.Attribute) else None
                if isinstance(base, ast.Name) and base.id in _CONFIG_VARS and node.args:
                    key, line = str_const(node.args[0]), node.lineno
            if (
                key
                and key[:1].isupper()
                and toplevel
                and key not in toplevel
                and "_" not in key  # section names are CamelCase words
            ):
                findings.append(Finding(
                    CHECKER_ID, rel, line,
                    f"top-level config section {key!r} is read here but not "
                    "declared in config/lint.py _TOPLEVEL_SECTIONS",
                    hint="declare the section in config/lint.py (and "
                         "document it in docs/CONFIG.md)",
                ))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="config-key drift: lint census == docs tables == code reads",
    rationale=(
        "config/lint.py and docs/CONFIG.md are both hand-maintained; by "
        "PR 14 a dozen handled keys had no docs row and several documented "
        "keys were 'unknown' to migration lint — every new config section "
        "(Serving, Telemetry, Mixture) drifted at least once"
    ),
    run=run,
))
