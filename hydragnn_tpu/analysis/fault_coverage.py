"""fault_coverage: every ``HYDRAGNN_FAULT_*`` injection point is
exercised by at least one test or smoke.

The fault-injection surface (utils/faultinject.py) exists so recovery
paths are *proven*, not trusted — a NaN at a known step, a SIGKILL inside
the checkpoint writer, a socket drop on the nth call. An injection point
nobody arms is worse than none: it documents a recovery path as tested
while the drill silently stopped running (the exact rot the doctor's
fault drills guard against at the diagnosis layer; this guards it at the
source layer).

Rule: parse the ``configure()`` keymap in utils/faultinject.py (the
authoritative point registry — a new point cannot exist without a keymap
entry, ``_get`` only reads through it and the env). For every
``HYDRAGNN_FAULT_*`` value, at least one file under ``tests/`` or
``run-scripts/`` must mention either the env name or its ``configure()``
keyword — otherwise the point is declared-but-undrilled.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Checker, Finding, Repo, register, str_const

CHECKER_ID = "fault_coverage"

FAULTINJECT_SUFFIX = "utils/faultinject.py"


def fault_points(repo: Repo) -> Dict[str, Dict[str, object]]:
    """env name -> {"key": configure keyword, "line": keymap line} from
    the faultinject keymap dict literal."""
    target: Optional[str] = None
    for rel in repo.python_files():
        if rel.replace("\\", "/").endswith(FAULTINJECT_SUFFIX):
            target = rel
            break
    out: Dict[str, Dict[str, object]] = {}
    if target is None:
        return out
    tree = repo.source(target).tree
    if tree is None:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            key = str_const(k) if k is not None else None
            val = str_const(v)
            if key and val and val.startswith("HYDRAGNN_FAULT_"):
                out[val] = {"key": key, "line": v.lineno, "rel": target}
    return out


def run(repo: Repo) -> List[Finding]:
    points = fault_points(repo)
    if not points:
        return []
    evidence = ""
    for rel in repo.aux_files("tests", "run-scripts", exts=(".py", ".sh", ".sbatch")):
        evidence += repo.read_text(rel) or ""
    findings: List[Finding] = []
    for env_name, meta in sorted(points.items()):
        key = str(meta["key"])
        if env_name in evidence or f'"{key}"' in evidence or f"{key}=" in evidence:
            continue
        findings.append(Finding(
            CHECKER_ID, str(meta["rel"]), int(meta["line"]),  # type: ignore[arg-type]
            f"fault-injection point {env_name} ({key!r}) is declared but "
            "no test or smoke arms it — its recovery path is documented "
            "as drilled while nothing drills it",
            hint="add a drill (tests/ or run-scripts/ smoke) that arms "
                 "the point and asserts the recovery, or delete the point",
        ))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="every HYDRAGNN_FAULT_* point armed by a test or smoke",
    rationale=(
        "the fault-tolerance layer's guarantees are only as real as their "
        "drills; an unarmed injection point is a recovery path that rotted "
        "out of CI without anyone noticing"
    ),
    run=run,
))
