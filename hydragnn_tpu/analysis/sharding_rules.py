"""sharding_rules: placement decisions live in the rule engine, nowhere
else.

The convention this encodes: ROADMAP item 1 collapsed the dp/zero/branch
builder trio into ONE sharding engine — an ordered regex->PartitionSpec
rule table (parallel/rules.py) consumed by one mesh-step builder
(parallel/engine.py). Its payoff (one before/after placement oracle, one
audit, one bit-identity test surface) only holds while the rule table is
the SINGLE source of placement truth. A ``with_sharding_constraint`` or
``NamedSharding`` call hand-placed in a model or training module is a
placement decision the table cannot see, the sharding inspector cannot
attribute, and the ``doctor diff`` sharding section cannot explain — the
exact per-builder drift the engine retired.

Scope: every package module OUTSIDE ``parallel/``. Flagged call targets:

- ``with_sharding_constraint(...)`` — in-step placement pins belong in
  the engine's ``_constrain`` (driven by the table's grads/params rules);
- ``NamedSharding(...)`` — device placement belongs in
  ``engine.place_state`` / the mesh helpers;
- ``shard_map(...)`` / ``compat_shard_map(...)`` — per-device program
  boundaries belong in the engine's step builders.

Mentions in strings/comments and ``isinstance(x, NamedSharding)`` type
checks do not place anything and are not flagged. The one legitimate
outlier — models/gps.py's ring-attention ``shard_map``, a collective that
lives with the model's attention math — carries a pragma waiver.
"""

from __future__ import annotations

from typing import List

from .core import Checker, Finding, Repo, dotted, register, walk_calls

CHECKER_ID = "sharding_rules"

# call-target tails that constitute a placement decision
_FORBIDDEN = (
    "with_sharding_constraint",
    "NamedSharding",
    "shard_map",
    "compat_shard_map",
)

_HINTS = {
    "with_sharding_constraint": (
        "express the pin as a rule (parallel/rules.py) so the engine's "
        "_constrain applies it — or move the code into parallel/"
    ),
    "NamedSharding": (
        "place state via parallel.engine.place_state(state, table, mesh) "
        "or the parallel/mesh.py helpers"
    ),
    "shard_map": (
        "per-device programs are built by parallel/engine.py's mesh-step "
        "builders; add a rule preset instead of a bespoke shard_map"
    ),
}
_HINTS["compat_shard_map"] = _HINTS["shard_map"]


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    allowed_prefix = f"{repo.package}/parallel/"
    for rel in repo.python_files():
        norm = rel.replace("\\", "/")
        if norm.startswith(allowed_prefix):
            continue
        src = repo.source(rel)
        if src.tree is None:
            continue
        for call in walk_calls(src.tree):
            name = dotted(call.func)
            tail = name.rsplit(".", 1)[-1]
            if tail not in _FORBIDDEN:
                continue
            findings.append(Finding(
                CHECKER_ID, rel, call.lineno,
                f"{name}(...) outside parallel/ is a sharding decision "
                "the rule table cannot see",
                hint=_HINTS[tail],
            ))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="sharding primitives only inside parallel/ (rule-engine monopoly)",
    rationale=(
        "ROADMAP item 1 replaced the dp/zero/branch builder trio with one "
        "rule-table engine; a hand-placed with_sharding_constraint/"
        "NamedSharding/shard_map elsewhere is placement the table, the "
        "sharding inspector, and doctor diff all miss — the per-builder "
        "drift the engine exists to end"
    ),
    run=run,
))
