"""atomic_write: checkpoint-adjacent state is published atomically
(tmp + fsync + ``os.replace``), never written in place.

The convention comes from the fault drills: ``HYDRAGNN_FAULT_KILL_AT``
SIGKILLs the process between a tmp write and its rename, and the resume
tests assert the reader never sees a torn file (train/checkpoint.py
``_fsync_replace`` is the one blessed publish primitive; the quarantine
manifest rotation and the LapPE cache both adopted the same shape after
review). A plain ``open(path, "w")`` in these modules is a torn-state
bug waiting for a preemption.

Scope: the modules that own checkpoint / quarantine / mixture-state /
hot-reload / resume-cursor files. Rule: any ``open(..., "w"/"wb")``
whose enclosing function does not also call ``os.replace`` (or the
``_fsync_replace`` helper / a ``*_atomic*`` wrapper) is a finding —
append-mode streams (manifests, JSONL sinks) are exempt by design, their
consumers tolerate a truncated tail.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .core import Checker, Finding, Repo, dotted, register, str_const

CHECKER_ID = "atomic_write"

# modules owning durable, resume-critical state
SCOPED_SUFFIXES: Tuple[str, ...] = (
    "train/checkpoint.py",
    "data/validate.py",     # quarantine manifest
    "data/lappe.py",        # eigendecomposition cache
    "mix/plane.py",         # mixture resume state
    "mix/sampler.py",
    "serve/reload.py",      # hot-reload pointer handling
    "utils/preemption.py",  # mid-epoch resume cursor
)

_ATOMIC_MARKERS = ("replace", "_fsync_replace", "atomic")


def _write_mode(call: ast.Call) -> Optional[str]:
    if dotted(call.func) != "open":
        return None
    mode = None
    if len(call.args) > 1:
        mode = str_const(call.args[1])
    for k in call.keywords:
        if k.arg == "mode":
            mode = str_const(k.value)
    if mode and "w" in mode:
        return mode
    return None


def _is_atomic_call(node: ast.Call) -> bool:
    tail = dotted(node.func).rsplit(".", 1)[-1]
    return any(m in tail for m in _ATOMIC_MARKERS)


def _fn_calls_atomic(fn: ast.AST) -> bool:
    return any(
        _is_atomic_call(n) for n in ast.walk(fn) if isinstance(n, ast.Call)
    )


def run(repo: Repo) -> List[Finding]:
    findings: List[Finding] = []
    for rel in repo.python_files():
        norm = rel.replace("\\", "/")
        if not any(norm.endswith(s) for s in SCOPED_SUFFIXES):
            continue
        src = repo.source(rel)
        if src.tree is None:
            continue
        # attribute every write-mode open to its innermost function (or
        # the module scope for top-level opens), and require the atomic
        # publish pattern in that same scope
        fns = [
            n for n in ast.walk(src.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        in_some_fn = {
            id(c)
            for f in fns
            for c in ast.walk(f)
            if isinstance(c, ast.Call)
        }
        for scope in fns + [src.tree]:
            body_calls = [
                n for n in ast.walk(scope)
                if isinstance(n, ast.Call) and _write_mode(n)
            ]
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # only opens directly in THIS function (not nested fns)
                nested = {
                    id(c)
                    for f in ast.walk(scope)
                    if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and f is not scope
                    for c in ast.walk(f)
                    if isinstance(c, ast.Call)
                }
                body_calls = [c for c in body_calls if id(c) not in nested]
                where = repr(scope.name)
            else:
                # module scope: top-level opens only
                body_calls = [c for c in body_calls if id(c) not in in_some_fn]
                where = "module scope"
            if not body_calls:
                continue
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                atomic = _fn_calls_atomic(scope)
            else:
                # module scope: a replace inside some function does not
                # excuse a top-level in-place write
                atomic = any(
                    id(c) not in in_some_fn
                    for c in ast.walk(scope)
                    if isinstance(c, ast.Call) and _is_atomic_call(c)
                )
            if atomic:
                continue
            for call in body_calls:
                findings.append(Finding(
                    CHECKER_ID, rel, call.lineno,
                    f"open(..., {_write_mode(call)!r}) in {where} writes "
                    "resume-critical state in place — a kill mid-write "
                    "leaves a torn file",
                    hint="publish via tmp + fsync + os.replace "
                         "(train/checkpoint._fsync_replace is the "
                         "blessed primitive)",
                ))
    return findings


register(Checker(
    id=CHECKER_ID,
    title="checkpoint-adjacent writes are tmp+fsync+os.replace atomic",
    rationale=(
        "the HYDRAGNN_FAULT_KILL_AT drills SIGKILL between write and "
        "rename; every resume guarantee (verified restore, quarantine "
        "manifest, LapPE cache, mixture fingerprint-exact resume) assumes "
        "no reader ever sees a torn file"
    ),
    run=run,
))
