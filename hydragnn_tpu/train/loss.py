"""Masked multi-task losses.

Equivalent of the reference's ``Base.loss``/``loss_hpweighted``
(hydragnn/models/Base.py:572-580, 659-686) adapted to padded batches: every
reduction is over *real* rows only (graph_mask / node_mask), which reproduces
the reference's per-batch mean over ragged tensors.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

from ..data.graph import GraphBatch
from ..models.base import ModelConfig


def _elementwise(loss_type: str, err: jnp.ndarray) -> jnp.ndarray:
    lt = loss_type.lower()
    if lt == "mse":
        return err**2
    if lt in ("mae", "l1"):
        return jnp.abs(err)
    if lt == "rmse":  # reduced later; rmse applied at head level
        return err**2
    raise ValueError(
        f"unknown loss_function_type {loss_type!r} (GaussianNLLLoss is handled "
        "by multitask_loss via the variance heads)"
    )


def masked_mean(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    m = mask.reshape(mask.shape + (1,) * (values.ndim - mask.ndim)).astype(values.dtype)
    denom = jnp.maximum(jnp.sum(m) * values.shape[-1], 1.0)
    return jnp.sum(values * m) / denom


def head_loss(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    loss_type: str,
) -> jnp.ndarray:
    per_elem = _elementwise(loss_type, pred - target)
    loss = masked_mean(per_elem, mask)
    if loss_type.lower() == "rmse":
        loss = jnp.sqrt(loss)
    return loss


def gaussian_nll(
    pred: jnp.ndarray,
    var: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float = 1e-6,
) -> jnp.ndarray:
    """Gaussian negative log likelihood with predicted variance
    (torch GaussianNLLLoss semantics, full=False; reference wires the variance
    head via var_output, Base.py:92-96 and the `headvar = out**2` split)."""
    v = jnp.maximum(var, eps)
    per_elem = 0.5 * (jnp.log(v) + (pred - target) ** 2 / v)
    return masked_mean(per_elem, mask)


def multitask_loss(
    outputs: Dict[str, jnp.ndarray],
    batch: GraphBatch,
    cfg: ModelConfig,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Total weighted loss + per-task unweighted losses
    (reference: loss_hpweighted, Base.py:659-686)."""
    weights = cfg.normalized_task_weights
    tot = 0.0
    tasks: Dict[str, jnp.ndarray] = {}
    for name, t, w in zip(cfg.output_names, cfg.output_type, weights):
        pred = outputs[name]
        if t == "graph":
            target = batch.graph_targets[name]
            mask = batch.graph_mask
        else:
            target = batch.node_targets[name]
            mask = batch.node_mask
        target = target.reshape(pred.shape)
        if cfg.var_output:
            task = gaussian_nll(pred, outputs[f"{name}__var"], target, mask)
        else:
            task = head_loss(pred, target, mask, cfg.loss_function_type)
        tasks[name] = task
        tot = tot + w * task
    return tot, tasks
