"""Masked multi-task losses.

Equivalent of the reference's ``Base.loss``/``loss_hpweighted``
(hydragnn/models/Base.py:572-580, 659-686) adapted to padded batches: every
reduction is over *real* rows only (graph_mask / node_mask), which reproduces
the reference's per-batch mean over ragged tensors.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..data.graph import GraphBatch
from ..models.base import ModelConfig


def _elementwise(loss_type: str, err: jnp.ndarray) -> jnp.ndarray:
    lt = loss_type.lower()
    if lt == "mse":
        return err**2
    if lt in ("mae", "l1"):
        return jnp.abs(err)
    if lt == "rmse":  # reduced later; rmse applied at head level
        return err**2
    raise ValueError(
        f"unknown loss_function_type {loss_type!r} (GaussianNLLLoss is handled "
        "by multitask_loss via the variance heads)"
    )


def masked_mean(
    values: jnp.ndarray, mask: jnp.ndarray, row_weights=None
) -> jnp.ndarray:
    """Mean over real rows. ``row_weights`` (optional, per-row) turns it
    into the weighted mean Σ w·m·v / Σ w·m·C — the per-branch loss
    balancing hook (docs/GFM.md): with all weights 1 (or None) the
    computation is byte-identical to the unweighted path."""
    m = mask.reshape(mask.shape + (1,) * (values.ndim - mask.ndim)).astype(values.dtype)
    if row_weights is not None:
        w = row_weights.reshape(
            row_weights.shape + (1,) * (values.ndim - row_weights.ndim)
        ).astype(values.dtype)
        m = m * w
    denom = jnp.maximum(jnp.sum(m) * values.shape[-1], 1.0)
    return jnp.sum(values * m) / denom


def head_loss(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    loss_type: str,
    row_weights=None,
) -> jnp.ndarray:
    per_elem = _elementwise(loss_type, pred - target)
    loss = masked_mean(per_elem, mask, row_weights)
    if loss_type.lower() == "rmse":
        loss = jnp.sqrt(loss)
    return loss


def gaussian_nll(
    pred: jnp.ndarray,
    var: jnp.ndarray,
    target: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float = 1e-6,
    row_weights=None,
) -> jnp.ndarray:
    """Gaussian negative log likelihood with predicted variance
    (torch GaussianNLLLoss semantics, full=False; reference wires the variance
    head via var_output, Base.py:92-96 and the `headvar = out**2` split)."""
    v = jnp.maximum(var, eps)
    per_elem = 0.5 * (jnp.log(v) + (pred - target) ** 2 / v)
    return masked_mean(per_elem, mask, row_weights)


def _per_branch_head_loss(
    per_elem: jnp.ndarray,
    mask: jnp.ndarray,
    branch_of_row: jnp.ndarray,
    num_branches: int,
    loss_type: str,
) -> jnp.ndarray:
    """[num_branches] masked mean of one head's per-element loss, reduced
    per branch — the in-graph per-branch loss census the mixture drift
    monitor consumes (mix/balance.py). Costs two segment-sums per head."""
    m = mask.reshape(
        mask.shape + (1,) * (per_elem.ndim - mask.ndim)
    ).astype(per_elem.dtype)
    row_num = jnp.sum(per_elem * m, axis=tuple(range(1, per_elem.ndim)))
    row_den = jnp.sum(m, axis=tuple(range(1, m.ndim))) * per_elem.shape[-1]
    seg = jnp.clip(branch_of_row.astype(jnp.int32), 0, num_branches - 1)
    num = jax.ops.segment_sum(row_num, seg, num_segments=num_branches)
    den = jax.ops.segment_sum(row_den, seg, num_segments=num_branches)
    out = num / jnp.maximum(den, 1.0)
    if loss_type.lower() == "rmse":
        out = jnp.sqrt(out)
    return out


def compute_loss(
    model,
    variables: Dict,
    batch: GraphBatch,
    cfg: ModelConfig,
    train: bool,
    rng,
    compute_grad_energy: bool,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], Dict, Dict[str, jnp.ndarray]]:
    """Single entry point for both objectives, shared by the single-device and
    mesh-parallel step builders: returns (total, per-task losses, mutated
    collections, outputs)."""
    if compute_grad_energy:
        def apply_outputs(b):
            if train:
                return model.apply(
                    variables,
                    b,
                    train=True,
                    mutable=["batch_stats"],
                    rngs={"dropout": rng},
                )
            return model.apply(variables, b, train=False), None

        tot, tasks, aux, preds = energy_force_loss(apply_outputs, batch, cfg)
        return tot, tasks, aux or {}, preds
    if train:
        outputs, mutated = model.apply(
            variables,
            batch,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": rng},
        )
    else:
        outputs, mutated = model.apply(variables, batch, train=False), {}
    tot, tasks = multitask_loss(outputs, batch, cfg)
    return tot, tasks, mutated, outputs


def energy_force_loss(
    apply_outputs: "callable",
    batch: GraphBatch,
    cfg: ModelConfig,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], object, Dict[str, jnp.ndarray]]:
    """Energy + autograd-force loss (reference: Base.energy_force_loss,
    hydragnn/models/Base.py:582-636). Returns
    ``(total, per_task_losses, aux, predictions)`` where ``predictions`` holds
    the graph energies [G] and forces [N,3] already computed for the loss.

    The model's single node head predicts per-node energy; graph energy is the
    masked segment-sum over nodes and forces are ``-dE/dpos`` — in JAX a plain
    ``jax.grad`` through the forward (vs the reference's
    ``torch.autograd.grad(..., create_graph=True)`` dance), so the force loss
    backward is just second-order AD handled by XLA.

    ``apply_outputs(batch) -> (outputs, aux)`` must close over params so that
    this function can differentiate w.r.t. positions only; ``aux`` (e.g.
    mutated batch stats) is threaded through ``has_aux`` and returned.

    Targets: ``batch.graph_targets['energy']`` [G,1] and
    ``batch.node_targets['forces']`` [N,3].
    """
    assert cfg.num_heads == 1 and cfg.output_type[0] == "node", (
        "energy-force training needs exactly one node head predicting nodal "
        "energy (reference assert, Base.py:590-593)"
    )
    name = cfg.output_names[0]
    node_mask_f = batch.node_mask.astype(batch.pos.dtype)
    graph_mask_f = batch.graph_mask.astype(batch.pos.dtype)

    def graph_energy_sum(pos):
        outputs, aux = apply_outputs(batch.replace(pos=pos))
        node_e = outputs[name][:, 0] * node_mask_f
        graph_e = jnp.zeros((batch.num_graphs,), node_e.dtype)
        graph_e = graph_e.at[batch.node_graph].add(node_e)
        return jnp.sum(graph_e * graph_mask_f), (graph_e, aux)

    (_, (graph_e_pred, aux)), de_dpos = jax.value_and_grad(
        graph_energy_sum, has_aux=True
    )(batch.pos)
    forces_pred = -de_dpos

    e_true = batch.graph_targets["energy"].reshape(-1)
    f_true = batch.node_targets["forces"]

    energy_loss = head_loss(
        graph_e_pred[:, None], e_true[:, None], batch.graph_mask, cfg.loss_function_type
    )
    force_loss = head_loss(
        forces_pred, f_true, batch.node_mask, cfg.loss_function_type
    )
    # auto-balanced force weight: energy and force terms contribute equally
    # in the units of the data (Base.py:626-631)
    e_w = cfg.normalized_task_weights[0]
    mean_abs_e = masked_mean(jnp.abs(e_true)[:, None], batch.graph_mask)
    mean_abs_f = masked_mean(jnp.abs(f_true), batch.node_mask)
    f_w = e_w * mean_abs_e / (mean_abs_f + 1e-8)
    tot = e_w * energy_loss + f_w * force_loss
    tasks = {name: energy_loss, "forces": force_loss}
    preds = {
        name: graph_e_pred[:, None],
        "forces": forces_pred * node_mask_f[:, None],
    }
    return tot, tasks, aux, preds


def predict_energy_forces(
    apply_outputs: "callable", batch: GraphBatch, cfg: ModelConfig
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Inference-side energies [G] and forces [N,3] (masked)."""
    name = cfg.output_names[0]
    node_mask_f = batch.node_mask.astype(batch.pos.dtype)

    def graph_energy_sum(pos):
        outputs, _ = apply_outputs(batch.replace(pos=pos))
        node_e = outputs[name][:, 0] * node_mask_f
        graph_e = jnp.zeros((batch.num_graphs,), node_e.dtype)
        graph_e = graph_e.at[batch.node_graph].add(node_e)
        return jnp.sum(graph_e * batch.graph_mask.astype(node_e.dtype)), graph_e

    (_, graph_e), de_dpos = jax.value_and_grad(graph_energy_sum, has_aux=True)(
        batch.pos
    )
    forces = -de_dpos * node_mask_f[:, None]
    return graph_e, forces


def multitask_loss(
    outputs: Dict[str, jnp.ndarray],
    batch: GraphBatch,
    cfg: ModelConfig,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Total weighted loss + per-task unweighted losses
    (reference: loss_hpweighted, Base.py:659-686).

    Multibranch models with ``cfg.branch_loss_weights`` set (planted by
    the Mixture config section, mix/balance.py) weight every graph's loss
    contribution by its branch's static weight — the in-graph half of
    per-branch loss balancing; ``cfg.branch_loss_metrics`` additionally
    emits per-branch total-loss scalars as ``branch<i>`` task entries, so
    the drift monitor gets its census through the loop's existing
    device-side bookkeeping (no extra host syncs)."""
    weights = cfg.normalized_task_weights
    B = int(cfg.num_branches)
    blw = cfg.branch_loss_weights if B > 1 else None
    graph_branch = batch.dataset_id.astype(jnp.int32)
    gw = None
    if blw:
        w_arr = jnp.asarray(blw, jnp.float32)
        gw = w_arr[jnp.clip(graph_branch, 0, B - 1)]
    want_branch = B > 1 and cfg.branch_loss_metrics
    tot = 0.0
    tasks: Dict[str, jnp.ndarray] = {}
    branch_tot = jnp.zeros((B,), jnp.float32) if want_branch else None
    for name, t, w in zip(cfg.output_names, cfg.output_type, weights):
        pred = outputs[name]
        if t == "graph":
            target = batch.graph_targets[name]
            mask = batch.graph_mask
            branch_of_row = graph_branch
        else:
            target = batch.node_targets[name]
            mask = batch.node_mask
            branch_of_row = graph_branch[batch.node_graph]
        target = target.reshape(pred.shape)
        row_w = None if gw is None else (
            gw if t == "graph" else gw[batch.node_graph]
        )
        if cfg.var_output:
            task = gaussian_nll(
                pred, outputs[f"{name}__var"], target, mask, row_weights=row_w
            )
        else:
            task = head_loss(
                pred, target, mask, cfg.loss_function_type, row_weights=row_w
            )
        tasks[name] = task
        tot = tot + w * task
        if want_branch:
            if cfg.var_output:
                # gaussian-NLL census: same per-element formula the head
                # loss reduces, never the rmse sqrt
                v = jnp.maximum(outputs[f"{name}__var"], 1e-6)
                per_elem = 0.5 * (jnp.log(v) + (pred - target) ** 2 / v)
                per_branch_type = "mse"
            else:
                per_elem = _elementwise(cfg.loss_function_type, pred - target)
                per_branch_type = cfg.loss_function_type
            branch_tot = branch_tot + w * _per_branch_head_loss(
                per_elem, mask, branch_of_row, B, per_branch_type
            )
    if want_branch:
        for b in range(B):
            tasks[f"branch{b}"] = branch_tot[b]
    return tot, tasks
