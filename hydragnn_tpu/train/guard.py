"""Non-finite step guard: in-graph skip of bad optimizer steps + the
host-side policy that decides what a skipped step means.

The failure mode this closes: long bf16 runs (mixed_precision puts bf16 on
every hot path) occasionally produce a non-finite loss or gradient — one such
step without a guard writes NaN into the parameters and the run is dead from
that point on, usually discovered hours later from a flatlined loss curve.
``Optimizer.clip_grad_norm`` bounds finite outliers but passes NaN/inf
through (0 * inf = NaN inside the clip scale).

In-graph side (used by every train-step builder — single-device, mesh DP,
branch-parallel): compute loss/global-grad-norm finiteness and gate the
optimizer update to identity on a bad step (per-leaf selects — see
``guarded_update`` for why not ``lax.cond``). The state carries
``skipped_steps`` (total) and ``consecutive_skips`` (reset by any good step)
counters, advanced in-graph, so the check costs no extra host sync — the
loop reads them once per epoch where it already syncs. On the mesh steps the
decision is computed AFTER the gradient pmean, so every device/host agrees
by construction.

Host side (train/loop.py): ``Training.non_finite_policy`` —
``error`` (raise at the epoch boundary), ``warn_skip`` (log and keep going;
the default), ``rollback`` (after K consecutive skips, restore the last
verified checkpoint with an LR backoff — agreed across hosts the same way
``preemption.preempted_global()`` agrees its stop).
"""

from __future__ import annotations

import sys
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from ..utils import envflags


def guard_enabled(guard: Optional[bool] = None) -> bool:
    """Resolve a step builder's ``guard`` argument: explicit True/False wins,
    None falls back to ``HYDRAGNN_STEP_GUARD`` (default on — the guard is
    numerically identical on finite steps, see tests/test_faults.py, and its
    cost is one global-norm pass bounded by the BENCH_GUARD A/B cell)."""
    if guard is not None:
        return bool(guard)
    return envflags.env_force("HYDRAGNN_STEP_GUARD") is not False


def step_ok(tot, grads):
    """In-graph finiteness decision: the loss and the global gradient norm
    (one reduction over all leaves — a single NaN/inf anywhere poisons the
    norm, so one scalar check covers the whole tree)."""
    return jnp.isfinite(tot) & jnp.isfinite(optax.global_norm(grads))


def guarded_update(
    state,
    ok,
    do_update: Callable[[], Tuple],
    new_stats,
):
    """Gate the optimizer update to identity on a bad step and advance the
    skip counters in-graph.

    ``do_update`` returns ``(params, opt_state)`` — the caller's full update
    arithmetic (tx.update + apply_updates + any ZeRO sharding constraints),
    so on a good step the committed values are EXACTLY the unguarded ones.
    ``new_stats`` are the batch statistics a good step would persist; a bad
    step keeps the previous ones (a NaN forward can poison running means).

    The merge is an elementwise ``select(ok, new, old)`` per leaf rather
    than a ``lax.cond``: a cond around the whole update forms an XLA
    conditional over every params/opt-state buffer, which blocks fusion
    with the surrounding program and (measured on the CPU backend) made the
    step ~30x slower end-to-end; selects fuse into the update arithmetic
    and cost one predicated copy per leaf. The update is computed
    unconditionally — its NaN outputs on a bad step are discarded by the
    select, never multiplied in. Donation-safe: old and new buffers share
    shape/dtype/sharding."""
    params_new, opt_new = do_update()

    def merge(new, old):
        new = jnp.asarray(new)
        return jnp.where(ok, new, jnp.asarray(old, new.dtype))

    params, opt_state, stats = jax.tree_util.tree_map(
        merge,
        (params_new, opt_new, new_stats),
        (state.params, state.opt_state, state.batch_stats),
    )
    # counter arithmetic must PRESERVE the leaves' (weak) dtype: the fresh
    # state carries python-int counters (weak int32 under jit, like `step`),
    # and an explicit int32 cast here would flip the output aval to strong
    # int32 — recompiling the ENTIRE step on its second call (measured: one
    # full extra XLA compile per train-step specialization suite-wide)
    return state.replace(
        params=params,
        opt_state=opt_state,
        batch_stats=stats,
        step=state.step + 1,
        skipped_steps=state.skipped_steps + jnp.where(ok, 0, 1),
        consecutive_skips=jnp.where(ok, 0, state.consecutive_skips + 1),
    )


def agreed_any(flag: bool) -> bool:
    """Cross-host agreement on a local boolean — ANY process's True wins,
    the same contract as ``preemption.preempted_global()``: the rollback
    decision must be unanimous or hosts diverge on which state they train
    (the counters are computed from pmean'd values and already agree; the
    allgather makes the host-side decision robust to any residual skew)."""
    if jax.process_count() == 1:
        return bool(flag)
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(np.asarray([bool(flag)], np.int32))
    return bool(np.asarray(flags).any())


class NonFinitePolicy:
    """Epoch-boundary driver of ``Training.non_finite_policy``.

    Owned by the training loop: call ``after_epoch(state, epoch)`` once per
    epoch; it reads the in-graph counters (the loop is already host-synced
    there), warns/raises per policy, and for ``rollback`` returns a restored
    + LR-backed-off state after K agreed consecutive skips."""

    POLICIES = ("error", "warn_skip", "rollback")

    def __init__(
        self,
        policy: str = "warn_skip",
        rollback_after: int = 3,
        lr_backoff: float = 0.5,
        max_rollbacks: int = 3,
        restore_fn: Optional[Callable] = None,
        log_name: str = "run",
    ):
        if policy not in self.POLICIES:
            raise ValueError(
                f"Training.non_finite_policy {policy!r} must be one of "
                f"{self.POLICIES}"
            )
        self.policy = policy
        self.rollback_after = int(rollback_after)
        self.lr_backoff = float(lr_backoff)
        self.max_rollbacks = int(max_rollbacks)
        self.restore_fn = restore_fn
        self.log_name = log_name
        self._prev_skipped = 0
        self.rollbacks_done = 0

    def after_epoch(self, state, epoch: int, provenance=None):
        """Apply the policy; returns the (possibly restored) state.

        ``provenance`` (optional) is the epoch's per-skip batch attribution
        — a list of dicts with ``batch`` / ``level`` (spec-ladder pad
        level) / ``sources`` (mixture draw ids) / ``layer`` (when the
        numerics drill-down located the tensor) — attached to the
        ``guard_skip`` event so a poisoned source or a recurring pad level
        is identifiable from the event stream alone (train/loop.py fills it
        from the NaN watch when ``Telemetry.numerics`` is on, else from the
        epoch's non-finite loss census)."""
        skipped = int(jax.device_get(state.skipped_steps))
        consec = int(jax.device_get(state.consecutive_skips))
        new_skips = skipped - self._prev_skipped
        self._prev_skipped = skipped
        if new_skips <= 0:
            return state
        msg = (
            f"[{self.log_name}] epoch {epoch}: {new_skips} non-finite "
            f"step(s) skipped by the train-step guard "
            f"(total {skipped}, {consec} consecutive at epoch end)"
        )
        # structured incident record (obs/events.py): the epoch's skip tally
        # with the active trace context attached (the loop opens a
        # train/guard_verdict span around this call when tracing is on)
        from ..obs.events import EV_GUARD_FATAL, EV_GUARD_SKIP
        from ..obs.events import emit as _emit_event

        extra = {}
        if provenance:
            levels = sorted({str(p["level"]) for p in provenance
                             if p.get("level")})
            sources = sorted({int(s) for p in provenance
                              for s in (p.get("sources") or [])})
            batches = [int(p["batch"]) for p in provenance
                       if p.get("batch") is not None]
            layers = sorted({str(p["layer"]) for p in provenance
                             if p.get("layer")})
            if levels:
                extra["levels"] = ",".join(levels)
            if sources:
                extra["sources"] = ",".join(str(s) for s in sources)
            if batches:  # bounded: a diverged epoch skips every step
                extra["batches"] = ",".join(str(b) for b in batches[:16])
            if layers:
                extra["layers"] = ",".join(layers[:8])
        _emit_event(
            EV_GUARD_SKIP,
            severity="warn",
            epoch=epoch,
            new_skips=new_skips,
            total=skipped,
            consecutive=consec,
            policy=self.policy,
            **extra,
        )
        if self.policy == "error":
            err = RuntimeError(
                msg + "; Training.non_finite_policy is 'error'. Inspect the "
                "data/LR, or set 'warn_skip'/'rollback' to ride through."
            )
            # black-box dump BEFORE raising: the fatal guard verdict is one
            # of the flight recorder's trigger points — the dump carries
            # this epoch's guard_skip/guard_fatal events + registry snapshot
            _emit_event(
                EV_GUARD_FATAL, severity="fatal", epoch=epoch, total=skipped
            )
            from ..obs import flightrec as _flightrec

            _flightrec.trigger("fatal_guard", exc=err)
            raise err
        print(msg, file=sys.stderr)
        if self.policy != "rollback":
            return state
        if not agreed_any(consec >= self.rollback_after):
            return state
        # agreed rollback: restore the last VERIFIED checkpoint and back
        # off the LR — the recovery for sustained divergence (K consecutive
        # bad steps means the current trajectory is lost, not one cosmic ray)
        self.rollbacks_done += 1
        if self.rollbacks_done > self.max_rollbacks:
            raise RuntimeError(
                f"[{self.log_name}] non_finite_policy=rollback exceeded "
                f"Training.non_finite_max_rollbacks={self.max_rollbacks}: "
                "the run keeps diverging after restore+LR-backoff. Lower "
                "the learning rate or inspect the data."
            )
        if self.restore_fn is None:
            raise RuntimeError(
                f"[{self.log_name}] non_finite_policy=rollback triggered "
                f"({consec} consecutive skips) but no checkpoint restore "
                "path is wired. Enable Training.Checkpoint so a verified "
                "checkpoint exists to roll back to."
            )
        state = self.restore_fn(state)
        from ..obs.events import EV_GUARD_ROLLBACK
        from ..obs.events import emit as _emit_rollback

        _emit_rollback(
            EV_GUARD_ROLLBACK,
            severity="error",
            epoch=epoch,
            rollback=self.rollbacks_done,
            max_rollbacks=self.max_rollbacks,
        )
        # COMPOUND the backoff across rollbacks: sustained divergence keeps
        # restoring the SAME checkpoint (BestCheckpoint only writes on val
        # improvement), so a flat factor would retry the identical LR until
        # max_rollbacks — rollback k runs at backoff^k of the restored LR
        # (matching the loop's per-rollback base_lr scaling for the ramp)
        lr = float(state.learning_rate) * self.lr_backoff**self.rollbacks_done
        state = state.with_learning_rate(lr)
        # the restored checkpoint carries its own (older) counters; re-sync
        # so the next epoch's delta is computed against the restored total
        self._prev_skipped = int(jax.device_get(state.skipped_steps))
        print(
            f"[{self.log_name}] rollback {self.rollbacks_done}/"
            f"{self.max_rollbacks}: restored last verified checkpoint, "
            f"learning rate backed off to {lr:.3e}",
            file=sys.stderr,
        )
        return state
