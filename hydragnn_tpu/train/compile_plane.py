"""Compile plane: the zero-recompile steady state.

On TPU the dominant non-step cost of this framework is XLA compilation: a
``num_pad_buckets=4`` SpecLadder (config/config.py) means up to 4 train + 4
eval step specializations per run, each one stalling the step loop mid-epoch
on its first visit — and the fault-tolerance work (rollback, SIGTERM resume,
preemption; docs/ROBUSTNESS.md) made restarts routine, so every recovery
used to repay the full compile bill from zero. Three mechanisms close that:

1. **Persistent compilation cache** (``setup_compile_cache``): jax's
   disk-backed executable cache wired from config
   (``Training.compile_cache_dir``, default under the run's log dir;
   ``HYDRAGNN_COMPILE_CACHE`` overrides, ``0``/``off`` disables). Restarts,
   rollbacks, and mid-epoch resumes deserialize compiled executables
   instead of recompiling them.

2. **Background AOT warm-up** (``CompilePlane``): the loaders' SpecLadder
   pad shapes are enumerated up front (``GraphLoader.spec_template_batches``
   — shapes are fully determined by the ladder, no epoch needs to run), and
   every (train, eval) x bucket specialization is ``lower().compile()``d in
   a worker thread while epoch 0 runs (``Training.precompile:
   off | blocking | background``). The AOT compile lands in the persistent
   cache, so the step loop's first organic visit to each bucket pays a
   cache *retrieval* (tens of ms) instead of a full XLA compile (tens of
   seconds through a tunnel). Lowering shares jax's trace cache with the
   call path, so warm-up also absorbs the Python tracing cost. Without a
   persistent cache directory the warm-up executables would be unreachable
   from the call path — the plane then degrades to ``off`` (AOT work whose
   results nothing can reuse is pure waste).

3. **Retrace sentinel**: every step builder's traced body calls
   ``note_trace(name, args)``, which records the call's abstract signature
   (shape/dtype/weak_type per leaf) — executed once per trace, by
   construction. Once warm-up has covered the ladder the sentinel is
   *armed*: any later trace whose signature is not among the known
   specializations is a silent-retrace bug (the PR 3 incident — one
   int32/weak-type flip on a counter silently doubled every
   specialization's compile bill), reported with the aval diff against the
   nearest known signature and handled per ``Training.retrace_policy:
   warn (default) | error``.

Observability: per-specialization compile seconds, cache hit/miss counts
(via ``jax.monitoring``), and time-to-first-step land in ``utils.Timer`` /
``utils.tracer`` and in the plane's ``report()``; bench.py banks them
(``time_to_first_step`` / ``compile_time_s`` / ``BENCH_COMPILE`` cells).
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
from ..utils import envflags

PRECOMPILE_MODES = ("off", "blocking", "background", "analysis")
RETRACE_POLICIES = ("warn", "error")

# how long finish() waits for a still-running warm-up worker before leaking
# the daemon thread with a warning (a wedged XLA compile must not hang run
# teardown); module-level so tests can pin it
_WORKER_JOIN_TIMEOUT_S = 30.0


class RetraceError(RuntimeError):
    """An armed retrace sentinel saw a trace outside the known
    specialization set — a silent-recompile bug (``Training.retrace_policy:
    error``). The message carries the aval diff against the nearest known
    specialization."""


# ---------------------------------------------------------------------------
# compile metrics: process-wide counters fed by jax.monitoring events
# ---------------------------------------------------------------------------

_METRICS_LOCK = threading.Lock()
_METRICS = {
    "cache_hits": 0,
    "cache_misses": 0,
    "backend_compile_s": 0.0,
    "cache_retrieval_s": 0.0,
}
_LISTENERS_INSTALLED = False


def _on_event(name: str, **kw) -> None:
    if name == "/jax/compilation_cache/cache_hits":
        with _METRICS_LOCK:
            _METRICS["cache_hits"] += 1
    elif name == "/jax/compilation_cache/cache_misses":
        with _METRICS_LOCK:
            _METRICS["cache_misses"] += 1
            misses = _METRICS["cache_misses"]
        # a miss in steady state is a full XLA compile paid — record it as
        # a typed incident (obs/events.py; misses are rare by design, so
        # the emission cost is irrelevant)
        try:
            from ..obs.events import EV_CACHE_MISS
            from ..obs.events import emit as _emit_event

            _emit_event(EV_CACHE_MISS, severity="warn", total=misses)
        except Exception:
            pass


def _on_duration(name: str, secs: float, **kw) -> None:
    if name == "/jax/core/compile/backend_compile_duration":
        with _METRICS_LOCK:
            _METRICS["backend_compile_s"] += float(secs)
    elif name == "/jax/compilation_cache/cache_retrieval_time_sec":
        with _METRICS_LOCK:
            _METRICS["cache_retrieval_s"] += float(secs)


def install_metrics_listeners() -> None:
    """Idempotently subscribe the counters to jax.monitoring. Must run
    before the compiles it should observe; listeners cannot be removed, so
    there is exactly one registration per process."""
    global _LISTENERS_INSTALLED
    with _METRICS_LOCK:
        if _LISTENERS_INSTALLED:
            return
        _LISTENERS_INSTALLED = True
    import jax

    jax.monitoring.register_event_listener(_on_event)
    jax.monitoring.register_event_duration_secs_listener(_on_duration)


def compile_metrics() -> Dict[str, float]:
    """Snapshot of the process-wide compile counters (cache hits/misses,
    cumulative backend-compile and cache-retrieval seconds)."""
    with _METRICS_LOCK:
        return dict(_METRICS)


def _metrics_delta(before: Dict[str, float]) -> Dict[str, float]:
    now = compile_metrics()
    return {k: now[k] - before.get(k, 0) for k in now}


# ---------------------------------------------------------------------------
# communication accounting: collective ops + bytes from the compiled HLO
# ---------------------------------------------------------------------------

# per-chip ICI bandwidth by TPU generation, bytes/second (public figures,
# same table discipline as PEAK_FLOPS in obs/telemetry.py) — the divisor of
# the collective-time estimate. CPU/unknown gets a deliberately modest
# figure so the estimate stays an ESTIMATE, never a claim.
ICI_BYTES_PER_S = {
    "v6": 400e9,
    "v5p": 600e9,
    "v5": 200e9,  # v5e / "TPU v5 lite"
    "v4": 300e9,
}


def ici_bytes_per_s(device_kind: str) -> float:
    kind = str(device_kind).lower()
    for key, val in ICI_BYTES_PER_S.items():
        if key in kind:
            return val
    return 50e9


# result-shape + op-name of one collective instruction in optimized HLO
# text. Async pairs count once: the `-start` op is matched, the matching
# `-done` never is (after the base op name only `-start(` or `(` match).
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s+(?P<shape>\([^)]*\)|[a-zA-Z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)(?P<start>-start)?\("
)
_HLO_SHAPE_TOKEN_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_HLO_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "s4": 1, "u4": 1, "pred": 1, "c64": 8, "c128": 16,
}


def _shape_bytes(shape: str, largest_only: bool = False) -> int:
    """Bytes of one HLO result shape (scalar, array, or tuple).
    ``largest_only`` keeps just the biggest tuple component — the async
    ``-start`` forms return ``(operand, destination, ...)`` tuples whose
    operand entries alias buffers already counted, so summing them would
    roughly double the sync form's figure."""
    sizes = []
    for dtype, dims in _HLO_SHAPE_TOKEN_RE.findall(shape):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * _HLO_DTYPE_BYTES.get(dtype, 4))
    if not sizes:
        return 0
    return max(sizes) if largest_only else sum(sizes)


def collective_census(hlo_text: str) -> Dict[str, Dict[str, float]]:
    """Count collective instructions and their per-device result bytes in
    an optimized HLO module: ``{op: {"count": n, "bytes": b}}``. The text
    is the PER-DEVICE SPMD program, so bytes are what each device's
    collective touches per step — the figure the ICI/DCN estimate divides.
    """
    out: Dict[str, Dict[str, float]] = {}
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        op = m.group("op")
        entry = out.setdefault(op, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += _shape_bytes(
            m.group("shape"), largest_only=m.group("start") is not None
        )
    return out


def summarize_comm(
    census: Dict[str, Dict[str, float]],
    flops: Optional[float],
    device_kind: str,
) -> Dict[str, Any]:
    """One spec's collective table + the compute-vs-comm step-time
    decomposition: ``comm_time_est_s`` = bytes / per-chip ICI bandwidth,
    ``compute_time_est_s`` = XLA-counted FLOPs / chip peak,
    ``comm_fraction_est`` their ratio — the direct instrument for the MFU
    hunt (a spec whose fraction dominates is bandwidth-bound, and no
    kernel fusion will move it)."""
    from ..obs.telemetry import peak_flops

    bytes_total = float(sum(e["bytes"] for e in census.values()))
    ops_total = int(sum(e["count"] for e in census.values()))
    comm_t = bytes_total / ici_bytes_per_s(device_kind)
    compute_t = (
        float(flops) / peak_flops(device_kind) if flops else None
    )
    fraction = None
    if compute_t is not None and (comm_t + compute_t) > 0:
        fraction = comm_t / (comm_t + compute_t)
    return {
        "collectives": {k: dict(v) for k, v in sorted(census.items())},
        "ops_total": ops_total,
        "bytes_total": int(bytes_total),
        "comm_time_est_s": comm_t,
        "compute_time_est_s": compute_t,
        "comm_fraction_est": fraction,
    }


# ---------------------------------------------------------------------------
# persistent compilation cache wiring
# ---------------------------------------------------------------------------


def cache_dir_active() -> Optional[str]:
    """The persistent cache directory jax currently writes to, or None."""
    import jax

    try:
        return jax.config.jax_compilation_cache_dir or None
    except AttributeError:  # pragma: no cover - ancient jax
        return None


def _reset_jax_cache_object() -> None:
    """jax materializes its persistent-cache object at most once per
    process (``compilation_cache._get_cache``), silently ignoring later
    ``jax_compilation_cache_dir`` changes — reset it so a re-pointed
    directory actually takes effect (tests, the BENCH_COMPILE cold/warm
    A/B)."""
    try:
        from jax.experimental.compilation_cache import compilation_cache as _jcc

        _jcc.reset_cache()
    except Exception:  # pragma: no cover - private-API drift tolerance
        pass


def set_cache_dir(
    path: Optional[str], min_compile_secs: Optional[float] = None
) -> Optional[str]:
    """Point jax's persistent compilation cache at ``path`` (abspath'd,
    created). ``min_compile_secs`` lowers the write threshold (jax default:
    1s — CPU test compiles would never be cached without 0). ``None`` path
    disables the cache."""
    import jax

    if path is None:
        if cache_dir_active() is not None:
            jax.config.update("jax_compilation_cache_dir", None)
            _reset_jax_cache_object()
        return None
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    if cache_dir_active() != path:
        jax.config.update("jax_compilation_cache_dir", path)
        _reset_jax_cache_object()
    if min_compile_secs is not None:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_secs)
        )
        # a 0-second threshold means "cache everything" — drop the entry-size
        # floor too, or trivial test-sized executables still skip the disk
        if float(min_compile_secs) <= 0:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    install_metrics_listeners()
    return path


def setup_compile_cache(
    training: Dict[str, Any], log_name: Optional[str] = None
) -> Optional[str]:
    """Resolve and activate the run's persistent compilation cache.

    Resolution order: ``HYDRAGNN_COMPILE_CACHE`` env (``0``/``off``/``none``
    disables, ``1`` forces the config/default resolution back on, a path
    overrides), then ``Training.compile_cache_dir`` (``false`` disables, a
    path overrides), else the default ``./logs/<run>/xla_cache``. The
    disable paths also DEACTIVATE a cache directory a previous run in this
    process pointed jax at. ``HYDRAGNN_COMPILE_CACHE_MIN_SECS`` lowers
    jax's min-compile-time write threshold (the smokes pin 0 so CPU-sized
    compiles are cached too). Returns the active directory, or None.
    """
    env = envflags.env_str("HYDRAGNN_COMPILE_CACHE")
    cfg = training.get("compile_cache_dir")
    if env is not None:
        s = env.strip()
        if s.lower() in ("0", "off", "none", "false", ""):
            # deactivate any directory a previous run in this process set
            return set_cache_dir(None)
        if s != "1":
            cfg = s  # an explicit path beats the config
        elif cfg is False or (
            isinstance(cfg, str) and cfg.strip().lower() in ("off", "none")
        ):
            # "1": force-on with the config/default resolution (the same
            # semantics as HYDRAGNN_LAPPE_CACHE=1)
            cfg = None
    if cfg is False or (isinstance(cfg, str) and cfg.strip().lower() in ("off", "none")):
        return set_cache_dir(None)
    if isinstance(cfg, str) and cfg:
        path = cfg
    else:
        path = os.path.join("./logs", log_name or "run", "xla_cache")
    min_secs = envflags.env_str("HYDRAGNN_COMPILE_CACHE_MIN_SECS")
    return set_cache_dir(
        path, float(min_secs) if min_secs is not None else None
    )


# ---------------------------------------------------------------------------
# retrace sentinel
# ---------------------------------------------------------------------------

# one leaf of a trace signature: (tree path, shape, dtype, weak_type)
_Leaf = Tuple[str, Tuple[int, ...], str, bool]
_Sig = Tuple[_Leaf, ...]


def _signature_of(args) -> _Sig:
    """Abstract signature of a (pytree of) traced argument(s): per-leaf
    (path, shape, dtype, weak_type). Called from inside traced function
    bodies, where leaves are tracers carrying ``.aval``."""
    import jax

    leaves = []
    for path, x in jax.tree_util.tree_flatten_with_path(args)[0]:
        aval = getattr(x, "aval", None)
        if aval is not None:
            shape = tuple(getattr(aval, "shape", ()))
            dtype = str(getattr(aval, "dtype", type(x).__name__))
            weak = bool(getattr(aval, "weak_type", False))
        else:  # non-array leaf (should not happen under jit; be tolerant)
            shape = tuple(np.shape(x))
            dtype = str(np.asarray(x).dtype) if np.ndim(x) else type(x).__name__
            weak = isinstance(x, (int, float, complex, bool))
        leaves.append((jax.tree_util.keystr(path), shape, dtype, weak))
    return tuple(leaves)


def _diff_sigs(got: _Sig, ref: _Sig, limit: int = 8) -> List[str]:
    """Human-readable per-leaf diff of two signatures (by tree path)."""
    ref_by_path = {p: (s, d, w) for p, s, d, w in ref}
    got_paths = {p for p, *_ in got}
    out = []
    for p, s, d, w in got:
        have = ref_by_path.get(p)
        if have is None:
            out.append(f"  {p}: NEW leaf {d}{list(s)}{' weak' if w else ''}")
        elif have != (s, d, w):
            rs, rd, rw = have
            out.append(
                f"  {p}: {rd}{list(rs)}{' weak' if rw else ''} -> "
                f"{d}{list(s)}{' weak' if w else ''}"
            )
    for p, s, d, w in ref:
        if p not in got_paths:
            out.append(f"  {p}: leaf DROPPED ({d}{list(s)})")
    if len(out) > limit:
        out = out[:limit] + [f"  ... {len(out) - limit} more differing leaves"]
    return out


class _TraceSentinel:
    """Process-wide trace counter per step builder, armable against a known
    specialization set. ``note`` is called from traced function bodies —
    i.e. exactly once per jit trace — so its counts ARE the retrace
    census."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sigs: Dict[str, List[_Sig]] = {}
        self._armed = False
        self._policy = "warn"
        self._known: Dict[str, set] = {}
        self._violations: List[str] = []

    def note(self, name: str, args) -> None:
        sig = _signature_of(args)
        with self._lock:
            self._sigs.setdefault(name, []).append(sig)
            if not self._armed:
                return
            known = self._known.get(name, set())
            if sig in known:
                # a re-trace of a known specialization: jit caches make this
                # impossible for a live builder — it means a builder was
                # rebuilt or a cache was invalidated mid-run. Beyond the
                # ladder budget either way.
                msg = (
                    f"retrace sentinel: {name} re-traced an already-known "
                    "specialization after warm-up (rebuilt step function or "
                    "invalidated jit cache?) — one extra XLA compile"
                )
            else:
                msg = self._unknown_sig_message(name, sig, known)
            # number the message: a recurring violation (step rebuilt every
            # epoch) would otherwise emit byte-identical warnings that
            # Python's default filter dedups down to ONE — silencing every
            # repeat of an each-time-paid recompile
            msg = f"{msg} [violation #{len(self._violations) + 1}]"
            self._violations.append(msg)
            policy = self._policy
            n_violations = len(self._violations)
        # structured incident record (obs/events.py) with the active trace
        # context — a violation inside a sampled serving request carries the
        # request's trace_id into the flight-recorder window
        try:
            from ..obs.events import EV_RETRACE_VIOLATION
            from ..obs.events import emit as _emit_event

            _emit_event(
                EV_RETRACE_VIOLATION,
                severity="error" if policy == "error" else "warn",
                builder=name,
                violation=n_violations,
            )
        except Exception:
            pass
        if policy == "error":
            raise RetraceError(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=3)

    @staticmethod
    def _unknown_sig_message(name: str, sig: _Sig, known: set) -> str:
        nearest = None
        best = None
        for k in known:
            d = len(_diff_sigs(sig, k, limit=10 ** 6))
            if best is None or d < best:
                best, nearest = d, k
        lines = [
            f"retrace sentinel: {name} traced a specialization outside the "
            "warmed ladder budget after warm-up completed — a silent "
            "recompile (one full XLA compile per occurrence)."
        ]
        if nearest is not None:
            lines.append(
                f"aval diff vs the nearest known specialization "
                f"({best} differing leaves):"
            )
            lines.extend(_diff_sigs(sig, nearest))
        else:
            lines.append(f"no known specializations recorded for {name!r}")
        return "\n".join(lines)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {k: len(v) for k, v in self._sigs.items()}

    def arm(self, policy: str) -> None:
        """Freeze every signature seen so far as the known set; later traces
        are violations handled per ``policy``."""
        with self._lock:
            self._known = {k: set(v) for k, v in self._sigs.items()}
            self._policy = policy
            self._armed = True

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    @property
    def armed(self) -> bool:
        return self._armed

    def violations(self) -> List[str]:
        with self._lock:
            return list(self._violations)

    def reset(self) -> None:
        with self._lock:
            self._sigs.clear()
            self._known.clear()
            self._violations.clear()
            self._armed = False
            self._policy = "warn"


_SENTINEL = _TraceSentinel()


def sentinel() -> _TraceSentinel:
    return _SENTINEL


def note_trace(name: str, args) -> None:
    """Record one trace of step builder ``name`` (call from the traced
    function body — it executes exactly once per trace). No-op cost at run
    time: the call does not appear in the jaxpr."""
    _SENTINEL.note(name, args)


def serve_warmup(
    fn,
    state,
    templates,
    policy: str = "error",
    label: str = "serve",
) -> Tuple[List[Tuple[str, float]], List[Tuple[str, str]], float]:
    """Serving-side blocking warm-up: CALL the jit object on one template
    batch per ladder level and block until each executes.

    Unlike the training plane's ``lower().compile()`` jobs (whose AOT
    executables are only reachable from the call path through the persistent
    cache), calling the jit object directly lands every specialization in
    its OWN executable cache — so once this returns, the serve loop's first
    organic visit to any level is a pure cache hit regardless of persistent-
    cache configuration (the persistent cache still buys down *restarts*).
    Readiness == zero-retrace steady state by construction.

    On full coverage the retrace sentinel is armed at ``policy`` (serving
    default ``error``: an unknown specialization under live traffic is a
    correctness bug). Returns ``(compiled, errors, last_exec_s)`` where
    ``compiled`` is [(label, seconds)] per level, ``errors`` the failures
    (arming is skipped if any), and ``last_exec_s`` the warm re-execution
    time of the final (worst-case) level — the serving-latency seed for the
    shed estimator."""
    if policy not in RETRACE_POLICIES:
        raise ValueError(
            f"retrace policy {policy!r} must be one of {RETRACE_POLICIES}"
        )
    import jax

    install_metrics_listeners()
    compiled: List[Tuple[str, float]] = []
    errors: List[Tuple[str, str]] = []
    last_exec_s = 0.0
    for spec, tmpl in templates:
        name = f"{label}:{spec.n_nodes}n/{spec.n_edges}e"
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(fn(state, tmpl))
        except Exception as e:  # noqa: BLE001 — reported, never raised here
            errors.append((name, f"{type(e).__name__}: {e}"))
            continue
        compiled.append((name, time.perf_counter() - t0))
    if templates and not errors:
        # warm re-execution of the worst level: compile excluded, pure step
        spec, tmpl = templates[-1]
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(fn(state, tmpl))
            last_exec_s = time.perf_counter() - t0
        except Exception:  # pragma: no cover - first call succeeded above
            pass
        _SENTINEL.arm(policy)
    return compiled, errors, last_exec_s


def attach_lower_fn(fn, jitted, batch_transform: Optional[Callable] = None,
                    batch_argnum: int = 1):
    """Mark a step-fn *wrapper* as AOT-lowerable: ``fn`` is what the loop
    calls (e.g. the mesh path's ``lambda s, b, r: _pstep(s, promote_batch(b,
    mesh), r)``), ``jitted`` the underlying jit object, ``batch_transform``
    the wrapper's batch preprocessing. The compile plane lowers through the
    SAME jit object and transform the loop uses, so the warmed executable is
    byte-identical to the organic one."""

    def _lower(*args):
        if batch_transform is not None:
            args = list(args)
            args[batch_argnum] = batch_transform(args[batch_argnum])
        return jitted.lower(*args)

    fn._compile_plane_lower = _lower
    return fn


def _aval_like(x):
    """Abstract stand-in for one (about-to-be-donated) argument leaf:
    shape/dtype/weak_type via the aval, plus the committed sharding when
    one exists — everything ``jit.lower`` specializes on, so a program
    lowered from these is identical to the organic call's."""
    import jax

    aval = jax.core.get_aval(x)
    sharding = getattr(x, "sharding", None)
    # only MESH shardings are program-relevant; a plain array's implicit
    # SingleDeviceSharding must stay implicit (an explicit one would mark
    # the aval committed and lower a different — device-pinned — program
    # than the organic call compiled)
    if isinstance(sharding, jax.sharding.NamedSharding):
        return jax.ShapeDtypeStruct(
            aval.shape, aval.dtype, sharding=sharding,
            weak_type=bool(getattr(aval, "weak_type", False)),
        )
    return aval


def _lower_fn_of(fn) -> Optional[Callable]:
    lower = getattr(fn, "_compile_plane_lower", None)
    if lower is not None:
        return lower
    return getattr(fn, "lower", None)


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------


class CompilePlane:
    """Per-run orchestrator: collect the ladder's warm-up jobs, run them
    (inline or in a worker thread), arm the sentinel when coverage is
    complete, and report compile observability at run end."""

    def __init__(
        self,
        mode: str = "background",
        retrace_policy: str = "warn",
        log_name: str = "run",
        remat_policy: str = "full",
    ):
        if mode not in PRECOMPILE_MODES:
            raise ValueError(
                f"precompile mode {mode!r} must be one of {PRECOMPILE_MODES}"
            )
        if retrace_policy not in RETRACE_POLICIES:
            raise ValueError(
                f"retrace_policy {retrace_policy!r} must be one of "
                f"{RETRACE_POLICIES}"
            )
        self.mode = mode
        self.retrace_policy = retrace_policy
        self.log_name = log_name
        # Training.remat_policy, carried so the flops/MFU accounting below
        # records WHICH recompute schedule its XLA-counted step FLOPs were
        # measured under (remat changes the counted FLOPs — a policy A/B
        # without this field would bank incomparable MFU numbers)
        self.remat_policy = remat_policy
        self.cache_dir: Optional[str] = None
        self.jobs: List[Tuple[str, Callable]] = []
        self.compiled: List[Tuple[str, float]] = []  # (label, secs)
        self.errors: List[Tuple[str, str]] = []
        # XLA-counted FLOPs per warmed specialization (label -> flops),
        # harvested from the AOT-compiled executables' cost_analysis — the
        # flops-audit recipe (run-scripts/flops_audit.py) at zero extra
        # compile cost. The telemetry plane's MFU gauge reads this table
        # (obs/telemetry.py attach_flops); dict writes are atomic under the
        # GIL, so the background worker publishes lock-free.
        self.flops_by_spec: Dict[str, float] = {}
        # HBM accounting (obs/memory.py): memory_analysis() figures per
        # warmed specialization, harvested beside the flops — argument /
        # output / temp / peak bytes. Published as hydragnn_hbm_* gauges
        # and rendered in report(); the flight recorder dumps the process
        # table as its OOM-forensics section.
        self.memory_by_spec: Dict[str, Dict[str, float]] = {}
        # communication accounting (collective_census): per warmed
        # specialization, the collective ops + per-device bytes walked out
        # of the compiled HLO and the compute-vs-comm decomposition —
        # published as hydragnn_comm_* gauges, rendered in report(), and
        # read per window by the telemetry layer (attach_comm). Dict
        # writes are atomic under the GIL like flops_by_spec.
        self.comm_by_spec: Dict[str, Dict[str, Any]] = {}
        # MFU-estimate fallback (obs/telemetry.py attach_flops consumer):
        # with precompile off nothing fills flops_by_spec — when armed via
        # enable_flops_fallback(), the first organic step's executable is
        # lowered + compiled through the persistent cache and its
        # cost/memory analysis harvested instead
        self._organic_flops = False
        self.time_to_first_step: Optional[float] = None
        self._t0: Optional[float] = None
        self._m0: Dict[str, float] = {}
        self._counts0: Dict[str, int] = {}
        self._viol0 = 0
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- job collection ----------------------------------------------------

    def _collect_jobs(self, step_fn, eval_fn, state, train_loader,
                      val_loader, test_loader, rng) -> None:
        lower_step = _lower_fn_of(step_fn) if step_fn is not None else None
        lower_eval = _lower_fn_of(eval_fn) if eval_fn is not None else None

        def template_list(loader):
            fn = getattr(loader, "spec_template_batches", None)
            return fn() if fn is not None else []

        if lower_step is not None and train_loader is not None:
            for spec, tmpl in template_list(train_loader):
                self.jobs.append(
                    (
                        f"train:{spec.n_nodes}n/{spec.n_edges}e",
                        lambda t=tmpl: lower_step(state, t, rng),
                    )
                )
        if lower_eval is not None:
            seen = set()
            for loader in (val_loader, test_loader):
                if loader is None:
                    continue
                for spec, tmpl in template_list(loader):
                    if spec in seen:
                        continue  # val/test share the ladder (api.py)
                    seen.add(spec)
                    self.jobs.append(
                        (
                            f"eval:{spec.n_nodes}n/{spec.n_edges}e",
                            lambda t=tmpl: lower_eval(state, t),
                        )
                    )

    # -- lifecycle ---------------------------------------------------------

    def launch(self, step_fn, eval_fn, state, train_loader,
               val_loader=None, test_loader=None, rng=None, skip_eval=False):
        """Start the plane for one run. Returns ``step_fn`` instrumented
        with a first-step timer; warm-up runs per ``self.mode``. Without an
        active persistent cache directory ``blocking``/``background``
        degrade to ``off``: the call path could never reuse the AOT
        executables, so warm-up would burn a core for nothing. Mode
        ``analysis`` is the explicit exception — it runs the (blocking)
        warm-up regardless, accepting that without a cache the
        executables are unreachable, because the harvests are the point:
        the FLOPs/HBM/collective tables and the MFU gauge on environments
        where a persistent cache cannot run (shared-FS quota, or a jaxlib
        whose cache-key serializer is broken — run-scripts/fleet_smoke.py
        runs under exactly that)."""
        from ..utils import tracer as tr
        from ..utils.timers import Timer

        install_metrics_listeners()
        self.cache_dir = cache_dir_active()
        self._t0 = time.perf_counter()
        # started HERE so blocking-mode warm-up is inside the span, exactly
        # like the report's time_to_first_step field (both measure launch ->
        # first completed step)
        ttfs_timer = Timer("time_to_first_step").start()
        self._m0 = compile_metrics()
        self._counts0 = _SENTINEL.counts()
        # the sentinel is process-global; baseline its violation count so
        # this plane's report never attributes an earlier run's retraces
        # to itself (in-process HPO trials, repeated run_training)
        self._viol0 = len(_SENTINEL.violations())
        if self.mode in ("blocking", "background") and self.cache_dir is None:
            self.mode = "off"
        if self.mode != "off":
            import jax

            if rng is None:
                rng = jax.random.PRNGKey(0)
            self._collect_jobs(
                step_fn, None if skip_eval else eval_fn, state,
                train_loader, val_loader, test_loader, rng,
            )
            if self.mode in ("blocking", "analysis"):
                with Timer("compile_plane_warmup"):
                    self._run_jobs()
                self._maybe_arm()
            elif self.jobs:
                self._worker = threading.Thread(
                    target=self._worker_main, daemon=True,
                    name="compile-plane-warmup",
                )
                self._worker.start()

        # first-step timer: time from plane launch to the first completed
        # optimizer step (the restart-latency metric the cache is buying
        # down); one flag check per call afterwards. The Timer entry
        # "time_to_first_step" records the same launch-to-done span as the
        # report field (started at launch above, stopped after the first
        # step; never stopped — so never recorded — if no step runs); the
        # tracer region "first_step" covers only the step call itself (a
        # launch-scoped xprof annotation would span half of epoch 0 and
        # break the tracer's LIFO unwind for regions opened in between).
        done = {"first": True}
        plane = self

        def instrumented(st, batch, step_rng, _fn=step_fn):
            if not done["first"]:
                return _fn(st, batch, step_rng)
            import jax

            # organic-executable harvest (enable_flops_fallback): the
            # donated STATE's buffers are dead after the step, so its
            # avals (shape/dtype/weak_type + committed sharding — pure
            # metadata, no trace, no copy) are captured here; the actual
            # lower()+compile() happens AFTER the first step, off the
            # time_to_first_step measurement (lowering is a full second
            # Python trace — on the critical path it would inflate the
            # first-step latency the bench gate bounds). batch/rng are
            # not donated, so they lower live.
            state_avals = None
            if plane._organic_flops:
                try:
                    state_avals = jax.tree_util.tree_map(_aval_like, st)
                except Exception:
                    state_avals = None
            tr.start("first_step")
            out = _fn(st, batch, step_rng)
            jax.block_until_ready(out[1])
            tr.stop("first_step")
            done["first"] = False
            plane.time_to_first_step = time.perf_counter() - plane._t0
            ttfs_timer.stop()
            if state_avals is not None:
                try:
                    lower = _lower_fn_of(_fn)
                    # compile() is a persistent-cache retrieval of the
                    # entry the organic call just wrote (aval-faithful
                    # lowering: weak types + shardings preserved, so the
                    # program is byte-identical to the organic one)
                    plane._harvest_analyses(
                        plane._batch_label(batch),
                        lower(state_avals, batch, step_rng).compile(),
                    )
                except Exception as e:
                    warnings.warn(
                        "organic FLOPs/HBM harvest failed "
                        f"({type(e).__name__}: {e}); the MFU gauge stays "
                        "unpublished for this run",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            return out

        return instrumented

    def _run_jobs(self) -> None:
        for label, thunk in self.jobs:
            if self._stop.is_set():
                return
            t0 = time.perf_counter()
            try:
                compiled = thunk().compile()
            except Exception as e:  # warm-up must never kill training
                self.errors.append((label, f"{type(e).__name__}: {e}"))
                continue
            self.compiled.append((label, time.perf_counter() - t0))
            self._harvest_analyses(label, compiled)

    def _harvest_analyses(self, label: str, compiled) -> None:
        """Best-effort cost (FLOPs) + memory (HBM) harvest from one
        compiled executable — the zero-extra-compile observability dividend
        of holding the executable at all."""
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            flops = float(cost.get("flops", 0.0))
            if flops > 0:
                self.flops_by_spec[label] = flops
        except Exception:  # cost analysis is best-effort observability
            pass
        try:
            from ..obs import memory as obs_memory

            stats = obs_memory.record(label, compiled)
            if stats is not None:
                self.memory_by_spec[label] = stats
        except Exception:  # memory analysis availability is backend-bound
            pass
        # collective census: walk the compiled per-device HLO for
        # collective ops + bytes. Multi-device programs only — a
        # single-device executable has no collectives, and its (possibly
        # tens of MB) HLO text is not worth materializing to prove it.
        try:
            import jax

            if jax.device_count() > 1:
                census = collective_census(compiled.as_text())
                summary = summarize_comm(
                    census,
                    self.flops_by_spec.get(label),
                    jax.devices()[0].device_kind,
                )
                self.comm_by_spec[label] = summary
                self._publish_comm(label, summary)
        except Exception:  # the census is best-effort observability
            pass

    @staticmethod
    def _publish_comm(label: str, summary: Dict[str, Any]) -> None:
        """hydragnn_comm_* gauges for one spec (best-effort)."""
        try:
            from ..obs.registry import registry

            reg = registry()
            g_ops = reg.gauge(
                "hydragnn_comm_collectives",
                "Collective instructions per compiled specialization "
                "(HLO census, train/compile_plane.py)",
                labelnames=("spec", "collective"),
            )
            g_bytes = reg.gauge(
                "hydragnn_comm_bytes",
                "Per-device bytes each collective touches per step",
                labelnames=("spec", "collective"),
            )
            for op, entry in summary["collectives"].items():
                g_ops.set(entry["count"], spec=label, collective=op)
                g_bytes.set(entry["bytes"], spec=label, collective=op)
            reg.gauge(
                "hydragnn_comm_bytes_total",
                "Per-device collective bytes per step, all collectives",
                labelnames=("spec",),
            ).set(summary["bytes_total"], spec=label)
            if summary["comm_fraction_est"] is not None:
                reg.gauge(
                    "hydragnn_comm_fraction_est",
                    "Estimated fraction of step time inside collectives "
                    "(bytes/ICI-bandwidth vs FLOPs/peak)",
                    labelnames=("spec",),
                ).set(summary["comm_fraction_est"], spec=label)
        except Exception:
            pass

    def _worker_main(self) -> None:
        from ..utils.timers import Timer

        with Timer("compile_plane_warmup"):
            self._run_jobs()
        self._maybe_arm()

    def _maybe_arm(self) -> None:
        # arm only on FULL coverage: a failed warm-up job means its organic
        # visit will legitimately trace later — flagging it would turn a
        # warm-up hiccup into a spurious (possibly fatal) sentinel report
        if self.jobs and not self.errors and not self._stop.is_set():
            _SENTINEL.arm(self.retrace_policy)

    def train_flops_for(self, key: Tuple[int, int]) -> Optional[float]:
        """FLOPs of the train-step specialization padded to ``key`` =
        (per-shard nodes, edges), or None while warm-up has not compiled
        it (background mode fills the table as it goes)."""
        return self.flops_by_spec.get(f"train:{key[0]}n/{key[1]}e")

    def train_comm_for(self, key: Tuple[int, int]) -> Optional[Dict[str, Any]]:
        """Collective table of the train-step specialization padded to
        ``key`` (obs/telemetry.py ``attach_comm`` consumer), or None while
        its HLO has not been walked."""
        return self.comm_by_spec.get(f"train:{key[0]}n/{key[1]}e")

    def enable_flops_fallback(self) -> None:
        """Arm the organic cost/memory harvest for ``precompile: off``
        runs (the loop calls this when telemetry wants an MFU estimate):
        ``flops_by_spec`` is otherwise populated only by AOT warm-up, so
        mode ``off`` silently zeroed the MFU gauge. With a persistent
        cache active, the first organic step's program is lowered (one
        extra Python trace) and ``compile()``d through the cache (a
        retrieval, not a recompile — the organic call just wrote the
        entry) purely to hold its analyses. Without a cache the fallback
        would pay a FULL duplicate XLA compile, so it warns once naming
        the cause instead."""
        if self.mode != "off":
            return  # warm-up fills the table; nothing to fall back from
        if self.cache_dir is None:
            warnings.warn(
                "telemetry MFU estimate has no FLOPs source: "
                "Training.precompile is 'off' (or degraded to off because "
                "no persistent compilation cache is active) and no cache "
                "directory is available to harvest the organic executable "
                "through — hydragnn_mfu_estimate will not be published. "
                "Enable Training.precompile or Training.compile_cache_dir.",
                RuntimeWarning,
                stacklevel=2,
            )
            return
        self._organic_flops = True

    @staticmethod
    def _batch_label(batch, prefix: str = "train") -> str:
        """Spec label of a (possibly device-stacked) batch from its mask
        shapes — the same per-shard (nodes, edges) key the telemetry
        layer's flops lookup uses (obs/telemetry.py _batch_census)."""
        return (
            f"{prefix}:{int(batch.node_mask.shape[-1])}n/"
            f"{int(batch.edge_mask.shape[-1])}e"
        )

    def finish(self, verbosity: int = 0) -> Dict[str, Any]:
        """End the run: stop/join the worker, disarm the sentinel, return
        (and at verbosity > 0 print) the report."""
        if self._worker is not None and self._worker.is_alive():
            # a still-compiling worker gets the FULL grace to drain the
            # queue — the remaining AOT compiles populate the persistent
            # cache for the next restart, which is the whole point (the
            # compile smoke's cold leg asserts full ladder coverage on a
            # run shorter than its warm-up). Only after the grace expires
            # is the stop flag set: the leaked daemon thread then exits at
            # its next job boundary instead of hanging teardown on a
            # wedged XLA compile.
            self._worker.join(timeout=_WORKER_JOIN_TIMEOUT_S)
            if self._worker.is_alive():
                self._stop.set()
                warnings.warn(
                    "compile-plane warm-up worker still compiling "
                    f"{_WORKER_JOIN_TIMEOUT_S}s after the run ended; "
                    "leaking the daemon thread",
                    RuntimeWarning,
                    stacklevel=2,
                )
        rep = self.report()
        _SENTINEL.disarm()
        if verbosity > 0:
            print(f"[{self.log_name}] {format_report(rep)}", file=sys.stderr)
        return rep

    def report(self) -> Dict[str, Any]:
        delta = _metrics_delta(self._m0) if self._m0 else compile_metrics()
        counts = _SENTINEL.counts()
        traces = {
            k: v - self._counts0.get(k, 0)
            for k, v in counts.items()
            if v - self._counts0.get(k, 0)
        }
        return {
            "mode": self.mode,
            "cache_dir": self.cache_dir,
            "remat_policy": self.remat_policy,
            "specializations": len(self.jobs),
            "precompiled": len(self.compiled),
            "compile_time_s": round(
                sum(s for _, s in self.compiled) or delta["backend_compile_s"], 3
            ),
            "backend_compile_s": round(delta["backend_compile_s"], 3),
            "cache_hits": int(delta["cache_hits"]),
            "cache_misses": int(delta["cache_misses"]),
            "time_to_first_step": (
                round(self.time_to_first_step, 3)
                if self.time_to_first_step is not None
                else None
            ),
            "traces": traces,
            "violations": len(_SENTINEL.violations()) - self._viol0,
            "warmup_errors": list(self.errors),
            # per-spec HBM table (memory_analysis harvest, obs/memory.py):
            # peak bytes per warmed specialization + the run's worst case —
            # the headroom figure that used to be guesswork before an OOM
            "hbm_by_spec": {
                label: int(stats["peak_bytes"])
                for label, stats in sorted(self.memory_by_spec.items())
            },
            "hbm_peak_bytes": (
                max(
                    int(s["peak_bytes"]) for s in self.memory_by_spec.values()
                )
                if self.memory_by_spec
                else None
            ),
            # per-spec collective table (HLO census): bytes + op count +
            # the compute-vs-comm decomposition — ROADMAP item 4's direct
            # instrument (a comm-bound spec shows up HERE, not in a guess)
            "comm_by_spec": {
                label: {
                    "bytes_total": int(c["bytes_total"]),
                    "ops_total": int(c["ops_total"]),
                    "comm_fraction_est": (
                        round(c["comm_fraction_est"], 6)
                        if c["comm_fraction_est"] is not None
                        else None
                    ),
                }
                for label, c in sorted(self.comm_by_spec.items())
            },
            "comm_bytes_peak": (
                max(
                    int(c["bytes_total"]) for c in self.comm_by_spec.values()
                )
                if self.comm_by_spec
                else None
            ),
            # accelerator memory capacity (None on backends that expose
            # no memory_stats, e.g. CPU): the denominator the run
            # doctor's HBM-pressure rule divides hbm_peak_bytes by
            "device_bytes_limit": device_bytes_limit(),
        }


def device_bytes_limit() -> Optional[float]:
    """Per-device memory capacity (obs/memory.py owns the helper — it
    also rides every flight dump's memory.json); kept as a best-effort
    delegate so the report never fails on an obs import problem."""
    try:
        from ..obs.memory import device_bytes_limit as _limit

        return _limit()
    except Exception:
        return None


def format_report(rep: Dict[str, Any]) -> str:
    """One grep-able line (the chaos/compile smokes parse these fields)."""
    ttfs = rep.get("time_to_first_step")
    hbm = rep.get("hbm_peak_bytes")
    comm = rep.get("comm_bytes_peak")
    comm_specs = rep.get("comm_by_spec") or {}
    fracs = [
        c["comm_fraction_est"]
        for c in comm_specs.values()
        if c.get("comm_fraction_est") is not None
    ]
    return (
        f"compile plane: mode={rep['mode']} "
        f"remat={rep.get('remat_policy', 'full')} "
        f"precompiled={rep['precompiled']}/{rep['specializations']} "
        f"compile_time_s={rep['compile_time_s']} "
        f"cache_hits={rep['cache_hits']} cache_misses={rep['cache_misses']} "
        f"time_to_first_step={ttfs if ttfs is not None else 'n/a'}s "
        f"traces={sum(rep['traces'].values())} "
        f"violations={rep['violations']} "
        f"hbm_peak={hbm if hbm is not None else 'n/a'} "
        f"comm_bytes_peak={comm if comm is not None else 'n/a'} "
        f"comm_frac_est={round(max(fracs), 4) if fracs else 'n/a'}"
        + (f" warmup_errors={len(rep['warmup_errors'])}"
           if rep["warmup_errors"] else "")
    )
