"""Model/optimizer checkpoint IO.

Equivalent of the reference's save/load (hydragnn/utils/model/model.py:63-149):
one file per save holding model + optimizer state, per-epoch files plus a
``latest`` pointer. Serialization is flax msgpack over the TrainState pytree
(device arrays -> host); restore requires a template state of the same
structure, which ``run_prediction`` rebuilds from the saved config.
"""

from __future__ import annotations

import os
from typing import Optional

from flax import serialization

from .state import TrainState


def _run_dir(log_name: str, path: str = "./logs") -> str:
    d = os.path.join(path, log_name)
    os.makedirs(d, exist_ok=True)
    return d


def save_model(
    state: TrainState, log_name: str, path: str = "./logs", epoch: Optional[int] = None
) -> str:
    """Serialize state; per-epoch filename + 'latest' pointer file
    (reference: model.py:63-106, HYDRAGNN_EPOCH env drives per-epoch names).

    Rank-gated: on multi-host runs only process 0 writes — but sharded
    leaves (ZeRO-1 moments, branch-parallel decoder banks) are first
    gathered COLLECTIVELY by every process, so all ranks must call this
    (reference: rank-0 save, model.py:63-75).
    """
    import jax

    from ..parallel.mesh import materialize_replicated

    state = materialize_replicated(state)
    if jax.process_index() != 0:
        return ""
    if epoch is None:
        env = os.getenv("HYDRAGNN_EPOCH")
        epoch = int(env) if env is not None else None
    d = _run_dir(log_name, path)
    suffix = f"_epoch{epoch}" if epoch is not None else ""
    fname = os.path.join(d, f"{log_name}{suffix}.msgpack")
    with open(fname, "wb") as f:
        f.write(serialization.to_bytes(state))
    latest = os.path.join(d, "latest")
    with open(latest, "w") as f:
        f.write(os.path.basename(fname))
    return fname


def save_model_orbax(
    state: TrainState, log_name: str, path: str = "./logs",
    epoch: Optional[int] = None,
) -> str:
    """Orbax save: the idiomatic JAX checkpoint path for pod scale —
    sharding-aware (every process writes its own shards; do NOT rank-gate)
    and layout-portable. Opt in with ``Training.checkpoint_backend:
    "orbax"``; the msgpack path stays the default for single-host runs."""
    import orbax.checkpoint as ocp

    if epoch is None:
        env = os.getenv("HYDRAGNN_EPOCH")
        epoch = int(env) if env is not None else 0
    d = _run_dir(log_name, path)
    ckpt_dir = os.path.abspath(os.path.join(d, "orbax"))
    with ocp.CheckpointManager(ckpt_dir) as mgr:
        # CheckpointManager.save refuses existing steps; re-saves of a step
        # (best-val updates, resumed runs) replace the old checkpoint
        if int(epoch) in mgr.all_steps():
            mgr.delete(int(epoch))
        mgr.save(int(epoch), args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    import jax

    if jax.process_index() == 0:
        with open(os.path.join(d, "latest"), "w") as f:
            f.write(f"orbax/{int(epoch)}")
    return os.path.join(ckpt_dir, str(int(epoch)))


def load_existing_model(
    template_state: TrainState, log_name: str, path: str = "./logs"
) -> TrainState:
    """Restore into a template with identical pytree structure
    (reference: load_existing_model, model.py:128-149). The ``latest``
    pointer selects the backend: an ``orbax/<step>`` entry restores through
    orbax, a ``*.msgpack`` entry through flax serialization."""
    d = os.path.join(path, log_name)
    latest = os.path.join(d, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            entry = f.read().strip()
    else:
        entry = f"{log_name}.msgpack"
    if entry.startswith("orbax/"):
        import orbax.checkpoint as ocp

        step = int(entry.split("/", 1)[1])
        with ocp.CheckpointManager(
            os.path.abspath(os.path.join(d, "orbax"))
        ) as mgr:
            return mgr.restore(
                step, args=ocp.args.StandardRestore(template_state)
            )
    fname = os.path.join(d, entry)
    with open(fname, "rb") as f:
        return serialization.from_bytes(template_state, f.read())
