"""Model/optimizer checkpoint IO — atomic, verified, fault-tolerant.

Equivalent of the reference's save/load (hydragnn/utils/model/model.py:63-149):
one file per save holding model + optimizer state, per-epoch files plus a
``latest`` pointer. Serialization is flax msgpack over the TrainState pytree
(device arrays -> host); restore requires a template state of the same
structure, which ``run_prediction`` rebuilds from the saved config.

Fault model (docs/ROBUSTNESS.md): a preemption can SIGKILL the process at
ANY instruction, and the parallel FS can throw transient IO errors or rot
bytes at rest. The protocol:

- every file (payload, sha256 sidecar, ``latest`` pointer) is written
  tmp-file -> fsync -> ``os.replace`` -> dir fsync, so a reader never sees
  a torn file — only the old version or the new one;
- the ``latest`` pointer is written LAST and is the commit point: a kill
  anywhere inside a save leaves ``latest`` on the previous verified
  checkpoint (<= 1 epoch lost);
- a sha256 sidecar is written with every payload; restore verifies the
  digest and walks back through older epoch files on mismatch/corruption;
- transient ``OSError``s retry with exponential backoff
  (HYDRAGNN_CKPT_RETRIES / HYDRAGNN_CKPT_RETRY_BASE — tests pin the base
  to 0 so no wall-clock sleeps gate CI);
- ``retention`` > 0 prunes the per-epoch chain to its newest N files after
  a committed save, bounding both disk and the restore walk.

Injection points for the chaos suite live in utils/faultinject.py
(``ckpt_write`` IO errors; ``ckpt_tmp_written`` / ``ckpt_msgpack_replaced``
/ ``ckpt_digest_written`` kill points).
"""

from __future__ import annotations

import hashlib
import os
import re
import time
import warnings
from typing import List, Optional

import numpy as np
from flax import serialization

from ..utils import faultinject
from .state import InferenceState, LoaderState, TrainState
from ..utils import envflags

_EPOCH_RE = re.compile(r"_epoch(\d+)\.msgpack$")
_LOADER_STATE_FILE = "loader_state.json"
_MIXTURE_STATE_FILE = "mixture_state.json"


def _run_dir(log_name: str, path: str = "./logs") -> str:
    d = os.path.join(path, log_name)
    os.makedirs(d, exist_ok=True)
    return d


def _retry_plan() -> List[float]:
    """Backoff schedule for transient IO errors: attempt i sleeps
    base * 2^i before retrying (base 0 => no sleeping, the CI setting)."""
    attempts = max(envflags.env_int("HYDRAGNN_CKPT_RETRIES", 4), 1)
    base = envflags.env_float("HYDRAGNN_CKPT_RETRY_BASE", 0.25)
    return [base * (2.0**i) for i in range(attempts)]


def _fsync_replace(path: str, data: bytes) -> None:
    """One atomic publish: tmp file + fsync + os.replace + dir fsync. A
    reader (or a restore after SIGKILL) sees the old content or the new —
    never a prefix."""
    faultinject.maybe_ioerror("ckpt_write")
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        faultinject.maybe_kill("ckpt_tmp_written")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    # fsync the directory so the rename itself is durable across power loss
    try:
        dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # some filesystems refuse directory fsync; the replace stands


def atomic_write(path: str, data: bytes) -> None:
    """`_fsync_replace` with exponential-backoff retries on transient
    OSErrors (flaky parallel FS). The LAST failure propagates."""
    plan = _retry_plan()
    for i, delay in enumerate(plan):
        try:
            return _fsync_replace(path, data)
        except OSError:
            if i == len(plan) - 1:
                raise
            if delay > 0:
                time.sleep(delay)


def _sha256_path(fname: str) -> str:
    return fname + ".sha256"


def _observe_duration(op: str, t0: float) -> None:
    """Publish one checkpoint write/restore duration into the telemetry
    registry, the span plane, and the event log (obs/;
    docs/OBSERVABILITY.md). Observability only: never allowed to fail a
    save/restore."""
    dt = time.perf_counter() - t0
    try:
        from ..obs.registry import registry

        registry().histogram(
            "hydragnn_checkpoint_seconds",
            "Checkpoint write/restore wall time",
            labelnames=("op",),
        ).observe(dt, op=op)
    except Exception:
        pass
    try:
        # span under the active tracer (a checkpoint inside a sampled step/
        # epoch span nests; otherwise it is its own single-span trace), and
        # a write event for the flight-recorder window
        from ..obs import trace as _obs_trace
        from ..obs.events import EV_CKPT_WRITE, emit as _emit_event

        _obs_trace.note_completed(
            f"train/checkpoint_{op}", dt, attributes={"op": op}
        )
        if op == "write":
            _emit_event(EV_CKPT_WRITE, seconds=round(dt, 6))
    except Exception:
        pass


def _epoch_from_env() -> Optional[int]:
    """HYDRAGNN_EPOCH, hardened: a malformed value at the very end of a run
    must not crash the save — warn and fall back to the unsuffixed name."""
    env = envflags.env_str("HYDRAGNN_EPOCH")
    if env is None:
        return None
    try:
        return int(env)
    except ValueError:
        warnings.warn(
            f"HYDRAGNN_EPOCH={env!r} is not an integer; saving without an "
            "epoch suffix instead of failing the checkpoint",
            stacklevel=3,
        )
        return None


def _prune_retention(d: str, log_name: str, retention: int) -> None:
    """Keep only the newest ``retention`` per-epoch msgpack files (plus
    sidecars). 0/negative = keep everything. Never touches the unsuffixed
    base file or the orbax tree."""
    if retention <= 0:
        return
    epochs = []
    for fn in os.listdir(d):
        m = _EPOCH_RE.search(fn)
        if m and fn.startswith(log_name):
            epochs.append((int(m.group(1)), fn))
    for _, fn in sorted(epochs, reverse=True)[retention:]:
        for victim in (os.path.join(d, fn), _sha256_path(os.path.join(d, fn))):
            try:
                os.unlink(victim)
            except OSError:
                pass  # pruning is best-effort; a leftover file is harmless


def save_model(
    state: TrainState,
    log_name: str,
    path: str = "./logs",
    epoch: Optional[int] = None,
    retention: int = 0,
) -> str:
    """Serialize state; per-epoch filename + 'latest' pointer file
    (reference: model.py:63-106, HYDRAGNN_EPOCH env drives per-epoch names).

    Writes payload -> sha256 sidecar -> ``latest``, each atomically; the
    pointer is the commit point. ``retention`` > 0 prunes older epoch files
    after the commit (Training.checkpoint_retention).

    Rank-gated: on multi-host runs only process 0 writes — but sharded
    leaves (ZeRO-1 moments, branch-parallel decoder banks) are first
    gathered COLLECTIVELY by every process, so all ranks must call this
    (reference: rank-0 save, model.py:63-75).
    """
    import jax

    from ..parallel.mesh import materialize_replicated

    t0 = time.perf_counter()
    state = materialize_replicated(state)
    if jax.process_index() != 0:
        return ""
    if epoch is None:
        epoch = _epoch_from_env()
    d = _run_dir(log_name, path)
    suffix = f"_epoch{epoch}" if epoch is not None else ""
    fname = os.path.join(d, f"{log_name}{suffix}.msgpack")
    blob = serialization.to_bytes(state)
    # SAME-NAME overwrite hazard: if this filename was saved before (epoch
    # suffix reused, or the unsuffixed default name), a kill between the
    # payload replace below and the new sidecar write would leave payload=v2
    # beside sidecar=sha(v1) — a fully valid checkpoint restore-rejected as
    # corrupt. Drop the old sidecar FIRST: every kill window then leaves
    # either a verified pair or a complete payload with no sidecar, which
    # restore accepts (atomic replace guarantees completeness) with an
    # 'unverified' warning.
    try:
        os.unlink(_sha256_path(fname))
    except FileNotFoundError:
        pass
    atomic_write(fname, blob)
    faultinject.maybe_kill("ckpt_msgpack_replaced")
    atomic_write(
        _sha256_path(fname), hashlib.sha256(blob).hexdigest().encode("ascii")
    )
    faultinject.maybe_kill("ckpt_digest_written")
    # the pointer commits the save: everything above is invisible to
    # restore until this replace lands
    atomic_write(
        os.path.join(d, "latest"), os.path.basename(fname).encode("utf-8")
    )
    _prune_retention(d, log_name, retention)
    _observe_duration("write", t0)
    return fname


def save_model_orbax(
    state: TrainState, log_name: str, path: str = "./logs",
    epoch: Optional[int] = None, retention: int = 0,
) -> str:
    """Orbax save: the idiomatic JAX checkpoint path for pod scale —
    sharding-aware (every process writes its own shards; do NOT rank-gate)
    and layout-portable. Opt in with ``Training.checkpoint_backend:
    "orbax"``; the msgpack path stays the default for single-host runs.
    Orbax's own commit protocol makes the step directory atomic; the
    ``latest`` pointer is published with the same tmp+fsync+replace as the
    msgpack path. ``retention`` maps Training.checkpoint_retention onto the
    manager's ``max_to_keep`` (0 = keep every step)."""
    import orbax.checkpoint as ocp

    t0 = time.perf_counter()
    if epoch is None:
        epoch = _epoch_from_env() or 0
    d = _run_dir(log_name, path)
    ckpt_dir = os.path.abspath(os.path.join(d, "orbax"))
    options = ocp.CheckpointManagerOptions(
        max_to_keep=retention if retention > 0 else None
    )
    with ocp.CheckpointManager(ckpt_dir, options=options) as mgr:
        # CheckpointManager.save refuses existing steps; re-saves of a step
        # (best-val updates, resumed runs) replace the old checkpoint
        if int(epoch) in mgr.all_steps():
            mgr.delete(int(epoch))
        mgr.save(int(epoch), args=ocp.args.StandardSave(state))
        mgr.wait_until_finished()
    import jax

    if jax.process_index() == 0:
        atomic_write(
            os.path.join(d, "latest"), f"orbax/{int(epoch)}".encode("utf-8")
        )
    _observe_duration("write", t0)
    return os.path.join(ckpt_dir, str(int(epoch)))


def save_loader_state(
    state: LoaderState, log_name: str, path: str = "./logs"
) -> str:
    """Publish the loader-position sidecar (``loader_state.json``) beside
    the TrainState checkpoint — the mid-epoch-resume record (docs/
    ROBUSTNESS.md "Data plane"). Written with the same atomic tmp+fsync+
    replace protocol as every other checkpoint file; the training loop
    writes it AFTER the model save of a mid-epoch preemption stop, and any
    epoch-boundary save clears it (``clear_loader_state``), so a present
    sidecar always describes the committed checkpoint. Rank-gated like the
    msgpack save."""
    import json

    import jax

    if jax.process_index() != 0:
        return ""
    d = _run_dir(log_name, path)
    fname = os.path.join(d, _LOADER_STATE_FILE)
    atomic_write(fname, json.dumps(state.to_dict()).encode("utf-8"))
    return fname


def load_loader_state(
    log_name: str, path: str = "./logs"
) -> Optional[LoaderState]:
    """Read the loader-position sidecar of a run, or None when the run
    stopped at an epoch boundary (no mid-epoch resume needed). A malformed
    sidecar degrades to epoch-granularity resume with a warning — it must
    never block the (far more valuable) model restore."""
    import json

    fname = os.path.join(path, log_name, _LOADER_STATE_FILE)
    if not os.path.exists(fname):
        return None
    try:
        with open(fname, encoding="utf-8") as f:
            return LoaderState.from_dict(json.load(f))
    except (OSError, ValueError, KeyError, TypeError) as e:
        warnings.warn(
            f"loader-state sidecar {fname} is unreadable ({e}); resuming at "
            "epoch granularity instead of mid-epoch",
            stacklevel=2,
        )
        return None


def save_mixture_state(
    snapshot: dict, log_name: str, path: str = "./logs"
) -> str:
    """Publish the mixture-plane snapshot (``mixture_state.json``) beside
    the checkpoint (docs/GFM.md "Resume"): active/demoted source sets,
    explicit weights, per-source cursors, absolute (epoch, draw). Unlike
    the loader-state sidecar it is NOT cleared at epoch boundaries — a
    SIGKILL at any point resumes the source topology from the last
    committed save (the sampler itself is pure, mix/sampler.py). Written
    atomically; rank-gated like the other sidecars."""
    import json

    import jax

    if jax.process_index() != 0:
        return ""
    d = _run_dir(log_name, path)
    fname = os.path.join(d, _MIXTURE_STATE_FILE)
    atomic_write(fname, json.dumps(snapshot).encode("utf-8"))
    return fname


def load_mixture_state(log_name: str, path: str = "./logs") -> Optional[dict]:
    """Read a run's mixture snapshot, or None (no mixture / fresh run). A
    malformed snapshot degrades to fresh mixture topology with a warning —
    it must never block the model restore."""
    import json

    fname = os.path.join(path, log_name, _MIXTURE_STATE_FILE)
    if not os.path.exists(fname):
        return None
    try:
        with open(fname, encoding="utf-8") as f:
            snap = json.load(f)
        if not isinstance(snap, dict):
            raise ValueError(f"expected a JSON object, got {type(snap).__name__}")
        return snap
    except (OSError, ValueError, KeyError, TypeError) as e:
        warnings.warn(
            f"mixture-state sidecar {fname} is unreadable ({e}); resuming "
            "with the fresh source topology instead",
            stacklevel=2,
        )
        return None


def clear_loader_state(log_name: str, path: str = "./logs") -> None:
    """Remove the loader-position sidecar (epoch-boundary saves make the
    mid-epoch cursor stale). Missing file is fine; rank-gated."""
    import jax

    if jax.process_index() != 0:
        return
    try:
        os.unlink(os.path.join(path, log_name, _LOADER_STATE_FILE))
    except OSError:
        pass


def _verified_read(full: str, tried: List[str]) -> Optional[bytes]:
    """Read a payload and check it against its sha256 sidecar. Returns the
    bytes, or None (with the reason appended to ``tried``)."""
    base = os.path.basename(full)
    try:
        with open(full, "rb") as f:
            blob = f.read()
    except OSError as e:
        tried.append(f"{base}: unreadable ({e})")
        return None
    side = _sha256_path(full)
    if os.path.exists(side):
        try:
            with open(side) as f:
                want = f.read().strip()
        except OSError as e:
            tried.append(f"{base}: sidecar unreadable ({e})")
            return None
        got = hashlib.sha256(blob).hexdigest()
        if got != want:
            tried.append(
                f"{base}: sha256 mismatch (file {got[:12]}… != sidecar "
                f"{want[:12]}… — torn or bit-rotted; falling back)"
            )
            return None
    else:
        # pre-sidecar checkpoint (or one whose save was killed between the
        # payload and the digest): accept, but say so — the pointer-commit
        # protocol means such a file was still completely written
        warnings.warn(
            f"checkpoint {base} has no sha256 sidecar; restoring unverified",
            stacklevel=4,
        )
    return blob


def _msgpack_candidates(d: str, entry: Optional[str]) -> List[str]:
    """Restore order: the ``latest`` entry first, then every other msgpack
    in the run dir, newest epoch first (unsuffixed base file last)."""
    out = []
    if entry and not entry.startswith("orbax/"):
        out.append(entry)
    epochs, plain = [], []
    for fn in os.listdir(d):
        if not fn.endswith(".msgpack") or fn in out:
            continue
        m = _EPOCH_RE.search(fn)
        (epochs if m else plain).append((int(m.group(1)) if m else -1, fn))
    out.extend(fn for _, fn in sorted(epochs, reverse=True))
    out.extend(fn for _, fn in sorted(plain))
    return out


def latest_checkpoint_entry(
    log_name: str, path: str = "./logs"
) -> Optional[str]:
    """Raw content of a run's ``latest`` pointer (e.g. ``run_epoch3.msgpack``
    or ``orbax/3``), or None when the pointer is missing/unreadable. The
    hot-reload watcher (serve/reload.py) polls this, and prediction uses it
    to pick the restore backend without touching the payloads."""
    fname = os.path.join(path, log_name, "latest")
    try:
        with open(fname) as f:
            return f.read().strip() or None
    except OSError:
        return None


def _resolve_restore_dir(log_name: str, path: str, tried: List[str]):
    """Shared restore preamble: the run dir (must exist) and the ``latest``
    entry (with the missing-pointer fallback recorded in ``tried``)."""
    d = os.path.join(path, log_name)
    if not os.path.isdir(d):
        raise FileNotFoundError(
            f"no checkpoint for run {log_name!r}: directory {d!r} does not "
            f"exist (searched under path={path!r}). Was the run saved with "
            "a different log name or Training.startfrom?"
        )
    latest = os.path.join(d, "latest")
    entry: Optional[str] = None
    if os.path.exists(latest):
        try:
            with open(latest) as f:
                entry = f.read().strip()
        except OSError as e:
            tried.append(f"latest: unreadable ({e})")
    else:
        entry = f"{log_name}.msgpack"
        tried.append("latest: missing (trying the default msgpack name)")
    return d, entry


def _verified_candidate_blobs(d: str, entry: Optional[str], tried: List[str]):
    """Yield ``(filename, verified bytes)`` for every restorable msgpack
    candidate, newest first — the digest-verified walk-back chain shared by
    the full and the inference-only restore."""
    for fn in _msgpack_candidates(d, entry):
        full = os.path.join(d, fn)
        if not os.path.exists(full):
            tried.append(f"{fn}: missing")
            continue
        blob = _verified_read(full, tried)
        if blob is not None:
            yield fn, blob


def _raise_no_checkpoint(log_name: str, d: str, tried: List[str]):
    try:
        files = sorted(os.listdir(d))
    except OSError:
        files = ["<unlistable>"]
    raise FileNotFoundError(
        f"no loadable checkpoint for run {log_name!r} in {d!r}.\n"
        f"  files present: {files}\n"
        f"  candidates tried: {tried or ['<none>']}\n"
        "Each candidate above was rejected for the stated reason; a sha256 "
        "mismatch means the file is corrupt — delete it to silence the "
        "fallback, or restore an older epoch by editing 'latest'."
    )


def load_inference_state(
    template, log_name: str, path: str = "./logs"
) -> "tuple[InferenceState, str]":
    """Restore ONLY the params/batch-stats/step subtrees of a run's newest
    verified checkpoint into an inference template — no optimizer state is
    deserialized or allocated (AdamW moments are 2x params of dead memory on
    a serving host). ``template`` is an ``InferenceState`` (or anything with
    ``.params``/``.batch_stats``/``.replace``, e.g. a live server state).

    Walks the same digest-verified candidate chain as
    ``load_existing_model`` and returns ``(state, loaded_filename)`` — the
    filename lets hot reload distinguish "the candidate restored" from "the
    chain fell back past a corrupt candidate" (serve/reload.py keeps the
    current weights in the latter case). Orbax-backed runs raise ValueError:
    their shard-parallel restore needs the full-template path."""
    t0 = time.perf_counter()
    tried: List[str] = []
    d, entry = _resolve_restore_dir(log_name, path, tried)
    if entry and entry.startswith("orbax/"):
        raise ValueError(
            f"run {log_name!r} checkpoints through orbax ({entry!r}); the "
            "inference-only restore covers the msgpack chain — restore "
            "through load_existing_model with a full TrainState template "
            "instead"
        )
    for fn, blob in _verified_candidate_blobs(d, entry, tried):
        try:
            raw = serialization.msgpack_restore(blob)
            restored = template.replace(
                params=serialization.from_state_dict(
                    template.params, raw["params"]
                ),
                batch_stats=serialization.from_state_dict(
                    template.batch_stats, raw.get("batch_stats", {})
                ),
                step=int(np.asarray(raw.get("step", 0))),
            )
            _observe_duration("restore", t0)
            return restored, fn
        except Exception as e:  # noqa: BLE001 — structure drift / truncation
            tried.append(f"{fn}: inference deserialization failed ({e})")
    _raise_no_checkpoint(log_name, d, tried)


def load_inference_entry(
    template, log_name: str, entry: str, path: str = "./logs"
) -> "InferenceState":
    """Restore one SPECIFIC digest-verified msgpack entry — no walk-back.

    The rolling-reload rollback (serve/fleet.py) needs "exactly the prior
    checkpoint or fail loudly", never "whatever older file the chain
    finds": silently restoring a third version during a rollback would
    leave the fleet serving a mix no one chose. Raises FileNotFoundError
    when the entry is missing and ValueError when it fails verification
    or deserialization."""
    tried: List[str] = []
    d = _run_dir(log_name, path)
    full = os.path.join(d, entry)
    if not os.path.exists(full):
        raise FileNotFoundError(
            f"checkpoint entry {entry!r} of run {log_name!r} does not exist "
            f"at {full!r}"
        )
    blob = _verified_read(full, tried)
    if blob is None:
        raise ValueError(
            f"checkpoint entry {entry!r} failed verification: {tried}"
        )
    try:
        raw = serialization.msgpack_restore(blob)
        return template.replace(
            params=serialization.from_state_dict(
                template.params, raw["params"]
            ),
            batch_stats=serialization.from_state_dict(
                template.batch_stats, raw.get("batch_stats", {})
            ),
            step=int(np.asarray(raw.get("step", 0))),
        )
    except (ValueError, FileNotFoundError):
        raise
    except Exception as e:  # noqa: BLE001 — structure drift / truncation
        raise ValueError(
            f"checkpoint entry {entry!r} failed to deserialize: "
            f"{type(e).__name__}: {e}"
        )


def load_existing_model(
    template_state: TrainState,
    log_name: str,
    path: str = "./logs",
    loaded_entry: Optional[List[str]] = None,
) -> TrainState:
    """Restore into a template with identical pytree structure
    (reference: load_existing_model, model.py:128-149). The ``latest``
    pointer selects the backend: an ``orbax/<step>`` entry restores through
    orbax, a ``*.msgpack`` entry through flax serialization.

    Every msgpack candidate is digest-verified against its sha256 sidecar;
    on corruption (or a failed orbax restore) the walk falls back through
    older retained epochs, newest first — pass a list as ``loaded_entry``
    to receive the entry ACTUALLY restored (it may be older than the
    pointer names). Total failure raises a FileNotFoundError that lists the
    run dir's files and every candidate tried with the reason it was
    rejected."""
    t0 = time.perf_counter()
    tried: List[str] = []
    d, entry = _resolve_restore_dir(log_name, path, tried)
    if entry and entry.startswith("orbax/"):
        try:
            import orbax.checkpoint as ocp

            step = int(entry.split("/", 1)[1])
            with ocp.CheckpointManager(
                os.path.abspath(os.path.join(d, "orbax"))
            ) as mgr:
                restored = mgr.restore(
                    step, args=ocp.args.StandardRestore(template_state)
                )
            if loaded_entry is not None:
                loaded_entry.append(entry)
            _observe_duration("restore", t0)
            return restored
        except Exception as e:  # noqa: BLE001 — fall back to the msgpack chain
            tried.append(f"{entry}: orbax restore failed ({e})")
    for fn, blob in _verified_candidate_blobs(d, entry, tried):
        try:
            restored = serialization.from_bytes(template_state, blob)
        except Exception as e:  # noqa: BLE001 — structure drift / truncation
            tried.append(f"{fn}: deserialization failed ({e})")
            continue
        if loaded_entry is not None:
            loaded_entry.append(fn)
        _observe_duration("restore", t0)
        return restored
    _raise_no_checkpoint(log_name, d, tried)
