from .checkpoint import (
    clear_loader_state,
    load_existing_model,
    load_loader_state,
    save_loader_state,
    save_model,
    save_model_orbax,
)
from .guard import NonFinitePolicy, guard_enabled, guarded_update, step_ok
from .loop import (
    BestCheckpoint,
    EarlyStopping,
    evaluate,
    make_eval_step,
    make_train_step,
    test_model,
    train_epoch,
    train_validate_test,
)
from .loss import (
    compute_loss,
    energy_force_loss,
    head_loss,
    masked_mean,
    multitask_loss,
    predict_energy_forces,
)
from .optimizer import ReduceLROnPlateau, make_optimizer
from .state import LoaderState, TrainState

__all__ = [
    "BestCheckpoint",
    "EarlyStopping",
    "NonFinitePolicy",
    "ReduceLROnPlateau",
    "LoaderState",
    "TrainState",
    "clear_loader_state",
    "load_loader_state",
    "save_loader_state",
    "guard_enabled",
    "guarded_update",
    "save_model_orbax",
    "step_ok",
    "compute_loss",
    "energy_force_loss",
    "evaluate",
    "head_loss",
    "predict_energy_forces",
    "load_existing_model",
    "make_eval_step",
    "make_optimizer",
    "make_train_step",
    "masked_mean",
    "multitask_loss",
    "save_model",
    "test_model",
    "train_epoch",
    "train_validate_test",
]
