"""Training state pytree: params, batch stats, optimizer state, step."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import optax
from flax import struct


@struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: int
    # non-finite guard counters (train/guard.py): advanced IN-GRAPH by the
    # guarded train steps — total skipped steps, and the consecutive-skip
    # streak any good step resets. Serialized with the checkpoint so a
    # resumed run keeps its fault history.
    skipped_steps: Any = 0
    consecutive_skips: Any = 0

    @staticmethod
    def create(variables: Dict[str, Any], tx: optax.GradientTransformation) -> "TrainState":
        params = variables["params"]
        return TrainState(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
            step=0,
        )

    def variables(self) -> Dict[str, Any]:
        v = {"params": self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v

    @property
    def learning_rate(self) -> float:
        """Current injected learning rate (inject_hyperparams state)."""
        return float(self.opt_state.hyperparams["learning_rate"])

    def with_learning_rate(self, lr: float) -> "TrainState":
        hp = dict(self.opt_state.hyperparams)
        hp["learning_rate"] = jax.numpy.asarray(lr, dtype=jax.numpy.float32)
        return self.replace(opt_state=self.opt_state._replace(hyperparams=hp))
