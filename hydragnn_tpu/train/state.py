"""Training state pytree: params, batch stats, optimizer state, step —
plus the loader-state record serialized beside it for mid-epoch resume."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import optax
from flax import struct


@struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: int
    # non-finite guard counters (train/guard.py): advanced IN-GRAPH by the
    # guarded train steps — total skipped steps, and the consecutive-skip
    # streak any good step resets. Serialized with the checkpoint so a
    # resumed run keeps its fault history.
    skipped_steps: Any = 0
    consecutive_skips: Any = 0

    @staticmethod
    def create(variables: Dict[str, Any], tx: optax.GradientTransformation) -> "TrainState":
        params = variables["params"]
        return TrainState(
            params=params,
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(params),
            step=0,
        )

    def variables(self) -> Dict[str, Any]:
        v = {"params": self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v

    @property
    def learning_rate(self) -> float:
        """Current injected learning rate (inject_hyperparams state)."""
        return float(self.opt_state.hyperparams["learning_rate"])

    def with_learning_rate(self, lr: float) -> "TrainState":
        hp = dict(self.opt_state.hyperparams)
        hp["learning_rate"] = jax.numpy.asarray(lr, dtype=jax.numpy.float32)
        return self.replace(opt_state=self.opt_state._replace(hyperparams=hp))


@struct.dataclass
class InferenceState:
    """Params + batch stats only — the optimizer-free restore target for
    prediction and serving.

    ``run_prediction``/``run_server`` used to build a full ``TrainState``
    (AdamW moments = 2x params of dead memory on large models) just to have
    a restore template; checkpoints now restore their params/batch-stats
    subtrees into this instead (train/checkpoint.py
    ``load_inference_state``). Mirrors ``TrainState.variables()`` so every
    eval/predict step accepts either state."""

    params: Any
    batch_stats: Any
    step: Any = 0

    @staticmethod
    def create(variables: Dict[str, Any]) -> "InferenceState":
        return InferenceState(
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            step=0,
        )

    def variables(self) -> Dict[str, Any]:
        v = {"params": self.params}
        if self.batch_stats:
            v["batch_stats"] = self.batch_stats
        return v


def cast_inference_weights(state, dtype):
    """Cast the state's floating-point params to ``dtype`` (the
    ``Serving.weights_dtype: bfloat16`` reduced-precision serving step —
    halved weight HBM and bf16 MXU streams at inference).

    Batch stats keep f32: they are running moments, and bf16 quantizing
    them shifts normalization statistics for no bandwidth win (they are
    a rounding error of the params' footprint). Integer/bool leaves pass
    through. Works on ``InferenceState`` and ``TrainState`` alike (the
    orbax restore path serves a full TrainState; its optimizer moments
    are dead at inference either way).

    ``dtype="int8"`` is not a cast but a quantization: it dispatches to
    the serving quantization plane's weight-only transform (per-channel
    symmetric int8 kernels + fp32 scales, serve/quantize.py) and returns
    a ``QuantizedInferenceState``. The serving layer adds calibration and
    the accuracy gate on top; this path is the ungated building block."""
    if str(dtype) == "int8":
        from ..serve.quantize import quantize_weights

        return quantize_weights(state)
    dt = jax.numpy.dtype(dtype)

    def _cast(x):
        if hasattr(x, "dtype") and jax.numpy.issubdtype(x.dtype, jax.numpy.floating):
            return x.astype(dt)
        return x

    params = jax.tree_util.tree_map(_cast, state.params)
    return state.replace(params=params)


@dataclasses.dataclass(frozen=True)
class LoaderState:
    """Sampler/loader position serialized beside the TrainState checkpoint
    (train/checkpoint.py ``save_loader_state``) so a preempted run resumes
    MID-epoch instead of replaying from the epoch boundary.

    The loader's shuffle RNG is a pure function of (seed, epoch)
    (data/pipeline.GraphLoader._global_indices), so this record is the
    loader's complete state: resuming at (epoch, next_batch) replays the
    remaining batches in exactly the order the interrupted epoch would have
    produced. ``seed``/``num_batches`` are consistency guards — a resume
    against a different recipe (changed seed, dataset, or batch size) is
    detected and the record ignored with a warning instead of silently
    replaying the wrong stream.
    """

    epoch: int
    next_batch: int
    seed: int = 0
    num_batches: int = 0
    # mixture extension (mix/plane.py MixturePlane.state_dict): active
    # source set, explicit weights, per-source cursors, and the absolute
    # draw index — everything the temperature sampler needs to replay the
    # remaining draw sequence exactly. None for plain GraphLoaders.
    mixture: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        if d.get("mixture") is None:
            d.pop("mixture", None)
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "LoaderState":
        return LoaderState(
            epoch=int(d["epoch"]),
            next_batch=int(d["next_batch"]),
            seed=int(d.get("seed", 0)),
            num_batches=int(d.get("num_batches", 0)),
            mixture=d.get("mixture") or None,
        )
