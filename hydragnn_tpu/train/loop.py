"""Epoch/step training loop — the hot path.

TPU re-design of the reference's ``train_validate_test``/``train``/``validate``
/``test`` (hydragnn/train/train_validate_test.py:52-748):

- the whole optimizer step is one jitted, donated function — forward, loss,
  backward, and update fuse into a single XLA program; gradient all-reduce is
  inserted by the compiler when the batch is sharded over a mesh (no DDP wrap);
- head-index bookkeeping (get_head_indices, :316-379) does not exist: targets
  arrive per-head from the loader with static shapes;
- H2D transfer of the next batch overlaps with device compute because JAX
  dispatch is async.
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..data.graph import GraphBatch
from ..models.base import HydraModel
from ..utils import envflags
from .loss import compute_loss
from .optimizer import ReduceLROnPlateau
from .state import TrainState


# float batch fields cast to bfloat16 under mixed precision (targets and
# masks stay f32/bool so the loss accumulates in f32 via promotion)
_MP_INPUT_FIELDS = ("x", "pos", "edge_attr", "edge_shifts", "pe", "rel_pe")


def cast_batch_bf16(batch: GraphBatch, keep_pos: bool = False) -> GraphBatch:
    """Cast the model-input channels of a batch to bfloat16. ``keep_pos``
    preserves f32 positions for the autograd-force objective, where forces
    come from d(energy)/d(pos) and bf16 positions would quantize them."""
    upd = {}
    for f in _MP_INPUT_FIELDS:
        if keep_pos and f == "pos":
            continue
        v = getattr(batch, f)
        if v is not None and jnp.issubdtype(v.dtype, jnp.floating):
            upd[f] = v.astype(jnp.bfloat16)
    return batch.replace(**upd)


def cast_floats(tree, dtype):
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype)
        if isinstance(p, jnp.ndarray) and jnp.issubdtype(p.dtype, jnp.floating)
        else p,
        tree,
    )


def mp_cast(params, batch, compute_grad_energy: bool):
    """The mixed-precision input cast, shared by the single-device and mesh
    step builders so their numerics stay byte-identical: bf16 params + bf16
    input channels (f32 positions under the autograd-force objective)."""
    return (
        cast_floats(params, jnp.bfloat16),
        cast_batch_bf16(batch, keep_pos=compute_grad_energy),
    )


def mp_restore_stats(mutated: dict) -> dict:
    """Persist batch-norm running statistics in f32 after a bf16 forward."""
    if "batch_stats" in mutated:
        mutated = dict(
            mutated, batch_stats=cast_floats(mutated["batch_stats"], jnp.float32)
        )
    return mutated


def mp_cast_eval(variables, batch, compute_grad_energy: bool):
    """Eval-side cast: bf16 params AND running stats (eval normalizes with
    the running statistics, unlike training)."""
    variables = {
        "params": cast_floats(variables["params"], jnp.bfloat16),
        "batch_stats": cast_floats(
            variables.get("batch_stats", {}), jnp.bfloat16
        ),
    }
    return variables, cast_batch_bf16(batch, keep_pos=compute_grad_energy)


def make_train_step(
    model: HydraModel,
    tx: optax.GradientTransformation,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
    guard: Optional[bool] = None,
    numerics: Optional[bool] = None,
):
    """Build the jitted SGD step: (state, batch, rng) -> (state, loss, tasks).

    ``compute_grad_energy=True`` switches to the energy+force objective
    (reference: train_validate_test.py:517-520 -> Base.energy_force_loss).

    ``mixed_precision=True`` runs the forward/backward in bfloat16 (MXU
    native) against f32 master weights: params and input channels are cast
    to bf16 inside the differentiated function, so gradients flow back
    through the cast and land in f32 for the optimizer; running batch-norm
    statistics are re-cast to f32 before being stored. Targets stay f32, so
    residuals and the loss accumulate in f32 by dtype promotion.

    ``guard`` (default: on, env HYDRAGNN_STEP_GUARD=0 disables): in-graph
    non-finite step guard — loss/global-grad-norm finiteness is computed in
    the same program and a bad step's optimizer update is gated to identity
    (per-leaf select), advancing the state's skip counters (train/guard.py).
    A good step commits the EXACT unguarded update values.

    ``numerics`` (default: off, env HYDRAGNN_NUMERICS=1 enables; wired from
    ``Telemetry.numerics``): in-graph per-layer activation + per-param-group
    gradient statistics (obs/numerics.py) ride the step as a FOURTH output
    ``{"ok", "act", "grad"}`` — the step then returns a 4-tuple, and the
    returned callable carries ``_numerics_meta`` (tensor name tables,
    written at trace time) and ``_nan_diagnose`` (the provenance
    drill-down) attributes. Off, the step and its outputs are byte-
    identical to the historical 3-tuple."""
    cfg = model.cfg
    from ..obs import numerics as obs_numerics
    from ..utils import faultinject
    from .guard import guard_enabled, guarded_update, step_ok

    use_guard = guard_enabled(guard)
    use_numerics = obs_numerics.numerics_enabled(numerics)
    meta = {"act_names": None, "grad_names": None}

    def loss_fn(params, batch_stats, batch, rng):
        if mixed_precision:
            params, batch = mp_cast(params, batch, compute_grad_energy)
        variables = {"params": params, "batch_stats": batch_stats}
        (tot, tasks, mutated, _), acts = obs_numerics.run_probed(
            use_numerics, meta,
            lambda: compute_loss(
                model, variables, batch, cfg, True, rng, compute_grad_energy
            ),
        )
        if mixed_precision:
            mutated = mp_restore_stats(mutated)
        return tot.astype(jnp.float32), (tasks, mutated, acts)

    if cfg.conv_checkpointing:
        # rematerialize the forward during backward (reference: per-conv torch
        # checkpoint, Base.py:459-465), with the save rule picked by
        # Training.remat_policy (ops/remat.py — 'names' keeps the Pallas
        # kernel outputs instead of re-running the kernels in the backward)
        from ..ops.remat import loss_remat

        loss_fn = loss_remat(loss_fn, cfg.remat_policy)

    from .compile_plane import note_trace

    @partial(jax.jit, donate_argnums=0)
    def train_step(state: TrainState, batch: GraphBatch, rng):
        # retrace sentinel: the body runs once per jit trace, so this call
        # IS the trace census (train/compile_plane.py)
        note_trace("train_step", (state, batch, rng))
        (tot, (tasks, mutated, acts)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.batch_stats, batch, rng)
        # chaos-test hook: exact no-op unless a fault is armed (trace-time)
        grads = faultinject.poison_grads(
            grads, state.step, faultinject.lr_of(state.opt_state)
        )
        new_stats = mutated.get("batch_stats", state.batch_stats)
        numer = None
        if use_numerics:
            # gradient stats AFTER the fault hook, so injected NaNs show up
            # in the same census the provenance drill-down reads
            gnames, gstats = obs_numerics.grad_group_stats(grads)
            meta["grad_names"] = gnames
            numer = {"ok": step_ok(tot, grads), "act": acts, "grad": gstats}
        if use_guard:

            def do_update():
                updates, opt_state = tx.update(
                    grads, state.opt_state, state.params
                )
                return optax.apply_updates(state.params, updates), opt_state

            new_state = guarded_update(
                state,
                numer["ok"] if numer is not None else step_ok(tot, grads),
                do_update,
                new_stats,
            )
        else:
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            new_state = state.replace(
                params=params,
                opt_state=opt_state,
                batch_stats=new_stats,
                step=state.step + 1,
            )
        if use_numerics:
            return new_state, tot, tasks, numer
        return new_state, tot, tasks

    if not use_numerics:
        return train_step
    # the numerics build returns a wrapper so the jit object stays AOT-
    # reachable (compile plane) and the host-side name tables + NaN
    # drill-down travel with the step function (obs/numerics.py)
    return obs_numerics.numerics_step_wrapper(
        train_step, meta, model, compute_grad_energy, mixed_precision
    )


def make_eval_step(
    model: HydraModel,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
):
    cfg = model.cfg
    from .compile_plane import note_trace

    @jax.jit
    def eval_step(state: TrainState, batch: GraphBatch):
        note_trace("eval_step", (state, batch))
        variables = state.variables()
        if mixed_precision:
            variables, batch = mp_cast_eval(
                variables, batch, compute_grad_energy
            )
        tot, tasks, _, outputs = compute_loss(
            model, variables, batch, cfg, False, None, compute_grad_energy
        )
        return tot, tasks, outputs

    return eval_step


def _weighted_avg(entries: List[Tuple[float, Dict[str, float], int]]):
    total_n = sum(n for _, _, n in entries) or 1
    tot = sum(l * n for l, _, n in entries) / total_n
    task_names = entries[0][1].keys() if entries else []
    tasks = {
        k: sum(t[k] * n for _, t, n in entries) / total_n for k in task_names
    }
    return tot, tasks


def device_prefetch(iterator, depth: int = 2, device=None):
    """Double-buffered device staging: a background thread ``device_put``s
    upcoming batches so the H2D copy overlaps the current step's compute.
    The reference pays this cost inline every step (``data.to(device)``,
    train_validate_test.py:514); async dispatch hides *compute* but the
    transfer itself still serializes with the dispatching thread — staging
    from a second thread takes it off the critical path entirely.

    Single-device only at the call sites (sharded stacked batches are placed
    by the parallel step's own sharding logic)."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
    stop = threading.Event()
    _END, _ERR = object(), object()

    def put_or_stop(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in iterator:
                if not put_or_stop(jax.device_put(batch, device)):
                    return
            put_or_stop(_END)
        except BaseException as e:  # surfaced in the consumer
            put_or_stop((_ERR, e))

    threading.Thread(target=producer, daemon=True).start()
    try:
        while True:
            item = q.get()  # graftlint: disable=threads -- producer is a daemon doing only device_put; it always posts _END/_ERR, and the loader-side stall watchdog (data/pipeline.py) owns stall detection
            if item is _END:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _ERR:
                raise item[1]
            yield item
    finally:
        stop.set()


def _maybe_device_prefetch(iterator, depth: Optional[int] = None):
    """Wrap with device_prefetch on single-device runs (multi-device batch
    placement belongs to the parallel step). ``depth`` comes from
    ``Training.double_buffer`` (true = 2, false = off, an int = that
    queue depth); the HYDRAGNN_DEVICE_PREFETCH env always wins (0
    disables), and None means "no config reached here" — the historical
    env-or-2 default, so direct callers keep their behavior."""
    if envflags.env_set("HYDRAGNN_DEVICE_PREFETCH"):
        depth = envflags.env_int("HYDRAGNN_DEVICE_PREFETCH", 2)
    elif depth is None:
        depth = 2
    active = (
        depth > 0
        and jax.local_device_count() == 1
        and jax.process_count() == 1
    )
    try:  # the telemetry smoke's A/B assertion reads this gauge
        from ..obs.registry import registry

        registry().gauge(
            "hydragnn_device_prefetch_depth",
            "Double-buffered device_put queue depth (0 = staging inline)",
        ).set(float(depth if active else 0))
    except Exception:
        pass
    if not active:
        return iterator
    return device_prefetch(iterator, depth=depth)


def train_epoch(loader, step_fn, state, rng, start_batch: int = 0,
                telemetry=None, tracer=None, prefetch_depth=None,
                nan_watch=None, guard_log=None):
    """One training epoch. Returns ``(state, tot, tasks, rng, cursor)``:
    ``cursor`` is None when the epoch completed, or the next-batch offset
    (loader-absolute) when a SIGTERM arrived between steps — the mid-epoch
    preemption stop (single-process only: the per-step flag check cannot be
    agreed across hosts without a per-step collective, so multi-host runs
    keep the epoch-boundary stop). ``start_batch`` fast-forwards a loader
    WITHOUT native resume support by consuming (not stepping) its first
    batches; loaders that implement ``resume()`` skip building them
    entirely and report their offset via ``start_batch`` attribute.
    ``telemetry`` (obs/telemetry.StepTelemetry, or None) receives every
    step's batch + host dispatch time — under async dispatch the queue
    throttles the host to the device rate, so the window means it
    publishes converge to device step time without per-step syncs.
    ``tracer`` (obs/trace.Tracer, or None) emits one span tree per
    every-Nth sampled step: a ``train/step`` root with retroactive
    ``train/host_batch_build`` (host batching + validation + H2D staging,
    the ``dataload`` region) and ``train/device_dispatch`` children —
    unsampled steps pay one ``is not None`` check.
    ``nan_watch`` (obs/numerics.NanWatch, or None) receives every step's
    ok flag + held batch for the deferred non-finite check and NaN
    provenance drill-down (requires a numerics-enabled ``step_fn``).
    ``guard_log`` (a dict, or None) is filled with this epoch's
    ``nonfinite`` step census — batch index, spec-ladder level, and (when
    the loader exposes ``batch_sources``) the mixture draw ids of every
    step whose loss came back non-finite — the batch provenance the
    epoch-boundary guard policy attaches to its ``guard_skip`` event."""
    from ..utils import faultinject, preemption
    from ..utils import tracer as tr

    # Device-side loss bookkeeping: the per-step (loss, tasks) scalars stay
    # on device and are read back ONCE at epoch end, so step i+1 dispatches
    # while step i is still executing (JAX async dispatch keeps the chip
    # saturated; a per-step float() would block the host on every step and
    # serialize the pipeline — the reference tolerates this because torch
    # .item() overlaps with DDP bucket comms, XLA does not).
    entries = []
    # the loader may already skip batches itself (GraphLoader.resume);
    # cursor values reported to checkpoints are absolute within the epoch
    offset = int(getattr(loader, "start_batch", 0) or 0)
    check_preempt = jax.process_count() == 1
    cursor = None
    consumed = 0
    # per-step provenance meta ((batch index, pad level, mixture sources)):
    # two ints and a small tuple per step, recorded only when a consumer
    # asked; MixturePlane exposes batch_sources, plain loaders don't
    step_meta = [] if (guard_log is not None or nan_watch is not None) else None
    src_fn = getattr(loader, "batch_sources", None)
    # the watch needs the failing step's state.step value (the fault-
    # injection hooks key on it); one host read of the incoming counter
    # per epoch, then pure python increments
    step0 = (
        int(jax.device_get(state.step)) if nan_watch is not None else 0
    )
    it = _maybe_device_prefetch(iter(loader), depth=prefetch_depth)
    for i in range(len(loader)):
        # dataload span covers host batching + H2D staging (the reference's
        # per-step data.to(device), train_validate_test.py:506-514; here the
        # jitted step overlaps with the next host batch via async dispatch)
        t_build = time.perf_counter()
        tr.start("dataload")
        try:
            batch = next(it)
        except StopIteration:
            tr.stop("dataload")
            break
        tr.stop("dataload")
        build_dt = time.perf_counter() - t_build
        consumed += 1
        if i < start_batch:
            continue  # fast-forward (mid-epoch resume on a generic loader)
        sp = None
        if tracer is not None and tracer.sample_step():
            sp = tracer.begin("train/step")
            sp.set_attribute("batch_index", offset + consumed - 1)
            tracer.emit_completed(
                "train/host_batch_build",
                time.time() - build_dt,
                build_dt,
                parent=sp,
            )
        rng, sub = jax.random.split(rng)
        tr.start("train_step")
        t_step = time.perf_counter()
        # fleet chaos hook: host-side sleep when HYDRAGNN_FAULT_STRAGGLE
        # is armed — the slow-host model the fleet watchdog must flag
        # (utils/faultinject.py; exact no-op unarmed, one dict lookup).
        # INSIDE the measured interval: the injected slowness must land
        # in the step time the telemetry window pushes as the fleet
        # heartbeat, or the drill would not model what the watchdog
        # measures
        faultinject.maybe_straggle(i)
        # host-loss drills (elastic_smoke): SIGKILL (dead host) or SIGTERM
        # (preemption with grace) this process before dispatching a step —
        # armed on the cumulative cross-epoch step count, not i
        faultinject.maybe_host_fault()
        out = step_fn(state, batch, sub)
        # a numerics-enabled step rides its stat bundle as a 4th output
        # (obs/numerics.py); the historical 3-tuple is unchanged otherwise
        state, tot, tasks = out[0], out[1], out[2]
        numer = out[3] if len(out) > 3 else None
        # graph_mask is loader data (host numpy, or an already-transferred
        # leaf under device_prefetch) — reading it never waits on compute
        n = int(np.asarray(batch.graph_mask).sum())
        tr.stop("train_step")
        entries.append((tot, tasks, n))
        if step_meta is not None:
            idx = offset + consumed - 1
            level = (
                f"{int(batch.node_mask.shape[-1])}n/"
                f"{int(batch.edge_mask.shape[-1])}e"
            )
            srcs = src_fn(idx) if src_fn is not None else None
            step_meta.append((idx, level, srcs))
            if nan_watch is not None:
                nan_watch.on_step(
                    state, batch, sub, step0 + len(entries) - 1, idx,
                    numer, level=level, sources=srcs,
                )
        if sp is not None:
            dispatch_dt = time.perf_counter() - t_step
            tracer.emit_completed(
                "train/device_dispatch",
                time.time() - dispatch_dt,
                dispatch_dt,
                parent=sp,
                attributes={"real_graphs": n},
            )
            sp.set_attribute("real_graphs", n)
            tracer.finish(sp)
        if telemetry is not None:
            telemetry.on_step(
                batch, time.perf_counter() - t_step, real_graphs=n,
                numerics=numer,
            )
        if check_preempt and preemption.preempted():
            # SIGTERM between steps: stop HERE and let the loop checkpoint
            # state + loader cursor, so resume replays exactly the batches
            # this epoch never stepped (docs/ROBUSTNESS.md "Data plane")
            cursor = offset + consumed
            break
        max_batches = envflags.env_int("HYDRAGNN_MAX_NUM_BATCH", 0)
        if max_batches > 0 and i + 1 >= max_batches:
            break
    if nan_watch is not None:
        # drain the watch ring at the boundary the loop syncs on anyway
        nan_watch.end_epoch(state)
    # single host sync for the whole epoch
    entries = jax.device_get(entries)
    entries = [
        (float(t), {k: float(v) for k, v in d.items()}, n)
        for t, d, n in entries
    ]
    if guard_log is not None and step_meta is not None:
        # non-finite loss census -> batch provenance for the guard-skip
        # event (grad-only NaNs keep a finite loss; the NaN watch covers
        # those precisely when Telemetry.numerics is on)
        guard_log["nonfinite"] = [
            {"batch": m[0], "level": m[1], "sources": m[2]}
            for e, m in zip(entries, step_meta)
            if not np.isfinite(e[0])
        ]
    # a guarded-and-skipped step reports its (non-finite) loss but applied
    # no update — excluding it keeps the epoch mean meaningful for the
    # plateau scheduler / early stopping. If EVERY step was non-finite
    # (unguarded collapse), keep them: a NaN epoch must not be masked.
    finite = [e for e in entries if np.isfinite(e[0])]
    if finite and len(finite) < len(entries):
        entries = finite
    tot, tasks = _weighted_avg(entries)
    return state, tot, tasks, rng, cursor


def evaluate(loader, eval_fn, state, prefetch_depth=None):
    entries = []
    for batch in _maybe_device_prefetch(iter(loader), depth=prefetch_depth):
        tot, tasks, _ = eval_fn(state, batch)
        n = int(np.asarray(batch.graph_mask).sum())
        entries.append((tot, tasks, n))
    entries = jax.device_get(entries)
    entries = [
        (float(t), {k: float(v) for k, v in d.items()}, n)
        for t, d, n in entries
    ]
    return _weighted_avg(entries)


class EarlyStopping:
    """(reference: hydragnn/utils/model/model.py:305-320)"""

    def __init__(self, patience: int = 10, min_delta: float = 0.0):
        self.patience = patience
        self.min_delta = min_delta
        self.best = float("inf")
        self.count = 0

    def __call__(self, val_loss: float) -> bool:
        if val_loss < self.best - self.min_delta:
            self.best = val_loss
            self.count = 0
            return False
        self.count += 1
        return self.count > self.patience


class BestCheckpoint:
    """Best-validation checkpointing with warmup
    (reference: Checkpoint, hydragnn/utils/model/model.py:323-363)."""

    def __init__(self, save_fn: Callable[..., None], warmup: int = 0):
        self.save_fn = save_fn
        self.warmup = warmup
        self.best = float("inf")

    def __call__(self, state: TrainState, val_loss: float, epoch: int) -> bool:
        if epoch < self.warmup or val_loss >= self.best:
            return False
        self.best = val_loss
        self.save_fn(state, epoch)
        return True


def train_validate_test(
    model: HydraModel,
    state: TrainState,
    tx: optax.GradientTransformation,
    train_loader,
    val_loader,
    test_loader,
    config: Dict[str, Any],
    log_name: str = "run",
    verbosity: int = 0,
    seed: int = 0,
    save_fn: Optional[Callable[..., None]] = None,
    log_fn: Optional[Callable[[int, Dict[str, float]], None]] = None,
    step_fn: Optional[Callable] = None,
    eval_fn: Optional[Callable] = None,
    restore_fn: Optional[Callable[[TrainState], TrainState]] = None,
    loader_state_fn: Optional[Callable[[Dict[str, int]], None]] = None,
    writer=None,
) -> Tuple[TrainState, Dict[str, List[float]]]:
    """Outer epoch loop (reference: train_validate_test.py:52-264).

    Returns the final state and the loss history. ``HYDRAGNN_VALTEST=0``
    skips val/test epochs (reference :179); ``HYDRAGNN_MAX_NUM_BATCH`` caps
    timed batches (reference :46-47). ``step_fn``/``eval_fn`` override the
    default single-host jitted steps (used by the multi-host mesh path,
    api.py). ``restore_fn`` (template_state -> restored state) is the
    rollback path of ``Training.non_finite_policy: rollback`` — api.py
    wires it to the verified-checkpoint restore with mesh re-placement.
    ``loader_state_fn`` persists the loader cursor dict of a MID-epoch
    preemption stop (api.py wires it to ``save_loader_state``); without it
    a mid-epoch SIGTERM still checkpoints, at epoch-replay granularity.
    ``writer`` (utils.MetricsWriter) additionally receives the run's
    already-counted health signals — guard skip totals, data-plane skip
    tallies, retrace violations, compile-cache hits/misses — so they land
    in ``scalars.jsonl``/TensorBoard instead of stdout-only report lines;
    it is also the TB mirror of the per-step telemetry layer when the
    ``Telemetry`` config section enables one (obs/telemetry.py).
    """
    training = config["NeuralNetwork"]["Training"]
    num_epoch = training["num_epoch"]
    do_valtest = envflags.env_flag("HYDRAGNN_VALTEST") is not False

    compute_grad_energy = training.get("compute_grad_energy", False)
    # bf16 compute against f32 master weights (MXU-native; make_train_step)
    mixed_precision = training.get("mixed_precision", False)
    # resolved BEFORE the step builders: Telemetry.numerics changes the
    # step program (in-graph probes ride the outputs — obs/numerics.py)
    from ..obs.telemetry import StepTelemetry, resolve_telemetry

    obs_settings = resolve_telemetry(config)
    if step_fn is None:
        step_fn = make_train_step(
            model, tx, compute_grad_energy, mixed_precision,
            numerics=obs_settings["numerics"],
        )
    if eval_fn is None:
        eval_fn = make_eval_step(model, compute_grad_energy, mixed_precision)
    # a numerics-enabled builder (here or api.py's mesh builders) carries
    # its name tables + NaN drill-down as attributes; capture them before
    # the compile plane wraps the callable below
    numerics_meta = getattr(step_fn, "_numerics_meta", None)
    nan_diagnose = getattr(step_fn, "_nan_diagnose", None)
    scheduler = ReduceLROnPlateau()
    stopper = (
        EarlyStopping(patience=training.get("patience", 10))
        if training.get("EarlyStopping", False)
        else None
    )
    checkpointer = (
        BestCheckpoint(save_fn, warmup=training.get("checkpoint_warmup", 0))
        if training.get("Checkpoint", False) and save_fn is not None
        else None
    )

    from ..utils import preemption
    from ..utils import tracer as tr
    from ..utils.profile import Profiler
    from ..utils.walltime import should_stop
    from .guard import NonFinitePolicy

    # Training.non_finite_policy: what a guard-skipped step means at the
    # epoch boundary (the only place the loop syncs the host anyway)
    nf_policy = NonFinitePolicy(
        policy=str(training.get("non_finite_policy", "warn_skip")),
        rollback_after=int(training.get("non_finite_rollback_after", 3)),
        lr_backoff=float(training.get("non_finite_lr_backoff", 0.5)),
        max_rollbacks=int(training.get("non_finite_max_rollbacks", 3)),
        restore_fn=restore_fn,
        log_name=log_name,
    )

    profiler = Profiler(
        # documented location first (docs/CONFIG.md "NeuralNetwork.Profile");
        # the historical top-level section keeps working
        config["NeuralNetwork"].get("Profile")
        or config.get("Profile"),  # graftlint: disable=config_keys -- legacy top-level Profile accepted for pre-r15 configs; NeuralNetwork.Profile is the documented home
        log_dir=f"./logs/{log_name}/profile",
    )
    check_remaining = training.get("CheckRemainingTime", False)
    preemption.install()
    tr.enable()

    # per-step telemetry layer (obs/telemetry.py): opt-in via the top-level
    # ``Telemetry`` config section (HYDRAGNN_TELEMETRY overrides) — step
    # time, goodput, padding waste, MFU estimate, memory gauges, the
    # versioned metrics.jsonl stream, an optional /metrics endpoint, and
    # the on-demand profiling trigger. None when disabled: the loop then
    # pays one `is not None` check per step and nothing else.
    # (obs_settings was resolved above, before the step builders.)
    telemetry = (
        StepTelemetry(obs_settings, log_name, writer=writer)
        if obs_settings["enabled"]
        else None
    )
    if telemetry is not None and numerics_meta is not None:
        telemetry.attach_numerics(numerics_meta)
    elif numerics_meta is not None:
        import warnings as _warnings

        # the probes are computed in-graph either way, but their window
        # gauges/records ride the enabled sinks — say so instead of
        # silently publishing nothing (the runbook's per-window history
        # would be missing; provenance events + flight dumps still work)
        _warnings.warn(
            "Telemetry.numerics is on but Telemetry.enabled is off: the "
            "hydragnn_numerics_* gauges and metrics.jsonl 'numerics' "
            "records are published by the enabled per-step layer and will "
            "not appear — NaN provenance events and flight-recorder dumps "
            "still fire. Set Telemetry.enabled: true for the full "
            "observatory.",
            RuntimeWarning,
            stacklevel=2,
        )
    # NaN provenance watch (obs/numerics.py): deferred per-step ok checks +
    # the drill-down on a guarded skip; exists exactly when the step rides
    # a numerics bundle
    nan_watch = None
    if numerics_meta is not None:
        from ..obs.numerics import NanWatch

        nan_watch = NanWatch(diagnose=nan_diagnose, log_name=log_name)
    run_dir = os.path.join("./logs", log_name)
    # tracing plane (obs/trace.py; docs/OBSERVABILITY.md "Tracing"): spans
    # for every trace_interval_steps-th step to logs/<run>/trace.jsonl,
    # with the region timers (dataload/train_step/...) folded in as child
    # spans of whatever sampled span is open
    tracer = None
    if obs_settings["trace"]:
        from ..obs import trace as obs_trace

        # fleet mode: every host writes its own span stream (host 0 keeps
        # the plain trace.jsonl name) — two processes appending one JSONL
        # on a shared filesystem interleave mid-line; obs/fleet.py
        # merge_traces stitches the streams into the run-level view
        trace_kw = {}
        if obs_settings.get("fleet"):
            from ..obs.fleet import host_identity

            host_i, _ = host_identity()
            if host_i > 0:
                trace_kw = {
                    "filename": f"trace-h{host_i}.jsonl", "rank0": True,
                }
        tracer = obs_trace.Tracer(
            run_dir,
            sample=float(obs_settings["trace_sample"]),
            every_n_steps=int(obs_settings["trace_interval_steps"]),
            **trace_kw,
        )
        obs_trace.install(tracer)
    # crash flight recorder (obs/flightrec.py): armed whenever the plane is
    # on — unhandled exception / SIGUSR2 / fatal guard policy dump the last
    # events + spans + a registry snapshot to logs/<run>/flightrec/
    flight = None
    if obs_settings["flight_recorder"] and (
        obs_settings["enabled"] or obs_settings["trace"]
        or obs_settings["numerics"]
    ):
        from ..obs.flightrec import FlightRecorder

        flight = FlightRecorder(run_dir, tracer=tracer).install()
    # persistent incident stream (obs/events.py): whenever the plane is
    # on, every typed event also lands in logs/<run>/events.jsonl so a
    # COMPLETED run's incidents are readable post-hoc — the run doctor's
    # (obs/doctor.py) primary event source; the in-memory ring alone only
    # survives inside flight dumps
    events_armed = False
    if (
        obs_settings["enabled"] or obs_settings["trace"]
        or obs_settings["numerics"]
    ):
        # submodule import: the package __init__ re-exports the events()
        # accessor under the submodule's name (the flightrec.py lesson)
        from ..obs.events import attach_stream as _attach_events

        events_armed = _attach_events(run_dir) is not None

    # kernel autotuning plane (tune/; docs/TUNING.md): install the run's
    # tuned table BEFORE the warm-up traces below, so every Pallas route's
    # tile_plan lookup consults it (autotune=cached) or a budgeted sweep
    # fills it first (autotune=sweep); off/no-table keeps pinned defaults
    from ..tune.runtime import setup_autotune

    setup_autotune(config, train_loader, log_name)

    # compile plane (train/compile_plane.py): AOT warm-up of every
    # (train, eval) x pad-bucket specialization against the persistent
    # compilation cache, plus the retrace sentinel. Degrades to off when no
    # cache directory is active (api.run_training wires one by default;
    # direct callers opt in via setup_compile_cache).
    from .compile_plane import CompilePlane

    plane = CompilePlane(
        mode=str(training.get("precompile", "background")),
        retrace_policy=str(training.get("retrace_policy", "warn")),
        log_name=log_name,
        remat_policy=str(training.get("remat_policy", "full")),
    )
    step_fn = plane.launch(
        step_fn,
        eval_fn,
        state,
        train_loader,
        val_loader,
        test_loader,
        rng=jax.random.PRNGKey(seed),
        skip_eval=not do_valtest,
    )
    if telemetry is not None:
        # MFU source: the AOT warm-up's cost_analysis table — background
        # mode fills it while epoch 0 runs, so early windows may publish
        # no MFU and later ones do (the flush handles None)
        telemetry.attach_flops(plane.train_flops_for)
        # comm-accounting source (same fill discipline): per-spec
        # collective bytes + the compute-vs-comm decomposition ride the
        # step_window records and the fleet heartbeat
        telemetry.attach_comm(plane.train_comm_for)
        if telemetry.want_mfu:
            # precompile: off never populates flops_by_spec — harvest the
            # first organic executable instead (or warn once naming the
            # cause) so the MFU gauge is not silently zeroed
            plane.enable_flops_fallback()

    rng = jax.random.PRNGKey(seed)
    hist: Dict[str, List[float]] = {"train": [], "val": [], "test": [], "lr": []}
    # Early stopping / best-val checkpointing RETURN THE BEST STATE, not
    # whatever the run degraded to during the patience window (the whole
    # point of patience; e.g. a tiny decoder can ReLU-die epochs after its
    # best epoch and the final state would evaluate at the constant-
    # prediction floor). The copy is host-materialized so step donation
    # can't invalidate it. SINGLE-PROCESS ONLY: the copy materializes
    # sharded leaves (a collective on multi-host) but the improvement
    # decision uses va_loss, which is weighted by each host's LOCAL
    # real-graph count on ragged tails — hosts could disagree at a
    # near-tie and deadlock in the gather. Multi-host runs keep the
    # final state; their best-val weights live in the BestCheckpoint
    # file (Training.Checkpoint).
    return_best = (
        training.get(
            "return_best", bool(stopper is not None or checkpointer is not None)
        )
        and do_valtest
        and jax.process_count() == 1
    )
    best_val = float("inf")
    best_state = None
    # Training.warmup_epochs: linear LR ramp over the first W epochs. Tiny
    # ReLU decoders can be killed outright by the first full-LR updates
    # (alive at init, dead by epoch 2 — the constant-prediction floor);
    # ramping bounds the early step sizes without changing the recipe's
    # steady state. The plateau scheduler only engages after the ramp.
    warmup_epochs = int(training.get("warmup_epochs", 0))
    base_lr = float(state.learning_rate)
    # Training.double_buffer -> device-staging queue depth (ROADMAP #3 H2D
    # overlap): true = depth 2, false = inline device_put, int = depth.
    # HYDRAGNN_DEVICE_PREFETCH still wins inside _maybe_device_prefetch.
    db = training.get("double_buffer", True)
    prefetch_depth = 0 if not db else (2 if db is True else int(db))
    # data-plane skip tally dedup: log at the epoch boundary only when the
    # run-level count changed (ingest skips report once, at epoch 0)
    reported_skips = 0
    # guard-skip EVENT accounting for the telemetry counter: a rollback
    # restores an older state whose skipped_steps total is LOWER, so
    # absorbing the raw total (max-merge) would swallow every post-rollback
    # skip until the old high-water mark is passed — accumulate positive
    # deltas instead, resyncing the reference on any decrease. Seeded from
    # the INCOMING state's counter: a Training.continue resume carries the
    # previous run's total, which are not THIS process's events
    guard_seen = (
        int(jax.device_get(state.skipped_steps))
        if writer is not None or telemetry is not None
        else 0
    )
    guard_events = 0
    try:
        for epoch in range(num_epoch):
            t0 = time.time()
            if warmup_epochs and epoch < warmup_epochs:
                # ramp ends AT base_lr on the last warmup epoch; the
                # plateau scheduler only engages afterwards
                state = state.with_learning_rate(
                    base_lr * (epoch + 1) / warmup_epochs
                )
            profiler.epoch_begin(epoch)
            train_loader.set_epoch(epoch)
            guard_log: Dict[str, Any] = {}
            with tr.timer("train"):
                state, tr_loss, tr_tasks, rng, cursor = train_epoch(
                    train_loader, step_fn, state, rng, telemetry=telemetry,
                    tracer=tracer, prefetch_depth=prefetch_depth,
                    nan_watch=nan_watch, guard_log=guard_log,
                )
            hist["train"].append(tr_loss)
            # mixture plane (mix/plane.py): per-source draw/skip tallies +
            # the per-branch loss drift monitor, at the epoch boundary the
            # loop already syncs on
            mix_hook = getattr(train_loader, "mixture_epoch_hook", None)
            if mix_hook is not None:
                mix_hook(
                    epoch, tr_tasks, writer=writer, verbosity=verbosity,
                    log_name=log_name,
                )
            # data-plane skip tally (data/validate.py): whenever the run's
            # validator has dropped samples, say so at the epoch boundary —
            # silent data loss is not an option (docs/ROBUSTNESS.md)
            sval = getattr(train_loader, "validator", None)
            if sval is not None and sval.skipped_total != reported_skips:
                reported_skips = sval.skipped_total
                print(
                    f"[{log_name}] epoch {epoch}: data-plane skips: "
                    f"{sval.tally()}",
                    file=sys.stderr,
                )
            # route the run's already-counted health signals into the
            # metric stream (scalars.jsonl + TensorBoard + the registry) —
            # machine-readable, not stdout-only: guard skips, data-plane
            # skip tally, retrace violations, this run's cache hits/misses
            if writer is not None or telemetry is not None:
                skipped_total = int(jax.device_get(state.skipped_steps))
                guard_events += max(skipped_total - guard_seen, 0)
                guard_seen = skipped_total
                plane_rep = plane.report()
                health = {
                    "guard/skipped_steps": skipped_total,
                    "data/skipped_samples": (
                        sval.skipped_total if sval is not None else 0
                    ),
                    "compile/retrace_violations": plane_rep["violations"],
                    "compile/cache_hits": plane_rep["cache_hits"],
                    "compile/cache_misses": plane_rep["cache_misses"],
                }
                if writer is not None:
                    writer.add_scalars(health, epoch)
                if telemetry is not None:
                    from .compile_plane import compile_metrics

                    telemetry.absorb_counters(
                        guard_skipped=guard_events,
                        data_skipped=(
                            dict(sval.counts) if sval is not None else None
                        ),
                        retrace_violations=plane_rep["violations"],
                        compile_metrics=compile_metrics(),
                    )
            if cursor is not None:
                # SIGTERM between steps: checkpoint state + loader cursor
                # NOW (the grace window is ticking — no val/test, no policy
                # pass) and stop; Training.continue replays the remaining
                # batches of THIS epoch in the same order (api.py wires
                # loader_state_fn -> save_loader_state). hist stays
                # rectangular by CARRYING the last real val/test values —
                # copying the partial epoch's train loss in (the pre-r7
                # behavior) corrupted HPO early-stopping comparisons, which
                # minimize over hist["val"] (hpo.py): a lucky partial-epoch
                # train loss would masquerade as a validation improvement.
                # A first-epoch preemption has no real value to carry, so
                # the train loss stands in there (the HYDRAGNN_VALTEST=0
                # degenerate case); either way the emitted stream marks the
                # row as filler so consumers can skip it.
                last_val = hist["val"][-1] if hist["val"] else tr_loss
                last_test = hist["test"][-1] if hist["test"] else tr_loss
                hist["val"].append(last_val)
                hist["test"].append(last_test)
                hist["lr"].append(state.learning_rate)
                filler_row = {
                    "train": tr_loss,
                    "val": last_val,
                    "test": last_test,
                    "lr": state.learning_rate,
                }
                if log_fn is not None:
                    # the filler row flows through the SAME epoch-logging
                    # hook as every measured epoch (api.py owns the tag
                    # schema there), keeping every sink rectangular like
                    # hist itself
                    log_fn(epoch, filler_row)
                if writer is not None:
                    # marks this epoch's val/test as carried, not measured
                    # (the scalars.jsonl/TB analog of the filler flag in
                    # metrics.jsonl)
                    writer.add_scalar("loss/filler", 1.0, epoch)
                if telemetry is not None:
                    telemetry.on_epoch(epoch, filler_row, filler=True)
                preemption.note_global_stop()
                if save_fn is not None:
                    save_fn(state, epoch)
                    if loader_state_fn is not None:
                        # GraphLoader owns the record shape (state_dict);
                        # generic loaders fall back to the same four fields
                        if hasattr(train_loader, "state_dict"):
                            sd = train_loader.state_dict(int(cursor))
                        else:
                            sd = {
                                "epoch": int(
                                    getattr(train_loader, "epoch", epoch)
                                ),
                                "next_batch": int(cursor),
                                "seed": int(
                                    getattr(train_loader, "seed", 0) or 0
                                ),
                                "num_batches": int(len(train_loader)),
                            }
                        loader_state_fn(sd)
                if verbosity > 0:
                    print(
                        f"[{log_name}] SIGTERM: checkpointed mid-epoch "
                        f"{epoch} at batch {cursor}, stopping"
                    )
                break
            # non-finite-step policy: warn/raise/rollback BEFORE val/test so
            # a rollback epoch evaluates the restored state, not a stale one.
            # Skip provenance for the guard_skip event: the NaN watch's
            # located records when numerics is on (covers grad-only NaNs +
            # layer attribution), else the epoch's non-finite loss census
            provenance = (
                nan_watch.take() if nan_watch is not None
                else guard_log.get("nonfinite")
            )
            rollbacks_before = nf_policy.rollbacks_done
            if tracer is not None:
                # every epoch's guard verdict is traced (epochs are rare;
                # the guard's skip/rollback/fatal events attach to this
                # span's trace_id, so a rollback post-mortem has its anchor)
                with tracer.span("train/guard_verdict", epoch=epoch):
                    state = nf_policy.after_epoch(
                        state, epoch, provenance=provenance
                    )
            else:
                state = nf_policy.after_epoch(
                    state, epoch, provenance=provenance
                )
            if nf_policy.rollbacks_done > rollbacks_before:
                # the warmup ramp below recomputes the LR from base_lr every
                # warmup epoch — scale the base too, or the next ramp line
                # would silently erase the backoff the rollback just applied
                base_lr *= nf_policy.lr_backoff ** (
                    nf_policy.rollbacks_done - rollbacks_before
                )

            if do_valtest:
                with tr.timer("validate"):
                    va_loss, _ = evaluate(
                        val_loader, eval_fn, state,
                        prefetch_depth=prefetch_depth,
                    )
                with tr.timer("test"):
                    te_loss, _ = evaluate(
                        test_loader, eval_fn, state,
                        prefetch_depth=prefetch_depth,
                    )
            else:
                va_loss = te_loss = tr_loss
            hist["val"].append(va_loss)
            hist["test"].append(te_loss)
            profiler.epoch_end(epoch)

            if epoch >= warmup_epochs:
                new_lr = scheduler.step(va_loss, state.learning_rate)
                if new_lr != state.learning_rate:
                    state = state.with_learning_rate(new_lr)
            hist["lr"].append(state.learning_rate)

            if log_fn is not None:
                log_fn(
                    epoch,
                    {"train": tr_loss, "val": va_loss, "test": te_loss, "lr": state.learning_rate},
                )
            if telemetry is not None:
                telemetry.on_epoch(
                    epoch,
                    {
                        "train": tr_loss,
                        "val": va_loss,
                        "test": te_loss,
                        "lr": state.learning_rate,
                    },
                )
            if verbosity > 0:
                print(
                    f"[{log_name}] epoch {epoch}: train {tr_loss:.5f} val {va_loss:.5f} "
                    f"test {te_loss:.5f} lr {state.learning_rate:.2e} ({time.time()-t0:.1f}s)"
                )

            if return_best and va_loss < best_val:
                best_val = va_loss
                from ..parallel.mesh import materialize_replicated

                best_state = materialize_replicated(state)
            if checkpointer is not None:
                checkpointer(state, va_loss, epoch)
            if stopper is not None and stopper(va_loss):
                break
            # SLURM walltime-aware stop (reference: train_validate_test.py:257-264)
            if check_remaining and should_stop(time.time() - t0):
                break
            # TPU-pod preemption (SIGTERM): checkpoint and stop cleanly so
            # Training.continue resumes with <= 1 epoch lost; the decision
            # is agreed across hosts so nobody blocks in a collective
            if preemption.preempted_global():
                preemption.note_global_stop()
                if save_fn is not None:
                    save_fn(state, epoch)
                if verbosity > 0:
                    print(f"[{log_name}] SIGTERM: checkpointed at epoch {epoch}, stopping")
                break
    except BaseException as e:
        # capture the crash while the black box is still armed: the
        # teardown below uninstalls the excepthook before the exception
        # could reach it (KeyboardInterrupt is a shutdown, not a crash)
        if flight is not None and not isinstance(e, KeyboardInterrupt):
            try:
                flight.dump("train_exception", exc=e)
            except Exception:  # noqa: BLE001 — never mask the real error
                pass
        raise
    finally:
        profiler.close()
        preemption.uninstall()
        # join the warm-up worker, disarm the sentinel, and (verbosity > 0)
        # print the one-line compile report the smokes parse
        rep = plane.finish(verbosity)
        if telemetry is not None:
            # final absorption AFTER plane.finish: the warm-up worker has
            # joined, so the flops table is complete and the run-level
            # compile tallies are final. The whole teardown is exception-
            # guarded: a telemetry failure here must neither mask the real
            # training exception nor discard a completed run's result.
            try:
                from .compile_plane import compile_metrics

                try:
                    guard_total = int(jax.device_get(state.skipped_steps))
                    guard_events += max(guard_total - guard_seen, 0)
                except Exception:  # state donated-dead on an error path
                    pass
                telemetry.absorb_counters(
                    guard_skipped=guard_events,
                    data_skipped=(
                        dict(train_loader.validator.counts)
                        if getattr(train_loader, "validator", None)
                        is not None
                        else None
                    ),
                    retrace_violations=rep["violations"],
                    compile_metrics=compile_metrics(),
                )
                # verdict hook (obs/doctor.py): the FULL compile-plane
                # report — HBM/comm tables, cache tallies, retrace
                # violations, device capacity — lands in metrics.jsonl as
                # a typed compile_report record, so the doctor's rules
                # read it instead of scraping the stderr line
                telemetry.compile_record(rep)
                telemetry.run_record(
                    {
                        "log_name": log_name,
                        "epochs": len(hist["train"]),
                        "global_step": telemetry.global_step,
                        "endpoint_port": telemetry.endpoint_port,
                        "compile": {
                            k: rep[k]
                            for k in (
                                "precompiled",
                                "specializations",
                                "cache_hits",
                                "cache_misses",
                                "violations",
                                "time_to_first_step",
                            )
                        },
                    }
                )
            except Exception as e:  # noqa: BLE001
                import warnings as _warnings

                _warnings.warn(
                    f"telemetry teardown failed ({type(e).__name__}: {e}); "
                    "the run result is unaffected",
                    RuntimeWarning,
                    stacklevel=2,
                )
            finally:
                try:
                    telemetry.close()
                except Exception:  # noqa: BLE001 — same contract
                    pass
        # tracing-plane teardown LAST: the flight recorder must still be
        # armed while the telemetry teardown above could raise, and the
        # tracer's close flushes the span tail (abnormal exits are covered
        # by its atexit hook + the recorder's excepthook)
        if flight is not None:
            try:
                flight.uninstall()
            except Exception:  # noqa: BLE001 — observability teardown
                pass
        if tracer is not None:
            from ..obs import trace as obs_trace

            try:
                obs_trace.uninstall(tracer)
                tracer.close()
            except Exception:  # noqa: BLE001 — same contract
                pass
        if events_armed:
            from ..obs.events import detach_stream as _detach_events

            try:
                _detach_events()
            except Exception:  # noqa: BLE001 — same contract
                pass
        # run-verdict hook: HYDRAGNN_DOCTOR=1 runs the diagnosis engine
        # over the run dir the moment the streams are closed, writing
        # logs/<run>/doctor.json and one grep-able verdict line — the
        # post-run analog of `python -m hydragnn_tpu.obs.doctor <run>`
        from ..obs.telemetry import env_flag as _env_flag

        if _env_flag("HYDRAGNN_DOCTOR"):
            try:
                import json as _json

                from ..obs import doctor as _doctor

                streams = _doctor.RunStreams.from_run_dir(run_dir)
                findings, d_report = _doctor.diagnose(streams)
                with open(os.path.join(run_dir, "doctor.json"), "w") as fh:
                    _json.dump(
                        {
                            "v": _doctor.DOCTOR_SCHEMA_VERSION,
                            "mode": "diagnose",
                            "target": run_dir,
                            "findings": [f.to_dict() for f in findings],
                            "report": d_report,
                            # was the binary under diagnosis built from a
                            # clean tree? (graftlint verdict — the static
                            # analog of the runtime evidence above)
                            "static_findings":
                                _doctor.static_findings_record(),
                        },
                        fh, indent=2, default=str,
                    )
                print(
                    f"[{log_name}] run doctor: {len(findings)} finding(s)"
                    + (
                        ": " + ",".join(f.kind for f in findings)
                        if findings else ""
                    ),
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — diagnosis must never
                print(                # take the diagnosed run down
                    f"[{log_name}] run doctor failed: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )
    if best_state is not None:
        state = best_state
    return state, hist


def test_model(
    model: HydraModel,
    state: TrainState,
    loader,
    compute_grad_energy: bool = False,
    mixed_precision: bool = False,
) -> Tuple[float, Dict[str, float], Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Full-dataset evaluation returning flattened real predictions/targets
    per head (reference: test(), train_validate_test.py:620-748).
    ``mixed_precision`` must match training so the reported test loss uses
    the same numerics that drove checkpoint selection."""
    eval_fn = make_eval_step(model, compute_grad_energy, mixed_precision)
    cfg = model.cfg
    if compute_grad_energy:
        # energy is reported graph-level, forces node-level, regardless of the
        # (node) head type (reference: test(), train_validate_test.py:655-698)
        names_types = [(cfg.output_names[0], "graph"), ("forces", "node")]
    else:
        names_types = list(zip(cfg.output_names, cfg.output_type))
    entries = []
    preds: Dict[str, List[np.ndarray]] = {n: [] for n, _ in names_types}
    trues: Dict[str, List[np.ndarray]] = {n: [] for n, _ in names_types}
    for batch in loader:
        tot, tasks, outputs = eval_fn(state, batch)
        n = int(np.asarray(batch.graph_mask).sum())
        entries.append((float(tot), {k: float(v) for k, v in tasks.items()}, n))
        for name, t in names_types:
            if t == "graph":
                mask = np.asarray(batch.graph_mask)
                if compute_grad_energy:
                    target = np.asarray(batch.graph_targets["energy"]).reshape(
                        -1, 1
                    )
                else:
                    target = np.asarray(batch.graph_targets[name])
            else:
                mask = np.asarray(batch.node_mask)
                target = np.asarray(batch.node_targets[name])
            preds[name].append(np.asarray(outputs[name]).reshape(target.shape)[mask])
            trues[name].append(target[mask])
    tot, tasks = _weighted_avg(entries)
    preds_flat = {k: np.concatenate(v) for k, v in preds.items()}
    trues_flat = {k: np.concatenate(v) for k, v in trues.items()}
    # per-rank pickle dump of the collected test samples (reference:
    # HYDRAGNN_DUMP_TESTDATA, train_validate_test.py:642-652). "0"/"false"
    # disable (matching HYDRAGNN_VALTEST semantics); "1"/"true" use the
    # default directory; anything else is the output directory.
    dump = envflags.env_str("HYDRAGNN_DUMP_TESTDATA", "")
    if dump and dump.lower() not in ("0", "false"):
        import pickle

        path = (
            dump
            if dump.lower() not in ("1", "true")
            else os.path.join("logs", "testdata")
        )
        os.makedirs(path, exist_ok=True)
        fname = os.path.join(path, f"testdata_rank{jax.process_index()}.pkl")
        with open(fname, "wb") as f:
            pickle.dump({"preds": preds_flat, "trues": trues_flat}, f)
    return (tot, tasks, preds_flat, trues_flat)
