"""Optimizer selection (optax) + ReduceLROnPlateau schedule.

Replaces the reference's torch optimizer factory and DeepSpeed FusedLAMB
(hydragnn/utils/optimizer/optimizer.py:12-113) with optax; the ZeRO
``ZeroRedundancyOptimizer`` analog is optimizer-state sharding handled by the
parallel layer (optimizer state inherits the parameter sharding or is sharded
over the data axis — see hydragnn_tpu/parallel).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import optax


def make_optimizer(opt_config: Dict[str, Any]) -> optax.GradientTransformation:
    """(reference: select_optimizer, optimizer.py:104-113)"""
    kind = opt_config.get("type", "AdamW")
    lr = float(opt_config.get("learning_rate", 1e-3))
    table = {
        "SGD": lambda: optax.sgd(lr),
        "Adam": lambda: optax.adam(lr),
        "Adadelta": lambda: optax.adadelta(lr),
        "Adagrad": lambda: optax.adagrad(lr),
        "Adamax": lambda: optax.adamax(lr),
        "AdamW": lambda: optax.adamw(lr),
        "RMSprop": lambda: optax.rmsprop(lr),
        # FusedLAMB (DeepSpeed CUDA kernel) -> optax.lamb: XLA fuses on TPU
        "FusedLAMB": lambda: optax.lamb(lr),
        "LAMB": lambda: optax.lamb(lr),
    }
    if kind not in table:
        raise ValueError(f"unknown optimizer {kind!r}; known: {sorted(table)}")
    # inject_hyperparams makes learning_rate runtime-adjustable so the
    # plateau scheduler can scale it between epochs without recompiling.
    return optax.inject_hyperparams(lambda learning_rate: _with_lr(kind, learning_rate))(
        learning_rate=lr
    )


def _with_lr(kind: str, lr) -> optax.GradientTransformation:
    return {
        "SGD": optax.sgd,
        "Adam": optax.adam,
        "Adadelta": optax.adadelta,
        "Adagrad": optax.adagrad,
        "Adamax": optax.adamax,
        "AdamW": optax.adamw,
        "RMSprop": optax.rmsprop,
        "FusedLAMB": optax.lamb,
        "LAMB": optax.lamb,
    }[kind](lr)


@dataclasses.dataclass
class ReduceLROnPlateau:
    """Host-side plateau scheduler with torch semantics
    (reference: run_training.py:102-104 — mode=min, factor=0.5, patience=5,
    min_lr=1e-5; stepped on validation loss each epoch,
    train_validate_test.py:197)."""

    factor: float = 0.5
    patience: int = 5
    min_lr: float = 1e-5
    best: float = float("inf")
    bad_epochs: int = 0

    def step(self, val_loss: float, current_lr: float) -> float:
        if val_loss < self.best:
            self.best = val_loss
            self.bad_epochs = 0
            return current_lr
        self.bad_epochs += 1
        if self.bad_epochs > self.patience:
            self.bad_epochs = 0
            return max(current_lr * self.factor, self.min_lr)
        return current_lr
