"""Optimizer selection (optax) + ReduceLROnPlateau schedule.

Replaces the reference's torch optimizer factory and DeepSpeed FusedLAMB
(hydragnn/utils/optimizer/optimizer.py:12-113) with optax; the ZeRO
``ZeroRedundancyOptimizer`` analog is optimizer-state sharding handled by the
parallel layer (optimizer state inherits the parameter sharding or is sharded
over the data axis — see hydragnn_tpu/parallel).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import optax

# param-tree top-level collections frozen by Architecture.freeze_conv_layers
# (reference: Base._freeze_conv freezes graph_convs + feature_layers params,
# hydragnn/models/Base.py:247-251; flax setup lists name them <attr>_<i>).
# The MACE module tree names its encoder blocks conv{i}/radial_embedding/
# node_embedding; readouts stay trainable.
_FROZEN_PREFIXES = (
    "graph_convs",
    "feature_layers",
    "conv",
    "radial_embedding",
    "node_embedding",
)


def freeze_conv_mask(params) -> Any:
    """True for every leaf under a conv/feature-layer module (to be zeroed)."""
    return {
        k: jax.tree_util.tree_map(
            lambda _: any(k.startswith(p) for p in _FROZEN_PREFIXES), v
        )
        for k, v in params.items()
    }


def make_optimizer(
    opt_config: Dict[str, Any], freeze_conv: bool = False
) -> optax.GradientTransformation:
    """(reference: select_optimizer, optimizer.py:104-113)

    ``freeze_conv=True`` zeroes updates to conv-stack parameters via a masked
    transform — the optax analog of requires_grad=False
    (reference: Base.py:175-176, 247-251).

    ``Optimizer.clip_grad_norm`` (off by default) prepends global-norm
    gradient clipping — the stability guard for deep multiplicative stacks
    (e.g. PaiNN-update chains in conv node heads), where a single outlier
    step can blow the scalar/vector product streams past float range."""
    kind = opt_config.get("type", "AdamW")
    lr = float(opt_config.get("learning_rate", 1e-3))
    clip = float(opt_config.get("clip_grad_norm", 0.0) or 0.0)
    if kind not in _OPT_TABLE:
        raise ValueError(f"unknown optimizer {kind!r}; known: {sorted(_OPT_TABLE)}")

    def build(learning_rate):
        tx = _OPT_TABLE[kind](learning_rate)
        if clip > 0.0:
            tx = optax.chain(optax.clip_by_global_norm(clip), tx)
        if freeze_conv:
            tx = optax.chain(
                tx, optax.masked(optax.set_to_zero(), freeze_conv_mask)
            )
        return tx

    # inject_hyperparams makes learning_rate runtime-adjustable so the
    # plateau scheduler can scale it between epochs without recompiling.
    return optax.inject_hyperparams(
        lambda learning_rate: build(learning_rate)
    )(learning_rate=lr)


_OPT_TABLE = {
    "SGD": optax.sgd,
    "Adam": optax.adam,
    "Adadelta": optax.adadelta,
    "Adagrad": optax.adagrad,
    "Adamax": optax.adamax,
    "AdamW": optax.adamw,
    "RMSprop": optax.rmsprop,
    # FusedLAMB (DeepSpeed CUDA kernel) -> optax.lamb: XLA fuses on TPU
    "FusedLAMB": optax.lamb,
    "LAMB": optax.lamb,
}


@dataclasses.dataclass
class ReduceLROnPlateau:
    """Host-side plateau scheduler with torch semantics
    (reference: run_training.py:102-104 — mode=min, factor=0.5, patience=5,
    min_lr=1e-5; stepped on validation loss each epoch,
    train_validate_test.py:197)."""

    factor: float = 0.5
    patience: int = 5
    min_lr: float = 1e-5
    best: float = float("inf")
    bad_epochs: int = 0

    def step(self, val_loss: float, current_lr: float) -> float:
        if val_loss < self.best:
            self.best = val_loss
            self.bad_epochs = 0
            return current_lr
        self.bad_epochs += 1
        if self.bad_epochs > self.patience:
            self.bad_epochs = 0
            return max(current_lr * self.factor, self.min_lr)
        return current_lr
