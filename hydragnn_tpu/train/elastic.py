"""Elastic shrink/grow coordination for pod-scale GFM runs
(docs/GFM.md "Multi-host and elastic operation").

The fleet plane (obs/fleet.py) *detects* dead and straggling hosts — a
missing heartbeat past the stale window emits a typed
``fleet_host_stale`` event — but never *acts*: the run just dies with the
host. This module closes the loop with a **checkpoint-restart** protocol
(not live migration — restarts here are cheap by design: persistent
compile cache, fingerprint-exact mixture resume, mid-epoch cursors):

1. **detect**: the driver (``run-scripts/elastic_smoke.py``, or a real
   launcher) feeds fleet watchdog events and child-process exits into an
   ``ElasticCoordinator``;
2. **plan**: a confirmed host loss yields an ``ElasticPlan`` — the
   survivor set, each survivor's remapped contiguous rank, and the per-
   child env overlay (``HYDRAGNN_FLEET_HOST_INDEX``/``_COUNT``) for the
   relaunch; a rejoin yields the symmetric grow plan;
3. **re-layout**: survivors restart with ``Training.continue`` on the
   shrunk topology — the mesh re-resolves through the rule table
   (parallel/rules.py) on the new ``(data, model)`` shape, and the
   mixture's draw stripes re-deal over the survivor set by global
   position (mix/plane.py ``restore_mixture``): no draw duplicated, none
   lost, bounded progress loss (at most the steps since the last
   coordinated checkpoint);
4. **record**: the restarted run detects the layout change during resume
   (api.run_training) and calls ``note_relayout``, emitting a typed
   ``elastic_shrink`` / ``elastic_grow`` event whose attrs carry the
   before/after layouts and the progress lost in steps — the evidence the
   run doctor's elastic rules surface (obs/doctor.py).

Config surface (``Training.elastic``, config/config.py): ``enabled`` arms
the driver-side coordinator, ``min_hosts`` refuses to shrink below a
floor, ``grace_s`` bounds how long a preempted host may checkpoint before
it counts as dead.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from ..obs.events import EV_ELASTIC_GROW, EV_ELASTIC_SHRINK, emit


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """One planned re-layout: relaunch every entry of ``ranks`` with its
    env overlay, resuming from the last coordinated checkpoint."""

    kind: str  # "shrink" | "grow"
    trigger: str  # e.g. "fleet_host_stale", "exit", "preempt", "rejoin"
    before_hosts: int
    after_hosts: int
    # old rank -> new contiguous rank for every survivor (grow plans map
    # identity for existing hosts and add fresh ranks at the tail)
    rank_map: Dict[int, int]

    @property
    def ranks(self) -> List[int]:
        """New contiguous ranks to (re)launch, ascending."""
        return sorted(self.rank_map.values())

    def child_env(self, new_rank: int) -> Dict[str, str]:
        """Env overlay for the relaunched child at ``new_rank`` — the
        simulated-fleet identity surface (obs/fleet.host_identity) that
        also feeds the mixture stripe (api.prepare_data)."""
        return {
            "HYDRAGNN_FLEET_HOST_INDEX": str(int(new_rank)),
            "HYDRAGNN_FLEET_HOST_COUNT": str(int(self.after_hosts)),
        }

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "trigger": self.trigger,
            "before_hosts": int(self.before_hosts),
            "after_hosts": int(self.after_hosts),
            "rank_map": {str(k): int(v) for k, v in self.rank_map.items()},
        }


def plan_shrink(
    host_count: int,
    dead_hosts: Sequence[int],
    min_hosts: int = 1,
    trigger: str = "fleet_host_stale",
) -> ElasticPlan:
    """Shrink plan after losing ``dead_hosts``: survivors keep their
    relative order and get contiguous new ranks (the mixture stripe and
    the GraphLoader host shard both need ``0 <= index < count``)."""
    dead = {int(h) for h in dead_hosts}
    survivors = [h for h in range(int(host_count)) if h not in dead]
    if len(survivors) < max(int(min_hosts), 1):
        raise RuntimeError(
            f"cannot shrink below Training.elastic.min_hosts="
            f"{min_hosts}: {len(survivors)} survivor(s) of "
            f"{host_count} after losing hosts {sorted(dead)}"
        )
    return ElasticPlan(
        kind="shrink",
        trigger=trigger,
        before_hosts=int(host_count),
        after_hosts=len(survivors),
        rank_map={h: i for i, h in enumerate(survivors)},
    )


def plan_grow(
    host_count: int, target_hosts: int, trigger: str = "rejoin"
) -> ElasticPlan:
    """Grow plan back to ``target_hosts``: current ranks keep their index,
    rejoined hosts fill the tail ranks."""
    if int(target_hosts) <= int(host_count):
        raise ValueError(
            f"grow target {target_hosts} is not larger than the current "
            f"{host_count} host(s)"
        )
    return ElasticPlan(
        kind="grow",
        trigger=trigger,
        before_hosts=int(host_count),
        after_hosts=int(target_hosts),
        rank_map={h: h for h in range(int(target_hosts))},
    )


class ElasticCoordinator:
    """Driver-side detection -> plan state machine.

    Feed it fleet watchdog events (``observe_event``), child exits
    (``observe_exit``) and rejoin notices (``observe_rejoin``); it answers
    with an ``ElasticPlan`` when the fleet must re-lay-out, or None. One
    coordinator instance tracks one logical fleet; ``host_count`` follows
    the applied plans."""

    def __init__(
        self,
        host_count: int,
        min_hosts: int = 1,
        grace_s: float = 30.0,
    ):
        self.host_count = int(host_count)
        self.min_hosts = max(int(min_hosts), 1)
        self.grace_s = float(grace_s)
        self._dead: set = set()

    @classmethod
    def from_config(
        cls, config: Dict[str, Any], host_count: int
    ) -> Optional["ElasticCoordinator"]:
        """Build from the completed config's ``Training.elastic`` block
        (config/config.py fills the defaults). Returns None when
        ``enabled`` is false — the driver then treats any host loss as
        fatal instead of planning a shrink."""
        el = (
            config.get("NeuralNetwork", {})
            .get("Training", {})
            .get("elastic", {})
        ) or {}
        if not el.get("enabled", False):
            return None
        return cls(
            host_count,
            min_hosts=int(el.get("min_hosts", 1)),
            grace_s=float(el.get("grace_s", 30.0)),
        )

    def _shrink(self, host: int, trigger: str) -> Optional[ElasticPlan]:
        h = int(host)
        if h in self._dead or not 0 <= h < self.host_count:
            return None  # already planned around, or not ours
        self._dead.add(h)
        plan = plan_shrink(
            self.host_count, self._dead, self.min_hosts, trigger=trigger
        )
        return plan

    def observe_event(
        self, kind: str, attrs: Optional[Dict[str, Any]] = None
    ) -> Optional[ElasticPlan]:
        """A fleet-plane event record: ``fleet_host_stale`` for a host not
        already planned around yields a shrink plan."""
        if kind != "fleet_host_stale":
            return None
        host = (attrs or {}).get("host")
        if host is None:
            return None
        return self._shrink(int(host), trigger="fleet_host_stale")

    def observe_exit(
        self, host: int, returncode: Optional[int]
    ) -> Optional[ElasticPlan]:
        """A fleet child exited. Exit 0 is a normal end (no plan); anything
        else — including signal deaths (negative returncodes) — is a host
        loss. SIGTERM exits had their grace window (the preemption handler
        checkpoints mid-epoch first), so both paths converge here."""
        if returncode == 0:
            return None
        trigger = "preempt" if returncode in (-15,) else "exit"
        return self._shrink(int(host), trigger=trigger)

    def observe_rejoin(self, target_hosts: int) -> Optional[ElasticPlan]:
        """A host (or the original fleet size) is available again."""
        if int(target_hosts) <= self.host_count - len(self._dead):
            return None
        plan = plan_grow(
            self.host_count - len(self._dead),
            int(target_hosts),
            trigger="rejoin",
        )
        return plan

    def applied(self, plan: ElasticPlan) -> None:
        """The driver relaunched per ``plan`` — track the new fleet."""
        self.host_count = plan.after_hosts
        self._dead.clear()


def note_relayout(
    old_layout: Dict[str, Any],
    new_layout: Dict[str, Any],
    trigger: str = "resume",
    progress_lost_steps: Optional[int] = None,
) -> None:
    """Record a detected re-layout as a typed event — called by the
    RESTARTED survivor when resume finds the sidecar was written under a
    different stripe layout (api.run_training), with the before/after
    layouts and the bounded progress loss as evidence. The run doctor's
    ``elastic_shrink``/``elastic_grow`` rules read exactly this record
    (obs/doctor.py), pairing it with the run's recorded sharding tables
    (obs/sharding.py snapshot -> flightrec sharding.json)."""
    before = int(old_layout.get("host_count", 1) or 1)
    after = int(new_layout.get("host_count", 1) or 1)
    kind = EV_ELASTIC_SHRINK if after < before else EV_ELASTIC_GROW
    attrs: Dict[str, Any] = {
        "trigger": str(trigger),
        "before": {k: old_layout[k] for k in sorted(old_layout)},
        "after": {k: new_layout[k] for k in sorted(new_layout)},
    }
    if progress_lost_steps is not None:
        attrs["progress_lost_steps"] = int(progress_lost_steps)
    try:
        from ..obs import sharding as _sharding

        snap = _sharding.snapshot()
        if snap:
            # compact per-table summaries, not the full leaf tables — the
            # event stream is a journal, not a dump
            attrs["sharding_tables"] = {
                name: rec.get("summary", {}) for name, rec in snap.items()
            }
    except Exception:
        pass
    emit(kind, **attrs)
