"""Prediction denormalization
(reference: hydragnn/postprocess/postprocess.py:13-55), vectorized over the
per-head arrays the tpu test path produces instead of the reference's
nested python loops."""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def output_denormalize(y_minmax, true_values, predicted_values):
    """Undo per-head min-max scaling in place on lists/arrays of per-head
    values (reference: postprocess.py:13-26)."""
    for ihead in range(len(y_minmax)):
        ymin = np.asarray(y_minmax[ihead][0])
        ymax = np.asarray(y_minmax[ihead][1])
        predicted_values[ihead] = (
            np.asarray(predicted_values[ihead]) * (ymax - ymin) + ymin
        )
        true_values[ihead] = np.asarray(true_values[ihead]) * (ymax - ymin) + ymin
    return true_values, predicted_values


def unscale_features_by_num_nodes(
    datasets_list: List, scaled_index_list: Sequence[int], nodes_num_list
):
    """Multiply per-graph-scaled heads back by node counts
    (reference: postprocess.py:29-40)."""
    nodes = np.asarray(nodes_num_list)
    for dataset in datasets_list:
        for scaled_index in scaled_index_list:
            vals = np.asarray(dataset[scaled_index])
            dataset[scaled_index] = vals * nodes.reshape(
                (-1,) + (1,) * (vals.ndim - 1)
            )
    return datasets_list


def unscale_features_by_num_nodes_config(config, datasets_list, nodes_num_list):
    """(reference: postprocess.py:43-55)"""
    var_config = config["NeuralNetwork"]["Variables_of_interest"]
    output_names = var_config["output_names"]
    scaled_feature_index = [
        i for i in range(len(output_names)) if "_scaled_num_nodes" in output_names[i]
    ]
    if scaled_feature_index:
        assert var_config[
            "denormalize_output"
        ], "Cannot unscale features without 'denormalize_output'"
        datasets_list = unscale_features_by_num_nodes(
            datasets_list, scaled_feature_index, nodes_num_list
        )
    return datasets_list
