"""Result visualization: parity plots, error histograms, loss history
(reference: hydragnn/postprocess/visualizer.py:24-742, trimmed to the plots
the train loop actually drives: create_scatter_plots, plot_history,
create_error_histograms). matplotlib is imported lazily so headless
installs without it still train."""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


class Visualizer:
    """(reference: Visualizer, visualizer.py:24-120 constructor semantics:
    one instance per run directory, plots written under <dir>/plots)."""

    def __init__(self, model_with_config_name: str):
        self.outdir = os.path.join("logs", model_with_config_name, "plots")
        os.makedirs(self.outdir, exist_ok=True)

    def create_scatter_plots(
        self,
        trues: Dict[str, np.ndarray],
        preds: Dict[str, np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> None:
        """Per-head parity scatter (reference: visualizer.py scatter plots)."""
        plt = _plt()
        names = output_names or list(trues)
        for name in names:
            t = np.asarray(trues[name]).ravel()
            p = np.asarray(preds[name]).ravel()
            fig, ax = plt.subplots(figsize=(4, 4))
            ax.scatter(t, p, s=4, alpha=0.5)
            lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
            ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
            ax.set_xlabel(f"true {name}")
            ax.set_ylabel(f"predicted {name}")
            rmse = float(np.sqrt(np.mean((t - p) ** 2)))
            ax.set_title(f"{name} (RMSE {rmse:.4f})")
            fig.tight_layout()
            fig.savefig(os.path.join(self.outdir, f"parity_{name}.png"), dpi=120)
            plt.close(fig)

    def create_error_histograms(
        self, trues: Dict[str, np.ndarray], preds: Dict[str, np.ndarray]
    ) -> None:
        plt = _plt()
        for name in trues:
            err = (np.asarray(preds[name]) - np.asarray(trues[name])).ravel()
            fig, ax = plt.subplots(figsize=(4, 3))
            ax.hist(err, bins=40)
            ax.set_xlabel(f"{name} error")
            ax.set_ylabel("count")
            fig.tight_layout()
            fig.savefig(os.path.join(self.outdir, f"error_hist_{name}.png"), dpi=120)
            plt.close(fig)

    def plot_history(self, hist: Dict[str, Sequence[float]]) -> None:
        """Loss curves (reference: visualizer.py plot_history)."""
        plt = _plt()
        fig, ax = plt.subplots(figsize=(5, 3.5))
        for key in ("train", "val", "test"):
            if key in hist and len(hist[key]):
                ax.plot(hist[key], label=key)
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss")
        ax.set_yscale("log")
        ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "history.png"), dpi=120)
        plt.close(fig)
