"""Result visualization (reference: hydragnn/postprocess/visualizer.py:24-742):
parity scatter, error histograms, loss history, three-panel global analysis
(scatter / conditional mean abs error / error PDF), vector and per-node
vector parity, and the graph-size histogram. matplotlib is imported lazily
so headless installs without it still train."""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


class Visualizer:
    """(reference: Visualizer, visualizer.py:24-120 constructor semantics:
    one instance per run directory, plots written under <dir>/plots)."""

    def __init__(self, model_with_config_name: str):
        self.outdir = os.path.join("logs", model_with_config_name, "plots")
        os.makedirs(self.outdir, exist_ok=True)

    def create_scatter_plots(
        self,
        trues: Dict[str, np.ndarray],
        preds: Dict[str, np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> None:
        """Per-head parity scatter (reference: visualizer.py scatter plots)."""
        plt = _plt()
        names = output_names or list(trues)
        for name in names:
            t = np.asarray(trues[name]).ravel()
            p = np.asarray(preds[name]).ravel()
            fig, ax = plt.subplots(figsize=(4, 4))
            ax.scatter(t, p, s=4, alpha=0.5)
            lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
            ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
            ax.set_xlabel(f"true {name}")
            ax.set_ylabel(f"predicted {name}")
            rmse = float(np.sqrt(np.mean((t - p) ** 2)))
            ax.set_title(f"{name} (RMSE {rmse:.4f})")
            fig.tight_layout()
            fig.savefig(os.path.join(self.outdir, f"parity_{name}.png"), dpi=120)
            plt.close(fig)

    def create_error_histograms(
        self, trues: Dict[str, np.ndarray], preds: Dict[str, np.ndarray]
    ) -> None:
        plt = _plt()
        for name in trues:
            err = (np.asarray(preds[name]) - np.asarray(trues[name])).ravel()
            fig, ax = plt.subplots(figsize=(4, 3))
            ax.hist(err, bins=40)
            ax.set_xlabel(f"{name} error")
            ax.set_ylabel("count")
            fig.tight_layout()
            fig.savefig(os.path.join(self.outdir, f"error_hist_{name}.png"), dpi=120)
            plt.close(fig)

    @staticmethod
    def _cond_mean_abs_error(t: np.ndarray, p: np.ndarray, bins: int = 25):
        """Mean |error| conditioned on the true value (reference:
        __err_condmean, visualizer.py:93-104)."""
        t = np.asarray(t, np.float64).ravel()
        err = np.abs(np.asarray(p, np.float64).ravel() - t)
        edges = np.linspace(t.min(), t.max() + 1e-12, bins + 1)
        which = np.clip(np.digitize(t, edges) - 1, 0, bins - 1)
        centers, means = [], []
        for b in range(bins):
            m = which == b
            if m.any():
                centers.append(0.5 * (edges[b] + edges[b + 1]))
                means.append(float(err[m].mean()))
        return np.asarray(centers), np.asarray(means)

    def create_plot_global_analysis(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
    ) -> None:
        """Three-panel analysis of one output: parity scatter, conditional
        mean absolute error vs the true value, and the error PDF
        (reference: create_plot_global_analysis, visualizer.py:134-279;
        the vector case adds magnitude and component-sum parity panels)."""
        plt = _plt()
        t = np.asarray(true_values, np.float64)
        p = np.asarray(predicted_values, np.float64)
        if t.ndim == 1:  # flat series = scalar output, one row per sample
            t, p = t[:, None], p[:, None]
        if t.shape[-1] <= 1:
            fig, axs = plt.subplots(1, 3, figsize=(12, 3.6))
            tr, pr = t.ravel(), p.ravel()
            axs[0].scatter(tr, pr, s=4, alpha=0.5)
            lo, hi = float(min(tr.min(), pr.min())), float(max(tr.max(), pr.max()))
            axs[0].plot([lo, hi], [lo, hi], "k--", linewidth=1)
            axs[0].set_title("Scalar output")
            axs[0].set_xlabel("True")
            axs[0].set_ylabel("Predicted")
            xs, ys = self._cond_mean_abs_error(tr, pr)
            axs[1].plot(xs, ys, "ro")
            axs[1].set_title("Conditional mean abs. error")
            axs[1].set_xlabel("True")
            axs[1].set_ylabel("abs. error")
            pdf, edges = np.histogram(pr - tr, bins=40, density=True)
            axs[2].plot(0.5 * (edges[:-1] + edges[1:]), pdf, "ro")
            axs[2].set_title("Error PDF")
            axs[2].set_xlabel("Error")
            axs[2].set_ylabel("PDF")
        else:
            # vector output: per-component parity + magnitude + sum
            k = t.shape[-1]
            fig, axs = plt.subplots(1, k + 2, figsize=(3.6 * (k + 2), 3.6))
            for c in range(k):
                axs[c].scatter(t[:, c], p[:, c], s=4, alpha=0.5)
                axs[c].set_title(f"component {c}")
                axs[c].set_xlabel("True")
                axs[c].set_ylabel("Predicted")
            tl, pl = np.linalg.norm(t, axis=-1), np.linalg.norm(p, axis=-1)
            axs[k].scatter(tl, pl, s=4, alpha=0.5)
            axs[k].set_title("magnitude")
            ts, ps = t.sum(axis=-1), p.sum(axis=-1)
            axs[k + 1].scatter(ts, ps, s=4, alpha=0.5)
            axs[k + 1].set_title("component sum")
        fig.suptitle(varname)
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, f"analysis_{varname}.png"), dpi=120)
        plt.close(fig)

    def create_parity_plot_per_node_vector(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
        max_points: int = 5000,
    ) -> None:
        """Component-colored parity for nodal vector outputs (forces etc.;
        reference: create_parity_plot_per_node_vector, visualizer.py:519-612)."""
        plt = _plt()
        t = np.asarray(true_values, np.float64).reshape(-1, 3)
        p = np.asarray(predicted_values, np.float64).reshape(-1, 3)
        if t.shape[0] > max_points:
            sel = np.random.default_rng(0).choice(t.shape[0], max_points, False)
            t, p = t[sel], p[sel]
        fig, ax = plt.subplots(figsize=(4.5, 4.5))
        for c, label in enumerate("xyz"):
            ax.scatter(t[:, c], p[:, c], s=3, alpha=0.4, label=label)
        lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
        ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
        ax.legend()
        ax.set_xlabel(f"true {varname}")
        ax.set_ylabel(f"predicted {varname}")
        fig.tight_layout()
        fig.savefig(
            os.path.join(self.outdir, f"parity_pernode_{varname}.png"), dpi=120
        )
        plt.close(fig)

    @staticmethod
    def _hist2d_contour(ax, t: np.ndarray, p: np.ndarray, bins: int = 40):
        """Density-contour parity (reference: __hist2d_contour,
        visualizer.py:83-91) — readable where a raw scatter saturates."""
        h, xe, ye = np.histogram2d(t.ravel(), p.ravel(), bins=bins)
        xc, yc = 0.5 * (xe[:-1] + xe[1:]), 0.5 * (ye[:-1] + ye[1:])
        ax.contourf(xc, yc, np.log1p(h.T), levels=12, cmap="viridis")

    def create_parity_plot_and_error_histogram_scalar(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
        density: bool = True,
    ) -> None:
        """Two-panel scalar summary: density-contour parity + error
        histogram (reference: create_parity_plot_and_error_histogram_scalar,
        visualizer.py:281-385)."""
        plt = _plt()
        t = np.asarray(true_values, np.float64).ravel()
        p = np.asarray(predicted_values, np.float64).ravel()
        fig, axs = plt.subplots(1, 2, figsize=(8, 3.6))
        if density and t.size > 200:
            self._hist2d_contour(axs[0], t, p)
        else:
            axs[0].scatter(t, p, s=4, alpha=0.5)
        lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
        axs[0].plot([lo, hi], [lo, hi], "w--" if density else "k--", linewidth=1)
        axs[0].set_xlabel(f"true {varname}")
        axs[0].set_ylabel(f"predicted {varname}")
        err = p - t
        axs[1].hist(err, bins=40)
        axs[1].set_xlabel(f"{varname} error")
        axs[1].set_ylabel("count")
        axs[1].set_title(
            f"MAE {np.abs(err).mean():.4f}  RMSE {np.sqrt((err**2).mean()):.4f}"
        )
        fig.tight_layout()
        fig.savefig(
            os.path.join(self.outdir, f"parity_errhist_{varname}.png"), dpi=120
        )
        plt.close(fig)

    def create_error_histogram_per_node(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
        node_index: np.ndarray,
        max_nodes: int = 16,
    ) -> None:
        """Per-node-position error histograms for nodal outputs (reference:
        create_error_histogram_per_node, visualizer.py:387-465): one panel
        per node slot, errors pooled across samples."""
        plt = _plt()
        t = np.asarray(true_values, np.float64).ravel()
        p = np.asarray(predicted_values, np.float64).ravel()
        idx = np.asarray(node_index).ravel()
        slots = np.unique(idx)[:max_nodes]
        cols = min(4, len(slots))
        rows = int(np.ceil(len(slots) / cols))
        fig, axs = plt.subplots(
            rows, cols, figsize=(3 * cols, 2.4 * rows), squeeze=False
        )
        for k, slot in enumerate(slots):
            ax = axs[k // cols][k % cols]
            m = idx == slot
            ax.hist(p[m] - t[m], bins=25)
            ax.set_title(f"node {int(slot)}", fontsize=8)
        for k in range(len(slots), rows * cols):
            axs[k // cols][k % cols].axis("off")
        fig.suptitle(f"{varname}: per-node error")
        fig.tight_layout()
        fig.savefig(
            os.path.join(self.outdir, f"errhist_pernode_{varname}.png"), dpi=120
        )
        plt.close(fig)

    def create_parity_plot_vector(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
    ) -> None:
        """Graph-level vector parity: one panel per component plus the
        magnitude (reference: create_parity_plot_vector,
        visualizer.py:467-517)."""
        plt = _plt()
        t = np.asarray(true_values, np.float64)
        p = np.asarray(predicted_values, np.float64)
        t = t.reshape(t.shape[0], -1)
        p = p.reshape(p.shape[0], -1)
        k = t.shape[1]
        fig, axs = plt.subplots(1, k + 1, figsize=(3.3 * (k + 1), 3.3))
        for c in range(k):
            axs[c].scatter(t[:, c], p[:, c], s=4, alpha=0.5)
            lo = float(min(t[:, c].min(), p[:, c].min()))
            hi = float(max(t[:, c].max(), p[:, c].max()))
            axs[c].plot([lo, hi], [lo, hi], "k--", linewidth=1)
            axs[c].set_title(f"{varname}[{c}]", fontsize=9)
        tm, pm = np.linalg.norm(t, axis=1), np.linalg.norm(p, axis=1)
        axs[k].scatter(tm, pm, s=4, alpha=0.5)
        axs[k].set_title("magnitude", fontsize=9)
        fig.tight_layout()
        fig.savefig(
            os.path.join(self.outdir, f"parity_vector_{varname}.png"), dpi=120
        )
        plt.close(fig)

    def create_plot_global(
        self,
        trues: Dict[str, np.ndarray],
        preds: Dict[str, np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> None:
        """One overview figure with a parity panel per output head
        (reference: create_plot_global, visualizer.py:722-732)."""
        plt = _plt()
        names = list(output_names or trues)
        fig, axs = plt.subplots(
            1, len(names), figsize=(3.6 * len(names), 3.6), squeeze=False
        )
        for k, name in enumerate(names):
            ax = axs[0][k]
            t = np.asarray(trues[name]).ravel()
            p = np.asarray(preds[name]).ravel()
            ax.scatter(t, p, s=3, alpha=0.4)
            lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
            ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
            ax.set_title(name, fontsize=9)
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "global_overview.png"), dpi=120)
        plt.close(fig)

    def num_nodes_plot(self, nodes_num_list: Sequence[int]) -> None:
        """Histogram of graph sizes in the dataset (reference:
        num_nodes_plot, visualizer.py:734-742)."""
        plt = _plt()
        fig, ax = plt.subplots(figsize=(4, 3))
        ax.hist(np.asarray(list(nodes_num_list)), bins=30)
        ax.set_xlabel("num nodes")
        ax.set_ylabel("count")
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "num_nodes.png"), dpi=120)
        plt.close(fig)

    def plot_history(self, hist: Dict[str, Sequence[float]]) -> None:
        """Loss curves (reference: visualizer.py plot_history)."""
        plt = _plt()
        fig, ax = plt.subplots(figsize=(5, 3.5))
        for key in ("train", "val", "test"):
            if key in hist and len(hist[key]):
                ax.plot(hist[key], label=key)
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss")
        ax.set_yscale("log")
        ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "history.png"), dpi=120)
        plt.close(fig)
