"""Result visualization (reference: hydragnn/postprocess/visualizer.py:24-742):
parity scatter, error histograms, loss history, three-panel global analysis
(scatter / conditional mean abs error / error PDF), vector and per-node
vector parity, and the graph-size histogram. matplotlib is imported lazily
so headless installs without it still train."""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


class Visualizer:
    """(reference: Visualizer, visualizer.py:24-120 constructor semantics:
    one instance per run directory, plots written under <dir>/plots)."""

    def __init__(self, model_with_config_name: str):
        self.outdir = os.path.join("logs", model_with_config_name, "plots")
        os.makedirs(self.outdir, exist_ok=True)

    def create_scatter_plots(
        self,
        trues: Dict[str, np.ndarray],
        preds: Dict[str, np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> None:
        """Per-head parity scatter (reference: visualizer.py scatter plots)."""
        plt = _plt()
        names = output_names or list(trues)
        for name in names:
            t = np.asarray(trues[name]).ravel()
            p = np.asarray(preds[name]).ravel()
            fig, ax = plt.subplots(figsize=(4, 4))
            ax.scatter(t, p, s=4, alpha=0.5)
            lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
            ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
            ax.set_xlabel(f"true {name}")
            ax.set_ylabel(f"predicted {name}")
            rmse = float(np.sqrt(np.mean((t - p) ** 2)))
            ax.set_title(f"{name} (RMSE {rmse:.4f})")
            fig.tight_layout()
            fig.savefig(os.path.join(self.outdir, f"parity_{name}.png"), dpi=120)
            plt.close(fig)

    def create_error_histograms(
        self, trues: Dict[str, np.ndarray], preds: Dict[str, np.ndarray]
    ) -> None:
        plt = _plt()
        for name in trues:
            err = (np.asarray(preds[name]) - np.asarray(trues[name])).ravel()
            fig, ax = plt.subplots(figsize=(4, 3))
            ax.hist(err, bins=40)
            ax.set_xlabel(f"{name} error")
            ax.set_ylabel("count")
            fig.tight_layout()
            fig.savefig(os.path.join(self.outdir, f"error_hist_{name}.png"), dpi=120)
            plt.close(fig)

    @staticmethod
    def _cond_mean_abs_error(t: np.ndarray, p: np.ndarray, bins: int = 25):
        """Mean |error| conditioned on the true value (reference:
        __err_condmean, visualizer.py:93-104)."""
        t = np.asarray(t, np.float64).ravel()
        err = np.abs(np.asarray(p, np.float64).ravel() - t)
        edges = np.linspace(t.min(), t.max() + 1e-12, bins + 1)
        which = np.clip(np.digitize(t, edges) - 1, 0, bins - 1)
        centers, means = [], []
        for b in range(bins):
            m = which == b
            if m.any():
                centers.append(0.5 * (edges[b] + edges[b + 1]))
                means.append(float(err[m].mean()))
        return np.asarray(centers), np.asarray(means)

    def create_plot_global_analysis(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
    ) -> None:
        """Three-panel analysis of one output: parity scatter, conditional
        mean absolute error vs the true value, and the error PDF
        (reference: create_plot_global_analysis, visualizer.py:134-279;
        the vector case adds magnitude and component-sum parity panels)."""
        plt = _plt()
        t = np.asarray(true_values, np.float64)
        p = np.asarray(predicted_values, np.float64)
        if t.ndim == 1:  # flat series = scalar output, one row per sample
            t, p = t[:, None], p[:, None]
        if t.shape[-1] <= 1:
            fig, axs = plt.subplots(1, 3, figsize=(12, 3.6))
            tr, pr = t.ravel(), p.ravel()
            axs[0].scatter(tr, pr, s=4, alpha=0.5)
            lo, hi = float(min(tr.min(), pr.min())), float(max(tr.max(), pr.max()))
            axs[0].plot([lo, hi], [lo, hi], "k--", linewidth=1)
            axs[0].set_title("Scalar output")
            axs[0].set_xlabel("True")
            axs[0].set_ylabel("Predicted")
            xs, ys = self._cond_mean_abs_error(tr, pr)
            axs[1].plot(xs, ys, "ro")
            axs[1].set_title("Conditional mean abs. error")
            axs[1].set_xlabel("True")
            axs[1].set_ylabel("abs. error")
            pdf, edges = np.histogram(pr - tr, bins=40, density=True)
            axs[2].plot(0.5 * (edges[:-1] + edges[1:]), pdf, "ro")
            axs[2].set_title("Error PDF")
            axs[2].set_xlabel("Error")
            axs[2].set_ylabel("PDF")
        else:
            # vector output: per-component parity + magnitude + sum
            k = t.shape[-1]
            fig, axs = plt.subplots(1, k + 2, figsize=(3.6 * (k + 2), 3.6))
            for c in range(k):
                axs[c].scatter(t[:, c], p[:, c], s=4, alpha=0.5)
                axs[c].set_title(f"component {c}")
                axs[c].set_xlabel("True")
                axs[c].set_ylabel("Predicted")
            tl, pl = np.linalg.norm(t, axis=-1), np.linalg.norm(p, axis=-1)
            axs[k].scatter(tl, pl, s=4, alpha=0.5)
            axs[k].set_title("magnitude")
            ts, ps = t.sum(axis=-1), p.sum(axis=-1)
            axs[k + 1].scatter(ts, ps, s=4, alpha=0.5)
            axs[k + 1].set_title("component sum")
        fig.suptitle(varname)
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, f"analysis_{varname}.png"), dpi=120)
        plt.close(fig)

    def create_parity_plot_per_node_vector(
        self,
        varname: str,
        true_values: np.ndarray,
        predicted_values: np.ndarray,
        max_points: int = 5000,
    ) -> None:
        """Component-colored parity for nodal vector outputs (forces etc.;
        reference: create_parity_plot_per_node_vector, visualizer.py:519-612)."""
        plt = _plt()
        t = np.asarray(true_values, np.float64).reshape(-1, 3)
        p = np.asarray(predicted_values, np.float64).reshape(-1, 3)
        if t.shape[0] > max_points:
            sel = np.random.default_rng(0).choice(t.shape[0], max_points, False)
            t, p = t[sel], p[sel]
        fig, ax = plt.subplots(figsize=(4.5, 4.5))
        for c, label in enumerate("xyz"):
            ax.scatter(t[:, c], p[:, c], s=3, alpha=0.4, label=label)
        lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
        ax.plot([lo, hi], [lo, hi], "k--", linewidth=1)
        ax.legend()
        ax.set_xlabel(f"true {varname}")
        ax.set_ylabel(f"predicted {varname}")
        fig.tight_layout()
        fig.savefig(
            os.path.join(self.outdir, f"parity_pernode_{varname}.png"), dpi=120
        )
        plt.close(fig)

    def num_nodes_plot(self, nodes_num_list: Sequence[int]) -> None:
        """Histogram of graph sizes in the dataset (reference:
        num_nodes_plot, visualizer.py:734-742)."""
        plt = _plt()
        fig, ax = plt.subplots(figsize=(4, 3))
        ax.hist(np.asarray(list(nodes_num_list)), bins=30)
        ax.set_xlabel("num nodes")
        ax.set_ylabel("count")
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "num_nodes.png"), dpi=120)
        plt.close(fig)

    def plot_history(self, hist: Dict[str, Sequence[float]]) -> None:
        """Loss curves (reference: visualizer.py plot_history)."""
        plt = _plt()
        fig, ax = plt.subplots(figsize=(5, 3.5))
        for key in ("train", "val", "test"):
            if key in hist and len(hist[key]):
                ax.plot(hist[key], label=key)
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss")
        ax.set_yscale("log")
        ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "history.png"), dpi=120)
        plt.close(fig)
