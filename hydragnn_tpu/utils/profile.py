"""Device profiler wrapper — the torch.profiler/Kineto analog
(reference: hydragnn/utils/profiling_and_tracing/profile.py:9-70).

Captures one configured epoch into a TensorBoard-compatible xprof trace via
``jax.profiler`` (reference semantics: config ``"Profile": {"enable": 1,
"target_epoch": N}`` profiles that epoch only; a null context otherwise).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional


class Profiler:
    def __init__(self, config: Optional[Dict[str, Any]] = None, log_dir: str = "./logs/profile"):
        config = config or {}
        self.enabled = bool(config.get("enable", 0))
        self.target_epoch = int(config.get("target_epoch", 0))
        self.log_dir = config.get("log_dir", log_dir)
        self._active = False

    def setup(self, config: Optional[Dict[str, Any]]) -> "Profiler":
        """(reference: profile.py:30-44 reads the Profile config section)"""
        if config:
            self.enabled = bool(config.get("enable", 0))
            self.target_epoch = int(config.get("target_epoch", self.target_epoch))
            self.log_dir = config.get("log_dir", self.log_dir)
        return self

    def epoch_begin(self, epoch: int) -> None:
        if self.enabled and epoch == self.target_epoch and not self._active:
            import jax

            os.makedirs(self.log_dir, exist_ok=True)
            # perfetto alongside the xplane pb: stdlib-parseable
            # (run-scripts/analyze_trace.py rolls up device op time)
            jax.profiler.start_trace(
                self.log_dir, create_perfetto_trace=True
            )
            self._active = True

    def epoch_end(self, epoch: int) -> None:
        if self._active and epoch == self.target_epoch:
            import jax

            jax.effects_barrier()
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


def peak_memory_stats() -> Dict[str, float]:
    """Per-device peak memory in bytes (reference prints
    torch.cuda.max_memory_allocated, distributed.py:354-361)."""
    import jax

    out = {}
    for d in jax.local_devices():
        stats = d.memory_stats() or {}
        out[str(d)] = float(stats.get("peak_bytes_in_use", 0))
    return out


def print_peak_memory(verbosity: int = 1, prefix: str = "") -> None:
    if verbosity <= 0:
        return
    for dev, peak in peak_memory_stats().items():
        print(f"{prefix}{dev}: peak memory {peak / 2**20:.1f} MiB")
