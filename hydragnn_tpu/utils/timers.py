"""Coarse phase timers with cross-process reduction
(reference: hydragnn/utils/profiling_and_tracing/time_utils.py:22-138).

``Timer`` accumulates wall time per named phase in class-level state; on
``print_timers`` the per-process totals are reduced to min/avg/max across
JAX processes (the torch.distributed all-reduce of the reference,
time_utils.py:48-83) — serial fallback when running single-process.
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np


class Timer:
    _totals: Dict[str, float] = {}
    _counts: Dict[str, int] = {}

    def __init__(self, name: str):
        self.name = name
        self._start = None

    def start(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def stop(self) -> float:
        assert self._start is not None, f"Timer {self.name} not started"
        dt = time.perf_counter() - self._start
        Timer._totals[self.name] = Timer._totals.get(self.name, 0.0) + dt
        Timer._counts[self.name] = Timer._counts.get(self.name, 0) + 1
        self._start = None
        return dt

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @classmethod
    def reset(cls) -> None:
        cls._totals.clear()
        cls._counts.clear()

    @classmethod
    def totals(cls) -> Dict[str, float]:
        return dict(cls._totals)


def _reduce_across_processes(values: np.ndarray) -> Dict[str, np.ndarray]:
    """min/avg/max over JAX processes; identity when single-process."""
    import jax

    if jax.process_count() == 1:
        return {"min": values, "avg": values, "max": values}
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(values)  # [P, K]
    return {
        "min": gathered.min(axis=0),
        "avg": gathered.mean(axis=0),
        "max": gathered.max(axis=0),
    }


def print_timers(verbosity: int = 1) -> None:
    """(reference: time_utils.py:95-138; table printed on process 0 only,
    after the collective reduction every process must join)"""
    if verbosity <= 0 or not Timer._totals:
        return
    import jax

    names = sorted(Timer._totals)
    vals = np.asarray([Timer._totals[n] for n in names])
    red = _reduce_across_processes(vals)
    if jax.process_index() != 0:
        return
    width = max(len(n) for n in names)
    print(f"{'timer'.ljust(width)}  count  min(s)      avg(s)      max(s)")
    for i, n in enumerate(names):
        print(
            f"{n.ljust(width)}  {Timer._counts[n]:<5d}"
            f"  {red['min'][i]:<10.4f}  {red['avg'][i]:<10.4f}  {red['max'][i]:<10.4f}"
        )
