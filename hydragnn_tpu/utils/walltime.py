"""SLURM walltime-aware early stop
(reference: hydragnn/utils/distributed/distributed.py:380-419; train-loop hook
train_validate_test.py:257-264, config key ``CheckRemainingTime``).

Process 0 queries ``squeue -h -j $SLURM_JOB_ID -o %L`` for the remaining
allocation, compares it to the last epoch's duration (x a safety factor) and
the decision is broadcast to all JAX processes so every rank stops at the
same epoch.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

import numpy as np


def parse_slurm_remaining(text: str) -> Optional[float]:
    """'[D-]HH:MM:SS' / 'MM:SS' -> seconds; None when unparseable
    (e.g. 'INVALID', 'UNLIMITED')."""
    text = text.strip()
    if not text or not text[0].isdigit():
        return None
    days = 0
    if "-" in text:
        d, text = text.split("-", 1)
        days = int(d)
    parts = [int(p) for p in text.split(":")]
    while len(parts) < 3:
        parts.insert(0, 0)
    h, m, s = parts[-3:]
    return float(((days * 24 + h) * 60 + m) * 60 + s)


def query_remaining_seconds() -> Optional[float]:
    job = os.getenv("SLURM_JOB_ID")
    if not job:
        return None
    try:
        out = subprocess.run(
            ["squeue", "-h", "-j", job, "-o", "%L"],
            capture_output=True,
            text=True,
            timeout=30,
        ).stdout
    except (OSError, subprocess.TimeoutExpired):
        return None
    return parse_slurm_remaining(out)


def should_stop(last_epoch_seconds: float, safety_factor: float = 2.0) -> bool:
    """True when the remaining walltime cannot fit another epoch
    (reference: check_remaining, distributed.py:394-419)."""
    import jax

    decision = 0.0
    if jax.process_index() == 0:
        remaining = query_remaining_seconds()
        if remaining is not None and remaining < safety_factor * last_epoch_seconds:
            decision = 1.0
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        decision = float(
            multihost_utils.broadcast_one_to_all(np.asarray(decision))
        )
    return decision > 0.5
