"""Training-metric writer: TensorBoard scalars + JSONL fallback
(reference: rank-0 SummaryWriter, hydragnn/utils/model/model.py:109-115;
per-epoch scalars train_validate_test.py:198-205).

Writes every scalar to ``scalars.jsonl`` always (machine-readable, no deps)
and mirrors to a torch ``SummaryWriter`` when tensorboard is importable.
Process 0 only.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional


class MetricsWriter:
    def __init__(self, log_name: str, path: str = "./logs"):
        try:
            import jax

            self._rank0 = jax.process_index() == 0
        except Exception:
            self._rank0 = True
        self.run_dir = os.path.join(path, log_name)
        self._jsonl = None
        self._tb = None
        if not self._rank0:
            return
        os.makedirs(self.run_dir, exist_ok=True)
        self._jsonl = open(os.path.join(self.run_dir, "scalars.jsonl"), "a")
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(log_dir=self.run_dir)
        except Exception:
            self._tb = None

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if self._jsonl is None:
            return
        self._jsonl.write(
            json.dumps({"tag": tag, "value": float(value), "step": int(step)}) + "\n"
        )
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), step)

    def add_scalars(self, scalars: Dict[str, float], step: int) -> None:
        for tag, v in scalars.items():
            self.add_scalar(tag, v, step)

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None
