"""Verbosity-leveled, process-aware printing and run logging
(reference: hydragnn/utils/print/print_utils.py).

Levels 0-4 as in the reference (print_utils.py:20-27); ``print_distributed``
prints on process 0 only unless level >= 4 (rank-prefixed everywhere,
print_utils.py:42-53); ``setup_log`` attaches python logging to
``./logs/<name>/run.log`` + console (print_utils.py:63-91).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def print_master(*args, verbosity_level: int = 2, verbosity: int = 2) -> None:
    if verbosity >= verbosity_level and _process_index() == 0:
        print(*args)


def print_distributed(verbosity: int, *args) -> None:
    """(reference: print_utils.py:42-53)"""
    if verbosity >= 4:
        print(f"[rank {_process_index()}]", *args)
    elif verbosity >= 1 and _process_index() == 0:
        print(*args)


def iterate_tqdm(iterable: Iterable, verbosity: int, **kwargs):
    """Rank-gated progress iterator (reference: print_utils.py:56-60)."""
    if verbosity >= 2 and _process_index() == 0:
        try:
            from tqdm import tqdm

            return tqdm(iterable, **kwargs)
        except ImportError:
            return iterable
    return iterable


def setup_log(name: str, path: str = "./logs") -> logging.Logger:
    """(reference: print_utils.py:63-91)"""
    run_dir = os.path.join(path, name)
    os.makedirs(run_dir, exist_ok=True)
    logger = logging.getLogger("hydragnn_tpu")
    logger.setLevel(logging.INFO)
    logger.handlers.clear()
    fmt = logging.Formatter(
        f"%(asctime)s [rank {_process_index()}] %(levelname)s: %(message)s"
    )
    fh = logging.FileHandler(os.path.join(run_dir, "run.log"))
    fh.setFormatter(fmt)
    logger.addHandler(fh)
    ch = logging.StreamHandler(sys.stdout)
    ch.setFormatter(fmt)
    logger.addHandler(ch)
    return logger


def print_model(variables: dict, verbosity: int = 2) -> int:
    """Parameter summary: per-module leaf shapes and the total count
    (reference: print_model, hydragnn/utils/model/model.py:289-297).
    Returns the total parameter count; prints at verbosity >= 2."""
    import numpy as np

    try:
        from flax.traverse_util import flatten_dict

        flat = flatten_dict(variables.get("params", variables))
    except Exception:
        flat = {("params",): variables}
    total = 0
    lines = []
    for path, leaf in sorted(flat.items()):
        n = int(np.prod(np.shape(leaf))) if np.ndim(leaf) else 1
        total += n
        lines.append(f"  {'/'.join(map(str, path))}: {tuple(np.shape(leaf))} = {n}")
    if verbosity >= 2 and _process_index() == 0:
        print("\n".join(lines))
        print(f"Total trainable parameters: {total}")
    return total
