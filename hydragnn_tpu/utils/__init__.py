"""Observability and run-support utilities (SURVEY §5.1, §5.5):
region tracer, phase timers, device profiler, leveled printing, metric
writer, SLURM walltime stop."""

from . import faultinject, tracer
from .printing import (
    iterate_tqdm,
    print_distributed,
    print_master,
    print_model,
    setup_log,
)
from .profile import Profiler, peak_memory_stats, print_peak_memory
from .timers import Timer, print_timers
from .walltime import parse_slurm_remaining, query_remaining_seconds, should_stop
from .writer import MetricsWriter

__all__ = [
    "MetricsWriter",
    "Profiler",
    "Timer",
    "faultinject",
    "iterate_tqdm",
    "parse_slurm_remaining",
    "peak_memory_stats",
    "print_distributed",
    "print_master",
    "print_model",
    "print_peak_memory",
    "print_timers",
    "query_remaining_seconds",
    "setup_log",
    "should_stop",
    "tracer",
]
