"""Preemption-safe training: checkpoint and stop cleanly on SIGTERM.

The reference's failure story is SLURM-walltime polling
(hydragnn/utils/distributed/distributed.py:380-419); cloud TPU pods add a
second failure mode it has no answer to — *preemption*, delivered as
SIGTERM with a grace window (spot/preemptible VMs, maintenance events).
This module turns that signal into an orderly epoch-boundary stop: the
handler only sets a flag (async-signal-safe), the training loop checks it
between epochs, checkpoints, and returns — so a preempted run resumes from
``Training.continue`` with at most one epoch of lost work.

Enabled by default inside ``train_validate_test``; multi-host runs stop in
lockstep because every worker of a preempted slice receives the signal.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional

_flag = threading.Event()
# set by the training loop when the CROSS-HOST agreed preemption stop fired
# (and the loop checkpointed) — consumers gate collective end-of-run saves
# on this, never on the per-process _flag: SIGTERM delivery can skew across
# hosts, and a save gated on the local flag would leave non-preempted hosts
# blocked in a collective orbax save the preempted host skips
_global_stop = threading.Event()
_installed: Optional[int] = None
_prev_handler = None


def install() -> None:
    """Install the SIGTERM handler (main thread only; re-entrant). Clears
    any stale flag from a previous run in the same process — without that,
    one handled SIGTERM would stop every later training run at epoch 0."""
    global _installed, _prev_handler
    _flag.clear()
    _global_stop.clear()
    if _installed is not None:
        return
    if threading.current_thread() is not threading.main_thread():
        return  # signal.signal is main-thread-only; workers skip
    try:
        _prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        _installed = signal.SIGTERM
    except ValueError:
        # embedded interpreter without signal support
        _installed = None


def uninstall() -> None:
    """Restore the previous SIGTERM disposition (training is over — the
    process must terminate normally on the next SIGTERM, not swallow it)."""
    global _installed, _prev_handler
    if _installed is None:
        return
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        signal.signal(signal.SIGTERM, _prev_handler or signal.SIG_DFL)
    except ValueError:
        pass
    _installed = None
    _prev_handler = None


def _on_sigterm(signum, frame):
    _flag.set()
    # chain to a previously installed *custom* handler (a launcher's own);
    # SIG_DFL/SIG_IGN are not callables — during training the orderly
    # epoch-boundary stop replaces the default kill
    if callable(_prev_handler):
        _prev_handler(signum, frame)


def preempted() -> bool:
    """True once SIGTERM has been received (this process only)."""
    return _flag.is_set()


def preempted_global() -> bool:
    """Cross-host agreement on the local flags: ANY preempted process stops
    every process at the same epoch boundary — signal-delivery skew across
    hosts would otherwise leave stragglers blocked in the next epoch's
    collectives (the walltime stop broadcasts its decision for the same
    reason, utils/walltime.py)."""
    import jax

    if jax.process_count() == 1:
        return _flag.is_set()
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray([_flag.is_set()], np.int32)
    )
    return bool(np.asarray(flags).any())


def note_global_stop() -> None:
    """Record that the agreed cross-host preemption stop happened (called by
    the training loop right before its preemption checkpoint). Because
    ``preempted_global()`` is a collective with one answer, every process
    records the same decision."""
    _global_stop.set()


def global_stop_noted() -> bool:
    """True iff the training loop stopped (and checkpointed) on the agreed
    cross-host preemption decision."""
    return _global_stop.is_set()


def reset() -> None:
    """Clear the flags (tests / consecutive runs in one process)."""
    _flag.clear()
    _global_stop.clear()
