"""Region tracer facade — the GPTL/Score-P analog
(reference: hydragnn/utils/profiling_and_tracing/tracer.py:35-167).

The reference fans ``tr.start/stop`` out to GPTL and Score-P C libraries with
optional ``torch.cuda.synchronize`` + MPI barrier per span. Here the backend
is (a) an in-process accumulator (count/total/min/max per region) and (b)
optional ``jax.profiler.TraceAnnotation`` so regions appear in xprof/
TensorBoard device traces. ``sync=True`` drains the async JAX dispatch queue
(``jax.effects_barrier``) before timestamping — the device-sync analog of the
reference's ``cudasync=True`` (tracer.py:106-127) — controlled globally by
``HYDRAGNN_TRACE_LEVEL`` exactly like the reference's train-loop spans
(train_validate_test.py:477-498).
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Dict, Optional
from . import envflags

_enabled = False
_regions: Dict[str, Dict[str, float]] = {}
# span-plane bridge (obs/trace.py), resolved lazily once: a region closing
# while a sampled span is open on this thread is emitted as a child span,
# so the pre-existing region instrumentation lands in the trace tree
_obs_trace = None
# per-name stacks so re-entrant start(name) nests instead of overwriting
_open: Dict[str, list] = {}
# one global LIFO of (name, TraceAnnotation): xprof annotations are scoped
# C++ objects and must exit in strict nesting order
_ann_stack: list = []


def _sync_devices() -> None:
    """Wait for all previously enqueued device work: enqueue a trivial op on
    each local device's (FIFO) compute stream and block on it —
    ``jax.effects_barrier`` alone would skip pure computations."""
    try:
        import jax
        import jax.numpy as jnp

        for d in jax.local_devices():
            jax.block_until_ready(jax.device_put(jnp.zeros(()), d) + 1)
    except Exception:
        pass


def _trace_level() -> int:
    return envflags.env_int("HYDRAGNN_TRACE_LEVEL", 0)


def initialize() -> None:
    """(reference: tracer.py:35-60 registers GPTL/Score-P if importable)"""
    reset()


def reset() -> None:
    _regions.clear()
    _open.clear()
    while _ann_stack:
        _, ann = _ann_stack.pop()
        try:
            ann.__exit__(None, None, None)
        except Exception:
            pass


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def start(name: str, sync: Optional[bool] = None) -> None:
    """Open a region (reference: tracer.py:106-116)."""
    if not _enabled:
        return
    if sync is None:
        sync = _trace_level() > 0
    if sync:
        _sync_devices()
    try:
        import jax

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        _ann_stack.append((name, ann))
    except Exception:
        pass
    _open.setdefault(name, []).append(time.perf_counter())


def stop(name: str, sync: Optional[bool] = None) -> None:
    """Close a region and accumulate (reference: tracer.py:118-127)."""
    if not _enabled or not _open.get(name):
        return
    if sync is None:
        sync = _trace_level() > 0
    if sync:
        _sync_devices()
    starts = _open[name]
    dt = time.perf_counter() - starts.pop()
    if not starts:
        del _open[name]
    # unwind annotations in strict LIFO order: an out-of-nesting stop closes
    # the inner (still-open) annotations early rather than corrupting the
    # xprof span tree by exiting out of order
    if any(n == name for n, _ in _ann_stack):
        while _ann_stack:
            top_name, ann = _ann_stack.pop()
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
            if top_name == name:
                break
    rec = _regions.setdefault(
        name, {"count": 0.0, "total": 0.0, "min": float("inf"), "max": 0.0}
    )
    rec["count"] += 1
    rec["total"] += dt
    rec["min"] = min(rec["min"], dt)
    rec["max"] = max(rec["max"], dt)
    _note_span(name, dt)


def _note_span(name: str, dt: float) -> None:
    """Forward a closed region to the span plane (no-op without an active
    tracer + open span — one attribute read on the unsampled hot path)."""
    global _obs_trace
    if _obs_trace is None:
        try:
            from ..obs import trace as _t

            _obs_trace = _t
        except Exception:
            _obs_trace = False
            return
    if _obs_trace is False:
        return
    try:
        _obs_trace.note_region(name, dt)
    except Exception:
        pass  # tracing must never fail the timed code


@contextlib.contextmanager
def timer(name: str, sync: Optional[bool] = None):
    """(reference: tracer.py:158-167)"""
    start(name, sync)
    try:
        yield
    finally:
        stop(name, sync)


def profile(name: str):
    """Decorator opening a region around the call (reference: tracer.py:145-155)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with timer(name):
                return fn(*args, **kwargs)

        return wrapped

    return deco


def get_regions() -> Dict[str, Dict[str, float]]:
    return {k: dict(v) for k, v in _regions.items()}


def print_report(prefix: str = "") -> None:
    """Per-process region dump (the GPTL ``pr_file`` analog,
    reference: examples/multibranch/train.py:507-514)."""
    if not _regions:
        return
    width = max(len(k) for k in _regions)
    print(f"{prefix}{'region'.ljust(width)}  count     total(s)    avg(s)      max(s)")
    for name, r in sorted(_regions.items()):
        avg = r["total"] / max(r["count"], 1)
        print(
            f"{prefix}{name.ljust(width)}  {int(r['count']):<8d}"
            f"  {r['total']:<10.4f}  {avg:<10.4f}  {r['max']:<10.4f}"
        )


def save_report(path: str) -> None:
    import json

    with open(path, "w") as f:
        json.dump(get_regions(), f, indent=2)
