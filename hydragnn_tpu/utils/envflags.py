"""The one parse boundary for the ``HYDRAGNN_*`` environment channel.

Before graftlint, each module hand-rolled its env parsing: ``int(os.getenv
(...))`` crashing a multi-hour run on a typo'd value (the
``HYDRAGNN_DDSTORE_RETRIES`` malformed-value crash class, fixed piecemeal
in data/ddstore.py and train/checkpoint.py with private ``_env_int``
copies), three spellings of tri-state booleans, and ``== "1"`` force
checks scattered across the kernel routers. This module is the single
shared vocabulary, and the ``env_census`` checker (analysis/env_census.py)
enforces that every ``HYDRAGNN_*`` read in the package routes through it —
a direct ``os.environ``/``os.getenv`` read of a ``HYDRAGNN_*`` name
anywhere else is a CI-gated finding.

Parse helpers and their grammars (docs/CONFIG.md "Environment flags"):

- ``env_flag``: tri-state on/off — None unset, else False for the falsy
  tokens (``0``/``off``/``false``/empty) and True otherwise. The
  HYDRAGNN_TELEMETRY-style overrides.
- ``env_force``: tri-state force/deny — None unset, True for exactly
  ``"1"``, False for anything else set. The kernel-route preferences
  (HYDRAGNN_PALLAS_SEGMENT=0/1), where an unrecognized token must mean
  "deny", never "force".
- ``env_int`` / ``env_float``: numeric with a default; a malformed value
  WARNS and falls back instead of crashing (the DDSTORE_RETRIES class).
- ``env_str``: raw string passthrough (paths, host:port addresses,
  fault-point specs whose grammar belongs to the consumer).

Every helper funnels through ``env_str`` so the census has exactly one
syscall site to audit.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional

ENV_PREFIX = "HYDRAGNN_"

_FALSY = ("0", "off", "false", "")


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw env read — the single ``os.environ`` touch point for the
    HYDRAGNN_* channel (and the helper consumers use for path/spec-valued
    flags whose grammar is their own)."""
    return os.environ.get(name, default)


def env_set(name: str) -> bool:
    """Whether the flag is present at all (some fault points are armed by
    existence, parsed later by their own grammar)."""
    return env_str(name) is not None


def env_flag(name: str) -> Optional[bool]:
    """Tri-state boolean: None when unset, else False for the falsy
    tokens (``0``/``off``/``false``/empty, case-insensitive) and True
    otherwise — ONE spelling for every HYDRAGNN_* on/off override
    (HYDRAGNN_TELEMETRY, HYDRAGNN_NUMERICS, HYDRAGNN_DOCTOR, ...), so the
    overrides cannot drift between entry points."""
    v = env_str(name)
    if v is None:
        return None
    return v.strip().lower() not in _FALSY


def env_force(name: str) -> Optional[bool]:
    """Tri-state force/deny preference: None when unset, True for exactly
    ``"1"``, anything else False. The kernel-route override grammar
    (HYDRAGNN_PALLAS_SEGMENT / _FLASH / _MULTIAGG / MACE_DENSE_CG ...):
    an unrecognized token denies the special route — falling back to the
    reference path is always correct, force-enabling it is not."""
    v = env_str(name)
    if v is None:
        return None
    return v == "1"


def env_int(name: str, default: int) -> int:
    """Integer env value; a malformed value warns and returns ``default``
    instead of raising — a typo'd knob must degrade the feature, never
    crash the run (the HYDRAGNN_DDSTORE_RETRIES incident class)."""
    v = env_str(name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        warnings.warn(
            f"{name}={v!r} is not an integer; using the default "
            f"{default!r} instead",
            RuntimeWarning,
            stacklevel=2,
        )
        return default


def env_float(name: str, default: float) -> float:
    """Float env value with the same malformed-value fallback contract as
    ``env_int``."""
    v = env_str(name)
    if v is None:
        return default
    try:
        return float(v)
    except ValueError:
        warnings.warn(
            f"{name}={v!r} is not a number; using the default "
            f"{default!r} instead",
            RuntimeWarning,
            stacklevel=2,
        )
        return default
