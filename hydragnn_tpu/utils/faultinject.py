"""Deterministic fault injection for the fault-tolerance layer.

Every recovery path in train/ and checkpoint IO is exercised in CI through
the injection points below instead of being trusted: a NaN landing in the
gradients at a known step, a SIGKILL at a named point inside the checkpoint
writer, a bit flipped in a saved checkpoint, an IOError on the first n write
attempts (the flaky-parallel-FS model). All points are env/config driven and
deterministic — no time-based races, no random faults.

Injection points (env is the primary surface; ``configure`` mirrors it for
in-process tests):

- ``HYDRAGNN_FAULT_NAN_STEP``: poison the gradients with NaN inside the
  jitted train step — ``"5"`` (exactly step 5), ``"5+"`` (every step >= 5),
  ``"3,7"`` (a list). Read at TRACE time: set it before the step function's
  first call.
- ``HYDRAGNN_FAULT_NAN_LR_GT``: poison the gradients while the injected
  learning rate is above the threshold — the deterministic model of
  "diverged because the LR is too high", which the rollback policy's LR
  backoff genuinely recovers from. ANDed with NAN_STEP when both are set.
- ``HYDRAGNN_FAULT_KILL_AT``: comma-separated point names; ``maybe_kill``
  SIGKILLs the process when called with a listed name (checkpoint writer
  points: ``ckpt_tmp_written``, ``ckpt_msgpack_replaced``,
  ``ckpt_digest_written`` — see train/checkpoint.py).
- ``HYDRAGNN_FAULT_IO_ERRORS``: ``maybe_ioerror`` raises OSError on the
  first n calls per point name (per process), then succeeds — the transient
  flaky-FS model the checkpoint writer's retry loop must absorb.

``flip_bit`` is the host-side corruption tool for the torn/rotted-checkpoint
tests: flip one bit of a saved file and assert restore falls back to the
previous verified epoch.
"""

from __future__ import annotations

import os
import signal
from typing import Dict, Optional

# per-point counters for maybe_ioerror (per process — checkpoint saves run
# in-process, so a counter here is exactly "the first n attempts")
_io_error_counts: Dict[str, int] = {}
# configure() overrides; env wins when both are set
_config: Dict[str, str] = {}


def configure(**kwargs: Optional[str]) -> None:
    """In-process mirror of the env surface for tests:
    ``configure(nan_step="5+", io_errors="2", kill_at="ckpt_tmp_written")``.
    Pass ``None`` to clear a key."""
    keymap = {
        "nan_step": "HYDRAGNN_FAULT_NAN_STEP",
        "nan_lr_gt": "HYDRAGNN_FAULT_NAN_LR_GT",
        "kill_at": "HYDRAGNN_FAULT_KILL_AT",
        "io_errors": "HYDRAGNN_FAULT_IO_ERRORS",
    }
    for k, v in kwargs.items():
        if k not in keymap:
            raise KeyError(f"unknown faultinject key {k!r}; known: {sorted(keymap)}")
        if v is None:
            _config.pop(keymap[k], None)
        else:
            _config[keymap[k]] = str(v)


def reset() -> None:
    """Clear configure() state and the per-point IO-error counters."""
    _config.clear()
    _io_error_counts.clear()


def _get(key: str) -> Optional[str]:
    env = os.environ.get(key)
    return env if env is not None else _config.get(key)


def poison_grads(grads, step, lr=None):
    """Inside the jitted train step: return ``grads`` with every floating
    leaf replaced by NaN when the armed condition holds at runtime, or
    ``grads`` unchanged (an exact no-op — the env is read at TRACE time, so
    an unarmed run compiles the identity).

    ``step`` is the (traced) ``state.step`` counter; ``lr`` the (traced)
    injected learning rate, when the optimizer carries one."""
    spec = _get("HYDRAGNN_FAULT_NAN_STEP")
    lr_gt = _get("HYDRAGNN_FAULT_NAN_LR_GT")
    if spec is None and lr_gt is None:
        return grads
    import jax
    import jax.numpy as jnp

    cond = None
    if spec is not None:
        s = jnp.asarray(step)
        if spec.endswith("+"):
            cond = s >= int(spec[:-1])
        else:
            cond = jnp.zeros((), bool)
            for k in spec.split(","):
                cond = cond | (s == int(k))
    if lr_gt is not None and lr is not None:
        c = jnp.asarray(lr) > float(lr_gt)
        cond = c if cond is None else cond & c
    if cond is None:
        return grads

    def poison(g):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        return jnp.where(cond, jnp.full_like(g, jnp.nan), g)

    return jax.tree_util.tree_map(poison, grads)


def lr_of(opt_state):
    """The (traced) injected learning rate of an inject_hyperparams optimizer
    state, or None — the lr hook for poison_grads' LR-threshold mode."""
    hp = getattr(opt_state, "hyperparams", None)
    if isinstance(hp, dict) and "learning_rate" in hp:
        return hp["learning_rate"]
    return None


def maybe_kill(point: str) -> None:
    """SIGKILL this process when ``point`` is armed — the preemption-
    mid-write model. SIGKILL (not SIGTERM): nothing may run after it, which
    is exactly the torn-write scenario the atomic checkpoint protocol must
    survive."""
    spec = _get("HYDRAGNN_FAULT_KILL_AT")
    if spec is None:
        return
    if point in (p.strip() for p in spec.split(",")):
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_ioerror(point: str) -> None:
    """Raise OSError on the first n calls for ``point`` (n from
    HYDRAGNN_FAULT_IO_ERRORS), then succeed — deterministic transient-IO
    model for the checkpoint writer's retry/backoff loop."""
    spec = _get("HYDRAGNN_FAULT_IO_ERRORS")
    if spec is None:
        return
    n = int(spec)
    done = _io_error_counts.get(point, 0)
    if done < n:
        _io_error_counts[point] = done + 1
        raise OSError(
            f"injected transient IO error {done + 1}/{n} at {point!r} "
            "(HYDRAGNN_FAULT_IO_ERRORS)"
        )


def flip_bit(path: str, byte_offset: Optional[int] = None, bit: int = 0) -> int:
    """Flip one bit of the file at ``path`` in place (default: the middle
    byte — inside the msgpack payload, past any header). Returns the byte
    offset flipped. The corruption tool for the verified-restore tests."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bit-flip empty file {path}")
    off = size // 2 if byte_offset is None else byte_offset
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ (1 << bit)]))
    return off
