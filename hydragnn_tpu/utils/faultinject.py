"""Deterministic fault injection for the fault-tolerance layer.

Every recovery path in train/ and checkpoint IO is exercised in CI through
the injection points below instead of being trusted: a NaN landing in the
gradients at a known step, a SIGKILL at a named point inside the checkpoint
writer, a bit flipped in a saved checkpoint, an IOError on the first n write
attempts (the flaky-parallel-FS model). All points are env/config driven and
deterministic — no time-based races, no random faults.

Injection points (env is the primary surface; ``configure`` mirrors it for
in-process tests):

- ``HYDRAGNN_FAULT_NAN_STEP``: poison the gradients with NaN inside the
  jitted train step — ``"5"`` (exactly step 5), ``"5+"`` (every step >= 5),
  ``"3,7"`` (a list). Read at TRACE time: set it before the step function's
  first call.
- ``HYDRAGNN_FAULT_NAN_LR_GT``: poison the gradients while the injected
  learning rate is above the threshold — the deterministic model of
  "diverged because the LR is too high", which the rollback policy's LR
  backoff genuinely recovers from. ANDed with NAN_STEP when both are set.
- ``HYDRAGNN_FAULT_KILL_AT``: comma-separated point names; ``maybe_kill``
  SIGKILLs the process when called with a listed name (checkpoint writer
  points: ``ckpt_tmp_written``, ``ckpt_msgpack_replaced``,
  ``ckpt_digest_written`` — see train/checkpoint.py).
- ``HYDRAGNN_FAULT_IO_ERRORS``: ``maybe_ioerror`` raises OSError on the
  first n calls per point name (per process), then succeeds — the transient
  flaky-FS model the checkpoint writer's retry loop must absorb.

Data-plane points (docs/ROBUSTNESS.md "Data plane"):

- ``HYDRAGNN_FAULT_SAMPLE_NAN``: ``poison_samples`` NaNs the first feature
  of the dataset samples at the listed indices (``"3"`` / ``"3,7"``) — the
  dirty-ingest model the sample validator must catch, with per-reason skip
  counts matching the injection plan exactly.
- ``HYDRAGNN_FAULT_CORRUPT_SAMPLE``: ``corrupt_blob`` flips the leading
  byte of the listed sample ids' serialized bytes on fetch, so
  deserialization fails deterministically (DistDataset's corrupt-sample
  error path).
- ``HYDRAGNN_FAULT_SOCKET_DROP``: ``maybe_socket_drop`` raises
  ConnectionError on the listed call numbers per point (``"2"`` = the 2nd
  call) — the transient-connection model RemoteStoreClient's
  reconnect/backoff loop must absorb with zero sample loss.
- ``HYDRAGNN_FAULT_LOADER_STALL`` (``"k"`` or ``"k:secs"``) /
  ``HYDRAGNN_FAULT_LOADER_DIE`` (``"k"``): ``maybe_loader_fault`` makes the
  prefetch producer sleep before batch k, or exit silently without its end
  sentinel — the wedged/dead-worker models the loader watchdog turns into
  an actionable LoaderStallError.

Serve-plane points (docs/SERVING.md "Failure model"):

- ``HYDRAGNN_FAULT_SERVE_REQ_NAN``: ``poison_request`` NaNs the first
  feature of the listed *submission indices* (``"3"`` / ``"3,7"``) right
  after the client hands the graph over — the corrupt-request model the
  admission gate must turn into a typed per-request error while the
  co-batched requests beside it succeed.
- ``HYDRAGNN_FAULT_SERVE_WEDGE`` (``"k"`` or ``"k:secs"``):
  ``maybe_serve_wedge`` sleeps inside the device-step runner before batch
  k's dispatch (default 60s — longer than any sane step watchdog) — the
  wedged-step model the serving watchdog must bound with a typed error and
  a recycled runner instead of hanging the server.
- ``HYDRAGNN_FAULT_SERVE_SLOW_CLIENT`` (``"i"`` or ``"i:secs"``):
  ``maybe_slow_client`` sleeps at the listed submissions' admission call —
  the slow-client model (admission must not be wedged by one caller; other
  threads keep being served).

Serving-fleet points (docs/SERVING.md "Fleet"): all three take a
``replica:...`` spec so ONE env set on the whole fleet arms exactly one
replica (the manager passes its environment through to every worker);
``replica`` is the worker's fleet index (HYDRAGNN_FLEET_HOST_INDEX).

- ``HYDRAGNN_FAULT_REPLICA_KILL`` (``"r:k"``, k in the ``_index_armed``
  grammar): ``maybe_replica_kill`` SIGKILLs replica r before serving its
  k-th /predict request — the dead-replica model: the router's retry must
  absorb the in-flight loss on a different replica and the ReplicaManager
  must restart the worker within its backoff bound.
- ``HYDRAGNN_FAULT_REPLICA_WEDGE`` (``"r:k[:secs]"``, default 30s):
  ``maybe_replica_wedge`` sleeps replica r's armed /predict requests
  before processing — the wedged-replica model that must open the
  router's circuit breaker, then reclose it via the half-open probe once
  the armed window passes.
- ``HYDRAGNN_FAULT_REPLICA_SLOW`` (``"r[:secs]"``, default 0.2s):
  ``maybe_replica_slow`` sleeps EVERY /predict on replica r — the
  slow-replica model the router's tail hedging must beat (duplicate to a
  fast replica past the hedge deadline, first answer wins).
- ``HYDRAGNN_FAULT_QUANT_DRIFT`` (``"<entry_substring>:<factor>"``, factor
  default 4.0; empty substring arms every entry): ``maybe_quant_drift``
  hands the serving quantizer (serve/quantize.py) a scale-distortion
  factor when the checkpoint entry being quantized matches — the
  drifted-candidate model the int8 accuracy gate must refuse with a typed
  ``quant_drift`` event while the prior weights keep serving.

Fleet-plane points (docs/OBSERVABILITY.md "Fleet"):

- ``HYDRAGNN_FAULT_STRAGGLE`` (``"k:secs"``, ``"k+:secs"``, or bare
  ``"k"``/``"k+"`` with a 0.05s default): ``maybe_straggle`` sleeps on the
  HOST side before dispatching the listed training-step indices (``"k+"``
  arms every step >= k) — the slow-host model the fleet watchdog
  (obs/fleet.py) must flag as a typed ``fleet_straggler`` event with a
  coordinated flight dump, exercised by ``run-scripts/fleet_smoke.py``
  with the env set on exactly one simulated host.
- ``HYDRAGNN_FAULT_HOST_KILL`` (``"k"``, ``"k+"``, comma lists; the index
  counts cumulative train steps across ALL epochs of this process, so a
  drill can fire after the epoch-0 checkpoint committed):
  ``maybe_host_fault`` SIGKILLs this process before dispatching the listed
  training-step indices — the dead-host model (hardware loss, OOM-killer):
  no grace, no signal handler, nothing runs after it. The fleet watchdog
  sees the heartbeat go stale and the elastic coordinator
  (train/elastic.py) drives the survivors' re-layout; exercised by
  ``run-scripts/elastic_smoke.py`` with the env set on one simulated host.
- ``HYDRAGNN_FAULT_HOST_PREEMPT`` (same grammar): ``maybe_host_fault``
  SIGTERMs this process at the listed step instead — the scheduler-
  preemption model WITH grace: the run's SIGTERM handler
  (train/preempt.py) performs the coordinated mid-epoch checkpoint before
  exit, so recovery resumes from the exact step rather than the last
  epoch boundary.

``flip_bit`` is the host-side corruption tool for the torn/rotted-checkpoint
tests: flip one bit of a saved file and assert restore falls back to the
previous verified epoch (the serve chaos smoke also uses it to corrupt a
hot-reload candidate).
"""

from __future__ import annotations

import os
import signal
from typing import Dict, Optional

from . import envflags

# per-point counters for maybe_ioerror (per process — checkpoint saves run
# in-process, so a counter here is exactly "the first n attempts")
_io_error_counts: Dict[str, int] = {}
# per-point call counters for maybe_socket_drop ("drop on the nth call")
_socket_call_counts: Dict[str, int] = {}
# configure() overrides; env wins when both are set
_config: Dict[str, str] = {}


def configure(**kwargs: Optional[str]) -> None:
    """In-process mirror of the env surface for tests:
    ``configure(nan_step="5+", io_errors="2", kill_at="ckpt_tmp_written")``.
    Pass ``None`` to clear a key."""
    keymap = {
        "nan_step": "HYDRAGNN_FAULT_NAN_STEP",
        "nan_lr_gt": "HYDRAGNN_FAULT_NAN_LR_GT",
        "kill_at": "HYDRAGNN_FAULT_KILL_AT",
        "io_errors": "HYDRAGNN_FAULT_IO_ERRORS",
        "sample_nan": "HYDRAGNN_FAULT_SAMPLE_NAN",
        "corrupt_sample": "HYDRAGNN_FAULT_CORRUPT_SAMPLE",
        "socket_drop": "HYDRAGNN_FAULT_SOCKET_DROP",
        "loader_stall": "HYDRAGNN_FAULT_LOADER_STALL",
        "loader_die": "HYDRAGNN_FAULT_LOADER_DIE",
        "serve_req_nan": "HYDRAGNN_FAULT_SERVE_REQ_NAN",
        "serve_wedge": "HYDRAGNN_FAULT_SERVE_WEDGE",
        "serve_slow_client": "HYDRAGNN_FAULT_SERVE_SLOW_CLIENT",
        "replica_kill": "HYDRAGNN_FAULT_REPLICA_KILL",
        "replica_wedge": "HYDRAGNN_FAULT_REPLICA_WEDGE",
        "replica_slow": "HYDRAGNN_FAULT_REPLICA_SLOW",
        "straggle": "HYDRAGNN_FAULT_STRAGGLE",
        "host_kill": "HYDRAGNN_FAULT_HOST_KILL",
        "host_preempt": "HYDRAGNN_FAULT_HOST_PREEMPT",
    }
    for k, v in kwargs.items():
        if k not in keymap:
            raise KeyError(f"unknown faultinject key {k!r}; known: {sorted(keymap)}")
        if v is None:
            _config.pop(keymap[k], None)
        else:
            _config[keymap[k]] = str(v)


def reset() -> None:
    """Clear configure() state and the per-point counters."""
    global _host_fault_steps
    _config.clear()
    _io_error_counts.clear()
    _socket_call_counts.clear()
    _host_fault_steps = 0


def _get(key: str) -> Optional[str]:
    env = envflags.env_str(key)
    return env if env is not None else _config.get(key)


def poison_grads(grads, step, lr=None):
    """Inside the jitted train step: return ``grads`` with every floating
    leaf replaced by NaN when the armed condition holds at runtime, or
    ``grads`` unchanged (an exact no-op — the env is read at TRACE time, so
    an unarmed run compiles the identity).

    ``step`` is the (traced) ``state.step`` counter; ``lr`` the (traced)
    injected learning rate, when the optimizer carries one."""
    spec = _get("HYDRAGNN_FAULT_NAN_STEP")
    lr_gt = _get("HYDRAGNN_FAULT_NAN_LR_GT")
    if spec is None and lr_gt is None:
        return grads
    import jax
    import jax.numpy as jnp

    cond = None
    if spec is not None:
        s = jnp.asarray(step)
        if spec.endswith("+"):
            cond = s >= int(spec[:-1])
        else:
            cond = jnp.zeros((), bool)
            for k in spec.split(","):
                cond = cond | (s == int(k))
    if lr_gt is not None and lr is not None:
        c = jnp.asarray(lr) > float(lr_gt)
        cond = c if cond is None else cond & c
    if cond is None:
        return grads

    def poison(g):
        if not jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return g
        return jnp.where(cond, jnp.full_like(g, jnp.nan), g)

    return jax.tree_util.tree_map(poison, grads)


def lr_of(opt_state):
    """The (traced) injected learning rate of an inject_hyperparams optimizer
    state, or None — the lr hook for poison_grads' LR-threshold mode."""
    hp = getattr(opt_state, "hyperparams", None)
    if isinstance(hp, dict) and "learning_rate" in hp:
        return hp["learning_rate"]
    return None


def maybe_kill(point: str) -> None:
    """SIGKILL this process when ``point`` is armed — the preemption-
    mid-write model. SIGKILL (not SIGTERM): nothing may run after it, which
    is exactly the torn-write scenario the atomic checkpoint protocol must
    survive."""
    spec = _get("HYDRAGNN_FAULT_KILL_AT")
    if spec is None:
        return
    if point in (p.strip() for p in spec.split(",")):
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_ioerror(point: str) -> None:
    """Raise OSError on the first n calls for ``point`` (n from
    HYDRAGNN_FAULT_IO_ERRORS), then succeed — deterministic transient-IO
    model for the checkpoint writer's retry/backoff loop."""
    spec = _get("HYDRAGNN_FAULT_IO_ERRORS")
    if spec is None:
        return
    n = int(spec)
    done = _io_error_counts.get(point, 0)
    if done < n:
        _io_error_counts[point] = done + 1
        raise OSError(
            f"injected transient IO error {done + 1}/{n} at {point!r} "
            "(HYDRAGNN_FAULT_IO_ERRORS)"
        )


def _index_set(spec: Optional[str]) -> set:
    """Parse a comma-separated index list spec (``"3"`` / ``"3,7"``)."""
    if not spec:
        return set()
    return {int(k) for k in spec.split(",") if k.strip()}


def _index_armed(spec: str, index: int) -> bool:
    """Whether ``index`` matches an index spec: comma-separated values
    (``"3"``/``"3,7"``, the _index_set grammar) plus the open-range form
    ``"k+"`` (every index >= k) — ONE grammar for every indexed
    HYDRAGNN_FAULT_* point."""
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if part.endswith("+"):
            if index >= int(part[:-1]):
                return True
        elif index == int(part):
            return True
    return False


def poison_samples(graphs):
    """Dataset-ingest corruption: return ``graphs`` with the first feature of
    every armed index (HYDRAGNN_FAULT_SAMPLE_NAN, ``"3,7"``) replaced by NaN.
    No-op (the same list object) when unarmed. The dirty-data model the
    sample validator must catch — each poisoned sample must show up as
    exactly one ``nonfinite_features`` skip."""
    spec = _get("HYDRAGNN_FAULT_SAMPLE_NAN")
    idxs = _index_set(spec)
    if not idxs:
        return graphs
    import dataclasses

    import numpy as np

    out = list(graphs)
    for i in idxs:
        if 0 <= i < len(out):
            x = np.array(out[i].x, dtype=np.float32, copy=True)
            x.flat[0] = np.nan
            out[i] = dataclasses.replace(out[i], x=x)
    return out


def corrupt_blob(blob: bytes, idx: int) -> bytes:
    """Fetched-bytes corruption: when ``idx`` is armed
    (HYDRAGNN_FAULT_CORRUPT_SAMPLE), flip the leading byte so
    deserialization fails deterministically (a pickle stream never survives
    a mangled protocol opcode). Returns ``blob`` unchanged otherwise."""
    if idx not in _index_set(_get("HYDRAGNN_FAULT_CORRUPT_SAMPLE")):
        return blob
    if not blob:
        return blob
    return bytes([blob[0] ^ 0xFF]) + blob[1:]


def maybe_socket_drop(point: str) -> None:
    """Raise ConnectionError on the armed call numbers for ``point``
    (HYDRAGNN_FAULT_SOCKET_DROP, 1-based: ``"2"`` drops the 2nd call,
    ``"1,3"`` the 1st and 3rd) — the transient-connection model the remote
    store client's reconnect/backoff loop must absorb."""
    spec = _get("HYDRAGNN_FAULT_SOCKET_DROP")
    if spec is None:
        return
    call = _socket_call_counts.get(point, 0) + 1
    _socket_call_counts[point] = call
    if call in _index_set(spec):
        raise ConnectionError(
            f"injected socket drop on call {call} at {point!r} "
            "(HYDRAGNN_FAULT_SOCKET_DROP)"
        )


def maybe_loader_fault(batch_index: int) -> Optional[str]:
    """Prefetch-producer fault hook, called before building batch
    ``batch_index``. Returns ``"die"`` when the producer must exit silently
    without its end sentinel (HYDRAGNN_FAULT_LOADER_DIE = ``"k"``); sleeps
    in place for the armed stall (HYDRAGNN_FAULT_LOADER_STALL = ``"k"`` or
    ``"k:secs"``, default 60s — longer than any sane watchdog timeout) and
    returns None. Both model a wedged/dead loader worker the watchdog must
    turn into an actionable error instead of a silent hang."""
    die = _get("HYDRAGNN_FAULT_LOADER_DIE")
    if die is not None and batch_index in _index_set(die):
        return "die"
    stall = _get("HYDRAGNN_FAULT_LOADER_STALL")
    if stall is not None:
        k, _, secs = stall.partition(":")
        if int(k) == batch_index:
            import time

            time.sleep(float(secs) if secs else 60.0)
    return None


def poison_request(graph, idx: int):
    """Serve-plane ingest corruption: when submission index ``idx`` is armed
    (HYDRAGNN_FAULT_SERVE_REQ_NAN), return ``graph`` with its first feature
    NaN'd; the same graph object otherwise (exact no-op unarmed). The
    corrupt-request model the admission validation gate must catch as a
    typed per-request error."""
    if idx not in _index_set(_get("HYDRAGNN_FAULT_SERVE_REQ_NAN")):
        return graph
    import dataclasses

    import numpy as np

    x = np.array(graph.x, dtype=np.float32, copy=True)
    x.flat[0] = np.nan
    return dataclasses.replace(graph, x=x)


def _indexed_sleep(spec: Optional[str], index: int, default_secs: float) -> None:
    if spec is None:
        return
    k, _, secs = spec.partition(":")
    if _index_armed(k, index):
        import time

        time.sleep(float(secs) if secs else default_secs)


def maybe_serve_wedge(batch_index: int) -> None:
    """Sleep inside the serving step runner before dispatching batch
    ``batch_index`` when armed (HYDRAGNN_FAULT_SERVE_WEDGE = ``"k"`` or
    ``"k:secs"``, default 60s) — the wedged-device-step model the serve
    watchdog must turn into a bounded WedgedStepError + runner recycle."""
    _indexed_sleep(_get("HYDRAGNN_FAULT_SERVE_WEDGE"), batch_index, 60.0)


def maybe_slow_client(request_index: int) -> None:
    """Sleep at submission ``request_index``'s admission call when armed
    (HYDRAGNN_FAULT_SERVE_SLOW_CLIENT = ``"i"`` or ``"i:secs"``, default
    1s) — the slow-client model: one dawdling caller must only delay
    itself, never the serve loop or other submitters."""
    _indexed_sleep(_get("HYDRAGNN_FAULT_SERVE_SLOW_CLIENT"), request_index, 1.0)


def _replica_spec(key: str, replica_index: int) -> Optional[str]:
    """Resolve a ``"r:..."`` replica-scoped spec: returns the ``...`` part
    when the leading replica index matches this worker, else None."""
    spec = _get(key)
    if spec is None:
        return None
    r, sep, rest = spec.partition(":")
    try:
        if int(r) != replica_index:
            return None
    except ValueError:
        return None
    return rest if sep else ""


def maybe_replica_kill(replica_index: int, request_index: int) -> None:
    """SIGKILL this replica before serving request ``request_index`` when
    armed (HYDRAGNN_FAULT_REPLICA_KILL = ``"r:k"``; k defaults to 0, the
    first request) — the dead-replica model: no grace, nothing runs after
    it; the in-flight request is the router's retry problem and the
    restart is the ReplicaManager's."""
    kspec = _replica_spec("HYDRAGNN_FAULT_REPLICA_KILL", replica_index)
    if kspec is None:
        return
    if _index_armed(kspec or "0", request_index):
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_replica_wedge(replica_index: int, request_index: int) -> None:
    """Sleep this replica's armed /predict requests before processing
    (HYDRAGNN_FAULT_REPLICA_WEDGE = ``"r:k[:secs]"``, default 30s — longer
    than any sane router timeout) — the wedged-replica model that must
    open the circuit breaker; requests past the armed window succeed, so
    the half-open probe recloses it."""
    rest = _replica_spec("HYDRAGNN_FAULT_REPLICA_WEDGE", replica_index)
    if rest is None:
        return
    _indexed_sleep(rest or "0", request_index, 30.0)


def maybe_replica_slow(replica_index: int) -> None:
    """Sleep EVERY /predict on this replica when armed
    (HYDRAGNN_FAULT_REPLICA_SLOW = ``"r[:secs]"``, default 0.2s) — the
    slow-replica model the router's tail hedging must beat."""
    rest = _replica_spec("HYDRAGNN_FAULT_REPLICA_SLOW", replica_index)
    if rest is None:
        return
    import time

    time.sleep(float(rest) if rest else 0.2)


def maybe_quant_drift(entry: Optional[str]) -> Optional[float]:
    """Drifted-quantization drill (HYDRAGNN_FAULT_QUANT_DRIFT =
    ``"<entry_substring>:<factor>"``; empty substring arms every entry,
    factor defaults to 4.0): returns the scale-distortion factor when the
    checkpoint entry being quantized matches, else None. The serving
    quantizer multiplies every weight scale by it, so the accuracy gate
    must refuse the candidate (typed quant_drift event) while entries
    outside the match keep quantizing cleanly — the deterministic
    bad-candidate model for the fleet smoke's rolling-reload leg."""
    spec = _get("HYDRAGNN_FAULT_QUANT_DRIFT")
    if spec is None:
        return None
    sub, sep, factor_s = spec.rpartition(":")
    if not sep:
        sub, factor_s = spec, ""
    if sub and (entry is None or sub not in str(entry)):
        return None
    try:
        return float(factor_s) if factor_s else 4.0
    except ValueError:
        return 4.0


def maybe_straggle(step_index: int) -> None:
    """Host-side per-step sleep when armed (HYDRAGNN_FAULT_STRAGGLE =
    ``"k:secs"`` for exactly step k, ``"k+:secs"`` for every step >= k,
    comma lists like the sibling points; seconds default 0.05) — the
    slow-host model of a fleet straggler. Called from the epoch loop
    before each step dispatch; an unarmed call is one dict lookup."""
    _indexed_sleep(_get("HYDRAGNN_FAULT_STRAGGLE"), step_index, 0.05)


_host_fault_steps = 0


def maybe_host_fault(step_index: Optional[int] = None) -> None:
    """Host-loss drill hook, called from the epoch loop before each step
    dispatch (beside ``maybe_straggle``). Unlike the other indexed points,
    the armed index counts CUMULATIVE train steps dispatched by this
    process across epochs — a dead-host drill must fire *after* the
    epoch-0 checkpoint committed, which a per-epoch index cannot express
    (the epoch loop restarts its counter every epoch). When the step is
    armed:

    - HYDRAGNN_FAULT_HOST_KILL → SIGKILL this process (dead-host model:
      nothing runs after it — the fleet watchdog must detect the stale
      heartbeat and the elastic coordinator re-lay-out the survivors);
    - HYDRAGNN_FAULT_HOST_PREEMPT → SIGTERM this process (preemption-with-
      grace model: the run's SIGTERM handler checkpoints mid-epoch first).

    Both use the shared ``_index_armed`` grammar (``"k"``, ``"k+"``, comma
    lists). ``step_index`` overrides the process counter (tests). An
    unarmed call is two dict lookups."""
    global _host_fault_steps
    if step_index is None:
        step_index = _host_fault_steps
    _host_fault_steps += 1
    kill = _get("HYDRAGNN_FAULT_HOST_KILL")
    if kill is not None and _index_armed(kill, step_index):
        os.kill(os.getpid(), signal.SIGKILL)
    preempt = _get("HYDRAGNN_FAULT_HOST_PREEMPT")
    if preempt is not None and _index_armed(preempt, step_index):
        os.kill(os.getpid(), signal.SIGTERM)


def flip_bit(path: str, byte_offset: Optional[int] = None, bit: int = 0) -> int:
    """Flip one bit of the file at ``path`` in place (default: the middle
    byte — inside the msgpack payload, past any header). Returns the byte
    offset flipped. The corruption tool for the verified-restore tests."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot bit-flip empty file {path}")
    off = size // 2 if byte_offset is None else byte_offset
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ (1 << bit)]))
    return off
