"""Deterministic temperature-sampled source scheduler (docs/GFM.md).

Every random decision is a pure function of small integers, never of
process history:

- the source picked at draw ``k`` of epoch ``e`` depends only on
  ``(seed, e, k)`` plus the active source set/weights AT that draw;
- the within-source sample order is a pure permutation of
  ``(seed, source id, e, pass)`` — a source drawn more often than its size
  wraps into its next reshuffled pass.

That purity is what makes mixture resume exact (docs/GFM.md "Resume"):
given the sidecar's (epoch, draw, per-source cursors, active set, weights),
any process replays the remaining draw sequence bit-for-bit — there is no
RNG object whose hidden state a SIGKILL could lose.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np


def temperature_weights(
    sizes: Dict[int, int],
    temperature: float,
    explicit: Optional[Dict[int, float]] = None,
) -> Dict[int, float]:
    """Normalized draw probabilities over sources: p_i ∝ w_i^(1/T), with
    w_i = m_i * |D_i| where ``explicit`` supplies per-source MULTIPLIERS
    m_i (default 1) — ``{"ds2": 5.0}`` means 5x ds2's natural share, so a
    user-scale knob never competes against the other sources' raw sample
    counts. Renormalization over exactly the keys of ``sizes`` is how
    weights track sources coming and going (hot add/remove/demotion)."""
    if not sizes:
        return {}
    inv_t = 1.0 / float(temperature)
    raw = {}
    for sid, n in sizes.items():
        base = float(n)
        if explicit and sid in explicit:
            base *= float(explicit[sid])
        raw[sid] = max(base, 0.0) ** inv_t
    total = sum(raw.values())
    if total <= 0:
        raise ValueError(
            f"all mixture source weights collapsed to zero: sizes={sizes}"
        )
    return {sid: w / total for sid, w in raw.items()}


def draw_source(
    seed: int, epoch: int, draw: int, ids: Sequence[int],
    probs: Sequence[float],
) -> int:
    """The source drawn at position ``draw`` of epoch ``epoch`` — pure in
    (seed, epoch, draw) given the active (ids, probs). ``ids``/``probs``
    must be aligned; ids order matters and callers pass them sorted so
    every process agrees."""
    u = np.random.default_rng(
        [int(seed) & 0x7FFFFFFF, int(epoch), int(draw)]
    ).random()
    acc = 0.0
    for sid, p in zip(ids, probs):
        acc += p
        if u < acc:
            return int(sid)
    return int(ids[-1])  # float-sum tail


def source_permutation(
    seed: int, sid: int, epoch: int, pass_idx: int, n: int
) -> np.ndarray:
    """Within-source sample order for one pass — pure in its arguments, so
    a cursor (pass, offset) fully locates the next sample."""
    rng = np.random.default_rng(
        [int(seed) & 0x7FFFFFFF, 0x5EED, int(sid), int(epoch), int(pass_idx)]
    )
    return rng.permutation(int(n))


class SourceCursor:
    """Position inside one source's (epoch-scoped) sample stream."""

    __slots__ = ("pass_idx", "offset")

    def __init__(self, pass_idx: int = 0, offset: int = 0):
        self.pass_idx = int(pass_idx)
        self.offset = int(offset)

    def to_list(self) -> Tuple[int, int]:
        return (self.pass_idx, self.offset)

    @staticmethod
    def from_list(v) -> "SourceCursor":
        return SourceCursor(int(v[0]), int(v[1]))

    def next_index(
        self, seed: int, sid: int, epoch: int, n: int, cache: Optional[dict] = None
    ) -> int:
        """Sample index of the next draw from this source; advances the
        cursor (wrapping into a fresh pure-permutation pass). ``cache`` is
        a PER-SOURCE dict memoizing the live pass's permutation so a draw
        costs O(1) after the first of its pass (stale passes are evicted —
        only the live one is ever re-read)."""
        if n <= 0:
            raise ValueError(f"source {sid} is empty")
        if self.offset >= n:
            self.pass_idx += 1
            self.offset = 0
        key = (int(sid), int(epoch), self.pass_idx)
        perm = cache.get(key) if cache is not None else None
        if perm is None or len(perm) != n:
            perm = source_permutation(seed, sid, epoch, self.pass_idx, n)
            if cache is not None:
                cache.clear()  # one live pass per source is enough
                cache[key] = perm
        idx = int(perm[self.offset])
        self.offset += 1
        return idx
