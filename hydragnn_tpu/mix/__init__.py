"""GFM mixture plane: streaming temperature-sampled multi-dataset training
with per-branch loss balancing, hot source add/remove under the quarantine
policy, and deterministic mixture resume (docs/GFM.md)."""

from .balance import DriftMonitor, branch_loss_weights_from
from .config import MIXTURE_DEFAULTS, resolve_mixture
from .plane import (
    MixtureExhaustedError,
    MixturePlane,
    MixtureSource,
    sources_from_graphs,
)
from .sampler import (
    SourceCursor,
    draw_source,
    source_permutation,
    temperature_weights,
)

__all__ = [
    "DriftMonitor",
    "branch_loss_weights_from",
    "MIXTURE_DEFAULTS",
    "resolve_mixture",
    "MixtureExhaustedError",
    "MixturePlane",
    "MixtureSource",
    "sources_from_graphs",
    "SourceCursor",
    "draw_source",
    "source_permutation",
    "temperature_weights",
]
