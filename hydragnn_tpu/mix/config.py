"""``Mixture`` config section: resolution + validation (docs/GFM.md).

Same eager-validation contract as the ``Serving``/``Telemetry`` sections
(config/config.py): a typo'd key or out-of-range value fails at config load
time, not mid-run; unknown keys warn-and-drop ONCE during completion. The
section is optional — absent means "no mixture plane" and the loaders stay
the plain single-stream ``GraphLoader``s.

Keys (defaults in ``MIXTURE_DEFAULTS``):

- ``temperature``: T > 0 of the source-sampling law p_i ∝ w_i^(1/T)
  (w_i defaults to |D_i|, the dataset size). T=1 reproduces
  proportional-to-size sampling; T→∞ approaches uniform-over-sources —
  the standard multi-corpus temperature knob.
- ``weights``: optional {source name: positive float} MULTIPLIER on the
  |D_i| base weight per source (``{"ds2": 5.0}`` = 5x ds2's natural
  share; still tempered by T, renormalized as sources come and go).
- ``draws_per_epoch``: samples drawn per epoch; 0 (default) = the total
  size of the active sources.
- ``balance``: per-branch loss balancing on/off (default on): static
  per-branch loss weights reach the jitted multibranch step
  (train/loss.py) and per-branch loss scalars feed the drift monitor.
- ``branch_loss_weights``: optional list (one per branch) or
  {branch index: w} of positive static loss weights; default equal.
  Normalized to mean 1 so the total-loss scale is unchanged.
- ``drift_ema_decay``: EMA decay of the per-branch loss tracker
  (mix/balance.DriftMonitor), in [0, 1).
- ``drift_threshold``: branch-EMA / mixture-median ratio beyond which a
  per-branch divergence event (EV_MIX_DRIFT) is emitted; > 1.
- ``demote_after``: per-source draw-time validation failures before the
  source is quarantine-demoted out of the active set (0 disables).
- ``seed``: sampler seed; null = ``Training.seed``.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict

MIXTURE_DEFAULTS: Dict[str, Any] = {
    "temperature": 1.0,
    "weights": None,
    "draws_per_epoch": 0,
    "balance": True,
    "branch_loss_weights": None,
    "drift_ema_decay": 0.9,
    "drift_threshold": 2.0,
    "demote_after": 8,
    "seed": None,
}


def resolve_mixture(config: Dict[str, Any]) -> Dict[str, Any]:
    """Completed ``Mixture`` section from a config dict: defaults filled,
    values validated, unknown keys warned-and-dropped. Raises ``ValueError``
    on out-of-range values — the fail-at-load-time contract."""
    section = dict(config.get("Mixture") or {})
    out = dict(MIXTURE_DEFAULTS)
    for key, val in section.items():
        if key not in MIXTURE_DEFAULTS:
            warnings.warn(
                f"Mixture.{key} is not a known mixture key; ignoring it "
                "(see docs/GFM.md for the Mixture section schema)",
                stacklevel=2,
            )
            continue
        out[key] = val

    t = float(out["temperature"])
    if not t > 0:
        raise ValueError(
            f"Mixture.temperature must be > 0 (got {out['temperature']!r}); "
            "T=1 is proportional-to-size, larger T flattens toward uniform"
        )
    out["temperature"] = t
    if out["weights"] is not None:
        if not isinstance(out["weights"], dict) or not out["weights"]:
            raise ValueError(
                "Mixture.weights must be a non-empty {source name: weight} "
                f"mapping or null, got {out['weights']!r}"
            )
        for name, w in out["weights"].items():
            if not float(w) > 0:
                raise ValueError(
                    f"Mixture.weights[{name!r}] must be positive, got {w!r}"
                )
        out["weights"] = {str(k): float(v) for k, v in out["weights"].items()}
    dpe = int(out["draws_per_epoch"])
    if dpe < 0:
        raise ValueError(
            f"Mixture.draws_per_epoch must be >= 0 (0 = total active source "
            f"size), got {out['draws_per_epoch']!r}"
        )
    out["draws_per_epoch"] = dpe
    out["balance"] = bool(out["balance"])
    blw = out["branch_loss_weights"]
    if blw is not None:
        if isinstance(blw, dict):
            blw = {int(k): float(v) for k, v in blw.items()}
            vals = blw.values()
        elif isinstance(blw, (list, tuple)):
            blw = [float(v) for v in blw]
            vals = blw
        else:
            raise ValueError(
                "Mixture.branch_loss_weights must be a list (one weight per "
                f"branch) or a {{branch index: weight}} mapping, got {blw!r}"
            )
        if any(not v > 0 for v in vals):
            raise ValueError(
                f"Mixture.branch_loss_weights must all be positive: {blw!r}"
            )
        out["branch_loss_weights"] = blw
    decay = float(out["drift_ema_decay"])
    if not (0.0 <= decay < 1.0):
        raise ValueError(
            f"Mixture.drift_ema_decay must be in [0, 1), got {decay!r}"
        )
    out["drift_ema_decay"] = decay
    thr = float(out["drift_threshold"])
    if not thr > 1.0:
        raise ValueError(
            "Mixture.drift_threshold is a ratio vs the mixture median and "
            f"must be > 1, got {thr!r}"
        )
    out["drift_threshold"] = thr
    da = int(out["demote_after"])
    if da < 0:
        raise ValueError(
            f"Mixture.demote_after must be >= 0 (0 disables quarantine "
            f"demotion), got {out['demote_after']!r}"
        )
    out["demote_after"] = da
    if out["seed"] is not None:
        out["seed"] = int(out["seed"])
    return out
