"""Per-branch loss balancing + drift monitoring for mixture training
(docs/GFM.md "Loss balancing").

Static balancing happens IN-GRAPH: ``branch_loss_weights_from`` resolves
the ``Mixture.branch_loss_weights`` setting into a per-branch weight
vector (normalized to mean 1 so the total-loss scale is unchanged) that
config completion plants into the Architecture section; the jitted
multibranch step weights every graph's loss contribution by its branch's
weight (train/loss.py ``multitask_loss``) and emits per-branch loss
scalars (``branch<i>`` task entries) at zero extra host syncs.

Dynamic monitoring happens HOST-SIDE at the epoch boundary: the
``DriftMonitor`` keeps an EMA of each branch's loss and compares it to the
mixture median — a branch whose smoothed loss diverges past
``Mixture.drift_threshold`` × median raises a typed EV_MIX_DRIFT event
(obs/events.py) and a registry gauge, so a collapsing or starved branch is
visible in the flight-recorder window and on /metrics long before the run
"finishes wrong". Monitoring never mutates training (the reference's
uneven-branch process groups have no runtime rebalancer either);
rebalancing stays an operator decision on the surfaced signal.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def branch_loss_weights_from(
    settings: Dict, num_branches: int
) -> Optional[Tuple[float, ...]]:
    """Resolve ``Mixture.branch_loss_weights`` into a per-branch vector of
    length ``num_branches``, normalized to mean 1. Returns None when
    balancing is off (``Mixture.balance: false``) — the loss path then
    stays byte-identical to a non-mixture run."""
    if not settings.get("balance", True):
        return None
    raw = settings.get("branch_loss_weights")
    if raw is None:
        w: List[float] = [1.0] * num_branches
    elif isinstance(raw, dict):
        w = [1.0] * num_branches
        for k, v in raw.items():
            if not 0 <= int(k) < num_branches:
                raise ValueError(
                    f"Mixture.branch_loss_weights names branch {k} but the "
                    f"model has {num_branches} branches"
                )
            w[int(k)] = float(v)
    else:
        w = [float(v) for v in raw]
        if len(w) != num_branches:
            raise ValueError(
                f"Mixture.branch_loss_weights has {len(w)} entries but the "
                f"model has {num_branches} branches"
            )
    mean = sum(w) / len(w)
    return tuple(v / mean for v in w)


class DriftMonitor:
    """EMA tracker of per-branch losses with a divergence alarm."""

    def __init__(self, decay: float = 0.9, threshold: float = 2.0):
        self.decay = float(decay)
        self.threshold = float(threshold)
        self.ema: Dict[int, float] = {}
        self.alarms = 0

    def update(self, epoch: int, losses: Dict[int, float],
               writer=None) -> Dict[int, float]:
        """Fold one epoch's per-branch losses in; returns each branch's
        drift ratio (EMA / mixture median EMA). Publishes gauges and emits
        EV_MIX_DRIFT for branches past the threshold."""
        for b, loss in losses.items():
            prev = self.ema.get(b)
            self.ema[b] = (
                float(loss)
                if prev is None
                else self.decay * prev + (1.0 - self.decay) * float(loss)
            )
        vals = sorted(self.ema[b] for b in losses)
        median = vals[len(vals) // 2] if vals else 0.0
        ratios: Dict[int, float] = {}
        for b in sorted(losses):
            ratios[b] = self.ema[b] / median if median > 0 else 1.0
        try:
            from ..obs.registry import registry

            g_loss = registry().gauge(
                "hydragnn_mix_branch_loss_ema",
                "EMA-smoothed per-branch training loss of the mixture",
                labelnames=("branch",),
            )
            g_drift = registry().gauge(
                "hydragnn_mix_branch_drift",
                "Per-branch loss EMA / mixture median (1.0 = balanced)",
                labelnames=("branch",),
            )
            for b, r in ratios.items():
                g_loss.set(self.ema[b], branch=str(b))
                g_drift.set(r, branch=str(b))
        except Exception:
            pass
        if writer is not None:
            for b, r in ratios.items():
                writer.add_scalar(f"mix/branch_drift_{b}", float(r), epoch)
        for b, r in sorted(ratios.items()):
            if r > self.threshold:
                self.alarms += 1
                try:
                    from ..obs.events import EV_MIX_DRIFT, emit

                    emit(
                        EV_MIX_DRIFT, severity="warn", branch=int(b),
                        ratio=round(float(r), 4), epoch=int(epoch),
                        ema=round(float(self.ema[b]), 6),
                        median=round(float(median), 6),
                    )
                except Exception:
                    pass
        return ratios
