"""MixturePlane: streaming temperature-sampled multi-dataset training
(docs/GFM.md) — the production pipeline behind the GFM workload.

A loader-compatible object (``set_epoch``/``__len__``/``__iter__``/
``spec_template_batches``/``state_dict``/``resume``) that streams N
registered sources (each a list of host ``Graph``s — local, DDStore-backed
datasets materialize to the same interface) through the weighted
temperature-sampled scheduler (mix/sampler.py) and packs every drawn
sample stream through the existing ``SpecLadder`` pad-bucket machinery, so
the compile plane warms exactly the specializations mixture batching can
emit and the retrace sentinel holds in ``error`` mode.

Fault model:

- **dirty sources**: every drawn sample re-validates through the run's
  ``SampleValidator`` (data/validate.py) at draw time — post-ingest rot
  (bit flips at rest, a corrupted shard) is skipped-and-counted per
  source, and a source whose draw-time failures cross
  ``Mixture.demote_after`` is quarantine-DEMOTED out of the active set
  with a typed event (EV_MIX_DEMOTE), not a crash; the remaining weights
  renormalize and the epoch's batch budget is still met.
- **churn**: ``add_source``/``remove_source`` retarget the scheduler at
  the next draw (EV_MIX_SOURCE_ADD/REMOVE); epoch length is frozen at
  epoch start so the step loop never desynchronizes mid-epoch.
- **crashes**: all sampling state is pure-in-integers (mix/sampler.py);
  the durable snapshot (``mixture_state_dict``) is the active set +
  weights + per-source cursors + (epoch, draw, position), serialized
  beside every checkpoint (train/checkpoint.py ``save_mixture_state``)
  and inside the PR 4 loader-state sidecar on a mid-epoch preemption
  stop — a SIGKILL anywhere resumes the exact remaining draw sequence.
- **host loss** (docs/GFM.md "Multi-host and elastic operation"): under
  ``host_count > 1`` every host advances the IDENTICAL absolute draw
  sequence (zero collectives — purity is the coordination) and owns the
  valid samples at global stripe positions ``p % host_count ==
  host_index`` (the GraphLoader/DistributedSampler stripe, applied at
  draw granularity). The snapshot's ``pos`` is the global valid-sample
  position, so a survivor restored at a DIFFERENT host count re-deals
  the remaining positions contiguously: no draw duplicated, none lost.

Observability (obs/): per-source weight/draw/skip gauges and counters in
the registry, demotion/churn/drift events in the event log, a per-epoch
tally line through the loop's ``mixture_epoch_hook``.
"""

from __future__ import annotations

import dataclasses
import sys
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..data.graph import (
    Graph,
    GraphBatch,
    PadSpec,
    SpecLadder,
    _triplet_count,
    batch_graphs,
)
from ..data.pipeline import (
    selectable_levels,
    spec_template_batches as _module_templates,
    stack_shard_batches,
)
from .balance import DriftMonitor
from .sampler import SourceCursor, draw_source, temperature_weights
from ..utils import envflags


class MixtureExhaustedError(RuntimeError):
    """Every mixture source was removed or demoted — nothing left to draw.
    The message carries the demotion history so the operator sees WHY the
    fleet emptied instead of a bare stop."""


@dataclasses.dataclass
class MixtureSource:
    """One registered dataset of the mixture."""

    sid: int
    name: str
    graphs: List[Graph]
    weight: Optional[float] = None  # multiplier on the |D| base (default 1)


def sources_from_graphs(
    graphs: Sequence[Graph], names: Optional[Dict[int, str]] = None
) -> List[MixtureSource]:
    """Group a merged multi-dataset list into per-``dataset_id`` sources —
    the bridge from the repo's existing merged-GFM datasets (examples/
    multidataset*) to the mixture plane."""
    by_id: Dict[int, List[Graph]] = {}
    for g in graphs:
        by_id.setdefault(int(getattr(g, "dataset_id", 0) or 0), []).append(g)
    out = []
    for sid in sorted(by_id):
        name = (names or {}).get(sid, f"ds{sid}")
        out.append(MixtureSource(sid=sid, name=name, graphs=by_id[sid]))
    return out


def _fingerprint(graphs: Sequence[Graph], sids: Sequence[int]) -> str:
    """Cheap stable digest of one batch's sample content + source draw
    sequence — the bit-exact-resume assertion currency of
    run-scripts/mix_chaos_smoke.py."""
    h = zlib.crc32(np.asarray(sids, np.int64).tobytes())
    for g in graphs:
        h = zlib.crc32(np.ascontiguousarray(np.asarray(g.x, np.float64)).tobytes(), h)
    return f"{h:08x}"


class MixturePlane:
    """Temperature-sampled multi-source training stream.

    ``settings`` is a resolved ``Mixture`` section (mix/config.py).
    ``spec`` is the run's PadSpec/SpecLadder (shared with the eval loaders
    so every specialization is reused); None derives a ladder from the
    registered sources. ``validator`` is the run's SampleValidator — draw
    gating and demotion degrade gracefully to "trust the ingest gate" when
    it is None.
    """

    # loader-compat surface consumed by the loop / api
    pack = False

    def __init__(
        self,
        sources: Sequence[MixtureSource],
        batch_size: int,
        settings: Dict[str, Any],
        spec=None,
        seed: int = 0,
        sort_edges: bool = False,
        validator=None,
        num_buckets: int = 1,
        host_count: int = 1,
        host_index: int = 0,
        num_shards: int = 1,
    ):
        if not sources:
            raise ValueError("MixturePlane needs at least one source")
        self.batch_size = int(batch_size)
        # local device shards: batches stack to [num_shards, ...] rows like
        # the stacked GraphLoader (data/pipeline.py)
        self.num_shards = max(int(num_shards), 1)
        if self.batch_size % self.num_shards:
            raise ValueError(
                f"mixture batch_size {self.batch_size} must divide evenly "
                f"across {self.num_shards} local device shards"
            )
        # multi-host draw stripe (GraphLoader/DistributedSampler semantics
        # at draw granularity): every host runs the identical absolute draw
        # sequence and keeps the valid samples at global positions
        # p % host_count == host_index — disjoint stripes, zero collectives
        self.host_count = max(int(host_count), 1)
        self.host_index = int(host_index)
        if not 0 <= self.host_index < self.host_count:
            raise ValueError(
                f"mixture host_index {self.host_index} out of range for "
                f"host_count {self.host_count}"
            )
        self.settings = dict(settings)
        self.temperature = float(settings.get("temperature", 1.0))
        self.demote_after = int(settings.get("demote_after", 0) or 0)
        self.draws_per_epoch = int(settings.get("draws_per_epoch", 0) or 0)
        self.seed = int(
            settings.get("seed") if settings.get("seed") is not None else seed
        )
        self.sort_edges = bool(sort_edges)
        self.validator = validator
        self.sources: Dict[int, MixtureSource] = {}
        self.demoted: Dict[int, str] = {}  # sid -> demotion reason
        self._explicit_weights: Dict[int, float] = {}
        self.weights: Dict[int, float] = {}
        # spec/ladder: shared with the eval loaders when the caller passes
        # the run ladder (api.prepare_data), else derived from the sources
        all_graphs = [g for s in sources for g in s.graphs]
        if spec is None:
            self.ladder = SpecLadder.for_dataset(
                all_graphs, self.batch_size, num_buckets=max(num_buckets, 1)
            )
        elif isinstance(spec, SpecLadder):
            self.ladder = spec
        else:
            self.ladder = SpecLadder((spec,))
        if self.host_count > 1 and len(self.ladder.specs) > 1:
            # multi-host: each host's stripe draws different graphs, so
            # per-batch ladder level selection would diverge across hosts
            # while the global array needs identical shapes — collapse to
            # the worst level instead of paying a per-batch collective
            # (the BranchRoutedLoader rule, parallel/routing.py)
            self.ladder = SpecLadder((self.ladder.specs[-1],))
        self.spec = self.ladder.specs[-1]
        # mixture position: epoch is the ABSOLUTE mixture epoch (a resumed
        # process maps its local epoch loop through _epoch_offset so the
        # draw sequence continues where the killed run's left off)
        self.epoch = 0
        self.start_batch = 0
        self._epoch_offset = 0
        self._resume: Optional[Tuple[int, int]] = None
        self.cursors: Dict[int, SourceCursor] = {}
        self._perm_caches: Dict[int, dict] = {}
        self._armed_cursors: Optional[Dict[int, SourceCursor]] = None
        self._armed_draw: Optional[int] = None
        # global valid-sample position paired with the armed cursors (the
        # stripe-resume currency); _replay_pos is the cursor-less fallback:
        # skip-replay to an exact POSITION, not a batch count, so a resume
        # onto a different host_count never re-consumes a stripe slot
        self._armed_pos: Optional[int] = None
        self._replay_pos: Optional[int] = None
        # per-run accounting (per-source; epoch tallies reset by the hook)
        self.epoch_draws: Dict[int, int] = {}
        self.epoch_skips: Dict[int, int] = {}
        self.fail_counts: Dict[int, int] = {}
        self._fail_seen: set = set()
        self.drift = DriftMonitor(
            decay=float(settings.get("drift_ema_decay", 0.9)),
            threshold=float(settings.get("drift_threshold", 2.0)),
        )
        self._fingerprint = bool(envflags.env_force("HYDRAGNN_MIX_FINGERPRINT"))
        # per-batch position journal of the CURRENT epoch: batch index ->
        # (draw, cursors) at that batch's first draw. state_dict(next_batch)
        # reads the journal so a snapshot pairs the cursor state with the
        # checkpoint's batch index even when device_prefetch built ahead
        self._journal: Dict[int, Dict[str, Any]] = {}
        # per-graph triplet counts (DimeNet ladders budget them), memoized
        # by object id — _triplet_count is O(E) interpreted python
        self._trip_memo: Dict[int, int] = {}
        for s in sources:
            self._register(s, event=False)
        explicit = settings.get("weights") or {}
        for key, w in explicit.items():
            sid = self._sid_of(key)
            if sid is None:
                raise ValueError(
                    f"Mixture.weights names unknown source {key!r}; "
                    f"registered: {[s.name for s in self.sources.values()]}"
                )
            self._explicit_weights[sid] = float(w)
        self._refresh_weights()

    # -- source registry ----------------------------------------------------

    def _sid_of(self, key) -> Optional[int]:
        """Source id from a name or an integer-ish key."""
        for s in self.sources.values():
            if s.name == str(key):
                return s.sid
        try:
            sid = int(key)
        except (TypeError, ValueError):
            return None
        return sid if sid in self.sources else None

    def _register(self, source: MixtureSource, event: bool = True) -> None:
        if source.sid in self.sources:
            raise ValueError(f"duplicate mixture source id {source.sid}")
        graphs = list(source.graphs)
        if self.validator is not None:
            worst = self.spec
            graphs = self.validator.filter(
                graphs,
                source=f"mix:{source.name}",
                max_nodes=worst.n_nodes - 1,
                max_edges=worst.n_edges,
            )
        if not graphs:
            raise ValueError(
                f"mixture source {source.name!r} has no valid samples"
            )
        self.sources[source.sid] = dataclasses.replace(source, graphs=graphs)
        if source.weight is not None:
            self._explicit_weights[source.sid] = float(source.weight)
        self.cursors.setdefault(source.sid, SourceCursor())
        self._perm_caches.setdefault(source.sid, {})
        if event:
            self._emit(
                "mix_source_add", severity="info", source=source.name,
                sid=source.sid, size=len(graphs),
            )

    def add_source(self, name: str, graphs: Sequence[Graph],
                   weight: Optional[float] = None) -> int:
        """Hot-add a dataset mid-run; takes effect at the next draw (this
        epoch's batch count stays frozen). Returns the new source id."""
        sid = max(list(self.sources) + list(self.demoted) + [-1]) + 1
        self._register(MixtureSource(sid, str(name), list(graphs), weight))
        self._refresh_weights()
        return sid

    def remove_source(self, key) -> None:
        """Hot-remove a dataset mid-run (operator decision, e.g. a corpus
        recalled for licensing); remaining weights renormalize at the next
        draw."""
        sid = self._sid_of(key)
        if sid is None:
            raise KeyError(f"no mixture source {key!r}")
        src = self.sources.pop(sid)
        self._explicit_weights.pop(sid, None)
        self._refresh_weights()
        self._emit(
            "mix_source_remove", severity="info", source=src.name, sid=sid,
            remaining=len(self.sources),
        )

    def _demote(self, sid: int, reason: str) -> None:
        src = self.sources.pop(sid)
        self._explicit_weights.pop(sid, None)
        self.demoted[sid] = reason
        self._refresh_weights()
        self._emit(
            "mix_demote", severity="error", source=src.name, sid=sid,
            reason=reason, failures=self.fail_counts.get(sid, 0),
            remaining=len(self.sources),
        )
        print(
            f"[hydragnn_tpu.mix] source {src.name!r} (id {sid}) quarantine-"
            f"demoted after {self.fail_counts.get(sid, 0)} draw-time "
            f"validation failures ({reason}); {len(self.sources)} source(s) "
            "remain active",
            file=sys.stderr,
        )

    def _refresh_weights(self) -> None:
        sizes = {sid: len(s.graphs) for sid, s in self.sources.items()}
        self.weights = temperature_weights(
            sizes, self.temperature, self._explicit_weights
        ) if sizes else {}
        self._publish_gauges()

    # -- observability -------------------------------------------------------

    def _emit(self, kind: str, severity: str = "info", **attrs) -> None:
        try:
            from ..obs.events import emit as _emit_event

            _emit_event(kind, severity=severity, **attrs)
        except Exception:
            pass

    def _publish_gauges(self) -> None:
        try:
            from ..obs.registry import registry

            g_w = registry().gauge(
                "hydragnn_mix_source_weight",
                "Normalized temperature-sampled draw probability per source",
                labelnames=("source",),
            )
            for sid, s in self.sources.items():
                g_w.set(self.weights.get(sid, 0.0), source=s.name)
            registry().gauge(
                "hydragnn_mix_active_sources",
                "Mixture sources currently in the active set",
            ).set(len(self.sources))
            registry().gauge(
                "hydragnn_mix_demoted_sources",
                "Mixture sources quarantine-demoted out of the active set",
            ).set(len(self.demoted))
        except Exception:
            pass

    def _count_draw(self, sid: int) -> None:
        self.epoch_draws[sid] = self.epoch_draws.get(sid, 0) + 1
        try:
            from ..obs.registry import registry

            registry().counter(
                "hydragnn_mix_draws_total",
                "Samples drawn from each mixture source",
                labelnames=("source",),
            ).inc(source=self.sources[sid].name)
        except Exception:
            pass

    def _count_skip(self, sid: int, name: str) -> None:
        self.epoch_skips[sid] = self.epoch_skips.get(sid, 0) + 1
        try:
            from ..obs.registry import registry

            registry().counter(
                "hydragnn_mix_skips_total",
                "Draw-time validation failures per mixture source",
                labelnames=("source",),
            ).inc(source=name)
        except Exception:
            pass

    # -- loader surface ------------------------------------------------------

    @property
    def graphs(self) -> List[Graph]:
        """Flat view over the active sources (loader-compat: consumers size
        plots/ladders off ``loader.graphs``)."""
        return [g for s in self.sources.values() for g in s.graphs]

    def _epoch_draw_budget(self) -> int:
        if self.draws_per_epoch > 0:
            return self.draws_per_epoch
        return sum(len(s.graphs) for s in self.sources.values())

    def __len__(self) -> int:
        # per-HOST batch count: the global valid-sample budget divides over
        # host_count equal stripes (equal per-host step counts keep a real
        # multi-host mesh in lockstep — GraphLoader truncates identically)
        return max(
            self._epoch_draw_budget() // (self.batch_size * self.host_count),
            1,
        )

    def set_epoch(self, epoch: int) -> None:
        """Per-epoch reseed. The first call after ``resume()`` keeps the
        armed (epoch, cursor); later calls map the local epoch counter
        through the resume offset so a restarted process CONTINUES the
        original run's epoch sequence instead of replaying epoch 0."""
        if self._resume is not None:
            self.epoch, self.start_batch = self._resume
            self._resume = None
        else:
            self.epoch = int(epoch) + self._epoch_offset
            self.start_batch = 0
            self._armed_cursors = None
            self._armed_draw = None
            self._armed_pos = None
            self._replay_pos = None

    def resume(self, epoch: int, next_batch: int) -> None:
        """Arm deterministic resume at absolute mixture position
        (``epoch``, ``next_batch``) — applied immediately AND kept through
        the loop's next ``set_epoch`` (the GraphLoader one-shot contract);
        later epochs continue the absolute numbering."""
        self.epoch = int(epoch)
        self.start_batch = int(next_batch)
        self._resume = (int(epoch), int(next_batch))
        self._epoch_offset = int(epoch)

    def state_dict(self, next_batch: int = 0) -> Dict[str, Any]:
        """Loader-state record (train/state.LoaderState shape) extended
        with the mixture snapshot — what the mid-epoch preemption sidecar
        persists (docs/GFM.md "Resume")."""
        return {
            "seed": int(self.seed),
            "epoch": int(self.epoch),
            "next_batch": int(next_batch),
            "num_batches": int(len(self)),
            "mixture": self.mixture_state_dict(next_batch=int(next_batch)),
        }

    def mixture_state_dict(self, next_batch: Optional[int] = None) -> Dict[str, Any]:
        """Durable mixture snapshot: active/demoted sets, explicit weights,
        per-source cursors, absolute (epoch, draw). Saved beside every
        checkpoint (api.py save_fn -> train/checkpoint.save_mixture_state).
        ``next_batch`` selects the journal entry whose cursors produce
        exactly that batch — NOT the live cursors, which device_prefetch's
        lookahead may have advanced past the checkpointed step."""
        draw = None
        pos = None
        cursors = self.cursors
        if next_batch is not None and int(next_batch) in self._journal:
            entry = self._journal[int(next_batch)]
            draw = int(entry["draw"])
            pos = int(entry["pos"])
            cursors = entry["cursors"]
        return {
            "epoch": int(self.epoch),
            "next_batch": int(next_batch) if next_batch is not None else None,
            "draw": draw,
            # global valid-sample position + the stripe layout that wrote
            # it: restore maps pos onto the RESTORING layout, so a shrunk
            # or regrown fleet re-deals the remaining stripe exactly
            "pos": pos,
            "host_count": int(self.host_count),
            "host_index": int(self.host_index),
            "active": sorted(self.sources),
            "demoted": {str(k): v for k, v in sorted(self.demoted.items())},
            "weights": {str(k): float(v) for k, v in self._explicit_weights.items()},
            "cursors": {
                str(sid): list(c.to_list()) for sid, c in sorted(cursors.items())
            },
            # failure accounting rides the snapshot: without it a resumed
            # run's demotion would fire demote_after NEW failures later
            # than the uninterrupted run's, diverging the draw sequence
            "fail_counts": {
                str(k): int(v) for k, v in sorted(self.fail_counts.items())
            },
            "fail_seen": sorted([int(s), int(i)] for s, i in self._fail_seen),
            "names": {str(sid): s.name for sid, s in sorted(self.sources.items())},
        }

    def restore_mixture(self, snap: Dict[str, Any],
                        mid_epoch: bool = False) -> None:
        """Re-arm the plane from a durable snapshot.

        ``mid_epoch=True`` (the loader-state sidecar path) additionally
        restores the per-source cursors + draw index AT the cursor, so the
        armed (epoch, next_batch) resumes without any skip-replay;
        otherwise (the epoch-boundary ``mixture_state.json`` path) only the
        source topology is restored — the next epoch starts at
        ``snap['epoch'] + 1``, cursors fresh (they are epoch-scoped)."""
        if not snap:
            return
        active = {int(s) for s in snap.get("active", [])}
        missing = active - set(self.sources) - set(
            int(k) for k in snap.get("demoted", {})
        )
        if missing:
            raise ValueError(
                f"mixture snapshot names source ids {sorted(missing)} that "
                "are not registered in this run — the source fleet changed "
                "incompatibly; delete the mixture sidecar to start fresh"
            )
        # replay removals/demotions the snapshot had already taken
        for sid in list(self.sources):
            if sid not in active:
                reason = snap.get("demoted", {}).get(str(sid))
                self.sources.pop(sid)
                self._explicit_weights.pop(sid, None)
                if reason is not None:
                    self.demoted[sid] = str(reason)
        for k, v in (snap.get("weights") or {}).items():
            if int(k) in self.sources:
                self._explicit_weights[int(k)] = float(v)
        for k, v in (snap.get("demoted") or {}).items():
            self.demoted.setdefault(int(k), str(v))
        for k, v in (snap.get("fail_counts") or {}).items():
            self.fail_counts[int(k)] = max(
                self.fail_counts.get(int(k), 0), int(v)
            )
        for s, i in snap.get("fail_seen") or []:
            self._fail_seen.add((int(s), int(i)))
        self._refresh_weights()
        if mid_epoch:
            pos = snap.get("pos")
            # a stripe re-deal: the snapshot was written under a different
            # (host_count, host_index) layout. At a coordinated checkpoint
            # (every old host at local batch k) the UNION of the old
            # stripes' consumed positions is exactly [0, k * batch_size *
            # old_host_count) — a host's own trajectory ``pos`` trails
            # that boundary by up to one stride, and resuming from it
            # would re-consume positions the OTHER old stripes already
            # took. So the re-deal advances to the boundary and deals the
            # remaining positions over THIS layout: no draw duplicated,
            # none lost.
            relayout = (
                int(snap.get("host_count", 1)) != self.host_count
                or int(snap.get("host_index", 0)) != self.host_index
            ) and (pos is not None or snap.get("next_batch") is not None)
            if snap.get("draw") is not None:
                self._armed_cursors = {
                    int(k): SourceCursor.from_list(v)
                    for k, v in (snap.get("cursors") or {}).items()
                }
                self._armed_draw = int(snap["draw"])
                self._armed_pos = int(pos) if pos is not None else None
            # a snapshot without a draw index (journal miss) falls back to
            # deterministic skip-replay — by exact position across a
            # layout change, by batch count otherwise (same sequence by
            # purity either way)
            if relayout:
                stride_old = self.batch_size * max(
                    int(snap.get("host_count", 1)), 1
                )
                if snap.get("next_batch") is not None:
                    boundary = int(snap["next_batch"]) * stride_old
                else:
                    boundary = -(-int(pos) // stride_old) * stride_old
                self._replay_pos = boundary
                stride = self.batch_size * self.host_count
                local = min(boundary // stride, max(len(self) - 1, 0))
                self.resume(int(snap["epoch"]), local)
            elif self._resume is None and snap.get("next_batch") is not None:
                self.resume(int(snap["epoch"]), int(snap["next_batch"]))
        else:
            # epoch-boundary snapshot: continue the absolute epoch sequence
            self._epoch_offset = int(snap.get("epoch", -1)) + 1
            self.epoch = self._epoch_offset

    # -- the draw/batch stream ----------------------------------------------

    def _draw_one(self, epoch: int, draw: int, cursors: Dict[int, SourceCursor]):
        """One scheduler draw -> (sid, graph) after validation, or
        (sid, None) for a skipped draw (the draw index is consumed either
        way — that is what keeps resume exact across skips)."""
        if not self.sources:
            raise MixtureExhaustedError(
                "every mixture source was removed or quarantine-demoted "
                f"(demotions: {self.demoted or 'none'}); nothing left to draw"
            )
        ids = sorted(self.sources)
        probs = [self.weights[sid] for sid in ids]
        sid = draw_source(self.seed, epoch, draw, ids, probs)
        src = self.sources[sid]
        cur = cursors.setdefault(sid, SourceCursor())
        idx = cur.next_index(
            self.seed, sid, epoch, len(src.graphs),
            cache=self._perm_caches.setdefault(sid, {}),
        )
        g = src.graphs[idx]
        if self.validator is not None:
            from ..data.validate import validate_graph

            reason = validate_graph(
                g, max_nodes=self.spec.n_nodes - 1, max_edges=self.spec.n_edges
            )
            if reason is not None:
                self.validator.reject(
                    g, idx, reason, source=f"mix:{src.name}",
                    detail=f"draw-time validation, epoch {epoch} draw {draw}",
                )
                self._count_skip(sid, src.name)
                # plane-level failure accounting dedups per sample so a
                # small source's one bad graph redrawn every epoch does not
                # demote it by repetition alone
                key = (sid, idx)
                if key not in self._fail_seen:
                    self._fail_seen.add(key)
                    self.fail_counts[sid] = self.fail_counts.get(sid, 0) + 1
                    if (
                        self.demote_after
                        and self.fail_counts[sid] >= self.demote_after
                    ):
                        self._demote(sid, reason)
                return sid, None
        self._count_draw(sid)
        return sid, g

    def _trip_count_of(self, g: Graph) -> int:
        got = self._trip_memo.get(id(g))
        if got is None:
            got = _triplet_count(g)
            self._trip_memo[id(g)] = got
        return got

    def _fill_batch(self, epoch: int, draw: int, pos: int,
                    cursors: Dict[int, SourceCursor], build: bool):
        """Consume draws until ``batch_size`` valid samples landed on THIS
        host's stripe (every valid draw advances the global position
        ``pos``; position p belongs to host ``p % host_count``). Returns
        (graphs, sids, draw', pos'); ``build=False`` advances position
        only (the skip-replay path of a cursor-less resume — validation,
        demotion, and tallies still run so the replay reproduces the
        original run's side effects deterministically). Single-host is the
        degenerate stripe: every position is owned, pos == samples
        consumed."""
        graphs: List[Graph] = []
        sids: List[int] = []
        filled = 0
        # safety valve: with demotion disabled (demote_after=0) a fully
        # rotted fleet would otherwise skip-draw forever
        budget = self.batch_size * self.host_count + max(
            20 * sum(len(s.graphs) for s in self.sources.values()), 1000
        )
        attempts = 0
        while filled < self.batch_size:
            if attempts > budget:
                raise MixtureExhaustedError(
                    f"{attempts} consecutive draws produced only {filled} "
                    f"valid samples (skips per source: {self.epoch_skips}); "
                    "the active sources are effectively all-invalid — fix "
                    "the data or enable Mixture.demote_after"
                )
            attempts += 1
            sid, g = self._draw_one(epoch, draw, cursors)
            draw += 1
            if g is None:
                continue
            mine = pos % self.host_count == self.host_index
            pos += 1
            if mine:
                filled += 1
                if build:
                    graphs.append(g)
                    sids.append(sid)
        return graphs, sids, draw, pos

    def _advance_to(self, epoch: int, target: int, draw: int, pos: int,
                    cursors: Dict[int, SourceCursor]) -> Tuple[int, int]:
        """Skip-replay from (draw, pos) to an exact global valid-sample
        position — the layout-change resume path (from zero when cursor-
        less, from the armed trajectory to the old layout's union boundary
        otherwise). Returns (draw, pos) at the target."""
        budget = 20 * max(int(target) - int(pos), 1) + max(
            20 * sum(len(s.graphs) for s in self.sources.values()), 1000
        )
        attempts = 0
        while pos < target:
            if attempts > budget:
                raise MixtureExhaustedError(
                    f"{attempts} replay draws reached only position {pos} "
                    f"of {target} (skips per source: {self.epoch_skips}); "
                    "the active sources are effectively all-invalid"
                )
            attempts += 1
            _, g = self._draw_one(epoch, draw, cursors)
            draw += 1
            if g is not None:
                pos += 1
        return draw, pos

    def _iter_raw(
        self, n_batches: Optional[int] = None
    ) -> Iterator[Tuple[int, List[Graph], List[int]]]:
        """Yield ``(b, graphs, sids)`` raw sample batches of this host's
        stripe with full resume/journal/fingerprint bookkeeping — the
        shared core of ``__iter__`` and the branch-routed mixture driver
        (parallel/routing.py), which stacks rows from several planes itself
        and passes its own globally-agreed ``n_batches`` (mixture sources
        cycle, so a plane can serve more batches than its own ``len``)."""
        epoch = self.epoch
        if n_batches is None:
            n_batches = len(self)
        start = max(int(self.start_batch), 0)
        self._journal = {}
        if self._armed_cursors is not None:
            # sidecar resume: cursors + draw + position restored AT the
            # armed batch (a missing position is a pre-stripe snapshot —
            # single-host, where position == batches * batch_size)
            cursors = {k: SourceCursor(*c.to_list())
                       for k, c in self._armed_cursors.items()}
            draw = int(self._armed_draw or 0)
            pos = (
                int(self._armed_pos)
                if self._armed_pos is not None
                else start * self.batch_size * self.host_count
            )
            self._armed_cursors = None
            self._armed_draw = None
            self._armed_pos = None
        else:
            cursors = {sid: SourceCursor() for sid in self.sources}
            draw = 0
            pos = 0
            if self._replay_pos is None:
                for _ in range(start):  # cursor-less resume: replay only
                    _, _, draw, pos = self._fill_batch(
                        epoch, draw, pos, cursors, build=False
                    )
        if self._replay_pos is not None and pos < self._replay_pos:
            # layout-change resume: advance to the old layout's union
            # boundary by exact global position, not the old batch grid
            draw, pos = self._advance_to(
                epoch, self._replay_pos, draw, pos, cursors
            )
        self._replay_pos = None
        self.cursors = cursors
        for b in range(start, n_batches):
            self._journal[b] = {
                "draw": draw,
                "pos": pos,
                "cursors": {k: SourceCursor(*c.to_list())
                            for k, c in cursors.items()},
            }
            d0, p0 = draw, pos
            graphs, sids, draw, pos = self._fill_batch(
                epoch, draw, pos, cursors, True
            )
            # batch provenance for the guard/numerics planes: which sources
            # this batch drew from, keyed by batch index — prefetch builds
            # ahead of consumption, so "last batch" would lie (batch_sources)
            self._journal[b]["sids"] = sorted(set(sids))
            # the position AFTER this batch too: a preemption cursor can
            # point one past the last batch built (lookahead == 0)
            self._journal[b + 1] = {
                "draw": draw,
                "pos": pos,
                "cursors": {k: SourceCursor(*c.to_list())
                            for k, c in cursors.items()},
            }
            if self._fingerprint:
                print(
                    f"MIXBATCH e{epoch} b{b} {_fingerprint(graphs, sids)}",
                    flush=True,
                )
                # the stripe audit line (run-scripts/elastic_smoke.py):
                # half-open global position/draw spans this batch consumed.
                # Every host replays the full sequence, so spans overlap
                # across hosts — it is the OWNED positions inside them
                # (p % host_count == host_index) that partition [0, end)
                print(
                    f"MIXSTRIPE e{epoch} b{b} "
                    f"h{self.host_index}/{self.host_count} "
                    f"p{p0}:{pos} d{d0}:{draw}",
                    flush=True,
                )
            yield b, graphs, sids

    def __iter__(self) -> Iterator[GraphBatch]:
        with_trip = bool(self.spec.n_triplets)
        for _, graphs, _sids in self._iter_raw():
            if self.num_shards == 1:
                spec = self.ladder.select(
                    sum(g.num_nodes for g in graphs),
                    sum(g.num_edges for g in graphs),
                    sum(self._trip_count_of(g) for g in graphs)
                    if with_trip
                    else 0,
                )
                yield batch_graphs(graphs, spec, sort_edges=self.sort_edges)
                continue
            shards = [
                graphs[s :: self.num_shards] for s in range(self.num_shards)
            ]
            # one spec for the whole stacked batch: the smallest level
            # fitting the largest shard (all rows share static shapes)
            spec = self.ladder.select(
                max(sum(g.num_nodes for g in s) for s in shards if s),
                max(sum(g.num_edges for g in s) for s in shards if s),
                max(
                    (sum(self._trip_count_of(g) for g in s)
                     for s in shards if s),
                    default=0,
                )
                if with_trip
                else 0,
            )
            yield stack_shard_batches(
                shards, spec, self.num_shards, sort_edges=self.sort_edges
            )

    def batch_sources(self, b) -> Optional[List[int]]:
        """Source ids batch ``b`` of the CURRENT epoch drew from, or None
        before the batch was built. The loop attaches this to guard-skip /
        numerics-provenance events (train/loop.py) so a poisoned source is
        identifiable from the event stream alone (ISSUE 12 satellite)."""
        entry = self._journal.get(int(b))
        if entry is None:
            return None
        sids = entry.get("sids")
        return list(sids) if sids else None

    def spec_template_batches(self) -> List[Tuple[PadSpec, GraphBatch]]:
        """Warm-up templates over the ladder levels any mixture batch can
        select — every source contributes its fitting graphs, so a level
        only one small source can reach is still covered (the compile
        plane's zero-retrace contract). Stacked (multi-shard) planes pad
        the extra shard rows, mirroring the stacked GraphLoader."""
        if self.num_shards == 1:
            return _module_templates(
                self.graphs, self.ladder, sort_edges=self.sort_edges
            )
        out: List[Tuple[PadSpec, GraphBatch]] = []
        for li, g in selectable_levels(
            self.graphs, self.ladder, self._trip_count_of
        ):
            spec = self.ladder.specs[li]
            shards = [[g]] + [[] for _ in range(self.num_shards - 1)]
            out.append((
                spec,
                stack_shard_batches(
                    shards, spec, self.num_shards,
                    sort_edges=self.sort_edges,
                ),
            ))
        return out

    # -- epoch boundary hook (train/loop.py) ---------------------------------

    def mixture_epoch_hook(self, epoch: int, tasks: Dict[str, float],
                           writer=None, verbosity: int = 0,
                           log_name: str = "run") -> None:
        """Called by the epoch loop after each training epoch: logs the
        per-source draw/skip tally, feeds the per-branch losses (the
        ``branch<i>`` task scalars the balanced loss emits) into the drift
        monitor, and mirrors both into the metrics writer."""
        tally = ", ".join(
            f"{self.sources[sid].name}={self.epoch_draws.get(sid, 0)}"
            + (
                f"(-{self.epoch_skips[sid]} skipped)"
                if self.epoch_skips.get(sid)
                else ""
            )
            for sid in sorted(self.sources)
        )
        demoted = (
            f"; demoted: {[self.demoted[k] for k in sorted(self.demoted)]}"
            if self.demoted
            else ""
        )
        if verbosity > 0 or self.epoch_skips or self.demoted:
            print(
                f"[{log_name}] epoch {epoch}: mixture draws: "
                f"{tally or 'none'}{demoted}",
                file=sys.stderr,
            )
        if writer is not None:
            for sid in sorted(self.sources):
                name = self.sources[sid].name
                writer.add_scalar(
                    f"mix/draws_{name}", float(self.epoch_draws.get(sid, 0)),
                    epoch,
                )
                writer.add_scalar(
                    f"mix/weight_{name}", float(self.weights.get(sid, 0.0)),
                    epoch,
                )
        branch_losses = {
            int(k[len("branch"):]): float(v)
            for k, v in tasks.items()
            if k.startswith("branch") and k[len("branch"):].isdigit()
        }
        if branch_losses:
            self.drift.update(epoch, branch_losses, writer=writer)
        self.epoch_draws = {}
        self.epoch_skips = {}
