"""Prometheus text exposition + the scrape/health HTTP endpoint.

``render_text`` serializes a ``MetricsRegistry`` in the Prometheus text
exposition format (version 0.0.4: ``# HELP``/``# TYPE`` headers, one
``name{labels} value`` line per sample, histogram ``_bucket``/``_sum``/
``_count`` series). ``TelemetryHTTPServer`` is the stdlib HTTP surface both
planes mount it on:

- ``/metrics``  — the scrape endpoint (text/plain; version=0.0.4)
- ``/healthz``  — liveness: 200 while the owning process is serving its
  purpose, 503 with a JSON detail once it has failed
- ``/readyz``   — readiness: 200 only once the owner's warm-up contract
  holds (for ``GraphServer`` that is the full-ladder warm-up flip — the
  same event that opens the serve loop; for training it is simply "loop
  running"). Load balancers route on this, so it must never report ready
  before the zero-retrace steady state is established.

Mandatory on ``GraphServer`` (``Serving.http_port``, default 0 = ephemeral
loopback port), opt-in for training (``Telemetry.http_port``). Binding is
best-effort at the call sites: an occupied port degrades to a warning —
losing the scrape surface must never take down training or serving.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from .registry import MetricsRegistry, registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def render_text(reg: Optional[MetricsRegistry] = None) -> str:
    """The registry in Prometheus text exposition format 0.0.4."""
    reg = reg if reg is not None else registry()
    lines = []
    for metric in reg.collect():
        if metric.help:
            lines.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        for suffix, labels, value in metric.samples():
            if labels:
                lab = ",".join(
                    f'{k}="{_escape_label(v)}"' for k, v in labels
                )
                lines.append(
                    f"{metric.name}{suffix}{{{lab}}} {_format_value(value)}"
                )
            else:
                lines.append(f"{metric.name}{suffix} {_format_value(value)}")
    return "\n".join(lines) + "\n"


class TelemetryHTTPServer:
    """Daemon-threaded scrape/health endpoint over a registry.

    ``ready_fn`` -> bool drives ``/readyz``; ``health_fn`` -> (ok, detail)
    drives ``/healthz``. Both are called per request on the handler thread,
    so they must be cheap and lock-free (the call sites pass Event checks).
    ``port=0`` binds an ephemeral port — read it back from ``.port``.

    ``post_routes`` maps a path to ``body_bytes -> (status, json_dict)`` —
    the fleet collector mounts its push sink here (obs/fleet.py), so the
    cross-host push rides the same HTTP substrate the scrape endpoint
    already owns instead of a second server stack. A handler exception
    returns 500 with the error named; there is no handler = 404, matching
    the GET side.
    """

    def __init__(
        self,
        reg: Optional[MetricsRegistry] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        ready_fn: Optional[Callable[[], bool]] = None,
        health_fn: Optional[Callable[[], Tuple[bool, str]]] = None,
        post_routes: Optional[
            Dict[str, Callable[[bytes], Tuple[int, dict]]]
        ] = None,
    ):
        self._registry = reg if reg is not None else registry()
        self._ready_fn = ready_fn or (lambda: True)
        self._health_fn = health_fn or (lambda: (True, "ok"))
        self._post_routes = dict(post_routes or {})
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: no per-scrape spam
                pass

            def _send(self, status: int, body: bytes, ctype: str) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            render_text(outer._registry).encode("utf-8"),
                            CONTENT_TYPE,
                        )
                    elif path == "/healthz":
                        ok, detail = outer._health_fn()
                        self._send(
                            200 if ok else 503,
                            json.dumps(
                                {"status": "ok" if ok else "unhealthy",
                                 "detail": detail}
                            ).encode("utf-8"),
                            "application/json",
                        )
                    elif path == "/readyz":
                        ready = bool(outer._ready_fn())
                        self._send(
                            200 if ready else 503,
                            json.dumps(
                                {"status": "ready" if ready else "not_ready"}
                            ).encode("utf-8"),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:  # client went away mid-scrape
                    pass

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                try:
                    handler = outer._post_routes.get(path)
                    if handler is None:
                        self._send(404, b"not found\n", "text/plain")
                        return
                    length = int(self.headers.get("Content-Length", 0) or 0)
                    body = self.rfile.read(length) if length else b""
                    try:
                        status, payload = handler(body)
                    except Exception as e:  # handler bug != dead endpoint
                        status, payload = 500, {
                            "error": f"{type(e).__name__}: {e}"
                        }
                    self._send(
                        status,
                        json.dumps(payload).encode("utf-8"),
                        "application/json",
                    )
                except BrokenPipeError:  # client went away mid-reply
                    pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            daemon=True,
            name="telemetry-http",
        )
        self._thread.start()

    def add_post_route(
        self, path: str, handler: Callable[[bytes], Tuple[int, dict]]
    ) -> None:
        """Mount (or replace) a POST handler after construction — the
        serving replica mounts /predict and /reload on the endpoint
        ``GraphServer.start`` already opened, instead of a second server
        stack. Dict assignment is atomic under the GIL, so mounting while
        handler threads are serving is safe."""
        self._post_routes[str(path)] = handler

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # teardown must never raise past the owner
            pass
        self._thread.join(timeout=2.0)


def start_endpoint(
    port: int,
    ready_fn: Optional[Callable[[], bool]] = None,
    health_fn: Optional[Callable[[], Tuple[bool, str]]] = None,
    reg: Optional[MetricsRegistry] = None,
    label: str = "telemetry",
    host: str = "127.0.0.1",
) -> Optional[TelemetryHTTPServer]:
    """Best-effort endpoint construction: a bind failure (occupied port,
    no loopback) warns and returns None — the scrape surface is an
    observability aid, never a reason to take the owning plane down.
    ``host`` defaults to loopback (metrics are not public by default);
    off-host scrapers / LB probes need ``http_host: "0.0.0.0"`` (or a
    specific interface) from the owning config section."""
    import warnings

    # every scrape self-describes (jax/jaxlib/backend/devices/git): the
    # build-info gauge is published the moment a scrape surface exists
    try:
        from .telemetry import publish_build_info

        publish_build_info()
    except Exception:
        pass
    try:
        return TelemetryHTTPServer(
            reg=reg, host=host, port=int(port), ready_fn=ready_fn,
            health_fn=health_fn,
        )
    # OverflowError: an out-of-range port raises it from the socket bind,
    # and it must degrade like any other bind failure
    except (OSError, OverflowError) as e:
        warnings.warn(
            f"{label}: could not bind the metrics endpoint on {host}:{port} "
            f"({e}); /metrics///healthz//readyz are unavailable for this "
            "process",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
