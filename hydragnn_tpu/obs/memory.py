"""HBM accounting: per-specialization memory figures harvested from XLA's
``memory_analysis()`` (docs/OBSERVABILITY.md "Memory").

Nothing in this repo read ``compiled.memory_analysis()`` before r12, so HBM
headroom on the production shape was guesswork until an OOM. The compile
plane already holds a compiled executable per AOT-warmed ladder level (it
harvests ``cost_analysis()`` FLOPs there — train/compile_plane.py); this
module is the memory sibling: ``record(label, compiled)`` harvests
argument / output / temp / alias bytes plus the derived peak estimate into
one process-wide table, publishes ``hydragnn_hbm_*`` gauges per spec, and
the flight recorder dumps the table (plus live per-device memory stats) as
the OOM-forensics section of every black box (obs/flightrec.py).

``memory_analysis()`` availability is backend-dependent — everything here
is best-effort by contract: a backend without it leaves the table empty and
never raises into the compile path.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

_LOCK = threading.Lock()
_TABLE: Dict[str, Dict[str, float]] = {}

# (table key, CompiledMemoryStats attribute, required) — only the fields
# the peak estimate needs are mandatory; a jaxlib whose stats object lacks
# e.g. generated_code_size_in_bytes must not blank the whole table
_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes", True),
    ("output_bytes", "output_size_in_bytes", True),
    ("temp_bytes", "temp_size_in_bytes", True),
    ("alias_bytes", "alias_size_in_bytes", True),
    ("generated_code_bytes", "generated_code_size_in_bytes", False),
)


def harvest(compiled) -> Optional[Dict[str, float]]:
    """Memory figures of one compiled executable, or None when the backend
    does not expose ``memory_analysis()``. ``peak_bytes`` is the standard
    estimate ``arguments + outputs + temp − aliased`` (donated buffers are
    the alias term, so a donated train step is not double-counted)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    if isinstance(ma, (list, tuple)):
        if not ma:
            return None
        ma = ma[0]
    out: Dict[str, float] = {}
    for key, attr, required in _FIELDS:
        v = getattr(ma, attr, None)
        if v is None:
            if required:
                return None  # a partial PEAK estimate would lie
            v = 0.0
        out[key] = float(v)
    out["peak_bytes"] = max(
        out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        - out["alias_bytes"],
        0.0,
    )
    return out


def record(label: str, compiled=None,
           stats: Optional[Dict[str, float]] = None) -> Optional[Dict[str, float]]:
    """Harvest (or accept pre-harvested) figures for one spec label, store
    them in the process table, and publish the ``hydragnn_hbm_*`` gauges.
    Returns the stats dict, or None when unavailable."""
    if stats is None:
        if compiled is None:
            return None
        stats = harvest(compiled)
    if stats is None:
        return None
    with _LOCK:
        _TABLE[label] = dict(stats)
    try:
        from .registry import registry

        reg = registry()
        for key in ("argument_bytes", "output_bytes", "temp_bytes",
                    "peak_bytes"):
            reg.gauge(
                f"hydragnn_hbm_{key}",
                f"XLA memory_analysis {key.replace('_', ' ')} per compiled "
                "specialization",
                labelnames=("spec",),
            ).set(stats[key], spec=label)
    except Exception:
        pass  # the table is the source of truth; gauges are best-effort
    return stats


def snapshot() -> Dict[str, Dict[str, float]]:
    """The per-spec table (what the flight recorder and the compile-plane
    report render)."""
    with _LOCK:
        return {k: dict(v) for k, v in _TABLE.items()}


def reset() -> None:
    """Drop the table (tests)."""
    with _LOCK:
        _TABLE.clear()


def device_memory_stats() -> Dict[str, Any]:
    """Live per-device peak-bytes-in-use, best-effort (the flight
    recorder's 'what was actually resident at the moment of death')."""
    try:
        from ..utils.profile import peak_memory_stats

        return {str(k): float(v) for k, v in peak_memory_stats().items()}
    except Exception:
        return {}


def device_bytes_limit() -> Optional[float]:
    """Per-device memory capacity in bytes, best-effort (``bytes_limit``
    of the first local device's memory_stats; None where the backend
    exposes none — the CPU case). The denominator the run doctor's
    HBM-pressure rule divides peak bytes by; rides the compile-plane
    report AND every flight dump's memory.json so the crash-forensics
    path can reach the same verdict."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        limit = (stats or {}).get("bytes_limit")
        return float(limit) if limit else None
    except Exception:
        return None
