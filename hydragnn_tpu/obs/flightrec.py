"""Crash flight recorder: the black box of a run
(docs/OBSERVABILITY.md "Flight recorder").

On an unhandled exception, ``SIGUSR2``, a fatal guard policy, or a
GraphServer wedge, the recorder dumps the last N structured events
(obs/events.py), the last N finished spans (obs/trace.py), and a full
registry snapshot (Prometheus text) atomically into
``logs/<run>/flightrec/<stamp>-<reason>/`` — so a post-mortem has the
incident cascade, its causal trace context, and every counter/gauge at the
moment of death without re-running anything.

Atomicity: each dump is assembled in a hidden temp directory and renamed
into place, so a consumer never sees a half-written dump; a crash *during*
the dump leaves only a ``.tmp-*`` directory behind, never a truncated
final one. Dumps are bounded (``max_dumps`` per recorder) so a crash loop
cannot fill the disk.

Triggering: ``install()`` chains ``sys.excepthook`` (unhandled exceptions
on the main thread), ``threading.excepthook`` (worker threads — the serve
loop and prefetch producers live there), and a ``SIGUSR2`` handler (the
operator's "dump now" button on a live process), and registers the
instance as the process-active recorder so call sites that cannot be
handed an instance (the guard's fatal path) reach it via ``trigger()``.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, Optional

# import from the submodule directly: the package __init__ re-exports the
# ``events()`` accessor under the submodule's own name, so ``from . import
# events`` would resolve to the function after package init
from .events import EV_FLIGHT_DUMP
from .events import emit as _emit_event
from .events import events as _event_log
from .prometheus import render_text


class FlightRecorder:
    """Per-run black box. Construct with the run dir; ``install()`` wires
    the crash hooks; ``dump(reason)`` is the manual trigger."""

    def __init__(
        self,
        run_dir: str,
        tracer=None,
        max_dumps: int = 8,
    ):
        self.out_root = os.path.join(run_dir, "flightrec")
        self.tracer = tracer
        self.max_dumps = int(max_dumps)
        self.dumps = 0
        self._lock = threading.Lock()
        self._prev_excepthook = None
        self._prev_thread_hook = None
        self._prev_sigusr2 = None
        self._installed = False

    # -- dumping --------------------------------------------------------------

    def _spans(self):
        if self.tracer is not None:
            return self.tracer.recent()
        from . import trace as _trace

        t = _trace.active()
        return t.recent() if t is not None else []

    def dump(self, reason: str, exc: Optional[BaseException] = None) -> Optional[str]:
        """Write one dump; returns its directory, or None when the dump
        budget is spent or the write failed (the recorder never raises —
        a black box that crashes the plane defeats its purpose)."""
        with self._lock:
            if self.dumps >= self.max_dumps:
                return None
            self.dumps += 1
            idx = self.dumps
        try:
            safe_reason = "".join(
                c if c.isalnum() or c in "-_" else "_" for c in str(reason)
            )[:64] or "dump"
            stamp = time.strftime("%Y%m%d-%H%M%S")
            # host-disambiguated directory: a coordinated fleet dump lands
            # every host's black box onto the SAME shared filesystem at the
            # same second — the process index keeps them side by side
            # instead of colliding (obs/fleet.py host_identity)
            try:
                from .fleet import host_identity

                host_i, _ = host_identity()
            except Exception:
                host_i = 0
            final = os.path.join(
                self.out_root, f"{stamp}-{idx:02d}-{safe_reason}-h{host_i}"
            )
            tmp = os.path.join(
                self.out_root,
                f".tmp-{idx:02d}-{safe_reason}-h{host_i}-{os.getpid()}",
            )
            os.makedirs(tmp, exist_ok=True)
            meta: Dict[str, Any] = {
                "reason": str(reason),
                "ts": round(time.time(), 6),
                "pid": os.getpid(),
                "host": host_i,
                "dump_index": idx,
            }
            if exc is not None:
                meta["exception"] = {
                    "type": type(exc).__name__,
                    "message": str(exc),
                    "traceback": "".join(
                        traceback.format_exception(
                            type(exc), exc, exc.__traceback__
                        )
                    ),
                }
            # incident census by severity (the per-kind ranks of
            # obs/events.py DEFAULT_SEVERITY): a post-mortem — and the run
            # doctor's dump ingestion — ranks the window without kind-name
            # heuristics, and the worst rank is grep-able from meta alone
            event_window = _event_log().snapshot()
            census: Dict[str, int] = {}
            for ev in event_window:
                sev = str(ev.get("severity", "info"))
                census[sev] = census.get(sev, 0) + 1
            meta["events_by_severity"] = census
            from .events import SEVERITIES as _SEVS

            meta["worst_severity"] = next(
                (s for s in reversed(_SEVS) if census.get(s)), "info"
            )
            with open(os.path.join(tmp, "meta.json"), "w") as fh:
                json.dump(meta, fh, indent=2)
            with open(os.path.join(tmp, "events.json"), "w") as fh:
                json.dump(event_window, fh, indent=2)
            with open(os.path.join(tmp, "spans.json"), "w") as fh:
                json.dump(self._spans(), fh, indent=2)
            with open(os.path.join(tmp, "metrics.prom"), "w") as fh:
                fh.write(render_text())
            # OOM forensics: the per-spec HBM table (compile-plane
            # memory_analysis harvest) + live per-device memory stats —
            # best-effort, a backend without either leaves empty sections
            try:
                from . import memory as _memory

                with open(os.path.join(tmp, "memory.json"), "w") as fh:
                    json.dump(
                        {
                            "hbm_by_spec": _memory.snapshot(),
                            "device_memory_peak_bytes":
                                _memory.device_memory_stats(),
                            # capacity denominator: lets the doctor's
                            # HBM-pressure rule reach its verdict from
                            # the dump alone (the OOM-forensics case)
                            "device_bytes_limit":
                                _memory.device_bytes_limit(),
                        },
                        fh,
                        indent=2,
                    )
            except Exception:
                pass
            # sharding-layout table (obs/sharding.py): the placement
            # oracle rides every black box so a fleet post-mortem can diff
            # each host's actual leaf placements — best-effort like memory
            try:
                from . import sharding as _sharding

                table = _sharding.snapshot()
                if table:
                    with open(
                        os.path.join(tmp, "sharding.json"), "w"
                    ) as fh:
                        json.dump(table, fh, indent=2)
            except Exception:
                pass
            os.rename(tmp, final)
            # the dump is itself an incident record (visible to later dumps
            # and to anyone tailing the event log)
            _emit_event(EV_FLIGHT_DUMP, reason=str(reason), path=final)
            return final
        except Exception:
            return None

    # -- crash hooks ----------------------------------------------------------

    def _on_exception(self, exc_type, exc, tb):
        try:
            if exc is not None and exc.__traceback__ is None:
                exc = exc.with_traceback(tb)
            self.dump("unhandled_exception", exc=exc)
        finally:
            hook = self._prev_excepthook or sys.__excepthook__
            hook(exc_type, exc, tb)

    def _on_thread_exception(self, args):
        try:
            # KeyboardInterrupt/SystemExit in a worker is a shutdown, not a
            # crash; everything else is black-box-worthy
            if not issubclass(args.exc_type, (SystemExit, KeyboardInterrupt)):
                self.dump(
                    f"thread_exception_{args.thread.name if args.thread else 'unknown'}",
                    exc=args.exc_value,
                )
        finally:
            hook = self._prev_thread_hook or threading.__excepthook__
            hook(args)

    def _on_sigusr2(self, signum, frame):
        self.dump("sigusr2")
        prev = self._prev_sigusr2
        if callable(prev):
            prev(signum, frame)

    def install(self, signal_hook: bool = True) -> "FlightRecorder":
        """Wire the crash hooks and register as the process-active
        recorder. Idempotent per instance."""
        if self._installed:
            return self
        self._installed = True
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._on_exception
        self._prev_thread_hook = threading.excepthook
        threading.excepthook = self._on_thread_exception
        if signal_hook:
            try:
                self._prev_sigusr2 = signal.signal(
                    signal.SIGUSR2, self._on_sigusr2
                )
            except ValueError:
                pass  # not the main thread: exception hooks only
        # every snapshot self-describes: the build-info gauge rides the
        # registry snapshot of every dump (and every Prometheus scrape)
        try:
            from .telemetry import publish_build_info

            publish_build_info()
        except Exception:
            pass
        _set_active(self)
        return self

    def uninstall(self) -> None:
        if not self._installed:
            _clear_active(self)
            return
        self._installed = False
        if sys.excepthook == self._on_exception:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        if threading.excepthook == self._on_thread_exception:
            threading.excepthook = (
                self._prev_thread_hook or threading.__excepthook__
            )
        if self._prev_sigusr2 is not None:
            try:
                signal.signal(signal.SIGUSR2, self._prev_sigusr2)
            except ValueError:
                pass
            self._prev_sigusr2 = None
        _clear_active(self)


# ---------------------------------------------------------------------------
# process-active recorder: the hook for call sites that cannot be handed an
# instance (guard fatal policy, serve wedge)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FlightRecorder] = None
_ACTIVE_LOCK = threading.Lock()


def _set_active(rec: FlightRecorder) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = rec


def _clear_active(rec: Optional[FlightRecorder]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if rec is None or _ACTIVE is rec:
            _ACTIVE = None


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def trigger(reason: str, exc: Optional[BaseException] = None) -> Optional[str]:
    """Dump via the process-active recorder; no-op (None) when none is
    installed — incident sites call this unconditionally."""
    rec = _ACTIVE
    if rec is None:
        return None
    return rec.dump(reason, exc=exc)
