"""In-graph numerics observatory: per-layer activation and per-param-group
gradient statistics computed inside the jitted step, plus the NaN provenance
drill-down (docs/OBSERVABILITY.md "Numerics").

The blind spot this closes: the step guard (train/guard.py) reports only
*that* a loss or gradient went non-finite — never which layer or channel.
On a long bf16 run the distance between "guard skipped 40 steps last epoch"
and "the PNAPlus gate head underflows bf16 at LR 3e-3" used to be a manual
bisection. Three pieces close it:

1. **Probe taps** (``probe(name, x, mask)``): one-line call sites in
   ``models/base.py`` / ``models/layers.py`` naming intermediates. A tap is
   a no-op unless a collection context is active *at trace time* — enabled
   runs pay a handful of fused reductions per tensor, disabled runs compile
   the identical program as before (the tap never appears in the jaxpr).
   Stats are collected as RAW moments (max-abs, sum-of-squares, element
   count, non-finite count, bf16-underflow count) so they reduce correctly
   across the window (max/sum) and across mesh devices (pmax/psum); hosts
   finalize rms / fractions at flush time.

2. **Step ride-along**: the train-step builders (train/loop.py,
   parallel/dp.py, parallel/branch.py) bundle the probe stack, per-param-
   group gradient stats, and the guard's ok flag into a 4th step output
   when ``Telemetry.numerics`` is on. The outputs are fresh (non-donated)
   device arrays; nothing syncs the host — the telemetry layer reads them
   back at its flush cadence, by which point the producing steps have long
   retired (obs/telemetry.py).

3. **NaN provenance** (``NanWatch``): the loop feeds every step's ok flag
   (plus the batch, rng, and ladder/source provenance) into a small ring;
   entries are checked once they are ``lag`` steps old — old enough that
   reading the flag never stalls the async dispatch pipeline. A failed step
   re-runs its HELD batch through a probe-instrumented diagnostic program
   (``make_nan_diagnostic``) that localizes the FIRST non-finite tensor in
   forward order (activations, then gradient groups), emits a typed
   ``numerics_provenance`` event, and triggers one flight-recorder dump per
   run. NOTE the diagnostic runs against the CURRENT params (the failing
   step's params were donated ``lag`` steps ago); data-driven and LR-driven
   divergence — the cases worth drilling into — reproduce, a one-off
   cosmic-ray flip does not (the event then reports ``layer:
   <unreproduced>`` and still carries the batch provenance).
"""

from __future__ import annotations

import threading
import warnings
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# raw stat vector layout, per probed tensor / gradient group:
#   [max_abs, sum_sq, count, nonfinite, bf16_underflow]
# max-abs merges by MAX (window steps, mesh devices), the rest by SUM;
# finalize_stats turns the raw moments into {max_abs, rms, nonfinite,
# bf16_underflow} on the host.
STAT_FIELDS = ("max_abs", "sum_sq", "count", "nonfinite", "bf16_underflow")
STAT_WIDTH = len(STAT_FIELDS)

# smallest positive NORMAL bfloat16/float32 magnitude (bf16 shares f32's
# 8-bit exponent): a nonzero value below this is subnormal in bf16 — the
# gradient-underflow precursor the mixed-precision guard wants to see
# coming before it flushes to zero
BF16_TINY = 1.1754944e-38


def numerics_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve a step builder's ``numerics`` argument: explicit True/False
    wins, None means OFF. Deliberately NOT an env fallback: numerics
    changes the step's return arity (3- to 4-tuple), and the
    ``HYDRAGNN_NUMERICS`` override must not break every direct builder
    caller that unpacks three values (bench.py, examples). The env is
    honored where the 4-tuple consumer lives — ``resolve_telemetry``
    (obs/telemetry.py ``env_flag``), which the loop and api.py feed into
    the builders' explicit ``numerics=`` argument."""
    return bool(flag)


# ---------------------------------------------------------------------------
# probe taps + collection context
# ---------------------------------------------------------------------------


class ProbeRecord:
    """One trace's ordered probe collection. ``add`` appends raw (possibly
    vmap-batched) stat components; ``stack`` reduces each probe to a [5]
    vector and stacks them [P, 5] in FORWARD order — the order the NaN
    drill-down walks to find the *first* non-finite tensor."""

    def __init__(self):
        self.entries: List[Tuple[str, Tuple]] = []

    def add(self, name: str, comps: Tuple) -> None:
        # repeated module calls keep distinct rows (suffix #k) so the
        # forward-order walk stays unambiguous
        seen = sum(1 for n, _ in self.entries if n == name or n.startswith(f"{name}#"))
        if seen:
            name = f"{name}#{seen}"
        self.entries.append((name, comps))

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.entries)

    def stack(self):
        """(names, [P, 5] f32 array) — P == 0 yields an empty stack (a
        model with no taps still produces a structurally valid bundle)."""
        import jax.numpy as jnp

        if not self.entries:
            return (), jnp.zeros((0, STAT_WIDTH), jnp.float32)
        rows = []
        for _, (maxabs, sumsq, cnt, nonfin, under) in self.entries:
            # components may carry leading vmap axes (branch banks): the
            # final reduction here collapses them with the right semantics
            rows.append(
                jnp.stack(
                    [
                        jnp.max(maxabs),
                        jnp.sum(sumsq),
                        jnp.sum(cnt),
                        jnp.sum(nonfin),
                        jnp.sum(under),
                    ]
                )
            )
        return self.names, jnp.stack(rows).astype(jnp.float32)


class _TapStack(threading.local):
    def __init__(self):
        self.stack: List[ProbeRecord] = []


_TAPS = _TapStack()


@contextmanager
def collecting(record: ProbeRecord):
    """Activate probe collection on this thread for the duration of a
    traced function body. Thread-local: the compile plane's background
    warm-up worker traces concurrently with epoch 0 without cross-talk."""
    _TAPS.stack.append(record)
    try:
        yield record
    finally:
        _TAPS.stack.pop()


def collection_active() -> bool:
    """Whether a collection context is open on this thread — call sites
    with non-trivial name construction guard on it so disabled runs pay
    only this list check at trace time."""
    return bool(_TAPS.stack)


def probe(name: str, x, mask=None) -> None:
    """Tap a named intermediate. No-op (one thread-local list check, at
    trace time only) unless a ``collecting`` context is active. ``mask``
    restricts the statistics to real rows — padding rows carry garbage by
    contract (models/base.py), and counting their NaNs would fire false
    provenance."""
    if not _TAPS.stack:
        return
    _TAPS.stack[-1].add(name, _stat_components(x, mask))


def _stat_components(x, mask=None) -> Tuple:
    """Raw stat components of one tensor: (max_abs, sum_sq, count,
    nonfinite, bf16_underflow), each a fully-reduced scalar at the trace
    site (vmap lifts them to per-branch vectors; ProbeRecord.stack
    re-reduces). Stats compute in f32 so a bf16 forward's sums don't
    themselves overflow/quantize.

    Op-lean by design (the probes ride EVERY step — the telemetry smoke's
    numerics A/B holds the bill at <= 2%): masked-out rows are zeroed ONCE
    (``where`` never propagates the unselected branch's NaNs), after which
    zero is finite and zero-magnitude — so the non-finite and underflow
    censuses need no further mask arithmetic; the element count comes from
    the (much smaller) mask array times the static row width; and all four
    tensor statistics come out of ONE variadic ``lax.reduce`` — a single
    fused traversal of the probed tensor (measured ~4.5x cheaper than four
    separate jnp reductions on the CPU backend), with the elementwise
    inputs fused into the reduction loop by XLA."""
    import jax.numpy as jnp

    x = jnp.asarray(x).astype(jnp.float32)
    if mask is not None:
        m = jnp.asarray(mask)
        m = m.reshape(m.shape + (1,) * (x.ndim - m.ndim))
        x = jnp.where(m, x, 0.0)
        cnt = jnp.sum(m.astype(jnp.float32)) * float(
            x.size // max(m.size, 1)
        )
    else:
        cnt = jnp.asarray(float(x.size), jnp.float32)
    maxabs, sumsq, nonfin, under = _fused_reduce()(x)
    return maxabs, sumsq, cnt, nonfin, under


_FUSED_REDUCE = None


def _fused_reduce():
    """The one-pass variadic stat reduction, built lazily (module import
    stays jax-free) and wrapped in a ``custom_jvp`` with zero tangents:
    the stats are observability outputs that must never be differentiated,
    and ``lax.reduce`` has no AD rule for the symbolic-zero tangents that
    linearizing the surrounding loss would otherwise push through it."""
    global _FUSED_REDUCE
    if _FUSED_REDUCE is not None:
        return _FUSED_REDUCE
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.custom_jvp
    def fused(x):
        ax = jnp.abs(x)
        sq = x * x
        nonfin_e = (~jnp.isfinite(x)).astype(jnp.float32)
        under_e = ((ax > 0.0) & (ax < BF16_TINY)).astype(jnp.float32)

        def _comb(a, b):
            # jnp.maximum propagates NaN -> a NaN'd tensor reports nan
            return (jnp.maximum(a[0], b[0]), a[1] + b[1], a[2] + b[2],
                    a[3] + b[3])

        return lax.reduce(
            (ax, sq, nonfin_e, under_e),
            (jnp.float32(0), jnp.float32(0), jnp.float32(0),
             jnp.float32(0)),
            _comb,
            tuple(range(x.ndim)),
        )

    @fused.defjvp
    def _fused_jvp(primals, tangents):
        out = fused(*primals)
        return out, tuple(jnp.zeros_like(o) for o in out)

    _FUSED_REDUCE = fused
    return fused


def run_probed(enabled: bool, meta: Dict[str, Any], thunk: Callable):
    """The step builders' shared collection wrapper: run ``thunk`` (the
    loss computation) under probe collection when ``enabled``, recording
    the forward-ordered tap names into the builder's mutable ``meta`` cell
    at trace time. Returns ``(thunk result, acts stack | None)`` — one
    spelling for train/loop.py, parallel/dp.py, and parallel/branch.py, so
    the collection protocol cannot desynchronize across builders."""
    if not enabled:
        return thunk(), None
    rec = ProbeRecord()
    with collecting(rec):
        out = thunk()
    names, acts = rec.stack()
    meta["act_names"] = names
    return out, acts


def numerics_step_wrapper(jitted, meta: Dict[str, Any], model,
                          compute_grad_energy: bool = False,
                          mixed_precision: bool = False):
    """The step builders' shared numerics epilogue: wrap the jit object so
    it stays AOT-reachable for the compile plane, and attach the host-side
    contract — ``_jitted`` (the true jit, for api.py's attach_lower_fn),
    ``_numerics_meta`` (tensor name tables), ``_nan_diagnose`` (the
    provenance drill-down)."""
    from ..train.compile_plane import attach_lower_fn

    wrapper = attach_lower_fn(lambda s, b, r: jitted(s, b, r), jitted)
    wrapper._jitted = jitted
    wrapper._numerics_meta = meta
    wrapper._nan_diagnose = make_nan_diagnostic(
        model, compute_grad_energy, mixed_precision
    )
    return wrapper


# ---------------------------------------------------------------------------
# gradient groups + reductions
# ---------------------------------------------------------------------------


def grad_group_stats(grads):
    """(names, [G, 5]) over the top-level param groups of a gradient tree
    (flax params dicts: one group per module — ``graph_convs_0``,
    ``heads_NN_0``, ...; non-dict trees collapse to one ``params`` group).
    Sorted-key order: deterministic across traces and processes."""
    import jax
    import jax.numpy as jnp

    if isinstance(grads, dict) and grads:
        groups = [(k, grads[k]) for k in sorted(grads)]
    else:
        groups = [("params", grads)]
    names = []
    rows = []
    for name, sub in groups:
        leaves = [l for l in jax.tree_util.tree_leaves(sub)
                  if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]
        if not leaves:
            continue
        # per-LEAF fused reductions, combined per group. Deliberately NOT a
        # ravel+concatenate of the group: on the branch-parallel and ZeRO
        # paths the gradient leaves are mesh-SHARDED, and a concat under
        # the outer jit would force GSPMD to all-gather the full bank per
        # step just to compute five scalars — per-leaf reductions partial-
        # reduce in place and only the scalars travel.
        comps = [_stat_components(l) for l in leaves]
        names.append(name)
        rows.append(
            jnp.stack(
                [
                    (comps[0][0] if len(comps) == 1
                     else jnp.max(jnp.stack([c[0] for c in comps]))),
                    sum(c[1] for c in comps),
                    sum(c[2] for c in comps),
                    sum(c[3] for c in comps),
                    sum(c[4] for c in comps),
                ]
            )
        )
    if not rows:
        return (), jnp.zeros((0, STAT_WIDTH), jnp.float32)
    return tuple(names), jnp.stack(rows).astype(jnp.float32)


def cross_device_reduce(stacked, axis_names):
    """Reduce a [P, 5] stat stack across mesh devices inside ``shard_map``:
    max-abs merges by ``pmax``, the summed moments by ``psum`` — the same
    merge semantics the host applies across window steps."""
    import jax
    import jax.numpy as jnp

    if stacked.shape[0] == 0:
        return stacked
    return jnp.concatenate(
        [
            jax.lax.pmax(stacked[:, :1], axis_names),
            jax.lax.psum(stacked[:, 1:], axis_names),
        ],
        axis=1,
    )


def finalize_stats(raw) -> Dict[str, float]:
    """Host-side finalization of one raw [5] vector."""
    import numpy as np

    maxabs, sumsq, cnt, nonfin, under = (float(v) for v in np.asarray(raw))
    denom = max(cnt, 1.0)
    rms = float(np.sqrt(max(sumsq, 0.0) / denom)) if np.isfinite(sumsq) else sumsq
    return {
        "max_abs": maxabs,
        "rms": rms,
        "nonfinite": nonfin,
        "bf16_underflow": under / denom,
    }


def _is_bad(row) -> bool:
    import numpy as np

    r = np.asarray(row)
    return bool(r[3] > 0 or not np.isfinite(r[0]) or not np.isfinite(r[1]))


def locate_first_nonfinite(act_names, acts, grad_names, gstats) -> Optional[Dict[str, Any]]:
    """First non-finite tensor in forward order: activations (probe order),
    then gradient groups. Returns {layer, kind, stats} or None."""
    import numpy as np

    acts = np.asarray(acts) if acts is not None else np.zeros((0, STAT_WIDTH))
    for p in range(acts.shape[0]):
        if _is_bad(acts[p]):
            name = act_names[p] if act_names and p < len(act_names) else f"probe{p}"
            return {"layer": name, "kind": "activation",
                    "stats": finalize_stats(acts[p])}
    gstats = np.asarray(gstats) if gstats is not None else np.zeros((0, STAT_WIDTH))
    for g in range(gstats.shape[0]):
        if _is_bad(gstats[g]):
            name = grad_names[g] if grad_names and g < len(grad_names) else f"group{g}"
            return {"layer": name, "kind": "gradient",
                    "stats": finalize_stats(gstats[g])}
    return None


# ---------------------------------------------------------------------------
# NaN provenance: diagnostic step + deferred watch
# ---------------------------------------------------------------------------


def make_nan_diagnostic(model, compute_grad_energy: bool = False,
                        mixed_precision: bool = False) -> Callable:
    """Build the host-callable drill-down ``diagnose(state, batch, rng,
    step) -> finding | None`` for one model/objective.

    The diagnostic is its own jit program (built lazily — compiled only on
    the first guarded skip, never on clean runs) running the replicated
    single-device objective with every probe active, full per-group
    gradient stats, and the SAME fault-injection hooks as the live step
    (``faultinject.poison_grads`` with the failing step's index, so an
    injected fault reproduces under diagnosis). Stacked mesh batches are
    diagnosed row by row; all-padding filler rows are skipped. It never
    traces a sentinel'd builder name, so an armed retrace sentinel ignores
    it."""
    holder: Dict[str, Any] = {"jit": None, "act_names": None, "grad_names": None}

    def _build():
        import jax
        import jax.numpy as jnp

        from ..train.loss import compute_loss
        from ..utils import faultinject

        cfg = model.cfg

        def loss_probe(params, batch_stats, batch, rng):
            if mixed_precision:
                from ..train.loop import mp_cast

                params, batch = mp_cast(params, batch, compute_grad_energy)
            rec = ProbeRecord()
            with collecting(rec):
                tot, _, _, _ = compute_loss(
                    model,
                    {"params": params, "batch_stats": batch_stats},
                    batch, cfg, True, rng, compute_grad_energy,
                )
            names, acts = rec.stack()
            holder["act_names"] = names
            return tot.astype(jnp.float32), acts

        @jax.jit
        def diag(params, batch_stats, batch, rng, step, lr):
            (tot, acts), grads = jax.value_and_grad(loss_probe, has_aux=True)(
                params, batch_stats, batch, rng
            )
            grads = faultinject.poison_grads(grads, step, lr)
            gnames, gstats = grad_group_stats(grads)
            holder["grad_names"] = gnames
            return tot, acts, gstats

        return diag

    def diagnose(state, batch, rng, step: int) -> Optional[Dict[str, Any]]:
        import jax
        import numpy as np

        from ..utils import faultinject

        if holder["jit"] is None:
            holder["jit"] = _build()
        diag = holder["jit"]
        lr = faultinject.lr_of(state.opt_state)
        if batch.graph_mask.ndim == 2:  # stacked [D, ...] mesh batch
            rows = [
                jax.tree_util.tree_map(lambda x, _r=r: x[_r], batch)
                for r in range(int(batch.graph_mask.shape[0]))
            ]
        else:
            rows = [batch]
        for r, row in enumerate(rows):
            if not bool(np.asarray(row.graph_mask).any()):
                continue  # all-padding filler row (BranchRoutedLoader)
            tot, acts, gstats = jax.device_get(
                diag(state.params, state.batch_stats, row, rng,
                     jnp_int(step), lr)
            )
            finding = locate_first_nonfinite(
                holder["act_names"], acts, holder["grad_names"], gstats
            )
            if finding is not None:
                if len(rows) > 1:
                    finding["shard"] = r
                finding["loss"] = float(tot)
                return finding
        return None

    return diagnose


def jnp_int(v: int):
    import jax.numpy as jnp

    return jnp.asarray(int(v), jnp.int32)


class NanWatch:
    """Deferred per-step non-finite watch + provenance driver.

    The loop feeds every step (``on_step``); entries are checked ``lag``
    steps later, when their ok flag has certainly retired — reading it then
    costs a host copy of one ready scalar, never a pipeline stall. A failed
    entry is drilled down via the diagnostic, emitted as a typed
    ``numerics_provenance`` event (layer, stat vector, batch spec, source
    draw ids), and — once per run — dumped to the flight recorder.
    ``take()`` hands the accumulated skip provenance to the epoch-boundary
    guard policy so ``guard_skip`` events carry it too.

    Bounded by design: a persistently diverged ``warn_skip`` run fails
    EVERY remaining step — after ``max_diagnoses`` drill-downs the watch
    stops re-running the (forward+backward) diagnostic and stops emitting
    per-skip events (which would evict the incident context out of the
    event ring), while the cheap skip bookkeeping (batch/level/sources for
    the epoch's ``guard_skip`` tally) continues. The same reasoning that
    caps flight-recorder dumps at one per run.

    Memory: the ring pins ``lag`` held batches — device-resident ones
    under ``Training.double_buffer`` staging, so numerics-on costs up to
    ``lag x batch`` extra HBM (a few hundred MB at the OC20 shape; budget
    it against ``hydragnn_hbm_peak_bytes``). ``lag`` defaults to 4: far
    past any async-dispatch queue depth (the flag is retired when read),
    half the residency of the first cut. Once the diagnostic budget is
    spent the batch references are dropped on insert — a long diverged
    run's ring holds no batches at all."""

    def __init__(self, diagnose: Optional[Callable] = None, lag: int = 4,
                 log_name: str = "run", max_diagnoses: int = 16):
        self.diagnose = diagnose
        self.lag = max(int(lag), 1)
        self.log_name = log_name
        self.max_diagnoses = max(int(max_diagnoses), 1)
        self._ring: deque = deque()
        self.skips: List[Dict[str, Any]] = []
        self.located = 0
        self.suppressed = 0
        self._attempts = 0
        self._dumped = False

    def on_step(self, state, batch, rng, step: int, batch_index: int,
                numerics, level: Optional[str] = None,
                sources: Optional[Sequence[int]] = None) -> None:
        if numerics is None:
            return
        if self._attempts >= self.max_diagnoses:
            batch = None  # budget spent: never pin another batch in HBM
        self._ring.append(
            (numerics.get("ok"), batch, rng, step, batch_index, level, sources)
        )
        while len(self._ring) > self.lag:
            self._check(state, self._ring.popleft())

    def end_epoch(self, state) -> None:
        """Drain the ring at the epoch boundary (the loop host-syncs there
        anyway, so the remaining flags are ready)."""
        while self._ring:
            self._check(state, self._ring.popleft())

    def take(self) -> List[Dict[str, Any]]:
        out, self.skips = self.skips, []
        return out

    def _check(self, state, entry) -> None:
        import numpy as np

        ok, batch, rng, step, batch_index, level, sources = entry
        try:
            if ok is None or bool(np.asarray(ok)):
                return
        except Exception:
            return  # a dead/donated flag is unreadable, not an incident
        prov: Dict[str, Any] = {"batch": int(batch_index), "step": int(step)}
        if level:
            prov["level"] = level
        if sources:
            prov["sources"] = [int(s) for s in sources]
        if self._attempts >= self.max_diagnoses:
            # diagnostic budget spent (sustained divergence): keep the
            # cheap bookkeeping for the epoch's guard_skip tally, skip the
            # drill-down re-run and the per-skip event — announced once
            self.suppressed += 1
            prov["layer"] = "<diagnostic_budget_spent>"
            prov["kind"] = "unknown"
            self.skips.append(prov)
            if self.suppressed == 1:
                try:
                    from .events import EV_NUMERICS_PROVENANCE
                    from .events import emit as _emit

                    _emit(
                        EV_NUMERICS_PROVENANCE,
                        severity="warn",
                        layer="<diagnostic_budget_spent>",
                        tensor_kind="unknown",
                        max_diagnoses=self.max_diagnoses,
                        note="sustained divergence: further skips are "
                             "tallied without per-skip drill-down",
                    )
                except Exception:
                    pass
            return
        self._attempts += 1
        finding = None
        if self.diagnose is not None:
            try:
                finding = self.diagnose(state, batch, rng, step)
            except Exception as e:  # diagnosis must never take training down
                warnings.warn(
                    f"NaN provenance diagnostic failed "
                    f"({type(e).__name__}: {e}); the guard skip is still "
                    "recorded without layer attribution",
                    RuntimeWarning,
                    stacklevel=2,
                )
        if finding is not None:
            import numpy as _np

            self.located += 1
            prov.update(
                {
                    "layer": finding["layer"],
                    "kind": finding["kind"],
                    # non-finite stats ARE the signal here; stringify them
                    # so the event ring stays strict-JSON serializable
                    # (flight-recorder events.json)
                    **{
                        f"stat_{k}": (
                            float(v) if _np.isfinite(v) else str(v)
                        )
                        for k, v in finding["stats"].items()
                    },
                }
            )
            if "shard" in finding:
                prov["shard"] = finding["shard"]
        else:
            # current-params re-run stayed finite (one-off flip, or the
            # trajectory moved on): still a typed record with provenance
            prov["layer"] = "<unreproduced>"
            prov["kind"] = "unknown"
        self.skips.append(prov)
        try:
            from .events import EV_NUMERICS_PROVENANCE
            from .events import emit as _emit

            attrs = dict(prov)
            # "kind" is the event's own discriminator — the tensor kind
            # (activation/gradient) travels as tensor_kind
            attrs["tensor_kind"] = attrs.pop("kind", "unknown")
            if "sources" in attrs:
                attrs["sources"] = ",".join(str(s) for s in attrs["sources"])
            _emit(EV_NUMERICS_PROVENANCE, severity="warn", **attrs)
        except Exception:
            pass
        if not self._dumped:
            # ONE flight-record dump per run: a diverging run skips every
            # remaining step — per-skip dumps would burn the whole dump
            # budget on copies of the same incident
            self._dumped = True
            try:
                from . import flightrec as _flightrec

                _flightrec.trigger("numerics_provenance")
            except Exception:
                pass
