"""Sharding-layout inspector: the param tree -> placement oracle
(docs/OBSERVABILITY.md "Fleet" / sharding audit).

The rule engine (parallel/engine.py ``place_state`` over a
parallel/rules.py table) decides every leaf's placement, and this module
renders the RESULT: whether a given leaf actually ended up sharded, over
which axis, and how many bytes of it every device holds. It predates the
rule-table refactor (ROADMAP item 1) as its before/after oracle and stays
its regression diff (``doctor diff`` reads the dumped ``sharding.json``):

- ``inspect_state`` walks a (placed) TrainState and tabulates every
  params / opt_state leaf: tree path, PartitionSpec, replicated-vs-
  sharded, total and per-device bytes (parallel/mesh.py
  ``leaf_sharding_info`` reads the committed shardings).
- ``format_report`` renders the table as grep-able ``sharding[...]``
  lines; ``record`` stores it in a process table the flight recorder
  dumps verbatim (``sharding.json``) and publishes the
  ``hydragnn_sharding_*`` gauges.
- the **audit** flags every leaf left fully replicated above a size
  threshold (``Telemetry.fleet_sharding_audit_bytes``) — the lint that
  catches "this 80 MB moment bank silently fell off the ZeRO path"
  before the HBM bill does. Findings are emitted as typed
  ``sharding_audit`` events (bounded), so they ride flight dumps too.

Everything is host-side metadata walking — no device transfers, no
compute — and best-effort by the plane's contract: a leaf the helper
cannot describe is skipped, never raised on.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

# bounded event emission per audit: a badly-placed model can have hundreds
# of offending leaves; the report carries them all, the event log does not
_MAX_AUDIT_EVENTS = 8

_LOCK = threading.Lock()
_REPORTS: Dict[str, Dict[str, Any]] = {}
# which step builder produced the live placement (parallel/dp.py,
# parallel/branch.py, train/loop.py note at build time) — report provenance
_BUILDER: Optional[Dict[str, Any]] = None


def note_builder(
    name: str, mesh_shape: Optional[Dict[str, int]] = None, **flags: Any
) -> None:
    """Record which step builder (and mesh / ZeRO flags) owns the live
    placement — called by the builders themselves so the inspector report
    names its provenance instead of guessing from leaf shapes."""
    global _BUILDER
    with _LOCK:
        _BUILDER = {
            "name": str(name),
            "mesh": dict(mesh_shape) if mesh_shape else None,
            **{k: v for k, v in flags.items()},
        }


def builder_info() -> Optional[Dict[str, Any]]:
    with _LOCK:
        return dict(_BUILDER) if _BUILDER is not None else None


def sharding_table(tree, section: str = "") -> List[Dict[str, Any]]:
    """Per-leaf placement entries of one pytree: ``{path, spec, sharded,
    total_bytes, per_device_bytes, devices, dtype, shape}``. Leaves the
    mesh helper cannot describe (non-arrays) are skipped."""
    import jax

    from ..parallel.mesh import leaf_sharding_info

    out: List[Dict[str, Any]] = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        try:
            info = leaf_sharding_info(leaf)
        except Exception:
            info = None
        if info is None:
            continue
        key = jax.tree_util.keystr(path)
        out.append({"path": f"{section}{key}", **info})
    return out


def audit_table(
    table: List[Dict[str, Any]], threshold_bytes: int
) -> List[Dict[str, Any]]:
    """Lint pass: every fully replicated leaf at/above the threshold is a
    finding — on a ZeRO/branch placement it means the leaf fell off the
    sharding path (or the rule table regressed)."""
    findings = []
    for e in table:
        if e["replicated"] and e["total_bytes"] >= int(threshold_bytes):
            findings.append(
                {
                    "path": e["path"],
                    "bytes": e["total_bytes"],
                    "spec": e["spec"],
                    "message": (
                        f"leaf {e['path']} is fully replicated at "
                        f"{e['total_bytes']} bytes (>= audit threshold "
                        f"{int(threshold_bytes)}) — every device holds a "
                        "full copy"
                    ),
                }
            )
    return findings


def _summary(table: List[Dict[str, Any]]) -> Dict[str, Any]:
    sharded = [e for e in table if not e["replicated"]]
    return {
        "leaves": len(table),
        "sharded_leaves": len(sharded),
        "total_bytes": int(sum(e["total_bytes"] for e in table)),
        "sharded_bytes": int(sum(e["total_bytes"] for e in sharded)),
        "replicated_bytes": int(
            sum(e["total_bytes"] for e in table if e["replicated"])
        ),
        "per_device_bytes": int(
            sum(e["per_device_bytes"] for e in table)
        ),
    }


def inspect_state(
    state,
    threshold_bytes: int = 1 << 20,
    label: str = "train_state",
    mesh=None,
) -> Dict[str, Any]:
    """Tabulate a (placed) TrainState's params + optimizer leaves and run
    the replication audit. ``mesh`` (a ``jax.sharding.Mesh``) adds the
    axis sizes to the report header; builder provenance comes from
    ``note_builder``."""
    sections: Dict[str, List[Dict[str, Any]]] = {}
    for name in ("params", "opt_state", "batch_stats"):
        sub = getattr(state, name, None)
        if sub is None:
            continue
        table = sharding_table(sub, section=name)
        if table:
            sections[name] = table
    flat = [e for table in sections.values() for e in table]
    report: Dict[str, Any] = {
        "label": str(label),
        "mesh": (
            {str(k): int(v) for k, v in dict(mesh.shape).items()}
            if mesh is not None
            else None
        ),
        "builder": builder_info(),
        "threshold_bytes": int(threshold_bytes),
        "sections": sections,
        "summary": _summary(flat),
        "audit": audit_table(flat, threshold_bytes),
    }
    return report


def format_report(report: Dict[str, Any], leaves: bool = True) -> str:
    """Grep-able text rendering: one ``sharding[label] ...`` summary line,
    one line per leaf (``leaves=False`` keeps just summary + audit)."""
    label = report["label"]
    s = report["summary"]
    mesh = report.get("mesh")
    mesh_s = (
        ",".join(f"{k}:{v}" for k, v in mesh.items()) if mesh else "none"
    )
    builder = report.get("builder") or {}
    lines = [
        f"sharding[{label}] builder={builder.get('name', 'unknown')} "
        f"mesh={mesh_s} leaves={s['leaves']} "
        f"sharded={s['sharded_leaves']} "
        f"total_bytes={s['total_bytes']} "
        f"replicated_bytes={s['replicated_bytes']} "
        f"per_device_bytes={s['per_device_bytes']} "
        f"audit_warnings={len(report['audit'])}"
    ]
    if leaves:
        for table in report["sections"].values():
            for e in table:
                lines.append(
                    f"sharding[{label}] leaf={e['path']} "
                    f"spec={e['spec']} "
                    f"{'SHARDED' if not e['replicated'] else 'REPLICATED'} "
                    f"bytes={e['total_bytes']} "
                    f"per_device={e['per_device_bytes']} "
                    f"dtype={e['dtype']} shape={list(e['shape'])}"
                )
    for f in report["audit"]:
        lines.append(f"sharding[{label}] AUDIT {f['message']}")
    return "\n".join(lines)


def record(report: Dict[str, Any], emit_events: bool = True) -> Dict[str, Any]:
    """Store the report in the process table (the flight recorder dumps it
    as ``sharding.json``), publish the ``hydragnn_sharding_*`` gauges, and
    emit (bounded) ``sharding_audit`` events for the findings."""
    label = report["label"]
    with _LOCK:
        _REPORTS[label] = report
    try:
        from .registry import registry

        reg = registry()
        s = report["summary"]
        g_bytes = reg.gauge(
            "hydragnn_sharding_bytes",
            "State bytes by placement (sharding inspector, obs/sharding.py)",
            labelnames=("label", "placement"),
        )
        g_bytes.set(s["sharded_bytes"], label=label, placement="sharded")
        g_bytes.set(
            s["replicated_bytes"], label=label, placement="replicated"
        )
        g_leaves = reg.gauge(
            "hydragnn_sharding_leaves",
            "State leaves by placement",
            labelnames=("label", "placement"),
        )
        g_leaves.set(
            s["sharded_leaves"], label=label, placement="sharded"
        )
        g_leaves.set(
            s["leaves"] - s["sharded_leaves"],
            label=label,
            placement="replicated",
        )
        reg.gauge(
            "hydragnn_sharding_audit_warnings",
            "Replicated-above-threshold leaves the sharding audit flagged",
            labelnames=("label",),
        ).set(float(len(report["audit"])), label=label)
    except Exception:
        pass  # the table is the source of truth; gauges are best-effort
    if emit_events and report["audit"]:
        try:
            from .events import EV_SHARDING_AUDIT
            from .events import emit as emit_event

            for f in report["audit"][:_MAX_AUDIT_EVENTS]:
                emit_event(
                    EV_SHARDING_AUDIT,
                    severity="warn",
                    label=label,
                    leaf=f["path"],
                    bytes=f["bytes"],
                    spec=f["spec"],
                )
        except Exception:
            pass
    return report


def record_unmatched(table_name: str, paths: List[str]) -> None:
    """Audit hook for the rule engine (parallel/engine.py place_state):
    every non-scalar leaf NO rule matched was placed replicated by the
    explicit default — legal, but loud, because on a hand-written inline
    table it usually means a forgotten pattern. Bounded ``sharding_audit``
    events + a gauge; the full path list rides the report table so flight
    dumps carry it."""
    if not paths:
        return
    with _LOCK:
        _REPORTS.setdefault("rule_audit", {"label": "rule_audit"}).update(
            {"table": str(table_name), "unmatched": [str(p) for p in paths]}
        )
    try:
        from .registry import registry

        registry().gauge(
            "hydragnn_sharding_unmatched_leaves",
            "Non-scalar leaves no partition rule matched (replicated by "
            "the audited default, parallel/rules.py)",
            labelnames=("table",),
        ).set(float(len(paths)), table=str(table_name))
    except Exception:
        pass
    try:
        from .events import EV_SHARDING_AUDIT
        from .events import emit as emit_event

        for p in paths[:_MAX_AUDIT_EVENTS]:
            emit_event(
                EV_SHARDING_AUDIT,
                severity="warn",
                label="rule_audit",
                table=str(table_name),
                leaf=str(p),
                reason="no partition rule matched; placed replicated",
            )
    except Exception:
        pass


def snapshot() -> Dict[str, Dict[str, Any]]:
    """The per-label report table (what the flight recorder dumps)."""
    with _LOCK:
        return {k: dict(v) for k, v in _REPORTS.items()}


def reset() -> None:
    """Drop reports + builder note (tests)."""
    global _BUILDER
    with _LOCK:
        _REPORTS.clear()
        _BUILDER = None
