"""Unified telemetry plane (docs/OBSERVABILITY.md): the process-wide
metrics registry every subsystem publishes into, the Prometheus scrape +
health endpoint, the per-step train instrumentation with its versioned
``metrics.jsonl`` stream, the on-demand profiling trigger, the tracing
plane — request/step spans (obs/trace.py), the structured event log
(obs/events.py), the crash flight recorder (obs/flightrec.py) — and the
fleet layer: cross-host aggregation + straggler/desync watchdog
(obs/fleet.py) and the sharding-layout inspector (obs/sharding.py)."""

from .events import EventLog, events
from .events import emit as emit_event
from .fleet import (
    FleetCollector,
    FleetPlane,
    FleetPusher,
    host_identity,
    merge_traces,
    registry_snapshot,
)
from .flightrec import FlightRecorder
from .numerics import NanWatch, numerics_enabled, probe
from .prometheus import TelemetryHTTPServer, render_text, start_endpoint
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
)
from .telemetry import (
    SCHEMA_VERSION,
    MetricsStream,
    ProfileTrigger,
    StepTelemetry,
    host_memory_bytes,
    mfu_estimate,
    peak_flops,
    publish_build_info,
    resolve_telemetry,
)
from .trace import Span, Tracer

__all__ = [
    "Counter",
    "EventLog",
    "FleetCollector",
    "FleetPlane",
    "FleetPusher",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsStream",
    "NanWatch",
    "ProfileTrigger",
    "SCHEMA_VERSION",
    "Span",
    "StepTelemetry",
    "TelemetryHTTPServer",
    "Tracer",
    "emit_event",
    "events",
    "host_identity",
    "host_memory_bytes",
    "merge_traces",
    "mfu_estimate",
    "numerics_enabled",
    "peak_flops",
    "probe",
    "publish_build_info",
    "registry",
    "registry_snapshot",
    "render_text",
    "resolve_telemetry",
    "start_endpoint",
]
