"""Unified telemetry plane (docs/OBSERVABILITY.md): the process-wide
metrics registry every subsystem publishes into, the Prometheus scrape +
health endpoint, the per-step train instrumentation with its versioned
``metrics.jsonl`` stream, and the on-demand profiling trigger."""

from .prometheus import TelemetryHTTPServer, render_text, start_endpoint
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
)
from .telemetry import (
    SCHEMA_VERSION,
    MetricsStream,
    ProfileTrigger,
    StepTelemetry,
    host_memory_bytes,
    mfu_estimate,
    peak_flops,
    resolve_telemetry,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsStream",
    "ProfileTrigger",
    "SCHEMA_VERSION",
    "StepTelemetry",
    "TelemetryHTTPServer",
    "host_memory_bytes",
    "mfu_estimate",
    "peak_flops",
    "registry",
    "render_text",
    "resolve_telemetry",
    "start_endpoint",
]
