"""Unified telemetry plane (docs/OBSERVABILITY.md): the process-wide
metrics registry every subsystem publishes into, the Prometheus scrape +
health endpoint, the per-step train instrumentation with its versioned
``metrics.jsonl`` stream, the on-demand profiling trigger, the tracing
plane — request/step spans (obs/trace.py), the structured event log
(obs/events.py), the crash flight recorder (obs/flightrec.py) — and the
fleet layer: cross-host aggregation + straggler/desync watchdog
(obs/fleet.py) and the sharding-layout inspector (obs/sharding.py)."""

from .events import (
    DEFAULT_SEVERITY,
    EventLog,
    attach_stream,
    detach_stream,
    events,
    severity_rank,
)
from .events import emit as emit_event
from .fleet import (
    FleetCollector,
    FleetPlane,
    FleetPusher,
    host_identity,
    merge_traces,
    registry_snapshot,
)
from .flightrec import FlightRecorder
from .numerics import NanWatch, numerics_enabled, probe
from .prometheus import TelemetryHTTPServer, render_text, start_endpoint
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
)
from .telemetry import (
    SCHEMA_VERSION,
    MetricsStream,
    ProfileTrigger,
    StepTelemetry,
    host_memory_bytes,
    mfu_estimate,
    peak_flops,
    publish_build_info,
    resolve_telemetry,
)
from .schema import (
    validate_event_record,
    validate_metrics_record,
    validate_span_record,
)
from .trace import Span, Tracer

__all__ = [
    "Counter",
    "DEFAULT_SEVERITY",
    "EventLog",
    "FleetCollector",
    "FleetPlane",
    "FleetPusher",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsStream",
    "NanWatch",
    "ProfileTrigger",
    "SCHEMA_VERSION",
    "Span",
    "StepTelemetry",
    "TelemetryHTTPServer",
    "Tracer",
    "attach_stream",
    "detach_stream",
    "emit_event",
    "events",
    "severity_rank",
    "validate_event_record",
    "validate_metrics_record",
    "validate_span_record",
    "host_identity",
    "host_memory_bytes",
    "merge_traces",
    "mfu_estimate",
    "numerics_enabled",
    "peak_flops",
    "probe",
    "publish_build_info",
    "registry",
    "registry_snapshot",
    "render_text",
    "resolve_telemetry",
    "start_endpoint",
]
