"""Span tracing plane: the causal layer on top of the metrics registry
(docs/OBSERVABILITY.md "Tracing").

Metrics aggregate — they cannot answer *where* one p99 request or one slow
step spent its time. Spans can: every sampled request/step becomes a trace
(trace_id) of timed spans (span_id/parent) written as OTLP-shaped JSONL to
``logs/<run>/trace.jsonl``, one JSON object per line, so any OTLP-literate
tool (or ``run-scripts/bench_gate.py --trace``) can consume it without an
exporter dependency.

Design points:

- **head-based sampling** — the keep/drop decision is made once, at the
  trace root (``Telemetry.trace_sample`` per serving request,
  ``Telemetry.trace_interval_steps`` every-Nth training step); unsampled
  work creates no span objects at all, which is what keeps the tracing
  bill inside the telemetry plane's <= 2% overhead budget
  (run-scripts/trace_smoke.py measures the A/B).
- **unified with the region timers** — ``utils/tracer.py`` ``start/stop``
  regions that close while a sampled span is open on the same thread are
  emitted as child spans (``note_region``), so the pre-existing
  ``dataload``/``train_step`` instrumentation lands in the same trace tree
  without a second instrumentation pass.
- **cross-thread spans** — serving forms batches on the serve loop thread
  from requests admitted on client threads; ``begin``/``finish`` take
  explicit parent/trace ids (no thread-local requirement) and spans carry
  OTLP links, so co-batched requests share the device-step span as a link.
- **crash-safe** — finished spans ride a ring buffer the flight recorder
  (obs/flightrec.py) dumps on crash, and the JSONL stream is flushed by an
  ``atexit`` hook, so an abnormal exit does not truncate the last window.

The writer follows the ``MetricsStream`` contract: observability never
takes the owner down — a full disk drops the stream with a warning and the
run keeps going.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import random
import threading
import time
import warnings
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .registry import registry

# span-record schema version (the trace.jsonl analog of metrics.jsonl "v";
# the shape itself is pinned in obs/schema.py SPAN_FIELDS)
from .schema import TRACE_SCHEMA_VERSION

# OTLP status codes (proto enum values)
STATUS_UNSET = 0
STATUS_OK = 1
STATUS_ERROR = 2


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


def _otlp_value(v: Any) -> Dict[str, Any]:
    """One attribute value in OTLP JSON shape (ints as strings, per the
    OTLP JSON mapping of 64-bit integers)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


class Span:
    """One timed operation: identity (trace/span/parent ids), wall-clock
    start, monotonic duration, attributes, links, and an OTLP status."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_unix", "_t0",
        "duration_s", "attributes", "links", "status_code", "status_message",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        parent_id: Optional[str] = None,
        start_unix: Optional[float] = None,
        attributes: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        now = time.time()
        self.start_unix = now if start_unix is None else float(start_unix)
        # a retroactive start (start_unix in the past) anchors the duration
        # clock too, so end() measures from the DECLARED start — a request
        # root begun after admission work still spans admission-to-outcome
        self._t0 = time.perf_counter() - max(now - self.start_unix, 0.0)
        self.duration_s: Optional[float] = None
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.links: List[Tuple[str, str]] = []
        self.status_code = STATUS_UNSET
        self.status_message = ""

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_link(self, trace_id: str, span_id: str) -> None:
        self.links.append((trace_id, span_id))

    def set_status(self, code: int, message: str = "") -> None:
        self.status_code = code
        self.status_message = message

    @property
    def ended(self) -> bool:
        return self.duration_s is not None

    def end(self, duration_s: Optional[float] = None) -> None:
        if self.duration_s is None:
            self.duration_s = (
                time.perf_counter() - self._t0
                if duration_s is None
                else float(duration_s)
            )

    def to_record(self) -> Dict[str, Any]:
        """OTLP-shaped JSON record (the Span proto's JSON mapping, plus a
        top-level schema version)."""
        dur = self.duration_s if self.duration_s is not None else 0.0
        rec: Dict[str, Any] = {
            "v": TRACE_SCHEMA_VERSION,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "name": self.name,
            "startTimeUnixNano": str(int(self.start_unix * 1e9)),
            "endTimeUnixNano": str(int((self.start_unix + dur) * 1e9)),
        }
        if self.parent_id:
            rec["parentSpanId"] = self.parent_id
        if self.attributes:
            rec["attributes"] = [
                {"key": k, "value": _otlp_value(v)}
                for k, v in self.attributes.items()
            ]
        if self.links:
            rec["links"] = [
                {"traceId": t, "spanId": s} for t, s in self.links
            ]
        if self.status_code != STATUS_UNSET:
            status: Dict[str, Any] = {"code": self.status_code}
            if self.status_message:
                status["message"] = self.status_message
            rec["status"] = status
        return rec


class Tracer:
    """Span factory + sink for one run.

    - ``sample_request()`` / ``sample_step()`` are the head-sampling
      decisions (probability / every-Nth); call once per root.
    - ``span(name)`` is the thread-local context manager (parents nest on
      this thread's stack); ``begin``/``finish`` are the explicit-context
      API for cross-thread spans; ``emit_completed`` records a span
      retroactively from a measured (start, duration) — the region-timer
      and queue-wait shape.
    - finished spans land in the JSONL stream (flushed at most once a
      second + atexit) and a ring buffer for the flight recorder.
    """

    def __init__(
        self,
        run_dir: Optional[str] = None,
        sample: float = 1.0,
        every_n_steps: int = 0,
        ring: int = 512,
        jsonl: bool = True,
        rank0: Optional[bool] = None,
        filename: str = "trace.jsonl",
    ):
        self.sample = float(sample)
        self.every_n_steps = int(every_n_steps)
        self.run_dir = run_dir
        # fleet identity: every span record self-identifies its host so
        # per-host streams stitch into one run-level view (obs/fleet.py
        # merge_traces); ``filename`` lets a non-zero fleet host write its
        # own host-suffixed stream on a shared filesystem (train/loop.py)
        from .fleet import host_identity

        self.host, _ = host_identity()
        self.path = (
            os.path.join(run_dir, filename)
            if run_dir and jsonl
            else None
        )
        if rank0 is None:
            try:
                import jax

                rank0 = jax.process_index() == 0
            except Exception:
                rank0 = True
        self._fh = None
        if self.path is not None and rank0:
            try:
                os.makedirs(run_dir, exist_ok=True)
                self._fh = open(self.path, "a")
            except OSError as e:
                warnings.warn(
                    f"trace.jsonl stream could not open ({e}); spans are "
                    "ring-buffered only for this run",
                    RuntimeWarning,
                    stacklevel=2,
                )
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=max(int(ring), 1))
        self._tls = threading.local()
        self._steps = 0
        self._flushed_at = 0.0
        self.emitted = 0
        self._c_spans = registry().counter(
            "hydragnn_trace_spans_total",
            "Spans emitted by the tracing plane, by span name",
            labelnames=("name",),
        )
        atexit.register(self._atexit_flush)

    # -- sampling -------------------------------------------------------------

    def sample_request(self) -> bool:
        """Head decision for one serving request (probability
        ``Telemetry.trace_sample``)."""
        return self.sample > 0 and random.random() < self.sample

    def sample_step(self) -> bool:
        """Head decision for one training step: every
        ``Telemetry.trace_interval_steps``-th step is traced (the first
        sampled step is step N, so warm-up noise is skipped)."""
        if self.every_n_steps <= 0:
            return False
        self._steps += 1
        return self._steps % self.every_n_steps == 0

    # -- thread-local context -------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    def current_trace_id(self) -> Optional[str]:
        cur = self.current()
        return cur.trace_id if cur is not None else None

    # -- span lifecycle -------------------------------------------------------

    def begin(
        self,
        name: str,
        parent: Optional[Span] = None,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attributes: Optional[Dict[str, Any]] = None,
        start_unix: Optional[float] = None,
    ) -> Span:
        """Open a span with an explicit context (cross-thread safe; does
        NOT touch the thread-local stack). With no parent/trace given, a
        new trace root is created. ``start_unix`` backdates the span (the
        sampling decision may only be reachable after the work started)."""
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        if trace_id is None:
            trace_id = _new_trace_id()
        return Span(
            name,
            trace_id,
            parent_id=parent_id,
            start_unix=start_unix,
            attributes=attributes,
        )

    def finish(self, span: Span) -> None:
        """End an explicitly begun span and emit it."""
        span.end()
        self._emit(span)

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[Span] = None, **attributes):
        """Thread-local span: parents under this thread's current span
        (or the explicit ``parent``), marks ERROR status on exception and
        re-raises."""
        sp = self.begin(
            name, parent=parent if parent is not None else self.current(),
            attributes=attributes,
        )
        stack = self._stack()
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.set_status(STATUS_ERROR, f"{type(e).__name__}: {e}")
            raise
        finally:
            if stack and stack[-1] is sp:
                stack.pop()
            else:  # out-of-order exit: drop it wherever it sits
                try:
                    stack.remove(sp)
                except ValueError:
                    pass
            self.finish(sp)

    def emit_completed(
        self,
        name: str,
        start_unix: float,
        duration_s: float,
        parent: Optional[Span] = None,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        attributes: Optional[Dict[str, Any]] = None,
        links: Iterable[Tuple[str, str]] = (),
        status: int = STATUS_UNSET,
        status_message: str = "",
    ) -> Span:
        """Record an already-measured operation as a finished span (the
        retroactive shape: queue waits, region timers, host batch build)."""
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        sp = Span(
            name,
            trace_id if trace_id is not None else _new_trace_id(),
            parent_id=parent_id,
            start_unix=start_unix,
            attributes=attributes,
        )
        for t, s in links:
            sp.add_link(t, s)
        if status != STATUS_UNSET:
            sp.set_status(status, status_message)
        sp.end(duration_s=duration_s)
        self._emit(sp)
        return sp

    # -- sink -----------------------------------------------------------------

    def _emit(self, span: Span) -> None:
        rec = span.to_record()
        rec["host"] = self.host
        with self._lock:
            self._ring.append(rec)
            self.emitted += 1
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                    now = time.monotonic()
                    # flush at most ~1/s (the MetricsStream cadence): the
                    # fsync-free flush is still a syscall on the hot path
                    if now - self._flushed_at >= 1.0:
                        self._fh.flush()
                        self._flushed_at = now
                except (OSError, ValueError) as e:
                    self._fh = None
                    warnings.warn(
                        f"trace.jsonl stream failed ({e}); spans are "
                        "ring-buffered only for the rest of this run",
                        RuntimeWarning,
                        stacklevel=3,
                    )
        self._c_spans.inc(name=span.name)

    def recent(self) -> List[Dict[str, Any]]:
        """The last N finished span records (the flight-recorder window)."""
        with self._lock:
            return list(self._ring)

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                except (OSError, ValueError):
                    self._fh = None

    def _atexit_flush(self) -> None:
        # abnormal-exit guarantee: whatever reached the writer is on disk
        # even when the owner never called close() (unhandled exception,
        # sys.exit from a signal handler)
        try:
            self.flush()
        except Exception:
            pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
        try:
            atexit.unregister(self._atexit_flush)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# process-active tracer: the hook point for subsystems that cannot be handed
# a Tracer instance (utils/tracer.py regions, checkpoint IO, event trace-id
# attachment)
# ---------------------------------------------------------------------------

_ACTIVE: Optional[Tracer] = None
_ACTIVE_LOCK = threading.Lock()


def install(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process-active tracer (last install wins — one
    live run per process is the deployment model, tests install/uninstall
    around themselves)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = tracer
    return tracer


def uninstall(tracer: Optional[Tracer] = None) -> None:
    """Clear the active tracer (only if it is ``tracer``, when given —
    a nested run tearing down must not clobber its parent's install)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if tracer is None or _ACTIVE is tracer:
            _ACTIVE = None


def active() -> Optional[Tracer]:
    return _ACTIVE


def current_trace_id() -> Optional[str]:
    """Trace id of the active tracer's current thread-local span, or None —
    the hook obs/events.py uses to stamp events with causal context."""
    t = _ACTIVE
    if t is None:
        return None
    try:
        return t.current_trace_id()
    except Exception:
        return None


def note_region(name: str, duration_s: float) -> None:
    """Region-timer unification hook (utils/tracer.py ``stop`` calls this):
    when a sampled span is open on this thread, the closed region becomes a
    retroactive child span of it. No active tracer / no open span = no-op,
    so unsampled steps pay one None check."""
    t = _ACTIVE
    if t is None:
        return
    cur = t.current()
    if cur is None:
        return
    t.emit_completed(
        name, time.time() - duration_s, duration_s, parent=cur
    )


def note_completed(
    name: str,
    duration_s: float,
    attributes: Optional[Dict[str, Any]] = None,
) -> None:
    """Standalone-operation hook (checkpoint IO): emit a finished span via
    the active tracer, parented under the current span when one is open,
    otherwise as its own single-span trace."""
    t = _ACTIVE
    if t is None:
        return
    t.emit_completed(
        name,
        time.time() - duration_s,
        duration_s,
        parent=t.current(),
        attributes=attributes,
    )
