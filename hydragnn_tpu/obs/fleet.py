"""Fleet observability plane: cross-host aggregation, straggler/desync
detection, and per-host trace stitching (docs/OBSERVABILITY.md "Fleet").

The r7/r8/r12 planes are strictly single-process: each host publishes its
own registry, metrics.jsonl, trace.jsonl, and flight dumps with no host
identity and no cross-host view — yet every open ROADMAP item is
multi-host, and a straggling or desynced host is invisible until the whole
mesh stalls. This module is the layer above them:

- **host identity** (``host_identity``): every metrics.jsonl record, span,
  build-info scrape, and flight dump self-identifies with the process
  index. ``HYDRAGNN_FLEET_HOST_INDEX``/``_COUNT`` override the live JAX
  runtime so a *simulated* fleet (independent CPU processes,
  run-scripts/fleet_smoke.py) carries real host identities.
- **push-based aggregation**: every host's ``StepTelemetry`` flush window
  serializes its registry (``registry_snapshot``) and POSTs it to the
  rank-0 collector over the existing Prometheus/HTTP substrate
  (obs/prometheus.py ``post_routes``) — loopback-compatible, so the
  single-host degenerate case runs the identical path. The collector
  merges per-host snapshots (counters max-merge, gauges last-write — the
  registry's own absorption semantics, applied across pushes) and
  publishes ``hydragnn_fleet_{min,mean,max}{series=...}`` across-host
  aggregates plus per-host step / step-lag / staleness gauges.
- **straggler & desync watchdog**: each push doubles as a heartbeat
  carrying the host's step index, window step time, and (when the compile
  plane's comm accounting filled it) its estimated collective fraction.
  The collector flags a host whose step time skews beyond
  ``fleet_straggler_factor`` x the fleet median (or whose collective
  fraction exceeds ``fleet_collective_budget``) as ``fleet_straggler``,
  and step progress skewed past ``fleet_max_step_lag`` as
  ``fleet_desync``. A detection queues a broadcast command; every host
  applies it exactly once from its next push response — emitting the
  typed event locally and triggering a coordinated flight-recorder dump
  keyed by the same fleet step index (dump directories are
  host-disambiguated, obs/flightrec.py). A host whose heartbeat goes
  missing past ``fleet_stale_after_s`` goes STALE: its series leave the
  fleet aggregates (they must not freeze them) and ``fleet_host_stale``
  is emitted once.
- **trace stitching** (``merge_traces`` / ``python -m
  hydragnn_tpu.obs.fleet``): per-host trace.jsonl streams (spans carry
  their host, obs/trace.py) merge into one time-ordered run-level view.

Everything here follows the plane's contract: observability never takes
the owner down. A dead collector degrades pushes to warn-once retries; a
bind failure degrades the collector to local-only; fleet off means ZERO
extra work (the loop holds no plane object at all) and the step program
is untouched either way — the fleet is host-side only by construction.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
import warnings
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .events import EV_FLEET_DESYNC, EV_FLEET_HOST_STALE, EV_FLEET_STRAGGLER
from .events import emit as emit_event
from .registry import MetricsRegistry, registry
from ..utils import envflags

# push payload schema version (the fleet analog of metrics.jsonl "v")
FLEET_SCHEMA_VERSION = 1

# how many broadcast commands the collector retains for late pushers; a
# host further behind than this missed a window the watchdog already
# re-fires on, so unbounded retention buys nothing
_COMMAND_RING = 16

# minimum seconds between pushes: a fast CPU step loop can flush telemetry
# windows every few milliseconds, and serializing + POSTing the registry at
# that rate is a double-digit step-time tax (the fleet smoke's A/B caught
# exactly this). 1 Hz is plenty for a 30 s staleness timeout and a
# seconds-scale watchdog — the same rate-limit discipline as the memory
# gauges and stream flushes (obs/telemetry.py).
_PUSH_MIN_INTERVAL_S = 1.0


def host_identity() -> Tuple[int, int]:
    """(host_index, host_count) of this process in the fleet.

    ``HYDRAGNN_FLEET_HOST_INDEX``/``HYDRAGNN_FLEET_HOST_COUNT`` override
    (the simulated-fleet surface: independent single-process JAX runtimes
    each believe they are process 0 — the env gives them their fleet
    identity); otherwise the live JAX distributed runtime, falling back
    to the scheduler envs the native launcher exports
    (``WORLD_SIZE``/``RANK``, SLURM, OMPI — parallel/mesh.py
    ``local_host_info``, which also knows a skipped rendezvous means the
    process really is alone); (0, 1) without any of them."""
    env_i = envflags.env_str("HYDRAGNN_FLEET_HOST_INDEX")
    env_c = envflags.env_str("HYDRAGNN_FLEET_HOST_COUNT")
    if env_i is not None or env_c is not None:
        try:
            return int(env_i or 0), max(int(env_c or 1), 1)
        except ValueError:
            # a typo'd identity env must not take the owner down (this
            # runs inside MetricsStream/Tracer construction) — warn and
            # fall through to the runtime/scheduler resolution
            warnings.warn(
                "malformed HYDRAGNN_FLEET_HOST_INDEX/_COUNT "
                f"({env_i!r}/{env_c!r}); falling back to the runtime's "
                "host identity",
                RuntimeWarning,
                stacklevel=2,
            )
    try:
        from ..parallel.mesh import local_host_info

        count, index = local_host_info()
        return index, count
    except Exception:
        return 0, 1


def _valid_collector_addr(addr: str) -> bool:
    """The 'host:port' grammar resolve_telemetry enforces on the config
    key, shared with the env path (obs/telemetry.py validation)."""
    host_part, sep, port_part = addr.rpartition(":")
    return bool(sep) and bool(host_part) and port_part.isdigit()


def series_key(name: str, labels: Iterable[Tuple[str, str]]) -> str:
    """Canonical one-string series identity (``name{k="v",...}``) — the
    label value of the fleet aggregate gauges."""
    labs = list(labels)
    if not labs:
        return name
    return name + "{" + ",".join(f'{k}="{v}"' for k, v in labs) + "}"


def registry_snapshot(
    reg: Optional[MetricsRegistry] = None,
) -> List[Dict[str, Any]]:
    """Serialize the registry's scalar samples for one push: counters and
    gauges verbatim, histograms as their ``_sum``/``_count`` series
    (buckets are excluded — per-host bucket CDFs do not min/mean/max into
    anything meaningful and dominate payload size). The fleet's own
    ``hydragnn_fleet_*`` output gauges are excluded too, or the rank-0
    host would aggregate its aggregates."""
    reg = reg if reg is not None else registry()
    out: List[Dict[str, Any]] = []
    for metric in reg.collect():
        if metric.name.startswith("hydragnn_fleet_"):
            continue
        for suffix, labels, value in metric.samples():
            if suffix == "_bucket":
                continue
            out.append(
                {
                    "n": metric.name + suffix,
                    "k": metric.kind,
                    "l": [list(kv) for kv in labels],
                    "v": float(value),
                }
            )
    return out


class _HostState:
    """Collector-side view of one pushing host."""

    __slots__ = (
        "host", "step", "step_time_s", "comm_fraction", "ts", "mono",
        "counters", "gauges", "stale", "pushes", "delivered_cmd",
        "push_gap_ema",
    )

    def __init__(self, host: int):
        self.host = host
        self.step = 0
        self.step_time_s: Optional[float] = None
        self.comm_fraction: Optional[float] = None
        self.ts = 0.0
        self.mono = 0.0
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.stale = False
        self.pushes = 0
        # highest broadcast-command id already RETURNED to this host:
        # a restarted pusher (fresh ack=0) must not replay the whole
        # command ring — each stale replay would cost a flight dump,
        # and 8 of them exhaust the recorder's per-run budget
        self.delivered_cmd = 0
        # EMA of this host's inter-push gap: the staleness threshold
        # scales with the host's OWN cadence (heartbeats ride telemetry
        # flush windows, so slow-step runs legitimately push slower than
        # any fixed wall-clock bound)
        self.push_gap_ema: Optional[float] = None


class FleetCollector:
    """Rank-0 absorber of per-host registry snapshots + the fleet
    watchdog. ``absorb(payload)`` is the push sink (mounted at
    ``/fleet/push`` by ``FleetPlane``); it merges the snapshot, refreshes
    the ``hydragnn_fleet_*`` aggregates, runs straggler/desync/staleness
    detection, and returns the response dict carrying any broadcast
    commands the pushing host has not applied yet.

    Merge semantics (the registry's own absorption contract, applied
    across pushes): counter series max-merge — a re-pushed or reordered
    snapshot can never move a monotonic total backwards — and gauge
    series last-write-wins. Aggregates are computed over LIVE hosts only:
    a host that disappears goes stale after ``stale_after_s`` and its
    series leave the min/mean/max, they do not freeze it."""

    def __init__(
        self,
        straggler_factor: float = 2.0,
        max_step_lag: int = 200,
        stale_after_s: float = 30.0,
        collective_budget: Optional[float] = None,
        straggler_min_skew_s: float = 0.005,
        reg: Optional[MetricsRegistry] = None,
    ):
        self.straggler_factor = float(straggler_factor)
        self.max_step_lag = int(max_step_lag)
        self.stale_after_s = float(stale_after_s)
        self.collective_budget = (
            float(collective_budget) if collective_budget is not None else None
        )
        self.straggler_min_skew_s = float(straggler_min_skew_s)
        self._lock = threading.Lock()
        self._hosts: Dict[int, _HostState] = {}
        self._commands: "deque[Dict[str, Any]]" = deque(maxlen=_COMMAND_RING)
        self._next_command_id = 1
        # (kind, host, cause) currently firing — a condition must clear
        # before the same detection can queue a second broadcast
        self._active: set = set()
        # aggregate series published last refresh (retired when their
        # contributors all go stale)
        self._published: set = set()
        reg = reg if reg is not None else registry()
        self._g_hosts = reg.gauge(
            "hydragnn_fleet_hosts",
            "Live (non-stale) hosts the fleet collector is aggregating",
        )
        self._g_step = reg.gauge(
            "hydragnn_fleet_host_step",
            "Latest optimizer step each host reported",
            labelnames=("host",),
        )
        self._g_lag = reg.gauge(
            "hydragnn_fleet_step_lag",
            "Steps each host trails the fleet's most advanced host",
            labelnames=("host",),
        )
        self._g_step_time = reg.gauge(
            "hydragnn_fleet_host_step_time_seconds",
            "Mean step time of each host's last telemetry window",
            labelnames=("host",),
        )
        self._g_stale = reg.gauge(
            "hydragnn_fleet_host_stale",
            "1 while a host's heartbeat is older than fleet_stale_after_s",
            labelnames=("host",),
        )
        self._g_min = reg.gauge(
            "hydragnn_fleet_min",
            "Across-host minimum of each scalar registry series",
            labelnames=("series",),
        )
        self._g_mean = reg.gauge(
            "hydragnn_fleet_mean",
            "Across-host mean of each scalar registry series",
            labelnames=("series",),
        )
        self._g_max = reg.gauge(
            "hydragnn_fleet_max",
            "Across-host maximum of each scalar registry series",
            labelnames=("series",),
        )
        self._c_pushes = reg.counter(
            "hydragnn_fleet_pushes_total",
            "Per-host registry snapshots absorbed by the collector",
            labelnames=("host",),
        )

    # -- push sink -----------------------------------------------------------

    def absorb(
        self, payload: Dict[str, Any], now: Optional[float] = None
    ) -> Dict[str, Any]:
        """Merge one host push; returns the response (ok + unapplied
        broadcast commands). ``now`` (monotonic seconds) is injectable for
        the staleness tests."""
        mono = time.monotonic() if now is None else float(now)
        host = int(payload.get("host", 0))
        ack = int(payload.get("ack", 0))
        with self._lock:
            st = self._hosts.setdefault(host, _HostState(host))
            if st.pushes > 0 and not st.stale:
                # a rejoin gap is an OUTAGE, not cadence — folding it into
                # the EMA would stretch the staleness threshold to cover
                # the very silence it is supposed to detect
                gap = max(mono - st.mono, 0.0)
                st.push_gap_ema = (
                    gap if st.push_gap_ema is None
                    else 0.7 * st.push_gap_ema + 0.3 * gap
                )
            st.pushes += 1
            st.mono = mono
            st.ts = float(payload.get("ts", time.time()))
            st.step = int(payload.get("step", st.step))
            # overwrite with the payload VERBATIM — None means "no fresh
            # measurement this window" and must clear the stored sample,
            # or the watchdog keeps evaluating (and never un-firing) a
            # collective-budget/straggler condition against an
            # arbitrarily old reading
            v = payload.get("step_time_s")
            st.step_time_s = float(v) if v is not None else None
            v = payload.get("comm_fraction_est")
            st.comm_fraction = float(v) if v is not None else None
            if st.stale:
                st.stale = False  # a returning host rejoins the aggregates
                self._g_stale.set(0.0, host=str(host))
            for s in payload.get("samples", ()):
                key = series_key(
                    str(s["n"]), [(str(k), str(v)) for k, v in s.get("l", ())]
                )
                val = float(s["v"])
                if s.get("k") == "counter":
                    # max-merge: monotonic totals absorb idempotently
                    st.counters[key] = max(st.counters.get(key, 0.0), val)
                else:
                    st.gauges[key] = val  # last write wins
            self._sweep_locked(mono)
            self._detect_locked(mono)
            self._publish_locked()
            # deliver each command to each host at most once (optimistic:
            # delivery is marked when the response is BUILT — a response
            # lost to a dying process loses its commands, which is the
            # right trade for an observability broadcast; re-delivering
            # on restart would burn the flight-dump budget on replays)
            floor = max(ack, st.delivered_cmd)
            commands = [
                dict(c) for c in self._commands if int(c["id"]) > floor
            ]
            if commands:
                st.delivered_cmd = max(int(c["id"]) for c in commands)
        self._c_pushes.inc(host=str(host))
        return {"ok": True, "v": FLEET_SCHEMA_VERSION, "commands": commands}

    def forget(self, host: int) -> None:
        """Drop one host's state entirely — the serving-fleet supervisor
        calls this when it respawns a replica, so the dead incarnation's
        heartbeat entry (which would go stale within seconds) can never be
        mistaken for the new process. Staleness on the slot resumes only
        after the new incarnation's first push recreates the entry. The
        host's per-host gauges are retired with it; its contributions to
        the min/mean/max aggregates leave at the next publish."""
        host = int(host)
        with self._lock:
            if self._hosts.pop(host, None) is None:
                return
            label = str(host)
            self._g_step.remove(host=label)
            self._g_lag.remove(host=label)
            self._g_step_time.remove(host=label)
            self._g_stale.remove(host=label)
            self._publish_locked()

    def sweep(self, now: Optional[float] = None) -> None:
        """Staleness pass without a push (tests; a timer would also fit
        here — in production every push sweeps, and a fleet with zero
        pushes has nothing to aggregate anyway)."""
        mono = time.monotonic() if now is None else float(now)
        with self._lock:
            self._sweep_locked(mono)
            self._publish_locked()

    # -- internals (all under self._lock) ------------------------------------

    def _live(self) -> List[_HostState]:
        return [h for h in self._hosts.values() if not h.stale]

    def _sweep_locked(self, mono: float) -> None:
        for st in self._hosts.values():
            # the threshold adapts to the host's own push cadence: a run
            # whose flush windows legitimately take 40 s must not flap
            # stale/rejoined on a 30 s wall-clock default — silence is
            # only staleness once it clearly exceeds BOTH the configured
            # bound and ~3 missed heartbeats
            threshold = max(
                self.stale_after_s, 3.0 * (st.push_gap_ema or 0.0)
            )
            if not st.stale and mono - st.mono > threshold:
                st.stale = True
                self._g_stale.set(1.0, host=str(st.host))
                try:
                    emit_event(
                        EV_FLEET_HOST_STALE,
                        severity="warn",
                        host=st.host,
                        last_step=st.step,
                        silent_s=round(mono - st.mono, 3),
                    )
                except Exception:
                    pass

    def _queue_command_locked(
        self, kind: str, offender: int, step: int, cause: str
    ) -> None:
        self._commands.append(
            {
                "id": self._next_command_id,
                "kind": kind,
                "host": offender,
                "step": int(step),
                "cause": cause,
            }
        )
        self._next_command_id += 1

    def _detect_locked(self, mono: float) -> None:
        live = self._live()
        firing: set = set()
        if live:
            fleet_step = max(h.step for h in live)
            # desync: step progress skewed beyond the configured bound
            for h in live:
                if fleet_step - h.step > self.max_step_lag:
                    firing.add((EV_FLEET_DESYNC, h.host, "step_lag"))
            # straggler: window step time beyond factor x the median of
            # the OTHER hosts. The candidate is excluded from its own
            # baseline: a fleet-wide median that averages the straggler
            # in makes a 2-host fleet mathematically undetectable at
            # factor >= 2 (slow > f*(slow+fast)/2 reduces to 0 > fast),
            # and large fleets are unaffected by dropping one sample.
            timed = [h for h in live if h.step_time_s is not None]
            if len(timed) >= 2:
                for h in timed:
                    others = sorted(
                        x.step_time_s for x in timed if x is not h
                    )
                    med = others[len(others) // 2]
                    if len(others) % 2 == 0:
                        med = (med + others[len(others) // 2 - 1]) / 2.0
                    if (
                        h.step_time_s > self.straggler_factor * med
                        and h.step_time_s - med > self.straggler_min_skew_s
                    ):
                        firing.add((EV_FLEET_STRAGGLER, h.host, "step_time"))
            # collective budget: time-inside-collective estimate over bound
            if self.collective_budget is not None:
                for h in live:
                    if (
                        h.comm_fraction is not None
                        and h.comm_fraction > self.collective_budget
                    ):
                        firing.add(
                            (EV_FLEET_STRAGGLER, h.host, "collective_budget")
                        )
            for key in firing - self._active:
                kind, offender, cause = key
                self._queue_command_locked(kind, offender, fleet_step, cause)
        # a cleared condition re-arms its detection
        self._active = firing

    def _publish_locked(self) -> None:
        live = self._live()
        self._g_hosts.set(float(len(live)))
        if not self._hosts:
            return
        fleet_step = max((h.step for h in live), default=0)
        for st in self._hosts.values():
            self._g_step.set(float(st.step), host=str(st.host))
            if not st.stale:
                self._g_lag.set(
                    float(max(fleet_step - st.step, 0)), host=str(st.host)
                )
                if st.step_time_s is not None:
                    self._g_step_time.set(
                        st.step_time_s, host=str(st.host)
                    )
        # across-host aggregates over live hosts only
        series: Dict[str, List[float]] = {}
        for st in live:
            for key, val in st.counters.items():
                series.setdefault(key, []).append(val)
            for key, val in st.gauges.items():
                series.setdefault(key, []).append(val)
        for key, vals in series.items():
            self._g_min.set(min(vals), series=key)
            self._g_mean.set(sum(vals) / len(vals), series=key)
            self._g_max.set(max(vals), series=key)
        # retire aggregates whose every contributor went stale: the
        # registry would otherwise scrape the dead host's last value
        # forever, indistinguishable from a live reading (the module
        # contract: stale series LEAVE the aggregates)
        for key in self._published - set(series):
            self._g_min.remove(series=key)
            self._g_mean.remove(series=key)
            self._g_max.remove(series=key)
        self._published = set(series)

    # -- introspection (tests, the smoke) ------------------------------------

    def hosts(self) -> Dict[int, Dict[str, Any]]:
        with self._lock:
            return {
                h.host: {
                    "step": h.step,
                    "step_time_s": h.step_time_s,
                    "stale": h.stale,
                    "pushes": h.pushes,
                    "series": len(h.counters) + len(h.gauges),
                }
                for h in self._hosts.values()
            }

    def host_series(self, host: int) -> Dict[str, float]:
        """One host's absorbed scalar series (gauges last-write, counters
        max-merged), keyed by canonical ``series_key`` — the serving fleet
        manager's per-replica load/health view (live queue depth, shed
        totals) without re-scraping each replica's /metrics. Empty dict
        for a host that never pushed."""
        with self._lock:
            st = self._hosts.get(int(host))
            if st is None:
                return {}
            out = dict(st.counters)
            out.update(st.gauges)
            return out

    def pending_commands(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(c) for c in self._commands]


class FleetPusher:
    """Per-host push client: serializes the local registry each telemetry
    flush window, POSTs it to the collector on a background thread (the
    step path never blocks on the network — a slower-than-window push
    drops the stale window and sends the latest), and applies broadcast
    commands from the response exactly once each: emit the typed fleet
    event locally and trigger a coordinated flight dump keyed by the
    command's fleet step."""

    def __init__(
        self,
        url: str,
        host: int,
        host_count: int,
        reg: Optional[MetricsRegistry] = None,
        timeout_s: float = 2.0,
        min_interval_s: float = _PUSH_MIN_INTERVAL_S,
    ):
        self.url = url
        self.host = int(host)
        self.host_count = int(host_count)
        self.timeout_s = float(timeout_s)
        self.min_interval_s = float(min_interval_s)
        self._last_accept = 0.0
        self._reg = reg
        self._ack = 0
        self.pushed = 0
        self.failures = 0
        self._warned = False
        self._lock = threading.Lock()
        self._pending: Optional[Dict[str, Any]] = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="fleet-push"
        )
        self._thread.start()

    def _payload(
        self,
        step: int,
        step_time_s: Optional[float],
        comm_fraction_est: Optional[float],
    ) -> Dict[str, Any]:
        return {
            "v": FLEET_SCHEMA_VERSION,
            "host": self.host,
            "host_count": self.host_count,
            "ts": round(time.time(), 3),
            "step": int(step),
            "step_time_s": step_time_s,
            "comm_fraction_est": comm_fraction_est,
            "ack": self._ack,
            "samples": registry_snapshot(self._reg),
        }

    def on_window(
        self,
        step: int,
        step_time_s: Optional[float] = None,
        comm_fraction_est: Optional[float] = None,
    ) -> None:
        """Queue this window's push (latest-wins when the worker is mid-
        push), rate-limited to ``min_interval_s`` — sub-second telemetry
        windows must not turn into a per-window serialize+POST tax. An
        accepted window's snapshot is serialized here — cheap dict walks
        — so the payload reflects the flush that triggered it."""
        now = time.monotonic()
        if now - self._last_accept < self.min_interval_s:
            return
        self._last_accept = now
        payload = self._payload(step, step_time_s, comm_fraction_est)
        with self._lock:
            self._pending = payload
        self._wake.set()

    def push_now(
        self,
        step: int,
        step_time_s: Optional[float] = None,
        comm_fraction_est: Optional[float] = None,
    ) -> bool:
        """Synchronous push (tests + the close() flush)."""
        return self._post(self._payload(step, step_time_s, comm_fraction_est))

    def _worker(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.5)
            if self._stop.is_set():
                return
            self._wake.clear()
            with self._lock:
                payload, self._pending = self._pending, None
            if payload is not None:
                self._post(payload)

    def _post(self, payload: Dict[str, Any]) -> bool:
        try:
            req = urllib.request.Request(
                self.url,
                data=json.dumps(payload).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                body = json.loads(resp.read().decode("utf-8"))
        except (OSError, urllib.error.URLError, ValueError) as e:
            self.failures += 1
            if not self._warned:
                self._warned = True
                warnings.warn(
                    f"fleet push to {self.url} failed ({e}); will keep "
                    "retrying each window (warn-once)",
                    RuntimeWarning,
                    stacklevel=2,
                )
            return False
        self.pushed += 1
        self._apply_commands(body.get("commands") or ())
        return True

    def _apply_commands(self, commands: Sequence[Dict[str, Any]]) -> None:
        for cmd in commands:
            try:
                cid = int(cmd.get("id", 0))
            except (TypeError, ValueError):
                continue
            if cid <= self._ack:
                continue  # applied already (or a replay)
            self._ack = cid
            kind = str(cmd.get("kind", EV_FLEET_DESYNC))
            if kind not in (EV_FLEET_STRAGGLER, EV_FLEET_DESYNC):
                kind = EV_FLEET_DESYNC
            step = cmd.get("step")
            try:
                emit_event(
                    kind,
                    severity="warn",
                    host=self.host,
                    offender=cmd.get("host"),
                    step=step,
                    cause=cmd.get("cause"),
                )
            except Exception:
                pass
            # coordinated flight dump: every host dumps under the SAME
            # fleet step key; directories are host-disambiguated
            # (obs/flightrec.py), so shared-filesystem dumps line up
            # side by side instead of colliding
            try:
                from . import flightrec

                flightrec.trigger(f"{kind}_step{step}")
            except Exception:
                pass

    def close(self, flush_step: Optional[int] = None) -> None:
        """Stop the worker; ``flush_step`` sends one final synchronous
        push so the collector sees the host's terminal step (and this
        host applies any last broadcast)."""
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2.0)
        if flush_step is not None:
            self.push_now(flush_step)


class FleetPlane:
    """Per-run wiring of the fleet plane (owned by ``StepTelemetry``).

    Host 0 mounts the collector's push sink on its own HTTP endpoint
    (``fleet_collector_host``:``fleet_collector_port``, or the port from
    the shared ``fleet_collector``/``HYDRAGNN_FLEET_COLLECTOR`` address);
    every host — including host 0, over loopback — runs a pusher against
    the resolved collector address. The symmetric push path is the point:
    the single-host degenerate case and the N-host fleet run identical
    code."""

    @staticmethod
    def from_settings(
        settings: Dict[str, Any], run_dir: Optional[str] = None
    ) -> Optional["FleetPlane"]:
        if not settings.get("fleet"):
            return None
        return FleetPlane(settings, run_dir=run_dir)

    def __init__(self, settings: Dict[str, Any], run_dir: Optional[str] = None):
        self.run_dir = run_dir
        self.host, self.host_count = host_identity()
        addr = envflags.env_str("HYDRAGNN_FLEET_COLLECTOR") or settings.get(
            "fleet_collector"
        )
        if addr is not None and not _valid_collector_addr(str(addr)):
            # the env path bypasses resolve_telemetry's host:port check —
            # apply the same grammar here, degrading loudly instead of
            # binding an unrelated port and pushing at port 80
            warnings.warn(
                f"fleet collector address {addr!r} is not 'host:port'; "
                "ignoring it (set HYDRAGNN_FLEET_COLLECTOR or "
                "Telemetry.fleet_collector to rank 0's host:port)",
                RuntimeWarning,
                stacklevel=2,
            )
            addr = None
        self.collector: Optional[FleetCollector] = None
        self.endpoint = None
        self.pusher: Optional[FleetPusher] = None
        if self.host == 0:
            self.collector = FleetCollector(
                straggler_factor=float(
                    settings.get("fleet_straggler_factor", 2.0)
                ),
                max_step_lag=int(settings.get("fleet_max_step_lag", 200)),
                stale_after_s=float(settings.get("fleet_stale_after_s", 30.0)),
                collective_budget=settings.get("fleet_collective_budget"),
            )
            port = int(settings.get("fleet_collector_port") or 0)
            bind_host = str(settings.get("fleet_collector_host", "127.0.0.1"))
            if addr:
                try:
                    port = int(str(addr).rsplit(":", 1)[1])
                except (IndexError, ValueError):
                    pass
                if bind_host == "127.0.0.1":
                    # an explicit collector address means off-host pushers
                    # exist — a loopback bind would refuse every one of
                    # them (and rank 0's own push aimed at the external
                    # address). Operators who really want loopback set
                    # fleet_collector to a 127.0.0.1:... address.
                    host_part = str(addr).rsplit(":", 1)[0]
                    bind_host = (
                        "127.0.0.1"
                        if host_part in ("127.0.0.1", "localhost")
                        else "0.0.0.0"
                    )
            from .prometheus import TelemetryHTTPServer

            try:
                self.endpoint = TelemetryHTTPServer(
                    host=bind_host,
                    port=port,
                    post_routes={"/fleet/push": self._on_push},
                )
            except (OSError, OverflowError) as e:
                warnings.warn(
                    f"fleet collector could not bind port {port} ({e}); "
                    "cross-host aggregation is unavailable for this run",
                    RuntimeWarning,
                    stacklevel=2,
                )
            if addr is None and self.endpoint is not None:
                addr = f"127.0.0.1:{self.endpoint.port}"
        if addr:
            self.pusher = FleetPusher(
                f"http://{addr}/fleet/push", self.host, self.host_count
            )
        elif self.host != 0:
            warnings.warn(
                "fleet plane is on but no collector address is configured "
                "for this non-zero host (set Telemetry.fleet_collector or "
                "HYDRAGNN_FLEET_COLLECTOR to rank 0's host:port); this "
                "host stays invisible to the fleet view",
                RuntimeWarning,
                stacklevel=2,
            )

    def _on_push(self, body: bytes) -> Tuple[int, Dict[str, Any]]:
        try:
            payload = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            return 400, {"ok": False, "error": f"bad payload: {e}"}
        if self.collector is None:  # pragma: no cover - defensive
            return 503, {"ok": False, "error": "no collector"}
        return 200, self.collector.absorb(payload)

    @property
    def collector_url(self) -> Optional[str]:
        return self.endpoint.url if self.endpoint is not None else None

    def on_window(
        self,
        step: int,
        step_time_s: Optional[float] = None,
        comm_fraction_est: Optional[float] = None,
    ) -> None:
        if self.pusher is not None:
            self.pusher.on_window(step, step_time_s, comm_fraction_est)

    def close(self, final_step: Optional[int] = None) -> None:
        if self.pusher is not None:
            try:
                self.pusher.close(flush_step=final_step)
            except Exception:
                pass
            self.pusher = None
        if self.endpoint is not None:
            try:
                self.endpoint.close()
            except Exception:
                pass
            self.endpoint = None


# ---------------------------------------------------------------------------
# trace stitching: per-host trace.jsonl streams -> one run-level view
# ---------------------------------------------------------------------------


def merge_traces(
    paths: Sequence[str], out_path: str
) -> Dict[str, Any]:
    """Stitch per-host trace.jsonl streams (spans carry their ``host``,
    obs/trace.py) into one time-ordered run-level stream. Unparseable
    lines are counted and skipped (a crash can truncate a host's last
    line); span records missing a host keep their absence — stitching
    never invents identity. Returns ``{spans, hosts, files, skipped}``."""
    records: List[Dict[str, Any]] = []
    skipped = 0
    hosts: set = set()
    for path in paths:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    skipped += 1
                    continue
                if "host" in rec:
                    hosts.add(rec["host"])
                records.append(rec)
    records.sort(key=lambda r: int(r.get("startTimeUnixNano", 0)))
    with open(out_path, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    return {
        "spans": len(records),
        "hosts": sorted(hosts),
        "files": len(paths),
        "skipped": skipped,
    }


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m hydragnn_tpu.obs.fleet merged.jsonl trace*.jsonl`` —
    the run-level trace stitch."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2:
        print(
            "usage: python -m hydragnn_tpu.obs.fleet OUT.jsonl "
            "TRACE.jsonl [TRACE.jsonl ...]"
        )
        return 2
    out, inputs = argv[0], argv[1:]
    try:
        summary = merge_traces(inputs, out)
    except OSError as e:
        print(f"hydragnn_tpu.obs.fleet: {e}")
        return 2
    print(
        f"merged {summary['spans']} spans from {summary['files']} stream(s) "
        f"(hosts: {summary['hosts'] or ['unknown']}, "
        f"{summary['skipped']} unparseable line(s) skipped) -> {out}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
