"""Structured event log: typed, ring-buffered records for every discrete
incident the subsystems already detect (docs/OBSERVABILITY.md "Event log").

Before r8 these incidents were counter increments plus scattered
warnings/stderr lines: a guard skip bumped ``skipped_steps``, a shed bumped
a stats key, a retrace violation appended to a list inside the sentinel.
The event log gives each one a typed record — timestamp, kind, severity,
the active trace_id (obs/trace.py) when one is open, and the incident's own
attributes — in one process-wide ring buffer the crash flight recorder
(obs/flightrec.py) dumps verbatim, so a post-mortem sees the last N
incidents in order without re-running anything.

Publishing is unconditional and cheap (one deque append + one counter inc
under the registry lock), matching the registry's contract; sinks
(flight-recorder dumps, ``snapshot()`` consumers) are opt-in. Emission is
exception-safe by construction: a malformed attribute is coerced to its
``str`` rather than raised, because an incident *reporter* must never
become an incident *source*.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from collections import deque
from typing import Any, Dict, List, Optional

from .registry import registry

# -- stable event vocabulary (the kinds subsystems emit today) ---------------
EV_GUARD_SKIP = "guard_skip"              # non-finite steps skipped (epoch tally)
EV_GUARD_ROLLBACK = "guard_rollback"      # rollback policy restored a checkpoint
EV_GUARD_FATAL = "guard_fatal"            # non_finite_policy=error raising
EV_DATA_SKIP = "data_skip"                # validator reject (incl. quarantine)
EV_RETRACE_VIOLATION = "retrace_violation"  # sentinel saw a silent recompile
EV_CACHE_MISS = "compile_cache_miss"      # persistent compile cache miss
EV_LOADER_STALL = "loader_stall"          # LoaderStallError raised
EV_CKPT_WRITE = "checkpoint_write"        # checkpoint committed
EV_SHED = "serve_shed"                    # SLO load shed at admission
EV_QUEUE_FULL = "serve_queue_full"        # admission queue at its bound
EV_DEADLINE = "serve_deadline"            # request expired while queued
EV_WEDGE = "serve_wedge"                  # device-step watchdog fired
EV_DRAIN = "serve_drain"                  # graceful drain initiated
EV_RELOAD_SWAP = "reload_swap"            # hot reload installed a checkpoint
EV_RELOAD_REJECT = "reload_reject"        # hot reload rejected a candidate
EV_FLIGHT_DUMP = "flightrec_dump"         # the recorder itself dumped
EV_MIX_SOURCE_ADD = "mix_source_add"      # mixture source hot-added
EV_MIX_SOURCE_REMOVE = "mix_source_remove"  # mixture source hot-removed
EV_MIX_DEMOTE = "mix_demote"              # source quarantine-demoted (mix/)
EV_MIX_DRIFT = "mix_drift"                # per-branch loss diverged past threshold
EV_NUMERICS_PROVENANCE = "numerics_provenance"  # NaN drill-down located a tensor
EV_FLEET_STRAGGLER = "fleet_straggler"    # fleet watchdog flagged a slow host
EV_FLEET_DESYNC = "fleet_desync"          # step progress skewed past the bound
EV_FLEET_HOST_STALE = "fleet_host_stale"  # host heartbeat missing past timeout
EV_SHARDING_AUDIT = "sharding_audit"      # inspector flagged an over-replicated leaf
EV_TILE_PLAN = "tile_plan"                # kernel tile-plan choice (tune/runtime.py)
EV_ELASTIC_SHRINK = "elastic_shrink"      # fleet re-laid-out onto fewer hosts
EV_ELASTIC_GROW = "elastic_grow"          # fleet re-laid-out back onto more hosts
EV_REPLICA_EXIT = "replica_exit"          # serving replica process died
EV_REPLICA_RESTART = "replica_restart"    # supervisor restarted a replica
EV_REPLICA_BENCHED = "replica_benched"    # flap breaker benched a replica
EV_BREAKER_OPEN = "breaker_open"          # router circuit breaker opened
EV_BREAKER_CLOSE = "breaker_close"        # half-open probe reclosed a breaker
EV_RELOAD_ROLLBACK = "reload_rollback"    # rolling reload rolled back a regression
EV_QUANT_DRIFT = "quant_drift"            # int8 accuracy gate refused a state

EVENT_KINDS = (
    EV_GUARD_SKIP, EV_GUARD_ROLLBACK, EV_GUARD_FATAL, EV_DATA_SKIP,
    EV_RETRACE_VIOLATION, EV_CACHE_MISS, EV_LOADER_STALL, EV_CKPT_WRITE,
    EV_SHED, EV_QUEUE_FULL, EV_DEADLINE, EV_WEDGE, EV_DRAIN,
    EV_RELOAD_SWAP, EV_RELOAD_REJECT, EV_FLIGHT_DUMP,
    EV_MIX_SOURCE_ADD, EV_MIX_SOURCE_REMOVE, EV_MIX_DEMOTE, EV_MIX_DRIFT,
    EV_NUMERICS_PROVENANCE,
    EV_FLEET_STRAGGLER, EV_FLEET_DESYNC, EV_FLEET_HOST_STALE,
    EV_SHARDING_AUDIT, EV_TILE_PLAN,
    EV_ELASTIC_SHRINK, EV_ELASTIC_GROW,
    EV_REPLICA_EXIT, EV_REPLICA_RESTART, EV_REPLICA_BENCHED,
    EV_BREAKER_OPEN, EV_BREAKER_CLOSE, EV_RELOAD_ROLLBACK,
    EV_QUANT_DRIFT,
)

SEVERITIES = ("info", "warn", "error", "fatal")

# per-kind default severities: emitters that do not rank their own
# incident inherit the kind's rank here, so consumers (the run doctor's
# rules, the flight recorder's incident census) can order incidents by
# severity instead of re-deriving rank from kind-name heuristics. An
# emitter passing an explicit severity still wins (a retrace violation
# under policy=error emits "error", not the table's "warn").
DEFAULT_SEVERITY: Dict[str, str] = {
    EV_GUARD_SKIP: "warn",
    EV_GUARD_ROLLBACK: "error",
    EV_GUARD_FATAL: "fatal",
    EV_DATA_SKIP: "warn",
    EV_RETRACE_VIOLATION: "warn",
    EV_CACHE_MISS: "info",
    EV_LOADER_STALL: "error",
    EV_CKPT_WRITE: "info",
    EV_SHED: "warn",
    EV_QUEUE_FULL: "warn",
    EV_DEADLINE: "warn",
    EV_WEDGE: "error",
    EV_DRAIN: "info",
    EV_RELOAD_SWAP: "info",
    EV_RELOAD_REJECT: "warn",
    EV_FLIGHT_DUMP: "info",
    EV_MIX_SOURCE_ADD: "info",
    EV_MIX_SOURCE_REMOVE: "info",
    EV_MIX_DEMOTE: "warn",
    EV_MIX_DRIFT: "warn",
    EV_NUMERICS_PROVENANCE: "warn",
    EV_FLEET_STRAGGLER: "warn",
    EV_FLEET_DESYNC: "error",
    EV_FLEET_HOST_STALE: "warn",
    EV_SHARDING_AUDIT: "warn",
    EV_TILE_PLAN: "info",
    # a shrink is progress lost + degraded capacity; a re-grow is recovery
    EV_ELASTIC_SHRINK: "warn",
    EV_ELASTIC_GROW: "info",
    # one replica death is absorbed by the fleet (warn); a bench means the
    # fleet permanently lost capacity until an operator intervenes (error),
    # and a reload rollback means a bad checkpoint reached serving (error)
    EV_REPLICA_EXIT: "warn",
    EV_REPLICA_RESTART: "warn",
    EV_REPLICA_BENCHED: "error",
    EV_BREAKER_OPEN: "warn",
    EV_BREAKER_CLOSE: "info",
    EV_RELOAD_ROLLBACK: "error",
    # a refused quantized state means a candidate would have served wrong
    # answers — the gate caught it, but the rollout it rode is dead
    EV_QUANT_DRIFT: "error",
}


def severity_rank(severity: str) -> int:
    """Numeric rank of a severity (info=0 .. fatal=3; unknown ranks as
    info) — the shared ordering for doctor rules and dump censuses."""
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        return 0

# default ring capacity: deep enough that a post-mortem sees the whole
# incident cascade (a wedge under load sheds dozens of requests), small
# enough that the resident cost is a few hundred dicts
DEFAULT_CAPACITY = 256


def _json_safe(v: Any) -> Any:
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    if isinstance(v, dict):
        # structured evidence (elastic before/after layouts, sharding-table
        # summaries) must survive as objects, not reprs — the doctor
        # indexes into them
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        items = sorted(v, key=str) if isinstance(v, (set, frozenset)) else v
        return [_json_safe(x) for x in items]
    return str(v)


class EventLog:
    """Process-wide ring buffer of typed incident records, mirrored into
    the metrics registry (``hydragnn_events_total{kind=...}``)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        # RLock, not Lock: emitters run from signal handlers too (the serve
        # drain hook emits EV_DRAIN from SIGTERM) — a handler interrupting
        # its own thread mid-emit must be able to re-acquire, matching the
        # registry's locking contract
        self._lock = threading.RLock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=max(int(capacity), 1))
        self.emitted = 0
        # persistent JSONL sink (events.jsonl; attach_stream): the on-disk
        # analog of the ring so a *completed* run's incidents are readable
        # post-hoc (the run doctor's primary event source) instead of only
        # surviving inside flight dumps
        self._sink_fh = None
        self._sink_path: Optional[str] = None
        # records emitted while no sink was attached, written out by the
        # next attach_jsonl (bounded by the ring capacity)
        self._unstreamed: List[Dict[str, Any]] = []
        self._counter = registry().counter(
            "hydragnn_events_total",
            "Structured incident events emitted, by kind "
            "(docs/OBSERVABILITY.md event vocabulary)",
            labelnames=("kind",),
        )

    def emit(
        self,
        kind: str,
        severity: Optional[str] = None,
        trace_id: Optional[str] = None,
        **attrs: Any,
    ) -> Dict[str, Any]:
        """Record one incident. ``severity=None`` (the default) resolves
        through the per-kind ``DEFAULT_SEVERITY`` table so every record is
        ranked even when the emitter did not rank it; ``trace_id``
        defaults to the active tracer's current span context, so incidents
        inside a sampled request/step carry their causal anchor for free."""
        if trace_id is None:
            from . import trace as _trace

            trace_id = _trace.current_trace_id()
        if severity is None:
            severity = DEFAULT_SEVERITY.get(str(kind), "info")
        rec: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "kind": str(kind),
            "severity": severity if severity in SEVERITIES else "info",
        }
        if trace_id:
            rec["trace_id"] = trace_id
        for k, v in attrs.items():
            rec[k] = _json_safe(v)
        with self._lock:
            self._ring.append(rec)
            self.emitted += 1
            if self._sink_fh is not None:
                try:
                    # flushed per record: events are rare incidents (the
                    # hot paths emit none), and a crash must not truncate
                    # the very record that explains it
                    self._sink_fh.write(json.dumps(rec) + "\n")
                    self._sink_fh.flush()
                except (OSError, ValueError) as e:
                    self._sink_fh = None
                    warnings.warn(
                        f"events.jsonl stream failed ({e}); incident "
                        "records are ring-buffered only from here on",
                        RuntimeWarning,
                        stacklevel=2,
                    )
            else:
                # no sink yet: hold for backfill on the next attach — an
                # incident emitted before the run dir exists (e.g. the
                # elastic_shrink record from the resume guard, which runs
                # before the train loop arms events.jsonl) must still
                # reach the doctor's on-disk stream
                self._unstreamed.append(rec)
                del self._unstreamed[: -self._ring.maxlen]
        try:
            self._counter.inc(kind=rec["kind"])
        except Exception:
            pass  # an invalid label value must not fail the reporter
        return rec

    # -- persistent sink -----------------------------------------------------

    def attach_jsonl(self, path: str) -> Optional[str]:
        """Append-mode JSONL sink for every subsequent emit (last attach
        wins — one live run per process, matching the tracer's install
        contract). Returns the path, or None when it could not open (the
        ring keeps working either way)."""
        with self._lock:
            if self._sink_fh is not None:
                try:
                    self._sink_fh.close()
                except OSError:
                    pass
                self._sink_fh = None
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                self._sink_fh = open(path, "a")
                self._sink_path = path
                if self._unstreamed:
                    # backfill incidents that predate the sink (see emit)
                    for rec in self._unstreamed:
                        self._sink_fh.write(json.dumps(rec) + "\n")
                    self._sink_fh.flush()
                    self._unstreamed.clear()
            except OSError as e:
                self._sink_path = None
                warnings.warn(
                    f"events.jsonl sink could not open ({e}); incidents "
                    "stay ring-buffered only",
                    RuntimeWarning,
                    stacklevel=2,
                )
                return None
        return path

    def detach_jsonl(self) -> None:
        with self._lock:
            if self._sink_fh is not None:
                try:
                    self._sink_fh.close()
                except OSError:
                    pass
            self._sink_fh = None
            self._sink_path = None

    @property
    def sink_path(self) -> Optional[str]:
        return self._sink_path

    def snapshot(self) -> List[Dict[str, Any]]:
        """The last N events, oldest first (what the flight recorder dumps)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        """Drop buffered events (tests; the counter keeps its totals)."""
        with self._lock:
            self._ring.clear()
            self._unstreamed.clear()


_EVENTS = EventLog()


def events() -> EventLog:
    """The process-wide event log every subsystem emits into."""
    return _EVENTS


def emit(kind: str, severity: Optional[str] = None,
         trace_id: Optional[str] = None, **attrs: Any) -> Dict[str, Any]:
    """Module-level shorthand for ``events().emit(...)`` — the one-line
    call subsystems use at their incident sites. ``severity=None``
    inherits the kind's ``DEFAULT_SEVERITY`` rank."""
    return _EVENTS.emit(kind, severity=severity, trace_id=trace_id, **attrs)


def attach_stream(run_dir: str) -> Optional[str]:
    """Arm the persistent ``events.jsonl`` sink for ``run_dir`` (host-
    suffixed on non-zero fleet hosts, like ``metrics.jsonl`` — two
    processes appending one JSONL on a shared filesystem interleave
    mid-line). train/loop.py and api.run_server call this when the
    observability plane is on; the run doctor reads it back."""
    try:
        from .fleet import host_identity

        host_i, _ = host_identity()
    except Exception:
        host_i = 0
    fname = "events.jsonl" if host_i == 0 else f"events-h{host_i}.jsonl"
    return _EVENTS.attach_jsonl(os.path.join(run_dir, fname))


def detach_stream() -> None:
    """Close the persistent sink (run teardown)."""
    _EVENTS.detach_jsonl()
