"""Structured event log: typed, ring-buffered records for every discrete
incident the subsystems already detect (docs/OBSERVABILITY.md "Event log").

Before r8 these incidents were counter increments plus scattered
warnings/stderr lines: a guard skip bumped ``skipped_steps``, a shed bumped
a stats key, a retrace violation appended to a list inside the sentinel.
The event log gives each one a typed record — timestamp, kind, severity,
the active trace_id (obs/trace.py) when one is open, and the incident's own
attributes — in one process-wide ring buffer the crash flight recorder
(obs/flightrec.py) dumps verbatim, so a post-mortem sees the last N
incidents in order without re-running anything.

Publishing is unconditional and cheap (one deque append + one counter inc
under the registry lock), matching the registry's contract; sinks
(flight-recorder dumps, ``snapshot()`` consumers) are opt-in. Emission is
exception-safe by construction: a malformed attribute is coerced to its
``str`` rather than raised, because an incident *reporter* must never
become an incident *source*.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .registry import registry

# -- stable event vocabulary (the kinds subsystems emit today) ---------------
EV_GUARD_SKIP = "guard_skip"              # non-finite steps skipped (epoch tally)
EV_GUARD_ROLLBACK = "guard_rollback"      # rollback policy restored a checkpoint
EV_GUARD_FATAL = "guard_fatal"            # non_finite_policy=error raising
EV_DATA_SKIP = "data_skip"                # validator reject (incl. quarantine)
EV_RETRACE_VIOLATION = "retrace_violation"  # sentinel saw a silent recompile
EV_CACHE_MISS = "compile_cache_miss"      # persistent compile cache miss
EV_LOADER_STALL = "loader_stall"          # LoaderStallError raised
EV_CKPT_WRITE = "checkpoint_write"        # checkpoint committed
EV_SHED = "serve_shed"                    # SLO load shed at admission
EV_QUEUE_FULL = "serve_queue_full"        # admission queue at its bound
EV_DEADLINE = "serve_deadline"            # request expired while queued
EV_WEDGE = "serve_wedge"                  # device-step watchdog fired
EV_DRAIN = "serve_drain"                  # graceful drain initiated
EV_RELOAD_SWAP = "reload_swap"            # hot reload installed a checkpoint
EV_RELOAD_REJECT = "reload_reject"        # hot reload rejected a candidate
EV_FLIGHT_DUMP = "flightrec_dump"         # the recorder itself dumped
EV_MIX_SOURCE_ADD = "mix_source_add"      # mixture source hot-added
EV_MIX_SOURCE_REMOVE = "mix_source_remove"  # mixture source hot-removed
EV_MIX_DEMOTE = "mix_demote"              # source quarantine-demoted (mix/)
EV_MIX_DRIFT = "mix_drift"                # per-branch loss diverged past threshold
EV_NUMERICS_PROVENANCE = "numerics_provenance"  # NaN drill-down located a tensor
EV_FLEET_STRAGGLER = "fleet_straggler"    # fleet watchdog flagged a slow host
EV_FLEET_DESYNC = "fleet_desync"          # step progress skewed past the bound
EV_FLEET_HOST_STALE = "fleet_host_stale"  # host heartbeat missing past timeout
EV_SHARDING_AUDIT = "sharding_audit"      # inspector flagged an over-replicated leaf

EVENT_KINDS = (
    EV_GUARD_SKIP, EV_GUARD_ROLLBACK, EV_GUARD_FATAL, EV_DATA_SKIP,
    EV_RETRACE_VIOLATION, EV_CACHE_MISS, EV_LOADER_STALL, EV_CKPT_WRITE,
    EV_SHED, EV_QUEUE_FULL, EV_DEADLINE, EV_WEDGE, EV_DRAIN,
    EV_RELOAD_SWAP, EV_RELOAD_REJECT, EV_FLIGHT_DUMP,
    EV_MIX_SOURCE_ADD, EV_MIX_SOURCE_REMOVE, EV_MIX_DEMOTE, EV_MIX_DRIFT,
    EV_NUMERICS_PROVENANCE,
    EV_FLEET_STRAGGLER, EV_FLEET_DESYNC, EV_FLEET_HOST_STALE,
    EV_SHARDING_AUDIT,
)

SEVERITIES = ("info", "warn", "error", "fatal")

# default ring capacity: deep enough that a post-mortem sees the whole
# incident cascade (a wedge under load sheds dozens of requests), small
# enough that the resident cost is a few hundred dicts
DEFAULT_CAPACITY = 256


def _json_safe(v: Any) -> Any:
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return str(v)


class EventLog:
    """Process-wide ring buffer of typed incident records, mirrored into
    the metrics registry (``hydragnn_events_total{kind=...}``)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        # RLock, not Lock: emitters run from signal handlers too (the serve
        # drain hook emits EV_DRAIN from SIGTERM) — a handler interrupting
        # its own thread mid-emit must be able to re-acquire, matching the
        # registry's locking contract
        self._lock = threading.RLock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=max(int(capacity), 1))
        self.emitted = 0
        self._counter = registry().counter(
            "hydragnn_events_total",
            "Structured incident events emitted, by kind "
            "(docs/OBSERVABILITY.md event vocabulary)",
            labelnames=("kind",),
        )

    def emit(
        self,
        kind: str,
        severity: str = "info",
        trace_id: Optional[str] = None,
        **attrs: Any,
    ) -> Dict[str, Any]:
        """Record one incident. ``trace_id`` defaults to the active
        tracer's current span context, so incidents inside a sampled
        request/step carry their causal anchor for free."""
        if trace_id is None:
            from . import trace as _trace

            trace_id = _trace.current_trace_id()
        rec: Dict[str, Any] = {
            "ts": round(time.time(), 6),
            "kind": str(kind),
            "severity": severity if severity in SEVERITIES else "info",
        }
        if trace_id:
            rec["trace_id"] = trace_id
        for k, v in attrs.items():
            rec[k] = _json_safe(v)
        with self._lock:
            self._ring.append(rec)
            self.emitted += 1
        try:
            self._counter.inc(kind=rec["kind"])
        except Exception:
            pass  # an invalid label value must not fail the reporter
        return rec

    def snapshot(self) -> List[Dict[str, Any]]:
        """The last N events, oldest first (what the flight recorder dumps)."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        """Drop buffered events (tests; the counter keeps its totals)."""
        with self._lock:
            self._ring.clear()


_EVENTS = EventLog()


def events() -> EventLog:
    """The process-wide event log every subsystem emits into."""
    return _EVENTS


def emit(kind: str, severity: str = "info",
         trace_id: Optional[str] = None, **attrs: Any) -> Dict[str, Any]:
    """Module-level shorthand for ``events().emit(...)`` — the one-line
    call subsystems use at their incident sites."""
    return _EVENTS.emit(kind, severity=severity, trace_id=trace_id, **attrs)
