"""One source of truth for the observability record shapes
(docs/OBSERVABILITY.md "Streams catalog").

Before r14 the shapes of the ``metrics.jsonl`` / ``trace.jsonl`` / event
records lived implicitly in their producers (obs/telemetry.py flush,
obs/trace.py ``to_record``, obs/events.py ``emit``) and every consumer
(the smokes, bench_gate's trace stats, the fleet stitcher) re-derived
them by inspection. This module pins them down as versioned field specs:

- producers keep emitting exactly what they emit today — the drift test
  (tests/test_doctor.py) asserts every record kind the planes produce
  validates here, so a producer change that breaks a consumer breaks CI
  first;
- the run doctor (obs/doctor.py) parses every stream through
  ``validate_*`` and degrades invalid records to parse warnings instead
  of crashing on them (a truncated flight dump is evidence, not an
  excuse to die).

A field spec is ``name -> (types, required, allow_none)``. Extra fields
are always allowed (records carry incident-specific attributes by
design); validation only complains about *missing required* fields and
*wrong types* — the failure modes that actually break consumers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# the stream schema versions the producers stamp (obs/telemetry.py
# SCHEMA_VERSION, obs/trace.py TRACE_SCHEMA_VERSION import from here so
# the stamp and the validator can never disagree)
METRICS_SCHEMA_VERSION = 1
TRACE_SCHEMA_VERSION = 1
EVENTS_SCHEMA_VERSION = 1

_NUM = (int, float)
_STR = (str,)
_INT = (int,)
_BOOL = (bool,)
_DICT = (dict,)
_LIST = (list,)

FieldSpec = Dict[str, Tuple[tuple, bool, bool]]

# ---------------------------------------------------------------------------
# metrics.jsonl (obs/telemetry.py MetricsStream)
# ---------------------------------------------------------------------------

# every record shares the envelope MetricsStream.write stamps
METRICS_ENVELOPE: FieldSpec = {
    "v": (_INT, True, False),
    "ts": (_NUM, True, False),
    "kind": (_STR, True, False),
    "host": (_INT, True, False),
}

# per-kind bodies (StepTelemetry.flush / on_epoch / run_record /
# compile_record; train/loop.py is the producer of "run"/"compile_report")
METRICS_KINDS: Dict[str, FieldSpec] = {
    "step_window": {
        "step": (_INT, True, False),
        "steps": (_INT, True, False),
        "step_time_ms": (_NUM, True, False),
        "graphs_per_sec": (_NUM, True, False),
        "nodes_per_sec": (_NUM, True, False),
        "edges_per_sec": (_NUM, True, False),
        "padding_waste": (_NUM, True, False),
        "padding_waste_graphs": (_NUM, True, False),
        "padding_waste_edges": (_NUM, True, False),
        "mfu_est": (_NUM, True, True),
        "comm_bytes_per_step": (_NUM, True, True),
        "comm_fraction_est": (_NUM, True, True),
        "buckets": (_DICT, True, False),
    },
    "epoch": {
        "epoch": (_INT, True, False),
        "filler": (_BOOL, True, False),
        # the scalar keys (train/val/test/lr, per-branch mirrors) are
        # recipe-dependent — validated as "extra numeric" by convention
    },
    "numerics": {
        "step": (_INT, True, False),
        # at least one of activations/gradients, each a name -> stats map
        "activations": (_DICT, False, False),
        "gradients": (_DICT, False, False),
    },
    "run": {
        "log_name": (_STR, True, False),
        "epochs": (_INT, True, False),
        "global_step": (_INT, True, False),
        "endpoint_port": (_INT, True, True),
        "compile": (_DICT, True, False),
    },
    # the compile plane's full end-of-run report (train/loop.py writes it
    # through StepTelemetry.compile_record — the doctor's source for HBM /
    # comm / cache / retrace verdicts without scraping stderr)
    "compile_report": {
        "mode": (_STR, True, False),
        "precompiled": (_INT, True, False),
        "specializations": (_INT, True, False),
        "cache_hits": (_INT, True, False),
        "cache_misses": (_INT, True, False),
        "violations": (_INT, True, False),
        "time_to_first_step": (_NUM, True, True),
        "hbm_by_spec": (_DICT, True, False),
        "hbm_peak_bytes": (_INT, True, True),
        "comm_by_spec": (_DICT, True, False),
        "comm_bytes_peak": (_INT, True, True),
        "device_bytes_limit": (_NUM, True, True),
    },
    # serving-fleet aggregate window (serve/fleet.py ReplicaManager writes
    # it to the run dir's metrics.jsonl ~1/s while the fleet is up): the
    # doctor's fleet-wide saturation source — ONE record spans every
    # replica, so queue_saturation/shed_spiral can fire once for the fleet
    # instead of once per replica stream
    "fleet_serve": {
        "replicas": (_INT, True, False),
        "ready": (_INT, True, False),
        "benched": (_INT, True, False),
        "queue_depth_mean": (_NUM, True, False),
        "queue_depth_max": (_NUM, True, False),
        "shed_total": (_NUM, True, False),
        "queue_full_total": (_NUM, True, False),
        "completed_total": (_NUM, True, False),
        "per_replica": (_DICT, True, False),
        # prediction-cache efficacy (serve/cache.py stats; optional so
        # pre-cache fleet streams stay schema-valid): cumulative lookup
        # counters + current entry census — the doctor's
        # cache_ineffective rule reads these
        "cache_enabled": (_BOOL, False, False),
        "cache_hits": (_NUM, False, False),
        "cache_misses": (_NUM, False, False),
        "cache_stores": (_NUM, False, False),
        "cache_entries": (_NUM, False, False),
        "cache_bytes": (_NUM, False, False),
    },
}

# ---------------------------------------------------------------------------
# trace.jsonl (obs/trace.py Span.to_record + the host stamp)
# ---------------------------------------------------------------------------

SPAN_FIELDS: FieldSpec = {
    "v": (_INT, True, False),
    "traceId": (_STR, True, False),
    "spanId": (_STR, True, False),
    "name": (_STR, True, False),
    # OTLP JSON maps 64-bit ints to strings
    "startTimeUnixNano": (_STR, True, False),
    "endTimeUnixNano": (_STR, True, False),
    "host": (_INT, True, False),
    "parentSpanId": (_STR, False, False),
    "attributes": (_LIST, False, False),
    "links": (_LIST, False, False),
    "status": (_DICT, False, False),
}

# ---------------------------------------------------------------------------
# event records (obs/events.py EventLog.emit; the ring, events.jsonl, and
# every flight dump's events.json share this shape)
# ---------------------------------------------------------------------------

EVENT_FIELDS: FieldSpec = {
    "ts": (_NUM, True, False),
    "kind": (_STR, True, False),
    "severity": (_STR, True, False),
    "trace_id": (_STR, False, False),
}


def _check(rec: Any, spec: FieldSpec, label: str) -> List[str]:
    if not isinstance(rec, dict):
        return [f"{label}: record is {type(rec).__name__}, not an object"]
    errors: List[str] = []
    for name, (types, required, allow_none) in spec.items():
        if name not in rec:
            if required:
                errors.append(f"{label}: missing required field {name!r}")
            continue
        v = rec[name]
        if v is None:
            if not allow_none:
                errors.append(f"{label}: field {name!r} is null")
            continue
        # bool is an int subclass — an int-typed field must not accept it
        if isinstance(v, bool) and bool not in types:
            errors.append(f"{label}: field {name!r} is a bool")
            continue
        if not isinstance(v, types):
            errors.append(
                f"{label}: field {name!r} is {type(v).__name__}, wanted "
                f"{'/'.join(t.__name__ for t in types)}"
            )
    return errors


def validate_metrics_record(rec: Any) -> List[str]:
    """Validate one metrics.jsonl record (envelope + per-kind body).
    Returns a list of error strings — empty means valid. Unknown kinds
    validate the envelope only (forward compatibility: a new producer
    kind must not fail every old consumer)."""
    errors = _check(rec, METRICS_ENVELOPE, "metrics")
    if errors or not isinstance(rec, dict):
        return errors
    if int(rec["v"]) > METRICS_SCHEMA_VERSION:
        return [
            f"metrics: record v={rec['v']} is newer than this reader "
            f"(v={METRICS_SCHEMA_VERSION})"
        ]
    kind = rec.get("kind")
    body = METRICS_KINDS.get(kind)
    if body is not None:
        errors = _check(rec, body, f"metrics[{kind}]")
        if kind == "numerics" and not errors:
            if "activations" not in rec and "gradients" not in rec:
                errors.append(
                    "metrics[numerics]: neither 'activations' nor "
                    "'gradients' present"
                )
    return errors


def validate_span_record(rec: Any) -> List[str]:
    """Validate one trace.jsonl span record."""
    errors = _check(rec, SPAN_FIELDS, "span")
    if not errors and int(rec["v"]) > TRACE_SCHEMA_VERSION:
        return [
            f"span: record v={rec['v']} is newer than this reader "
            f"(v={TRACE_SCHEMA_VERSION})"
        ]
    if not errors:
        try:
            if int(rec["endTimeUnixNano"]) < int(rec["startTimeUnixNano"]):
                errors.append("span: endTimeUnixNano before startTimeUnixNano")
        except ValueError:
            errors.append("span: non-integer time bounds")
    return errors


def validate_event_record(rec: Any) -> List[str]:
    """Validate one event record (ring snapshot / events.jsonl /
    flight-dump events.json entry)."""
    errors = _check(rec, EVENT_FIELDS, "event")
    if not errors:
        from .events import SEVERITIES

        if rec["severity"] not in SEVERITIES:
            errors.append(
                f"event: severity {rec['severity']!r} not in {SEVERITIES}"
            )
    return errors


def span_duration_ms(rec: Dict[str, Any]) -> Optional[float]:
    """Duration of a validated span record in milliseconds (the shared
    consumer helper — bench_gate's trace stats and the doctor's span
    decomposition must compute the same number)."""
    try:
        return (
            int(rec["endTimeUnixNano"]) - int(rec["startTimeUnixNano"])
        ) / 1e6
    except (KeyError, ValueError, TypeError):
        return None


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list — the ONE
    implementation behind every trace-percentile consumer (bench_gate's
    stage gate and the doctor's span decomposition/diff); two copies
    drifting (e.g. one growing interpolation) would silently make the
    gate's baseline and the doctor's report disagree on the same data."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[idx]
