"""Run doctor: automated diagnosis over the observability streams
(docs/OBSERVABILITY.md "Run doctor").

Four PRs built observability *producers* — per-step telemetry
(metrics.jsonl, r7), OTLP spans + events + flight dumps (r8), in-graph
numerics/HBM (r12), fleet aggregation + comm accounting (r13) — and until
now the only consumer was a human hand-correlating six files. The doctor
closes the loop: it ingests every stream a run emits and applies a
rulebook of pathologies the codebase can already exhibit, emitting typed
findings — each with a severity, the concrete evidence records that
triggered it, and a remediation naming the exact config knob.

Modes (``python -m hydragnn_tpu.obs.doctor``):

- ``<run_dir>`` — diagnose one run (also accepts a single flight-dump
  directory: the crash-forensics path works from the black box alone).
  Exit 0 = zero findings, 1 = findings, 2 = usage/IO error.
- ``diff <A> <B>`` — cross-run regression diff: completed-config diff +
  metric / trace-percentile / finding deltas. ``A``/``B`` are run dirs
  or committed ``BENCH_r*.json`` rounds (per-cell deltas; ``--gate``
  cross-checks bench_gate.py's ``gate_verdict.json``). This is the
  promotion-gate primitive for ROADMAP items 3/5.
- ``watch <run_dir>`` — tail a live run's streams and print findings as
  they fire.
- ``trace <trace.jsonl>`` — span-decomposition report (the successor of
  run-scripts/analyze_trace.py for the r8 span plane).

Every stream is parsed through obs/schema.py; invalid or truncated
records degrade to parse warnings, never crashes — a half-written flight
dump is still evidence. The correctness loop is fault-drill-verified:
run-scripts/doctor_smoke.py drives every ``HYDRAGNN_FAULT_*`` injection
point through real runs and asserts the doctor names exactly the planted
pathology, and that a clean run yields zero findings (the false-positive
gate every threshold below is tuned against).
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .events import (
    EV_DATA_SKIP,
    EV_ELASTIC_GROW,
    EV_ELASTIC_SHRINK,
    EV_FLEET_DESYNC,
    EV_FLEET_HOST_STALE,
    EV_FLEET_STRAGGLER,
    EV_GUARD_ROLLBACK,
    EV_GUARD_SKIP,
    EV_LOADER_STALL,
    EV_MIX_DEMOTE,
    EV_NUMERICS_PROVENANCE,
    EV_BREAKER_CLOSE,
    EV_BREAKER_OPEN,
    EV_QUANT_DRIFT,
    EV_QUEUE_FULL,
    EV_RELOAD_ROLLBACK,
    EV_REPLICA_BENCHED,
    EV_REPLICA_EXIT,
    EV_REPLICA_RESTART,
    EV_RETRACE_VIOLATION,
    EV_SHED,
    EV_TILE_PLAN,
    EV_WEDGE,
    severity_rank,
)
from .schema import (
    percentile as _percentile,
    span_duration_ms,
    validate_event_record,
    validate_metrics_record,
    validate_span_record,
)

DOCTOR_SCHEMA_VERSION = 1

# -- finding vocabulary (the rulebook's stable kind names) -------------------
F_INPUT_BOUND = "input_bound"            # host batch build dominates the step
F_RETRACE_STORM = "retrace_storm"        # silent recompiles kept firing
F_PADDING_WASTE = "padding_waste"        # a pad bucket burns its slots
F_NAN_DIVERGENCE = "nan_divergence"      # non-finite steps, with provenance
F_LR_ROLLBACK_LOOP = "lr_rollback_loop"  # rollback policy kept restoring
F_STRAGGLER = "straggler"                # one host's steps are slow
F_DESYNC = "desync"                      # fleet progress skew past bound
F_STALE_HOST = "stale_host"              # host heartbeats went silent
F_HBM_PRESSURE = "hbm_pressure"          # peak HBM near the device limit
F_COMM_DOMINANT = "comm_dominant"        # collectives dominate step time
F_SHED_SPIRAL = "shed_spiral"            # serving kept shedding load
F_QUEUE_SATURATION = "queue_saturation"  # queue wait dominates latency
F_QUARANTINE_ROT = "quarantine_rot"      # data rot: quarantine/demotions
F_LOADER_STALL = "loader_stall"          # loader watchdog fired
F_WEDGED_STEP = "wedged_step"            # serving device step wedged
F_COLD_START = "compile_cold_start"      # warm path regressed to recompiles
F_UNTUNED_KERNEL = "untuned_kernel"      # TPU run rode default tile plans
F_CRASH = "crash"                        # unexplained crash dump
F_ELASTIC_SHRINK = "elastic_shrink"      # fleet re-laid-out onto fewer hosts
F_ELASTIC_GROW = "elastic_grow"          # fleet re-grew to more hosts
F_REPLICA_FLAP = "replica_flap"          # serving replica crash-looped
F_BREAKER_OPEN = "breaker_open"          # router circuit breaker tripped
F_RELOAD_ROLLBACK = "reload_rollback"    # rolling reload auto-rolled back
F_QUANT_DRIFT = "quant_drift"            # int8 accuracy gate refused a state
F_CACHE_INEFFECTIVE = "cache_ineffective"  # prediction cache barely hitting

FINDING_KINDS = (
    F_INPUT_BOUND, F_RETRACE_STORM, F_PADDING_WASTE, F_NAN_DIVERGENCE,
    F_LR_ROLLBACK_LOOP, F_STRAGGLER, F_DESYNC, F_STALE_HOST,
    F_HBM_PRESSURE, F_COMM_DOMINANT, F_SHED_SPIRAL, F_QUEUE_SATURATION,
    F_QUARANTINE_ROT, F_LOADER_STALL, F_WEDGED_STEP, F_COLD_START,
    F_UNTUNED_KERNEL, F_CRASH, F_ELASTIC_SHRINK, F_ELASTIC_GROW,
    F_REPLICA_FLAP, F_BREAKER_OPEN, F_RELOAD_ROLLBACK,
    F_QUANT_DRIFT, F_CACHE_INEFFECTIVE,
)

_EVIDENCE_CAP = 16  # per finding; a shed spiral does not need 300 records


@dataclass
class DoctorConfig:
    """Rule thresholds. The defaults are tuned against the false-positive
    gate (doctor_smoke's clean leg must yield ZERO findings on a CPU toy
    run) while still firing on every injected drill."""

    # input-bound: host batch build p50 must exceed this multiple of the
    # device dispatch p50, over at least min_span_samples sampled steps
    input_bound_factor: float = 2.0
    min_span_samples: int = 5
    # retrace storm: violations below this are a one-off, not a storm
    retrace_storm_min: int = 3
    # padding waste: a bucket above this fraction, observed over at least
    # this many steps (toy CPU ladders legitimately idle ~40% of slots)
    padding_waste_threshold: float = 0.75
    padding_waste_min_steps: int = 4
    # straggler: worst host's median step time vs the other hosts' median
    straggler_factor: float = 2.0
    # HBM: peak within this fraction of the device limit is pressure
    hbm_headroom_fraction: float = 0.92
    # comm: estimated collective fraction of step time above this
    comm_fraction_threshold: float = 0.4
    # serving
    shed_spiral_min: int = 5
    queue_full_min: int = 5
    queue_wait_fraction: float = 0.5
    # fleet: one supervisor restart is recovery, this many is instability
    # (benching fires the finding regardless of this threshold)
    replica_flap_min_restarts: int = 3
    # rollbacks: 1 recovers, this many is a loop
    rollback_loop_min: int = 2
    # prediction cache: judge efficacy only after this many lookups (a
    # fleet that barely ran has no verdict), and call it ineffective when
    # the hit rate sits below the floor — a cache-enabled fleet paying
    # key-hash + disk probes per request for almost no reuse
    cache_min_lookups: int = 100
    cache_hit_rate_min: float = 0.05
    # diff mode: time_to_first_step growth beyond this factor with fresh
    # cache misses is a cold-start regression
    cold_start_factor: float = 1.5


@dataclass
class Finding:
    """One diagnosed pathology: what, how bad, the records that prove it,
    and the config knob that fixes it."""

    kind: str
    severity: str
    summary: str
    remediation: str
    evidence: List[Dict[str, Any]] = field(default_factory=list)
    data: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "severity": self.severity,
            "summary": self.summary,
            "remediation": self.remediation,
            "evidence": self.evidence[:_EVIDENCE_CAP],
            "evidence_total": len(self.evidence),
            "data": self.data,
        }


# ---------------------------------------------------------------------------
# stream ingestion
# ---------------------------------------------------------------------------


def _read_jsonl(path: str, validate: Callable[[Any], List[str]],
                warnings_out: List[str]) -> List[Dict[str, Any]]:
    """Parse one JSONL stream through a schema validator. Malformed lines
    (incl. a torn final line from a crash) and schema-invalid records
    become warnings, not exceptions."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError as e:
        warnings_out.append(f"{os.path.basename(path)}: unreadable ({e})")
        return out
    bad = 0
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            # a torn final line is the expected crash artifact; mid-file
            # corruption is worth one warning per file either way
            bad += 1
            continue
        errs = validate(rec)
        if errs:
            bad += 1
            if bad == 1:
                warnings_out.append(
                    f"{os.path.basename(path)}: line {i + 1}: {errs[0]}"
                )
            continue
        out.append(rec)
    if bad:
        warnings_out.append(
            f"{os.path.basename(path)}: {bad} malformed/invalid record(s) "
            "skipped"
        )
    return out


def _read_json(path: str, warnings_out: List[str],
               label: Optional[str] = None) -> Optional[Any]:
    """Best-effort JSON file read; a truncated/partial file degrades to a
    warning (the half-written-flight-dump contract)."""
    label = label or os.path.basename(path)
    try:
        with open(path) as fh:
            return json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError) as e:
        warnings_out.append(f"{label}: unreadable/truncated ({e})")
        return None


def _event_key(rec: Dict[str, Any]) -> Tuple:
    return (rec.get("ts"), rec.get("kind"), rec.get("trace_id"),
            tuple(sorted((k, str(v)) for k, v in rec.items()
                         if k not in ("ts", "kind", "trace_id"))))


@dataclass
class RunStreams:
    """Everything one run (or one flight dump) emitted, parsed and
    schema-checked: the doctor's working set."""

    target: str
    source: str  # "run_dir" | "flight_dump"
    metrics: List[Dict[str, Any]] = field(default_factory=list)
    spans: List[Dict[str, Any]] = field(default_factory=list)
    events: List[Dict[str, Any]] = field(default_factory=list)
    quarantine: List[Dict[str, Any]] = field(default_factory=list)
    dumps: List[Dict[str, Any]] = field(default_factory=list)
    config: Optional[Dict[str, Any]] = None
    memory: Optional[Dict[str, Any]] = None
    # obs/sharding.py snapshot a flight dump carried (label -> report):
    # per-leaf PartitionSpec tables + replication audit
    sharding: Optional[Dict[str, Any]] = None
    parse_warnings: List[str] = field(default_factory=list)

    # -- derived views -------------------------------------------------------

    def events_of(self, *kinds: str) -> List[Dict[str, Any]]:
        want = set(kinds)
        return [e for e in self.events if e.get("kind") in want]

    def records_of(self, kind: str) -> List[Dict[str, Any]]:
        return [r for r in self.metrics if r.get("kind") == kind]

    def compile_report(self) -> Optional[Dict[str, Any]]:
        reps = self.records_of("compile_report")
        return reps[-1] if reps else None

    @classmethod
    def load(cls, target: str) -> "RunStreams":
        """Auto-detect: a directory with a ``meta.json``/``events.json``
        (and no metrics stream) is a flight dump; anything else is a run
        directory."""
        if os.path.isfile(os.path.join(target, "meta.json")) or (
            os.path.isfile(os.path.join(target, "events.json"))
            and not os.path.isfile(os.path.join(target, "metrics.jsonl"))
        ):
            return cls.from_flight_dump(target)
        return cls.from_run_dir(target)

    @classmethod
    def from_run_dir(cls, run_dir: str) -> "RunStreams":
        s = cls(target=run_dir, source="run_dir")
        w = s.parse_warnings
        # metrics.jsonl + per-host fleet streams
        for path in sorted(
            glob.glob(os.path.join(run_dir, "metrics.jsonl"))
            + glob.glob(os.path.join(run_dir, "metrics-h*.jsonl"))
        ):
            s.metrics.extend(_read_jsonl(path, validate_metrics_record, w))
        # trace.jsonl + per-host fleet streams
        for path in sorted(
            glob.glob(os.path.join(run_dir, "trace.jsonl"))
            + glob.glob(os.path.join(run_dir, "trace-h*.jsonl"))
        ):
            s.spans.extend(_read_jsonl(path, validate_span_record, w))
        # events.jsonl (r14 persistent sink) + per-host streams
        event_paths = sorted(
            glob.glob(os.path.join(run_dir, "events.jsonl"))
            + glob.glob(os.path.join(run_dir, "events-h*.jsonl"))
        )
        for path in event_paths:
            s.events.extend(_read_jsonl(path, validate_event_record, w))
        # quarantine manifest (data/validate.py)
        man = os.path.join(run_dir, "quarantine", "manifest.jsonl")
        if os.path.isfile(man):
            s.quarantine.extend(_read_jsonl(man, lambda r: [], w))
        # completed config (config.save_config)
        s.config = _read_json(os.path.join(run_dir, "config.json"), w)
        # flight dumps: meta always; events only as the fallback source
        # for pre-r14 runs (an events.jsonl already holds the superset —
        # double-ingesting the ring would double every event-derived
        # evidence list)
        seen = {_event_key(e) for e in s.events}
        for d in sorted(glob.glob(os.path.join(run_dir, "flightrec", "*"))):
            if not os.path.isdir(d) or os.path.basename(d).startswith("."):
                continue
            meta = _read_json(os.path.join(d, "meta.json"), w,
                              label=f"flightrec/{os.path.basename(d)}/meta")
            s.dumps.append({"dir": d, "meta": meta or {}})
            if s.memory is None:
                s.memory = _read_json(os.path.join(d, "memory.json"), w)
            if s.sharding is None:
                s.sharding = _read_json(
                    os.path.join(d, "sharding.json"), w
                )
            if not event_paths:
                for ev in (_read_json(
                    os.path.join(d, "events.json"), w,
                    label=f"flightrec/{os.path.basename(d)}/events",
                ) or []):
                    if validate_event_record(ev):
                        continue
                    key = _event_key(ev)
                    if key not in seen:
                        seen.add(key)
                        s.events.append(ev)
        s.events.sort(key=lambda e: e.get("ts", 0))
        return s

    @classmethod
    def from_flight_dump(cls, dump_dir: str) -> "RunStreams":
        """The crash-forensics path: diagnose from a black box alone. A
        truncated/partially-written dump degrades to parse warnings."""
        s = cls(target=dump_dir, source="flight_dump")
        w = s.parse_warnings
        meta = _read_json(os.path.join(dump_dir, "meta.json"), w)
        s.dumps.append({"dir": dump_dir, "meta": meta or {}})
        for ev in (_read_json(os.path.join(dump_dir, "events.json"), w)
                   or []):
            errs = validate_event_record(ev)
            if errs:
                w.append(f"events.json: {errs[0]}")
                continue
            s.events.append(ev)
        for sp in (_read_json(os.path.join(dump_dir, "spans.json"), w)
                   or []):
            if validate_span_record(sp):
                continue
            s.spans.append(sp)
        s.memory = _read_json(os.path.join(dump_dir, "memory.json"), w)
        s.sharding = _read_json(os.path.join(dump_dir, "sharding.json"), w)
        return s


def _tail_jsonl(
    path: str,
    offset: int,
    validate: Callable[[Any], List[str]],
    warnings_out: List[str],
) -> Tuple[List[Dict[str, Any]], int]:
    """Parse the COMPLETE lines appended to ``path`` since ``offset``;
    returns (records, new offset). A trailing line without its newline is
    left unconsumed — the producer is mid-write and the next tick picks
    it up whole (watch mode must not mis-parse a torn tail as corruption)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as fh:
            fh.seek(offset)
            chunk = fh.read()
    except OSError as e:
        warnings_out.append(f"{os.path.basename(path)}: unreadable ({e})")
        return out, offset
    end = chunk.rfind(b"\n")
    if end < 0:
        return out, offset
    consumed = chunk[: end + 1]
    for line in consumed.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line.decode("utf-8", errors="replace"))
        except json.JSONDecodeError:
            warnings_out.append(
                f"{os.path.basename(path)}: malformed record skipped"
            )
            continue
        if validate(rec):
            warnings_out.append(
                f"{os.path.basename(path)}: invalid record skipped"
            )
            continue
        out.append(rec)
    return out, offset + len(consumed)


class StreamTail:
    """Incremental run-dir ingester for watch mode: per-file byte
    offsets mean each tick parses only what was appended since the last
    one, instead of re-reading (and re-validating) the whole history —
    a multi-hour live run would otherwise make every 2-second tick
    linear in total stream size. New files (a fleet host joining, the
    first flight dump) are picked up by re-globbing; dumps and the
    config are scanned once each."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self._offsets: Dict[str, int] = {}
        self.streams = RunStreams(target=run_dir, source="run_dir")

    def refresh(self) -> "RunStreams":
        s = self.streams
        w = s.parse_warnings
        for pattern, validate, sink in (
            ("metrics*.jsonl", validate_metrics_record, s.metrics),
            ("trace*.jsonl", validate_span_record, s.spans),
            ("events*.jsonl", validate_event_record, s.events),
            (os.path.join("quarantine", "manifest.jsonl"),
             lambda r: [], s.quarantine),
        ):
            for path in sorted(
                glob.glob(os.path.join(self.run_dir, pattern))
            ):
                recs, off = _tail_jsonl(
                    path, self._offsets.get(path, 0), validate, w
                )
                self._offsets[path] = off
                sink.extend(recs)
        known = {d["dir"] for d in s.dumps}
        for d in sorted(glob.glob(os.path.join(self.run_dir,
                                               "flightrec", "*"))):
            if (not os.path.isdir(d) or os.path.basename(d).startswith(".")
                    or d in known):
                continue
            meta = _read_json(
                os.path.join(d, "meta.json"), w,
                label=f"flightrec/{os.path.basename(d)}/meta",
            )
            s.dumps.append({"dir": d, "meta": meta or {}})
        if s.config is None:
            # no warning sink: the config legitimately appears late
            s.config = _read_json(
                os.path.join(self.run_dir, "config.json"), []
            )
        return s


# ---------------------------------------------------------------------------
# span decomposition (the analyze_trace successor)
# ---------------------------------------------------------------------------


def span_decomposition(
    spans: Iterable[Dict[str, Any]],
) -> Dict[str, Dict[str, float]]:
    """Per-span-name duration stats: count, p50/p99, total — the stage
    decomposition the input-bound rule and the diff mode consume."""
    durs: Dict[str, List[float]] = {}
    for rec in spans:
        ms = span_duration_ms(rec)
        if ms is None:
            continue
        durs.setdefault(str(rec.get("name", "?")), []).append(ms)
    out: Dict[str, Dict[str, float]] = {}
    for name, vals in durs.items():
        vals.sort()
        out[name] = {
            "count": len(vals),
            "p50_ms": round(_percentile(vals, 0.50), 4),
            "p99_ms": round(_percentile(vals, 0.99), 4),
            "total_ms": round(sum(vals), 3),
        }
    return out


def step_phase_verdict(
    decomp: Dict[str, Dict[str, float]], cfg: DoctorConfig
) -> Optional[Dict[str, Any]]:
    """Input-bound vs compute-bound decomposition of the sampled training
    steps (``train/host_batch_build`` vs ``train/device_dispatch``
    children of ``train/step``). None when there are not enough samples
    to say anything."""
    hb = decomp.get("train/host_batch_build")
    dd = decomp.get("train/device_dispatch")
    if not hb or not dd:
        return None
    n = min(hb["count"], dd["count"])
    if n < cfg.min_span_samples:
        return None
    ratio = hb["p50_ms"] / max(dd["p50_ms"], 1e-9)
    verdict = (
        "input_bound" if ratio > cfg.input_bound_factor
        else "compute_bound" if ratio < 1.0 / cfg.input_bound_factor
        else "balanced"
    )
    return {
        "verdict": verdict,
        "host_batch_build_p50_ms": hb["p50_ms"],
        "device_dispatch_p50_ms": dd["p50_ms"],
        "ratio": round(ratio, 3),
        "samples": n,
    }


# ---------------------------------------------------------------------------
# the rulebook
# ---------------------------------------------------------------------------

Rule = Callable[[RunStreams, DoctorConfig], List[Finding]]
_RULES: List[Rule] = []


def rule(fn: Rule) -> Rule:
    _RULES.append(fn)
    return fn


@rule
def r_input_bound(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    decomp = span_decomposition(s.spans)
    phase = step_phase_verdict(decomp, cfg)
    if phase is None or phase["verdict"] != "input_bound":
        return []
    return [Finding(
        F_INPUT_BOUND, "warn",
        f"training is input-bound: host batch build p50 "
        f"{phase['host_batch_build_p50_ms']:.1f}ms is "
        f"{phase['ratio']:.1f}x the device dispatch p50 "
        f"{phase['device_dispatch_p50_ms']:.1f}ms over {phase['samples']} "
        "sampled steps — the accelerator is waiting on the host",
        "raise Training.double_buffer (device staging depth) and the "
        "loader prefetch; if batch *construction* dominates, enable "
        "Dataset.lappe_cache / move featurization offline",
        evidence=[{"span_stats": {k: decomp[k] for k in
                                  ("train/host_batch_build",
                                   "train/device_dispatch")}}],
        data=phase,
    )]


@rule
def r_retrace_storm(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_RETRACE_VIOLATION)
    rep = s.compile_report()
    violations = max(
        len(evs), int(rep["violations"]) if rep is not None else 0
    )
    if violations < cfg.retrace_storm_min:
        return []
    return [Finding(
        F_RETRACE_STORM, "error",
        f"retrace storm: {violations} sentinel violations — a step "
        "specialization keeps silently recompiling (each one is a full "
        "XLA compile on the critical path)",
        "set Training.precompile: blocking so warm-up covers the full "
        "ladder before epoch 0, and Training.retrace_policy: error to "
        "fail fast at the violating aval (the report names the per-leaf "
        "diff vs the nearest known specialization)",
        evidence=evs,
        data={"violations": violations},
    )]


@rule
def r_padding_waste(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    # aggregate per pad bucket over every step_window
    buckets: Dict[str, Dict[str, float]] = {}
    for wrec in s.records_of("step_window"):
        for label, b in (wrec.get("buckets") or {}).items():
            if not isinstance(b, dict):
                continue
            agg = buckets.setdefault(label, {"steps": 0, "waste_x_steps": 0.0})
            steps = int(b.get("steps", 0))
            agg["steps"] += steps
            agg["waste_x_steps"] += float(b.get("padding_waste", 0.0)) * steps
    bad = {}
    for label, agg in buckets.items():
        if agg["steps"] < cfg.padding_waste_min_steps:
            continue
        waste = agg["waste_x_steps"] / max(agg["steps"], 1)
        if waste > cfg.padding_waste_threshold:
            bad[label] = {"steps": agg["steps"], "padding_waste": round(waste, 4)}
    if not bad:
        return []
    worst = max(bad.items(), key=lambda kv: kv[1]["padding_waste"])
    return [Finding(
        F_PADDING_WASTE, "warn",
        f"padding waste above {cfg.padding_waste_threshold:.0%} in "
        f"{len(bad)} pad bucket(s) — worst: {worst[0]} at "
        f"{worst[1]['padding_waste']:.0%} over {worst[1]['steps']} steps "
        "(those node slots burn FLOPs on masked garbage)",
        "raise Training.num_pad_buckets (finer ladder levels) or lower "
        "Training.batch_size for the offending shapes; packed batching "
        "(Dataset pack mode) eliminates the tail for skewed graph sizes",
        evidence=[{"bucket": k, **v} for k, v in sorted(bad.items())],
        data={"buckets": bad},
    )]


@rule
def r_nan_divergence(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    prov = s.events_of(EV_NUMERICS_PROVENANCE)
    skips = s.events_of(EV_GUARD_SKIP)
    if not prov and not skips:
        return []
    total_skips = sum(int(e.get("new_skips", e.get("total", 1)) or 0)
                      for e in skips) or len(skips)
    layers = sorted({str(e.get("layer")) for e in prov
                     if e.get("layer") and e.get("layer") != "<unreproduced>"})
    sources: set = set()
    for e in prov + skips:
        sv = e.get("sources")
        if sv:
            sources.update(str(x) for x in str(sv).split(","))
    chain = ""
    if layers:
        chain += f"; first non-finite tensor: {', '.join(layers[:4])}"
    if sources:
        chain += (
            f"; implicated mixture source id(s): "
            f"{', '.join(sorted(sources)[:8])}"
        )
    remediation = (
        "lower NeuralNetwork.Training.Optimizer.learning_rate (or set "
        "Training.non_finite_policy: rollback for automatic LR backoff)"
    )
    if sources:
        remediation += (
            "; the implicated sources suggest data rot — set "
            "Dataset.bad_sample_policy: quarantine and/or lower "
            "Mixture.demote_after to demote them"
        )
    if layers:
        remediation += (
            "; Telemetry.numerics window stats for the named layer show "
            "whether it saturated gradually (LR) or spiked (data)"
        )
    return [Finding(
        F_NAN_DIVERGENCE, "error",
        f"non-finite divergence: {total_skips} guarded step skip(s), "
        f"{len(prov)} NaN provenance drill-down(s){chain}",
        remediation,
        evidence=prov + skips,
        data={"skips": total_skips, "layers": layers,
              "sources": sorted(sources)},
    )]


@rule
def r_lr_rollback_loop(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_GUARD_ROLLBACK)
    if not evs:
        return []
    loop = len(evs) >= cfg.rollback_loop_min
    return [Finding(
        F_LR_ROLLBACK_LOOP, "error" if loop else "warn",
        f"{len(evs)} guard rollback(s) restored a verified checkpoint"
        + (" — a sustained LR-too-hot divergence loop, each iteration "
           "loses the epochs since the last checkpoint" if loop else ""),
        "lower NeuralNetwork.Training.Optimizer.learning_rate at the "
        "recipe level; Training.non_finite_lr_backoff compounds per "
        "rollback, so a loop that is not converging means the base LR is "
        "far past stable — also check Training.non_finite_max_rollbacks "
        "before the run turns fatal",
        evidence=evs,
        data={"rollbacks": len(evs)},
    )]


@rule
def r_straggler(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_FLEET_STRAGGLER)
    # metrics-derived detection: per-host median window step time (works
    # post-hoc from the host-suffixed streams even when no collector ran)
    per_host: Dict[int, List[float]] = {}
    for wrec in s.records_of("step_window"):
        per_host.setdefault(int(wrec.get("host", 0)), []).append(
            float(wrec["step_time_ms"])
        )
    skew = None
    if len(per_host) >= 2:
        medians = {
            h: _percentile(sorted(v), 0.5) for h, v in per_host.items()
        }
        worst_host = max(medians, key=lambda h: medians[h])
        others = [v for h, v in medians.items() if h != worst_host]
        baseline = _percentile(sorted(others), 0.5)
        if baseline > 0 and medians[worst_host] > cfg.straggler_factor * baseline:
            skew = {
                "host": worst_host,
                "median_step_ms": round(medians[worst_host], 3),
                "fleet_median_step_ms": round(baseline, 3),
                "factor": round(medians[worst_host] / baseline, 2),
            }
    if not evs and skew is None:
        return []
    hosts = sorted({str(e.get("host")) for e in evs if e.get("host")
                    is not None} | ({str(skew["host"])} if skew else set()))
    summary = (
        f"straggler host(s) {', '.join(hosts) or '?'}: "
        + (f"{len(evs)} fleet watchdog detection(s)" if evs else "")
        + (" and " if evs and skew else "")
        + (f"median step {skew['median_step_ms']}ms is {skew['factor']}x "
           f"the other hosts' {skew['fleet_median_step_ms']}ms" if skew
           else "")
    )
    return [Finding(
        F_STRAGGLER, "warn", summary,
        "inspect the named host (thermals, input pipeline, noisy "
        "neighbor); Telemetry.fleet_straggler_factor tunes the watchdog "
        "threshold and the coordinated flight dumps carry each host's "
        "registry snapshot for the moment of detection",
        evidence=evs or [{"step_time_skew": skew}],
        data={"hosts": hosts, **({"skew": skew} if skew else {})},
    )]


@rule
def r_desync(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_FLEET_DESYNC)
    if not evs:
        return []
    return [Finding(
        F_DESYNC, "error",
        f"fleet desync: {len(evs)} progress-skew detection(s) — hosts "
        "disagree on the step index beyond Telemetry.fleet_max_step_lag "
        "(a collective will eventually deadlock or mispair)",
        "find what stalled the lagging host (its coordinated flight dump "
        "is keyed by the same fleet step); raise "
        "Telemetry.fleet_max_step_lag only if the skew is benign by "
        "construction (e.g. uneven per-host batch counts)",
        evidence=evs,
        data={"detections": len(evs)},
    )]


@rule
def r_stale_host(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_FLEET_HOST_STALE)
    if not evs:
        return []
    hosts = sorted({str(e.get("host")) for e in evs if e.get("host")
                    is not None})
    return [Finding(
        F_STALE_HOST, "warn",
        f"stale fleet host(s) {', '.join(hosts) or '?'}: heartbeats went "
        f"silent past the staleness threshold ({len(evs)} detection(s)) — "
        "their series were retired from the fleet aggregates",
        "check whether the host process died (its metrics-h<N>.jsonl tail "
        "names the last completed step) or only its collector route; "
        "Telemetry.fleet_stale_after_s tunes the threshold",
        evidence=evs,
        data={"hosts": hosts},
    )]


def _elastic_findings(s: "RunStreams", kind: str, fkind: str,
                      severity: str, what: str, action: str) -> List[Finding]:
    """Shared body of the elastic shrink/grow rules: one finding per
    re-layout event, with the event's before/after layouts, the measured
    progress loss, and the run's recorded sharding tables as evidence."""
    evs = s.events_of(kind)
    out: List[Finding] = []
    stale = s.events_of(EV_FLEET_HOST_STALE)
    for e in evs:
        before = e.get("before") or {}
        after = e.get("after") or {}
        lost = e.get("progress_lost_steps")
        evidence: List[Dict[str, Any]] = [e]
        if stale:
            evidence.extend(stale)
        if s.sharding:
            # the re-layout's placement record: the rule table's sharding
            # tables as recorded AFTER the survivor re-laid-out
            evidence.append({"sharding_tables": sorted(s.sharding)})
        out.append(Finding(
            fkind, severity,
            f"{what}: {before.get('host_count', '?')} -> "
            f"{after.get('host_count', '?')} host(s) "
            f"(trigger: {e.get('trigger', '?')}, progress lost: "
            + (f"{lost} step(s)" if lost is not None
               else "bounded by the checkpoint cadence") + ")",
            action,
            evidence=evidence,
            data={
                "before": before, "after": after,
                **({"progress_lost_steps": int(lost)}
                   if lost is not None else {}),
            },
        ))
    return out


@rule
def r_elastic_shrink(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    return _elastic_findings(
        s, EV_ELASTIC_SHRINK, F_ELASTIC_SHRINK, "warn",
        "elastic shrink: the fleet re-laid-out onto fewer hosts after a "
        "host loss and resumed from the coordinated checkpoint",
        "the run is healthy but degraded — re-grow when the host returns "
        "(the mixture re-deals its draw stripes either way); if shrinks "
        "recur, check the stale-host findings for the failing host and "
        "Training.elastic.min_hosts for the capacity floor",
    )


@rule
def r_elastic_grow(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    return _elastic_findings(
        s, EV_ELASTIC_GROW, F_ELASTIC_GROW, "info",
        "elastic re-grow: the fleet returned to a larger topology and "
        "resumed from the coordinated checkpoint",
        "no action needed — verify steady-state retraces stayed at zero "
        "after the re-layout (the compile cache makes the re-grown step "
        "a cache hit); the paired elastic_shrink finding names what was "
        "lost in between",
    )


@rule
def r_hbm_pressure(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    rep = s.compile_report()
    peak = limit = None
    by_spec: Dict[str, Any] = {}
    if rep is not None:
        peak = rep.get("hbm_peak_bytes")
        limit = rep.get("device_bytes_limit")
        by_spec = rep.get("hbm_by_spec") or {}
    if (peak is None or limit is None) and s.memory:
        specs = s.memory.get("hbm_by_spec") or {}
        peaks = [v.get("peak_bytes") for v in specs.values()
                 if isinstance(v, dict) and v.get("peak_bytes")]
        if peaks and peak is None:
            peak = max(peaks)
            by_spec = {k: v.get("peak_bytes") for k, v in specs.items()
                       if isinstance(v, dict)}
        if limit is None:
            limit = s.memory.get("device_bytes_limit")
    if not peak or not limit:
        return []
    frac = float(peak) / float(limit)
    if frac < cfg.hbm_headroom_fraction:
        return []
    worst = max(by_spec.items(), key=lambda kv: kv[1] or 0)[0] if by_spec \
        else "?"
    return [Finding(
        F_HBM_PRESSURE, "warn",
        f"HBM peak {peak / 1e9:.2f}GB is {frac:.0%} of the device limit "
        f"{float(limit) / 1e9:.2f}GB (worst spec: {worst}) — one ladder "
        "level up or a fragmentation spike from here is an OOM",
        "set Training.remat_policy: full (recompute instead of stash), "
        "lower Training.batch_size, or shard the optimizer state "
        "(Optimizer.zero_stage); the per-spec table names which pad "
        "bucket to shrink",
        evidence=[{"hbm_by_spec": by_spec}],
        data={"peak_bytes": int(peak), "limit_bytes": int(limit),
              "fraction": round(frac, 4)},
    )]


@rule
def r_comm_dominant(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    rep = s.compile_report()
    bad: Dict[str, float] = {}
    if rep is not None:
        for spec, c in (rep.get("comm_by_spec") or {}).items():
            frac = (c or {}).get("comm_fraction_est")
            if frac is not None and float(frac) > cfg.comm_fraction_threshold:
                bad[spec] = float(frac)
    # window-level confirmation/fallback (attach_comm step records)
    fracs = [r["comm_fraction_est"] for r in s.records_of("step_window")
             if r.get("comm_fraction_est") is not None]
    window_mean = sum(fracs) / len(fracs) if fracs else None
    if not bad and (window_mean is None
                    or window_mean <= cfg.comm_fraction_threshold):
        return []
    worst = max(bad.items(), key=lambda kv: kv[1]) if bad else (
        "window_mean", window_mean)
    return [Finding(
        F_COMM_DOMINANT, "warn",
        f"collectives dominate: estimated comm fraction {worst[1]:.0%} "
        f"({worst[0]}) exceeds {cfg.comm_fraction_threshold:.0%} of step "
        "time — the mesh is paying more in gradient movement than it "
        "earns in parallel compute",
        "lower Optimizer.zero_stage (stage 3 all-gathers weights every "
        "step), grow the per-host batch to amortize the fixed collective "
        "cost, or re-shard via the mesh layout; the compile report's "
        "comm_by_spec table names bytes per specialization",
        evidence=[{"comm_by_spec": (rep or {}).get("comm_by_spec")},
                  {"window_comm_fraction_mean": window_mean}],
        data={"specs": bad, "window_mean": window_mean},
    )]


def _fleet_serve_latest(s: RunStreams) -> Optional[Dict[str, Any]]:
    """Last fleet-aggregated serving window (serve/fleet.py writes them
    ~1/s; counters in them are cumulative, so the last record carries the
    fleet totals). None for single-server runs."""
    recs = s.records_of("fleet_serve")
    return recs[-1] if recs else None


def _per_replica_breakdown(rec: Dict[str, Any], key: str) -> Dict[str, float]:
    return {
        f"replica{h}": float(v.get(key, 0.0))
        for h, v in (rec.get("per_replica") or {}).items()
        if isinstance(v, dict)
    }


@rule
def r_shed_spiral(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    # fleet deployments: judge the AGGREGATED shed total from the
    # manager's fleet_serve records so fleet-wide overload is ONE finding
    # with a per-replica breakdown, not one finding per replica stream
    fleet = _fleet_serve_latest(s)
    if fleet is not None:
        sheds = int(fleet.get("shed_total", 0))
        if sheds < cfg.shed_spiral_min:
            return []
        breakdown = _per_replica_breakdown(fleet, "shed")
        return [Finding(
            F_SHED_SPIRAL, "warn",
            f"fleet-wide shed spiral: {sheds} SLO load sheds across "
            f"{fleet.get('replicas')} replicas ({fleet.get('ready')} "
            "ready) — offered load is persistently above what the FLEET "
            "can finish inside Serving.slo_p99_s",
            "scale out (raise Serving.fleet_replicas) or raise "
            "Serving.micro_batch_graphs for better per-replica device "
            "utilization; if sheds concentrate on one replica (see "
            "breakdown) its device set is the straggler",
            evidence=[fleet],
            data={"sheds": sheds, "per_replica": breakdown},
        )]
    evs = s.events_of(EV_SHED)
    if len(evs) < cfg.shed_spiral_min:
        return []
    return [Finding(
        F_SHED_SPIRAL, "warn",
        f"serve shed spiral: {len(evs)} SLO load sheds — offered load is "
        "persistently above what the server can finish inside "
        "Serving.slo_p99_s (projected queue wait at admission kept "
        "exceeding the SLO)",
        "scale out (more replicas) or raise Serving.micro_batch_graphs "
        "toward the warmed ladder's batch size for better device "
        "utilization; raising Serving.slo_p99_s trades latency for "
        "goodput only if clients tolerate it",
        evidence=evs,
        data={"sheds": len(evs)},
    )]


@rule
def r_queue_saturation(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    fleet = _fleet_serve_latest(s)
    if fleet is not None:
        # same aggregation argument as r_shed_spiral: one fleet verdict
        qfull = int(fleet.get("queue_full_total", 0))
        if qfull < cfg.queue_full_min:
            return []
        breakdown = _per_replica_breakdown(fleet, "queue_full")
        return [Finding(
            F_QUEUE_SATURATION, "warn",
            f"fleet-wide queue saturation: {qfull} queue-full rejections "
            f"across {fleet.get('replicas')} replicas (mean depth "
            f"{fleet.get('queue_depth_mean')}, max "
            f"{fleet.get('queue_depth_max')})",
            "the device step is the bottleneck, not admission: add "
            "capacity (Serving.fleet_replicas / bigger "
            "Serving.micro_batch_graphs) rather than raising "
            "Serving.max_queue_requests — a deeper queue only adds "
            "latency to the same throughput",
            evidence=[fleet],
            data={"queue_full": qfull, "per_replica": breakdown},
        )]
    evs = s.events_of(EV_QUEUE_FULL)
    decomp = span_decomposition(s.spans)
    qw = decomp.get("serve/queue_wait")
    req = decomp.get("serve/request")
    wait_frac = None
    if qw and req and req["p99_ms"] > 0 and \
            req["count"] >= cfg.min_span_samples:
        wait_frac = qw["p99_ms"] / req["p99_ms"]
    if len(evs) < cfg.queue_full_min and (
        wait_frac is None or wait_frac < cfg.queue_wait_fraction
    ):
        return []
    parts = []
    if len(evs) >= cfg.queue_full_min:
        parts.append(f"{len(evs)} queue-full rejections")
    if wait_frac is not None and wait_frac >= cfg.queue_wait_fraction:
        parts.append(
            f"queue wait explains {wait_frac:.0%} of request p99 "
            f"({qw['p99_ms']:.1f}ms of {req['p99_ms']:.1f}ms)"
        )
    return [Finding(
        F_QUEUE_SATURATION, "warn",
        "serve queue saturation: " + "; ".join(parts),
        "the device step is the bottleneck, not admission: add capacity "
        "(replicas / bigger Serving.micro_batch_graphs) rather than "
        "raising Serving.max_queue_requests — a deeper queue only adds "
        "latency to the same throughput",
        evidence=evs[:_EVIDENCE_CAP] or [{"span_stats": {
            "serve/queue_wait": qw, "serve/request": req}}],
        data={"queue_full": len(evs), "queue_wait_fraction": wait_frac},
    )]


@rule
def r_replica_flap(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    """A benched replica is a finding by itself (the supervisor only
    benches after fleet_flap_max_restarts deaths inside the window —
    restarts cannot fix it), and restarts short of the bench threshold
    still get surfaced once they repeat."""
    benched = s.events_of(EV_REPLICA_BENCHED)
    restarts = s.events_of(EV_REPLICA_RESTART)
    if benched:
        idxs = sorted({e.get("replica") for e in benched})
        return [Finding(
            F_REPLICA_FLAP, "error",
            f"replica(s) {idxs} BENCHED by the flap breaker: each died "
            "fleet_flap_max_restarts times inside fleet_flap_window_s — "
            "a crash loop restarts cannot fix (bad device set, corrupt "
            "checkpoint, OOM on warm-up)",
            "read logs/<run>/replica_<i>.log for the crash cause; the "
            "fleet keeps serving on the remaining replicas but at reduced "
            "capacity until the fleet is restarted",
            evidence=(benched + s.events_of(EV_REPLICA_EXIT))[:_EVIDENCE_CAP],
            data={"benched": idxs, "restarts": len(restarts)},
        )]
    if len(restarts) < cfg.replica_flap_min_restarts:
        return []
    per = {}
    for e in restarts:
        per[e.get("replica")] = per.get(e.get("replica"), 0) + 1
    return [Finding(
        F_REPLICA_FLAP, "warn",
        f"{len(restarts)} replica restart(s) this run "
        f"(per replica: {per}) — the supervisor recovered each time, but "
        "repeated deaths mean the workers are unstable",
        "check replica_<i>.log for the exit cause; if deaths cluster on "
        "one replica its device set or host is suspect",
        evidence=restarts[:_EVIDENCE_CAP],
        data={"restarts": len(restarts), "per_replica": per},
    )]


@rule
def r_breaker_open(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    opens = s.events_of(EV_BREAKER_OPEN)
    if not opens:
        return []
    closes = s.events_of(EV_BREAKER_CLOSE)
    still_open = len(opens) > len(closes)
    return [Finding(
        F_BREAKER_OPEN, "error" if still_open else "warn",
        f"router circuit breaker tripped {len(opens)} time(s)"
        + ("" if not still_open else
           f" and {len(opens) - len(closes)} breaker(s) never re-closed")
        + " — a replica kept failing typed-retryable requests and the "
        "router stopped sending it traffic",
        "breakers that re-closed mean the half-open probe found the "
        "replica healthy again (transient); a breaker still open at run "
        "end means the replica stayed broken — cross-check replica_flap "
        "and the replica's log",
        evidence=(opens + closes)[:_EVIDENCE_CAP],
        data={"opens": len(opens), "closes": len(closes),
              "still_open": still_open},
    )]


@rule
def r_reload_rollback(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_RELOAD_ROLLBACK)
    if not evs:
        return []
    last = evs[-1]
    return [Finding(
        F_RELOAD_ROLLBACK, "error",
        f"rolling reload rolled back: first reloaded replica's probe "
        f"error rate {last.get('error_rate')} crossed "
        "Serving.reload_error_spike, so the fleet was restored to "
        f"checkpoint {last.get('rolled_back_to')!r} and the rollout "
        "aborted (the regressed checkpoint reached at most one replica)",
        "the candidate checkpoint is the problem, not the fleet: inspect "
        f"the regressed entry {last.get('regressed')!r} (training-side "
        "divergence, wrong export) before re-publishing the pointer",
        evidence=evs,
        data={"rollbacks": len(evs), "last": last},
    )]


@rule
def r_quant_drift(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    """Every quant_drift event is a refused install: the int8 accuracy
    gate (serve/quantize.py) caught a quantized state whose predictions
    drifted past the configured bound. One refusal is already a finding —
    a candidate that would have served wrong answers reached the gate."""
    evs = s.events_of(EV_QUANT_DRIFT)
    if not evs:
        return []
    last = evs[-1]
    candidates = sorted(
        {str(e.get("candidate")) for e in evs if e.get("candidate")}
    )
    return [Finding(
        F_QUANT_DRIFT, "error",
        f"int8 accuracy gate refused {len(evs)} quantized state(s) "
        f"(mode {last.get('mode')!r}): relative max error "
        f"{last.get('max_error')} crossed the "
        f"Serving.quantization.max_error={last.get('limit')} bound "
        f"(worst heads: {last.get('per_head')}); the previous weights "
        "kept serving",
        "the checkpoint's weight distribution no longer quantizes within "
        "the bound: widen Serving.quantization.max_error only if the "
        "drift is acceptable, exclude the worst layers via "
        "Serving.quantization.exclude, drop Serving.quantization.mode "
        "from w8a8 to weight_only, or serve this run at "
        "weights_dtype bfloat16",
        evidence=evs,
        data={"refusals": len(evs), "candidates": candidates,
              "last": last},
    )]


@rule
def r_cache_ineffective(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    """A cache-enabled fleet whose hit rate stays on the floor: every
    request pays the content hash + disk probe and almost none reuse an
    entry. Judged from the manager's aggregated fleet_serve window
    (counters are cumulative — the last record is the fleet total)."""
    fleet = _fleet_serve_latest(s)
    if fleet is None or not fleet.get("cache_enabled"):
        return []
    hits = int(fleet.get("cache_hits", 0))
    misses = int(fleet.get("cache_misses", 0))
    lookups = hits + misses
    if lookups < cfg.cache_min_lookups:
        return []
    rate = hits / lookups
    if rate >= cfg.cache_hit_rate_min:
        return []
    return [Finding(
        F_CACHE_INEFFECTIVE, "warn",
        f"prediction cache is ineffective: {hits} hit(s) in {lookups} "
        f"lookups ({rate:.1%}, floor {cfg.cache_hit_rate_min:.0%}) across "
        f"{fleet.get('replicas')} replica(s) — the traffic's graphs "
        "almost never repeat bit-identically under the current cache "
        "context",
        "disable Serving.prediction_cache for this traffic (the cache "
        "only pays off on repeated identical inputs), or check for a "
        "context churn source: every checkpoint swap and weights_dtype/"
        "quantization change namespaces the keys, so a flapping rollout "
        "orphans all prior entries",
        evidence=[fleet],
        data={"hits": hits, "misses": misses, "hit_rate": round(rate, 4),
              "entries": int(fleet.get("cache_entries", 0)),
              "bytes": int(fleet.get("cache_bytes", 0))},
    )]


@rule
def r_quarantine_rot(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    demotes = s.events_of(EV_MIX_DEMOTE)
    skips = s.events_of(EV_DATA_SKIP)
    manifest = s.quarantine
    if not demotes and not skips and not manifest:
        return []
    sids = sorted({str(e.get("source")) for e in demotes
                   if e.get("source") is not None})
    parts = []
    if manifest:
        reasons = sorted({str(m.get("reason")) for m in manifest})
        parts.append(
            f"{len(manifest)} quarantined sample(s) "
            f"({', '.join(reasons[:4])})"
        )
    if skips:
        parts.append(f"{len(skips)} validator skip event(s)")
    if demotes:
        parts.append(f"source(s) {', '.join(sids)} quarantine-DEMOTED")
    return [Finding(
        F_QUARANTINE_ROT, "error" if demotes else "warn",
        "data rot: " + "; ".join(parts),
        "inspect quarantine/manifest.jsonl for the per-sample reasons; "
        "Dataset.bad_sample_policy picks the response (quarantine keeps "
        "the audit trail) and Mixture.demote_after bounds how much rot a "
        "mixture source may show before demotion — re-ingest or drop the "
        "named sources",
        evidence=(demotes + skips + manifest)[:_EVIDENCE_CAP * 2],
        data={"quarantined": len(manifest), "skip_events": len(skips),
              "demoted_sources": sids},
    )]


@rule
def r_loader_stall(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_LOADER_STALL)
    if not evs:
        return []
    causes = sorted({str(e.get("cause")) for e in evs if e.get("cause")})
    return [Finding(
        F_LOADER_STALL, "error",
        f"loader stall: the prefetch watchdog fired {len(evs)} time(s) "
        f"(cause(s): {', '.join(causes) or '?'}) — a producer thread "
        "wedged or died without its end sentinel",
        "check the storage path / remote store the producer reads "
        "(HYDRAGNN_DDSTORE_* retry knobs bound transient drops); "
        "Training.loader_stall_timeout tunes how long an alive-but-"
        "silent producer may hold the step loop before the typed error",
        evidence=evs,
        data={"stalls": len(evs), "causes": causes},
    )]


@rule
def r_wedged_step(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    evs = s.events_of(EV_WEDGE)
    if not evs:
        return []
    return [Finding(
        F_WEDGED_STEP, "error",
        f"wedged device step: the serve watchdog abandoned {len(evs)} "
        "hung step(s) and recycled the runner — an XLA program stopped "
        "making progress mid-dispatch",
        "Serving.step_timeout_s bounds the watchdog; a recurring wedge "
        "at the same pad bucket points at a pathological shape — check "
        "the flight dump the wedge triggered (spans carry the batch "
        "index) and warm that level explicitly",
        evidence=evs,
        data={"wedges": len(evs)},
    )]


@rule
def r_untuned_kernel(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    """A TPU run whose Pallas kernels rode pinned default tile plans —
    free MFU left on the table. Fires only for real accelerator device
    kinds: CPU/interpret runs (CI, doctor_smoke's clean leg) legitimately
    ride defaults and stay silent."""
    evs = s.events_of(EV_TILE_PLAN)
    defaults = [
        e for e in evs
        if e.get("source") == "default"
        and "tpu" in str(e.get("device", "")).lower()
    ]
    if not defaults:
        return []
    kernels = sorted({str(e.get("kernel")) for e in defaults})
    return [Finding(
        F_UNTUNED_KERNEL, "info",
        f"untuned kernel(s) on {defaults[0].get('device')}: "
        f"{', '.join(kernels)} ran {len(defaults)} specialization(s) on "
        "pinned default tile plans — no tuned-table entry matched this "
        "(kernel version, device, dtype, shape)",
        "run `python -m hydragnn_tpu.tune <config.json>` on this device "
        "to sweep and persist winners, then point "
        "Training.autotune_cache_dir (or HYDRAGNN_TUNE_CACHE) at the "
        "table; Training.autotune: sweep does it inline at warm-up "
        "(docs/TUNING.md)",
        evidence=defaults,
        data={"kernels": kernels, "default_lookups": len(defaults)},
    )]


@rule
def r_cold_start(s: RunStreams, cfg: DoctorConfig) -> List[Finding]:
    """Single-run variant: a RESUMED run (Training.continue) that still
    paid compile-cache misses regressed its restart latency — the cache
    the resume was supposed to be warm from did not serve. The cross-run
    variant (time_to_first_step growth) lives in diff mode."""
    rep = s.compile_report()
    if rep is None or not s.config:
        return []
    training = (s.config.get("NeuralNetwork") or {}).get("Training") or {}
    resumed = bool(training.get("continue"))
    misses = int(rep.get("cache_misses") or 0)
    if not resumed or misses <= 0:
        return []
    return [Finding(
        F_COLD_START, "warn",
        f"compile-cache cold start on a resumed run: {misses} cache "
        f"miss(es) (hits: {rep.get('cache_hits')}) — the restart paid "
        f"time_to_first_step={rep.get('time_to_first_step')}s in "
        "recompilation the persistent cache should have absorbed",
        "check Training.compile_cache_dir points at the SAME directory "
        "as the original run (the default is per-run-name, so a renamed "
        "run cold-starts by construction) and that HYDRAGNN_COMPILE_CACHE "
        "is not overriding it; a jax/jaxlib upgrade also invalidates "
        "every key",
        evidence=[{"compile_report": {
            k: rep.get(k) for k in ("cache_hits", "cache_misses",
                                    "time_to_first_step", "mode")}}],
        data={"cache_misses": misses,
              "time_to_first_step": rep.get("time_to_first_step")},
    )]


# exception types a kind-specific rule already explains: the crash rule
# folds those dumps into the existing finding instead of double-reporting
_EXPLAINED_EXC = {
    "LoaderStallError": F_LOADER_STALL,
    "WedgedStepError": F_WEDGED_STEP,
    "RetraceError": F_RETRACE_STORM,
    "MixtureExhaustedError": F_QUARANTINE_ROT,
}
_CRASH_REASON_RE = re.compile(
    r"unhandled_exception|train_exception|thread_exception|fatal_guard"
)


def r_crash(s: RunStreams, cfg: DoctorConfig,
            findings: List[Finding]) -> List[Finding]:
    """Runs AFTER the rulebook (it needs the other findings): crash dumps
    whose exception an existing finding explains become its evidence;
    anything else is an unexplained crash of its own."""
    by_kind = {f.kind: f for f in findings}
    out: List[Finding] = []
    for dump in s.dumps:
        meta = dump.get("meta") or {}
        reason = str(meta.get("reason", ""))
        exc = meta.get("exception") or {}
        if not exc and not _CRASH_REASON_RE.search(reason):
            continue
        exc_type = str(exc.get("type", ""))
        mapped = _EXPLAINED_EXC.get(exc_type)
        if mapped is None and reason == "fatal_guard":
            mapped = F_NAN_DIVERGENCE
        if mapped is not None and mapped in by_kind:
            f = by_kind[mapped]
            f.evidence.append({"flight_dump": dump["dir"], "meta": meta})
            f.data["crash_dump"] = dump["dir"]
            continue
        out.append(Finding(
            F_CRASH, "error",
            f"crash dump {os.path.basename(dump['dir'])}: "
            + (f"{exc_type}: {exc.get('message', '')}" if exc_type
               else f"reason={reason}"),
            "read the dump's meta.json traceback; events.json holds the "
            "last incidents before death ranked by severity, spans.json "
            "the causal trace, metrics.prom every counter at the moment "
            "of death",
            evidence=[{"flight_dump": dump["dir"], "meta": meta}],
            data={"reason": reason, "exception_type": exc_type},
        ))
    return out


# ---------------------------------------------------------------------------
# diagnosis driver
# ---------------------------------------------------------------------------


def diagnose(
    streams: RunStreams, cfg: Optional[DoctorConfig] = None
) -> Tuple[List[Finding], Dict[str, Any]]:
    """Apply the rulebook. Returns (findings sorted most-severe-first,
    report dict with the span decomposition + stream census)."""
    cfg = cfg or DoctorConfig()
    findings: List[Finding] = []
    for r in _RULES:
        try:
            findings.extend(r(streams, cfg))
        except Exception as e:  # a broken rule must not mask the others
            streams.parse_warnings.append(
                f"rule {r.__name__} failed: {type(e).__name__}: {e}"
            )
    findings.extend(r_crash(streams, cfg, findings))
    findings.sort(key=lambda f: (-severity_rank(f.severity), f.kind))
    decomp = span_decomposition(streams.spans)
    report = {
        "target": streams.target,
        "source": streams.source,
        "streams": {
            "metrics_records": len(streams.metrics),
            "spans": len(streams.spans),
            "events": len(streams.events),
            "quarantined": len(streams.quarantine),
            "flight_dumps": len(streams.dumps),
        },
        "span_decomposition": decomp,
        "step_phase": step_phase_verdict(decomp, cfg),
        "parse_warnings": list(streams.parse_warnings),
    }
    return findings, report


def static_findings_record(root: Optional[str] = None) -> Dict[str, Any]:
    """The graftlint verdict for doctor.json (HYDRAGNN_DOCTOR teardown):
    whether the tree the diagnosed binary ran from was clean under
    ``python -m hydragnn_tpu.analysis``, so post-hoc forensics can rule
    convention rot in or out before chasing runtime causes. Analysis is
    pure host-side AST work (no jax import); any failure degrades to an
    ``error`` field — the verdict hook must never take teardown down."""
    try:
        from .. import analysis

        findings = analysis.analyze(root)
        summary = analysis.summarize(findings)
        rec: Dict[str, Any] = {
            "v": analysis.ANALYSIS_SCHEMA_VERSION,
            "clean": summary["clean"],
            "active": summary["active"],
            "waived": summary["waived"],
            "by_checker": summary["by_checker"],
        }
        if summary["active"]:
            rec["findings"] = [
                f.to_dict() for f in findings if not f.waived
            ][:50]  # bounded: doctor.json is a forensic record, not a report
        return rec
    except Exception as e:  # noqa: BLE001 — degrade, never raise
        return {"error": f"{type(e).__name__}: {e}"}


def run_summary(streams: RunStreams) -> Dict[str, Any]:
    """Comparable scalar summary of one run (the diff mode's per-side
    metric table)."""
    out: Dict[str, Any] = {}
    windows = streams.records_of("step_window")
    if windows:
        steps = sum(int(w["steps"]) for w in windows)
        out["steps"] = steps
        out["step_time_ms_mean"] = round(
            sum(float(w["step_time_ms"]) * int(w["steps"]) for w in windows)
            / max(steps, 1), 3)
        out["graphs_per_sec_mean"] = round(
            sum(float(w["graphs_per_sec"]) * int(w["steps"])
                for w in windows) / max(steps, 1), 2)
        out["padding_waste_mean"] = round(
            sum(float(w["padding_waste"]) * int(w["steps"])
                for w in windows) / max(steps, 1), 4)
        mfus = [w["mfu_est"] for w in windows if w.get("mfu_est") is not None]
        out["mfu_est_last"] = mfus[-1] if mfus else None
    epochs = streams.records_of("epoch")
    if epochs:
        real = [e for e in epochs if not e.get("filler")]
        last = (real or epochs)[-1]
        out["epochs"] = len(epochs)
        for k in ("train", "val", "test", "lr"):
            if k in last:
                out[f"loss_{k}_final"] = last[k]
    rep = streams.compile_report()
    if rep is not None:
        for k in ("time_to_first_step", "cache_hits", "cache_misses",
                  "violations", "hbm_peak_bytes", "comm_bytes_peak"):
            out[k] = rep.get(k)
    return out


# ---------------------------------------------------------------------------
# diff mode
# ---------------------------------------------------------------------------

_BENCH_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")
_BENCH_PRIMARY = ("value", "mfu", "vs_baseline")
_BENCH_AUX_RE = re.compile(r"graphs_per_sec")


def _is_number(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def load_bench_cells(path: str) -> Tuple[int, Dict[str, float]]:
    """Parse one committed BENCH_r*.json round into gated cells — the
    SAME keying as run-scripts/bench_gate.py (primary keys namespaced by
    the metric string; *graphs_per_sec* auxiliaries by name), so a doctor
    diff and a gate verdict over the same rounds name the same cells."""
    m = _BENCH_ROUND_RE.search(os.path.basename(path))
    if not m:
        raise ValueError(f"{path!r} is not a BENCH_r*.json round")
    with open(path) as fh:
        doc = json.load(fh)
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict):
        raise ValueError(f"{path!r} has no parsed cell object")
    if int(doc.get("rc", 0)) != 0 or "error" in parsed:
        raise ValueError(f"{path!r} is not a valid round (rc/error)")
    metric = str(parsed.get("metric", ""))
    cells: Dict[str, float] = {}
    for key, val in parsed.items():
        if not _is_number(val) or val <= 0:
            continue
        if key in _BENCH_PRIMARY:
            cells[f"{metric} :: {key}"] = float(val)
        elif _BENCH_AUX_RE.search(key):
            cells[key] = float(val)
    return int(m.group(1)), cells


def _flatten(cfg: Any, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    if isinstance(cfg, dict) and cfg:
        for k, v in cfg.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    else:
        out[prefix[:-1]] = cfg
    return out


def config_diff(a: Optional[Dict], b: Optional[Dict]) -> Dict[str, Any]:
    """Changed/added/removed keys between two completed configs
    (dot-path flattened; lists compare as values)."""
    if a is None or b is None:
        return {"available": False}
    fa, fb = _flatten(a), _flatten(b)
    changed = {
        k: {"a": fa[k], "b": fb[k]}
        for k in sorted(set(fa) & set(fb))
        if fa[k] != fb[k]
    }
    return {
        "available": True,
        "changed": changed,
        "added": sorted(set(fb) - set(fa)),
        "removed": sorted(set(fa) - set(fb)),
    }


def sharding_diff(
    a: Optional[Dict[str, Any]], b: Optional[Dict[str, Any]]
) -> Dict[str, Any]:
    """Placement regression diff between two runs' ``sharding.json``
    snapshots (obs/sharding.py reports the flight recorder dumped): per
    label, every leaf whose PartitionSpec changed, leaves present in only
    one run, and the replicated/per-device byte deltas — the oracle pair
    for rule-table edits (docs/PARALLELISM.md "Auditing a table"). The
    ``rule_audit`` entry (unmatched-leaf lists) diffs as path sets."""
    if a is None or b is None:
        return {"available": False}

    def _leaves(report: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
        return {
            e["path"]: e
            for table in (report.get("sections") or {}).values()
            for e in table
        }

    labels: Dict[str, Dict[str, Any]] = {}
    for label in sorted(set(a) & set(b)):
        ra, rb = a[label], b[label]
        if label == "rule_audit" or "summary" not in ra or "summary" not in rb:
            continue
        la, lb = _leaves(ra), _leaves(rb)
        changed = {
            p: {"a": la[p].get("spec"), "b": lb[p].get("spec")}
            for p in sorted(set(la) & set(lb))
            if la[p].get("spec") != lb[p].get("spec")
        }
        sa_, sb_ = ra["summary"], rb["summary"]
        deltas = {
            k: {"a": sa_.get(k), "b": sb_.get(k),
                "delta": (sb_.get(k) or 0) - (sa_.get(k) or 0)}
            for k in ("replicated_bytes", "per_device_bytes",
                      "sharded_bytes", "sharded_leaves")
        }
        labels[label] = {
            "builder": {
                "a": (ra.get("builder") or {}).get("name"),
                "b": (rb.get("builder") or {}).get("name"),
            },
            "mesh": {"a": ra.get("mesh"), "b": rb.get("mesh")},
            "spec_changed": changed,
            "only_in_a": sorted(set(la) - set(lb)),
            "only_in_b": sorted(set(lb) - set(la)),
            "summary": deltas,
            "audit_warnings": {
                "a": len(ra.get("audit") or ()),
                "b": len(rb.get("audit") or ()),
            },
        }
    ua = set((a.get("rule_audit") or {}).get("unmatched") or ())
    ub = set((b.get("rule_audit") or {}).get("unmatched") or ())
    return {
        "available": True,
        "labels": labels,
        "unmatched_new_in_b": sorted(ub - ua),
        "unmatched_resolved_in_b": sorted(ua - ub),
    }


def diff_runs(
    a: str,
    b: str,
    cfg: Optional[DoctorConfig] = None,
    gate_verdict: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Cross-run regression diff — the promotion-gate primitive. ``a``
    and ``b`` are run directories (stream diff) or BENCH_r*.json rounds
    (per-cell delta diff); ``gate_verdict`` (bench_gate.py --verdict-out)
    is cross-checked per cell when given."""
    cfg = cfg or DoctorConfig()
    a_bench = bool(_BENCH_ROUND_RE.search(os.path.basename(a)))
    b_bench = bool(_BENCH_ROUND_RE.search(os.path.basename(b)))
    if a_bench != b_bench:
        raise ValueError(
            f"cannot diff a bench round against a run dir ({a!r} vs {b!r})"
        )
    if a_bench:
        round_a, cells_a = load_bench_cells(a)
        round_b, cells_b = load_bench_cells(b)
        cells: Dict[str, Dict[str, Any]] = {}
        for name in sorted(set(cells_a) | set(cells_b)):
            va, vb = cells_a.get(name), cells_b.get(name)
            entry: Dict[str, Any] = {"a": va, "b": vb}
            if va is not None and vb is not None and va > 0:
                entry["delta_frac"] = round((vb - va) / va, 6)
            cells[name] = entry
        out: Dict[str, Any] = {
            "mode": "bench_rounds",
            "a": {"path": a, "round": round_a},
            "b": {"path": b, "round": round_b},
            "cells": cells,
        }
        if gate_verdict is not None:
            out["gate"] = _check_gate_consistency(
                cells, round_a, gate_verdict
            )
        return out

    sa, sb = RunStreams.load(a), RunStreams.load(b)
    fa, _ = diagnose(sa, cfg)
    fb, _ = diagnose(sb, cfg)
    sum_a, sum_b = run_summary(sa), run_summary(sb)
    metrics: Dict[str, Dict[str, Any]] = {}
    for key in sorted(set(sum_a) | set(sum_b)):
        va, vb = sum_a.get(key), sum_b.get(key)
        entry: Dict[str, Any] = {"a": va, "b": vb}
        if _is_number(va) and _is_number(vb) and va:
            entry["delta_frac"] = round((vb - va) / abs(va), 6)
        metrics[key] = entry
    da = span_decomposition(sa.spans)
    db = span_decomposition(sb.spans)
    trace: Dict[str, Dict[str, Any]] = {}
    for name in sorted(set(da) & set(db)):
        trace[name] = {
            q: {
                "a": da[name][q], "b": db[name][q],
                "delta_frac": (
                    round((db[name][q] - da[name][q]) / da[name][q], 4)
                    if da[name][q] else None
                ),
            }
            for q in ("p50_ms", "p99_ms")
        }
    kinds_a = {f.kind for f in fa}
    kinds_b = {f.kind for f in fb}
    diff_findings: List[Dict[str, Any]] = []
    # cross-run cold-start: run B paid recompiles run A's warm path did not
    ttfs_a, ttfs_b = sum_a.get("time_to_first_step"), sum_b.get(
        "time_to_first_step")
    if (
        _is_number(ttfs_a) and _is_number(ttfs_b) and ttfs_a > 0
        and ttfs_b > cfg.cold_start_factor * ttfs_a
        and int(sum_b.get("cache_misses") or 0)
        > int(sum_a.get("cache_misses") or 0)
    ):
        diff_findings.append(Finding(
            F_COLD_START, "warn",
            f"compile-cache cold-start regression: time_to_first_step "
            f"{ttfs_b}s vs {ttfs_a}s "
            f"({ttfs_b / ttfs_a:.1f}x) with cache misses "
            f"{sum_b.get('cache_misses')} vs {sum_a.get('cache_misses')}",
            "run B recompiled what run A served from cache — check "
            "Training.compile_cache_dir stability across the two runs "
            "and whether the step program changed (the retrace sentinel "
            "report names the differing avals)",
            data={"ttfs_a": ttfs_a, "ttfs_b": ttfs_b},
        ).to_dict())
    return {
        "mode": "run_dirs",
        "a": {"path": a, "summary": sum_a,
              "findings": [f.to_dict() for f in fa]},
        "b": {"path": b, "summary": sum_b,
              "findings": [f.to_dict() for f in fb]},
        "config_diff": config_diff(sa.config, sb.config),
        "sharding": sharding_diff(sa.sharding, sb.sharding),
        "metrics": metrics,
        "trace": trace,
        "findings_new_in_b": sorted(kinds_b - kinds_a),
        "findings_resolved_in_b": sorted(kinds_a - kinds_b),
        "diff_findings": diff_findings,
    }


def _check_gate_consistency(
    cells: Dict[str, Dict[str, Any]],
    round_a: int,
    verdict: Dict[str, Any],
) -> Dict[str, Any]:
    """Cross-check the doctor's per-cell deltas against a
    ``gate_verdict.json`` (bench_gate.py). Only cells the gate baselined
    against round ``a`` are comparable — the gate walks back to the most
    recent prior round carrying each cell, which may be older than A."""
    checked = 0
    mismatches: List[str] = []
    statuses: Dict[str, str] = {}
    for entry in verdict.get("cells", []):
        name = entry.get("cell")
        statuses[name] = entry.get("status", "?")
        if entry.get("baseline_round") != round_a:
            continue
        mine = cells.get(name, {})
        dv, dm = entry.get("delta_frac"), mine.get("delta_frac")
        if dv is None or dm is None:
            continue
        checked += 1
        if abs(float(dv) - float(dm)) > 1e-6:
            mismatches.append(
                f"{name}: doctor delta {dm:+.4f} vs gate {float(dv):+.4f}"
            )
    return {
        "gate_rc": verdict.get("rc"),
        "cells_checked": checked,
        "consistent": not mismatches,
        "mismatches": mismatches,
        "statuses": statuses,
    }


# ---------------------------------------------------------------------------
# watch mode
# ---------------------------------------------------------------------------


def watch(
    run_dir: str,
    interval_s: float = 2.0,
    max_seconds: Optional[float] = None,
    cfg: Optional[DoctorConfig] = None,
    exit_on_finding: bool = False,
    out=None,
) -> List[Finding]:
    """Tail a live run's streams: re-diagnose every ``interval_s`` and
    print each finding once, the moment it first fires. Returns every
    finding seen. Stops on ``max_seconds``, ``exit_on_finding`` (first
    finding), or KeyboardInterrupt."""
    out = out or sys.stdout
    cfg = cfg or DoctorConfig()
    seen: Dict[str, Finding] = {}
    t0 = time.monotonic()
    tail = StreamTail(run_dir)
    print(f"doctor[watch]: tailing {run_dir} (interval {interval_s}s)",
          file=out, flush=True)
    try:
        while True:
            try:
                findings, _ = diagnose(tail.refresh(), cfg)
            except Exception as e:  # a mid-write race must not kill watch
                print(f"doctor[watch]: ingest failed ({e}); retrying",
                      file=out, flush=True)
                findings = []
            fired = False
            for f in findings:
                if f.kind in seen:
                    seen[f.kind] = f  # keep the freshest evidence
                    continue
                seen[f.kind] = f
                fired = True
                print(
                    f"doctor[watch] FINDING [{f.severity}] {f.kind}: "
                    f"{f.summary}\n  remediation: {f.remediation}",
                    file=out, flush=True,
                )
            if exit_on_finding and fired:
                break
            if max_seconds is not None and \
                    time.monotonic() - t0 >= max_seconds:
                break
            time.sleep(interval_s)
    except KeyboardInterrupt:
        pass
    print(f"doctor[watch]: done ({len(seen)} finding kind(s))",
          file=out, flush=True)
    return list(seen.values())


# ---------------------------------------------------------------------------
# rendering + CLI
# ---------------------------------------------------------------------------


def render_findings(findings: List[Finding], report: Dict[str, Any],
                    out=None) -> None:
    out = out or sys.stdout
    st = report.get("streams", {})
    print(
        f"doctor: {report.get('target')} [{report.get('source')}] — "
        f"{st.get('metrics_records', 0)} metric records, "
        f"{st.get('spans', 0)} spans, {st.get('events', 0)} events, "
        f"{st.get('quarantined', 0)} quarantined, "
        f"{st.get('flight_dumps', 0)} flight dump(s)",
        file=out,
    )
    phase = report.get("step_phase")
    if phase:
        print(
            f"doctor: step decomposition: {phase['verdict']} "
            f"(host_batch_build p50 {phase['host_batch_build_p50_ms']}ms "
            f"vs device_dispatch p50 {phase['device_dispatch_p50_ms']}ms "
            f"over {phase['samples']} sampled steps)",
            file=out,
        )
    for wmsg in report.get("parse_warnings", []):
        print(f"doctor: warning: {wmsg}", file=out)
    if not findings:
        print("doctor: 0 findings — no known pathology detected", file=out)
        return
    print(f"doctor: {len(findings)} finding(s):", file=out)
    for f in findings:
        print(f"  [{f.severity.upper():5s}] {f.kind}: {f.summary}",
              file=out)
        print(f"          remediation: {f.remediation}", file=out)
        print(f"          evidence: {len(f.evidence)} record(s)", file=out)


def render_span_report(decomp: Dict[str, Dict[str, float]],
                       out=None) -> None:
    out = out or sys.stdout
    if not decomp:
        print("doctor[trace]: no spans found", file=out)
        return
    total = sum(v["total_ms"] for v in decomp.values())
    print(f"doctor[trace]: {sum(v['count'] for v in decomp.values())} "
          f"spans, {total:.1f}ms total span time", file=out)
    print(f"  {'span':<28} {'count':>6} {'p50 ms':>10} {'p99 ms':>10} "
          f"{'total ms':>11} {'share':>6}", file=out)
    for name, v in sorted(decomp.items(), key=lambda kv: -kv[1]["total_ms"]):
        share = v["total_ms"] / total if total else 0.0
        print(
            f"  {name:<28} {v['count']:>6} {v['p50_ms']:>10.3f} "
            f"{v['p99_ms']:>10.3f} {v['total_ms']:>11.2f} {share:>6.1%}",
            file=out,
        )


def _write_json(path: str, doc: Dict[str, Any]) -> None:
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, default=str)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.obs.doctor",
        description=__doc__.splitlines()[0],
    )
    sub = ap.add_subparsers(dest="mode")
    d = sub.add_parser("diagnose", help="diagnose one run dir / flight dump")
    d.add_argument("target")
    d.add_argument("--json", default=None, metavar="PATH",
                   help="also write the findings as JSON")
    df = sub.add_parser("diff", help="cross-run regression diff")
    df.add_argument("a")
    df.add_argument("b")
    df.add_argument("--gate", default=None, metavar="PATH",
                    help="gate_verdict.json (bench_gate.py --verdict-out) "
                         "to cross-check per-cell deltas against")
    df.add_argument("--json", default=None, metavar="PATH")
    wt = sub.add_parser("watch", help="tail a live run, print findings")
    wt.add_argument("target")
    wt.add_argument("--interval", type=float, default=2.0)
    wt.add_argument("--max-seconds", type=float, default=None)
    wt.add_argument("--exit-on-finding", action="store_true")
    tr = sub.add_parser("trace", help="span-decomposition report")
    tr.add_argument("trace_jsonl")
    # bare `doctor <run_dir>` is the diagnose shorthand
    if argv and argv[0] not in ("diagnose", "diff", "watch", "trace",
                                "-h", "--help"):
        argv = ["diagnose"] + argv
    args = ap.parse_args(argv)

    if args.mode == "diagnose":
        if not os.path.isdir(args.target):
            print(f"doctor: {args.target!r} is not a directory",
                  file=sys.stderr)
            return 2
        streams = RunStreams.load(args.target)
        findings, report = diagnose(streams)
        render_findings(findings, report)
        if args.json:
            _write_json(args.json, {
                "v": DOCTOR_SCHEMA_VERSION, "mode": "diagnose",
                "target": args.target,
                "findings": [f.to_dict() for f in findings],
                "report": report,
            })
        return 1 if findings else 0

    if args.mode == "diff":
        for p in (args.a, args.b):
            if not os.path.exists(p):
                print(f"doctor: {p!r} not found", file=sys.stderr)
                return 2
        gate = None
        if args.gate:
            warnings_: List[str] = []
            gate = _read_json(args.gate, warnings_)
            if gate is None:
                print(f"doctor: cannot read gate verdict {args.gate!r}: "
                      f"{warnings_}", file=sys.stderr)
                return 2
        try:
            result = diff_runs(args.a, args.b, gate_verdict=gate)
        except (ValueError, OSError, json.JSONDecodeError) as e:
            print(f"doctor: diff failed: {e}", file=sys.stderr)
            return 2
        if result["mode"] == "bench_rounds":
            print(f"doctor[diff]: BENCH r{result['a']['round']:02d} -> "
                  f"r{result['b']['round']:02d}")
            for name, entry in result["cells"].items():
                delta = entry.get("delta_frac")
                print(f"  {name!r}: {entry['a']} -> {entry['b']}"
                      + (f" ({delta:+.1%})" if delta is not None else ""))
            gate_res = result.get("gate")
            if gate_res is not None:
                print(
                    f"doctor[diff]: gate verdict rc={gate_res['gate_rc']} "
                    f"cells_checked={gate_res['cells_checked']} "
                    f"consistent={gate_res['consistent']}"
                )
                for mm in gate_res["mismatches"]:
                    print(f"  MISMATCH {mm}", file=sys.stderr)
        else:
            cd = result["config_diff"]
            if cd.get("available"):
                print(f"doctor[diff]: config: {len(cd['changed'])} "
                      f"changed, {len(cd['added'])} added, "
                      f"{len(cd['removed'])} removed")
                for k, v in list(cd["changed"].items())[:20]:
                    print(f"  {k}: {v['a']!r} -> {v['b']!r}")
            sh = result["sharding"]
            if sh.get("available"):
                for label, entry in sh["labels"].items():
                    dv = entry["summary"]["replicated_bytes"]
                    print(
                        f"doctor[diff]: sharding[{label}] "
                        f"builder {entry['builder']['a']} -> "
                        f"{entry['builder']['b']}, "
                        f"{len(entry['spec_changed'])} leaf spec(s) "
                        f"changed, replicated_bytes {dv['a']} -> "
                        f"{dv['b']} ({dv['delta']:+d}), audit warnings "
                        f"{entry['audit_warnings']['a']} -> "
                        f"{entry['audit_warnings']['b']}"
                    )
                    for p, v in list(entry["spec_changed"].items())[:20]:
                        print(f"  {p}: {v['a']!r} -> {v['b']!r}")
                if sh["unmatched_new_in_b"]:
                    print(
                        "doctor[diff]: rule_audit unmatched leaves new in "
                        f"B: {sh['unmatched_new_in_b']}"
                    )
            for key, entry in result["metrics"].items():
                delta = entry.get("delta_frac")
                print(f"  {key}: {entry['a']} -> {entry['b']}"
                      + (f" ({delta:+.1%})" if delta is not None else ""))
            for name, qs in result["trace"].items():
                print(f"  trace {name}: p50 {qs['p50_ms']['a']} -> "
                      f"{qs['p50_ms']['b']}ms, p99 {qs['p99_ms']['a']} -> "
                      f"{qs['p99_ms']['b']}ms")
            print(f"doctor[diff]: findings new in B: "
                  f"{result['findings_new_in_b'] or 'none'}; resolved: "
                  f"{result['findings_resolved_in_b'] or 'none'}")
            for fd in result["diff_findings"]:
                print(f"  [{fd['severity'].upper()}] {fd['kind']}: "
                      f"{fd['summary']}")
        if args.json:
            _write_json(args.json, {
                "v": DOCTOR_SCHEMA_VERSION, "mode": "diff", **result,
            })
        gate_res = result.get("gate")
        if gate_res is not None and not gate_res["consistent"]:
            return 1
        return 0

    if args.mode == "watch":
        if not os.path.isdir(args.target):
            print(f"doctor: {args.target!r} is not a directory",
                  file=sys.stderr)
            return 2
        watch(args.target, interval_s=args.interval,
              max_seconds=args.max_seconds,
              exit_on_finding=args.exit_on_finding)
        return 0

    if args.mode == "trace":
        warnings_: List[str] = []
        spans = _read_jsonl(args.trace_jsonl, validate_span_record,
                            warnings_)
        for wmsg in warnings_:
            print(f"doctor[trace]: warning: {wmsg}")
        if not spans and not os.path.exists(args.trace_jsonl):
            print(f"doctor: {args.trace_jsonl!r} not found",
                  file=sys.stderr)
            return 2
        render_span_report(span_decomposition(spans))
        return 0

    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
