"""Per-step train telemetry: goodput, padding waste, MFU estimate, memory,
the versioned ``metrics.jsonl`` stream, and the on-demand profiling trigger.

This is the measurement substrate of the next MFU round (ROADMAP item 3 —
you cannot close a padding-waste or H2D-stall gap you never measure) and of
the HPO fleet (item 5 — the scheduler consumes the stream instead of
scraping stdout). Opt-in for training via the top-level ``Telemetry``
config section (docs/CONFIG.md; ``HYDRAGNN_TELEMETRY=1/0`` overrides);
publishing is rank-0-gated like ``MetricsWriter``.

What ``StepTelemetry`` measures, per flush window of ``interval_steps``:

- **step time** (host dispatch-to-dispatch wall time per optimizer step;
  under JAX async dispatch the queue throttles the host to the device
  rate, so the steady-state mean converges to the device step time without
  forcing a per-step sync — the same reasoning the epoch loop uses for its
  loss bookkeeping),
- **goodput**: real (mask-counted) graphs / nodes / edges per second,
- **padding-waste fraction** per axis (graphs / nodes / edges): 1 − real
  slots / padded slots, overall and per pad-bucket label,
- **MFU estimate**: XLA-counted FLOPs of each visited specialization (the
  flops-audit recipe, run-scripts/flops_audit.py — cost analysis of the
  compiled executable, cached by the compile plane's AOT warm-up) divided
  by elapsed time and the chip's peak (``peak_flops``),
- **memory**: per-device peak bytes in use + host RSS.

Sinks: (a) ``logs/<run>/metrics.jsonl`` — one JSON record per window /
epoch / run, every record stamped ``{"v": 1, "ts": ...}``; (b) the
existing ``MetricsWriter`` (TensorBoard + scalars.jsonl); (c) the
process-wide registry (obs/registry.py), scrapeable when an endpoint is
mounted (``Telemetry.http_port`` / ``Serving.http_port``).

On-demand profiling: touching ``logs/<run>/profile_trigger`` (or sending
``SIGUSR1``) makes the next flush start an xprof capture of the following
``profile_steps`` steps into ``logs/<run>/profile_on_demand/`` — the
live-run analog of the epoch-scoped ``Profile`` config section
(utils/profile.py), for when the slowdown is happening *now*.
"""

from __future__ import annotations

import json
import math
import os
import signal
import threading
import time
import warnings
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..utils import envflags
from .registry import registry

# record shapes + version live in obs/schema.py (one source of truth the
# producers stamp and the consumers — doctor, smokes — validate against)
from .schema import METRICS_SCHEMA_VERSION as SCHEMA_VERSION

# memory gauges are the one flush component with a real price (device
# memory_stats + /proc reads, ~300us) — refresh at most this often rather
# than every window, keeping the per-step telemetry bill in microseconds
_MEMORY_REFRESH_S = 1.0

TELEMETRY_DEFAULTS: Dict[str, Any] = {
    "enabled": False,
    "interval_steps": 10,
    "http_port": None,  # None = no training-side endpoint; 0 = ephemeral
    "http_host": "127.0.0.1",  # bind interface; "0.0.0.0" for off-host
    "mfu": True,
    "jsonl": True,
    "profile_trigger": True,
    "profile_steps": 5,
    # tracing plane (obs/trace.py; docs/OBSERVABILITY.md "Tracing"):
    # spans to logs/<run>/trace.jsonl under head-based sampling —
    # trace_sample is the per-request probability on the serving side,
    # trace_interval_steps the every-Nth-step cadence on the training side
    "trace": False,
    "trace_sample": 0.01,
    "trace_interval_steps": 50,
    # crash flight recorder (obs/flightrec.py): events + spans + registry
    # snapshot dumped on unhandled exception / SIGUSR2 / fatal guard /
    # serve wedge; armed whenever the plane is on (enabled, trace, or
    # numerics)
    "flight_recorder": True,
    # in-graph numerics probes + NaN provenance drill-down (obs/numerics.py;
    # docs/OBSERVABILITY.md "Numerics"): per-layer activation and per-param-
    # group gradient statistics ride the step outputs, and a guarded skip
    # re-runs its held batch through a probe-instrumented diagnostic that
    # names the first non-finite tensor. HYDRAGNN_NUMERICS=1/0 overrides.
    "numerics": False,
    # fleet plane (obs/fleet.py; docs/OBSERVABILITY.md "Fleet"): per-host
    # registry snapshots push to a rank-0 collector each flush window,
    # which publishes across-host hydragnn_fleet_* aggregates and runs the
    # straggler/desync watchdog. HYDRAGNN_FLEET=1/0 overrides "fleet";
    # HYDRAGNN_FLEET_COLLECTOR overrides the collector address.
    "fleet": False,
    "fleet_collector": None,        # "host:port" push target / rank-0 bind port
    "fleet_collector_port": 0,      # rank-0 bind port when no address is given
    "fleet_collector_host": "127.0.0.1",  # rank-0 bind interface
    "fleet_straggler_factor": 2.0,  # step time vs fleet median before flagging
    "fleet_max_step_lag": 200,      # steps of progress skew before fleet_desync
    "fleet_stale_after_s": 30.0,    # heartbeat silence before a host goes stale
    "fleet_collective_budget": None,  # est. collective fraction bound (None=off)
    "fleet_sharding_audit_bytes": 1 << 20,  # replicated-leaf audit threshold
}

# peak dense bf16 FLOP/s by TPU generation (public figures; bench.py
# delegates here so the bench cells and the live MFU gauge share one table)
PEAK_FLOPS = {
    "v6": 918e12,
    "v5p": 459e12,
    "v5": 197e12,  # v5e / "TPU v5 lite"
    "v4": 275e12,
}


# the tri-state on/off env parse moved to the shared boundary module in
# r15 (utils/envflags.py, enforced by analysis/env_census.py); re-exported
# here because every plane historically imported it from telemetry
env_flag = envflags.env_flag


def peak_flops(device_kind: str) -> float:
    kind = str(device_kind).lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def mfu_estimate(flops: float, seconds: float, device_kind: str) -> float:
    """Model FLOPs utilization: achieved FLOP/s over the chip peak."""
    if seconds <= 0:
        return 0.0
    return (float(flops) / float(seconds)) / peak_flops(device_kind)


def resolve_telemetry(config: Dict[str, Any]) -> Dict[str, Any]:
    """Resolve the top-level ``Telemetry`` section to a complete, validated
    settings dict. Unknown keys warn (matching config completion's
    ignore-unknown behavior); ``HYDRAGNN_TELEMETRY`` env overrides
    ``enabled`` (``0``/``off`` forces off, ``1`` forces on)."""
    section = dict((config or {}).get("Telemetry", {}) or {})
    unknown = sorted(set(section) - set(TELEMETRY_DEFAULTS))
    if unknown:
        warnings.warn(
            f"Telemetry config keys {unknown} are not consumed (known keys: "
            f"{sorted(TELEMETRY_DEFAULTS)}); check docs/OBSERVABILITY.md",
            stacklevel=2,
        )
        for k in unknown:
            section.pop(k)
    out = dict(TELEMETRY_DEFAULTS)
    out.update(section)
    env = env_flag("HYDRAGNN_TELEMETRY")
    if env is not None:
        out["enabled"] = env
    env_num = env_flag("HYDRAGNN_NUMERICS")
    if env_num is not None:
        out["numerics"] = env_num
    if not isinstance(out["numerics"], bool):
        raise ValueError(
            f"Telemetry.numerics must be true/false, got {out['numerics']!r}"
        )
    if int(out["interval_steps"]) < 1:
        raise ValueError(
            f"Telemetry.interval_steps must be >= 1, got "
            f"{out['interval_steps']!r}"
        )
    if int(out["profile_steps"]) < 1:
        raise ValueError(
            f"Telemetry.profile_steps must be >= 1, got "
            f"{out['profile_steps']!r}"
        )
    if out["http_port"] is not None and not (
        0 <= int(out["http_port"]) <= 65535
    ):
        raise ValueError(
            "Telemetry.http_port must be null (off), 0 (ephemeral), or a "
            f"port number <= 65535, got {out['http_port']!r}"
        )
    if not isinstance(out["http_host"], str) or not out["http_host"]:
        raise ValueError(
            "Telemetry.http_host must be a non-empty bind address, got "
            f"{out['http_host']!r}"
        )
    if not (0.0 <= float(out["trace_sample"]) <= 1.0):
        raise ValueError(
            "Telemetry.trace_sample must be a probability in [0, 1], got "
            f"{out['trace_sample']!r}"
        )
    if int(out["trace_interval_steps"]) < 1:
        raise ValueError(
            "Telemetry.trace_interval_steps must be >= 1, got "
            f"{out['trace_interval_steps']!r}"
        )
    env_fleet = env_flag("HYDRAGNN_FLEET")
    if env_fleet is not None:
        out["fleet"] = env_fleet
    if not isinstance(out["fleet"], bool):
        raise ValueError(
            f"Telemetry.fleet must be true/false, got {out['fleet']!r}"
        )
    if float(out["fleet_straggler_factor"]) <= 1.0:
        raise ValueError(
            "Telemetry.fleet_straggler_factor must be > 1 (it multiplies "
            f"the fleet median step time), got "
            f"{out['fleet_straggler_factor']!r}"
        )
    if int(out["fleet_max_step_lag"]) < 1:
        raise ValueError(
            "Telemetry.fleet_max_step_lag must be >= 1, got "
            f"{out['fleet_max_step_lag']!r}"
        )
    if float(out["fleet_stale_after_s"]) <= 0:
        raise ValueError(
            "Telemetry.fleet_stale_after_s must be > 0, got "
            f"{out['fleet_stale_after_s']!r}"
        )
    if out["fleet_collective_budget"] is not None and not (
        0.0 < float(out["fleet_collective_budget"]) <= 1.0
    ):
        raise ValueError(
            "Telemetry.fleet_collective_budget must be null (off) or a "
            f"fraction in (0, 1], got {out['fleet_collective_budget']!r}"
        )
    if int(out["fleet_sharding_audit_bytes"]) < 0:
        raise ValueError(
            "Telemetry.fleet_sharding_audit_bytes must be >= 0, got "
            f"{out['fleet_sharding_audit_bytes']!r}"
        )
    if out["fleet_collector"] is not None:
        from .fleet import _valid_collector_addr

        # ONE grammar with the HYDRAGNN_FLEET_COLLECTOR env path
        # (obs/fleet.py applies the same helper, warn-and-degrade there)
        if not _valid_collector_addr(str(out["fleet_collector"])):
            raise ValueError(
                "Telemetry.fleet_collector must be a 'host:port' address, "
                f"got {out['fleet_collector']!r}"
            )
    return out


_GIT_DESCRIBE: Optional[str] = None


def _git_describe() -> str:
    """``git describe --always --dirty`` of the repo this package runs
    from, cached; "unknown" outside a checkout (wheels, containers)."""
    global _GIT_DESCRIBE
    if _GIT_DESCRIBE is not None:
        return _GIT_DESCRIBE
    try:
        import subprocess

        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        # only trust git if the discovered repo IS this package's root: an
        # installed (non-checkout) copy nested under some other project's
        # checkout would otherwise stamp build-info with that repo's
        # describe — a confidently wrong process identity
        top = subprocess.run(
            ["git", "-C", root, "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=5,
        )
        if top.returncode != 0 or os.path.realpath(
            top.stdout.strip()
        ) != os.path.realpath(root):
            _GIT_DESCRIBE = "unknown"
            return _GIT_DESCRIBE
        out = subprocess.run(
            ["git", "describe", "--always", "--dirty"],
            cwd=root, capture_output=True, text=True, timeout=5,
        )
        _GIT_DESCRIBE = (
            out.stdout.strip() if out.returncode == 0 and out.stdout.strip()
            else "unknown"
        )
    except Exception:
        _GIT_DESCRIBE = "unknown"
    return _GIT_DESCRIBE


def publish_build_info() -> None:
    """Publish the ``hydragnn_build_info`` info-gauge (value 1; the facts
    ride the labels, Prometheus *_info convention): jax/jaxlib versions,
    backend, device count, git describe. Idempotent by REGISTRY state, not
    a module flag — a ``registry().reset()`` (second in-process run, tests)
    must not leave later scrapes/dumps permanently without the series (the
    per-process-baseline lesson the PR 5 sentinel report recorded). Every
    scrape and flight-recorder snapshot self-describes once any publisher
    (StepTelemetry, the endpoint, the recorder) has run."""
    have = registry().get("hydragnn_build_info")
    if have is not None and have.samples():
        return
    jax_v = jaxlib_v = backend = "unknown"
    devices = 0
    try:
        import jax

        jax_v = jax.__version__
        backend = jax.default_backend()
        devices = jax.device_count()
    except Exception:
        pass
    try:
        import jaxlib

        jaxlib_v = jaxlib.__version__
    except Exception:
        pass
    try:
        from .fleet import host_identity

        host_i, host_n = host_identity()
        registry().gauge(
            "hydragnn_build_info",
            "Build/runtime identity of this process (value is always 1; "
            "the facts are the labels)",
            labelnames=(
                "jax", "jaxlib", "backend", "devices", "git",
                "process_index", "process_count",
            ),
        ).set(
            1.0,
            jax=jax_v,
            jaxlib=jaxlib_v,
            backend=backend,
            devices=str(devices),
            git=_git_describe(),
            # fleet identity: every scrape self-identifies which host of
            # how many produced it (obs/fleet.py host_identity)
            process_index=str(host_i),
            process_count=str(host_n),
        )
    except Exception:
        pass


def host_memory_bytes() -> float:
    """Resident-set size of this process in bytes (stdlib-only: /proc on
    Linux, ru_maxrss as the portable fallback)."""
    try:
        with open("/proc/self/statm") as fh:
            rss_pages = int(fh.read().split()[1])
        return float(rss_pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        try:
            import resource

            rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return float(rss_kb) * 1024.0
        except Exception:
            return 0.0


class MetricsStream:
    """The versioned ``metrics.jsonl`` sink: one JSON object per line, every
    record stamped with the schema version and a wall-clock timestamp.
    Rank-0-gated like ``MetricsWriter`` — exactly one stream per run."""

    def __init__(self, run_dir: str, rank0: Optional[bool] = None,
                 fleet: bool = False):
        if rank0 is None:
            try:
                import jax

                rank0 = jax.process_index() == 0
            except Exception:
                rank0 = True
        # fleet identity: every record self-identifies its host, and a
        # non-zero host writing onto a shared filesystem gets its own
        # stream file (two processes appending one JSONL interleave
        # mid-line) — obs/fleet.py host_identity. With the fleet plane ON
        # the per-host stream overrides the historical rank-0 gate: the
        # whole point of the plane is per-host records, and the suffixed
        # filename makes the multi-writer case safe (the Tracer gets the
        # same override in train/loop.py)
        from .fleet import host_identity

        self._host, _ = host_identity()
        fname = (
            "metrics.jsonl" if self._host == 0
            else f"metrics-h{self._host}.jsonl"
        )
        if fleet and self._host > 0:
            rank0 = True
        self.path = os.path.join(run_dir, fname)
        self._fh = None
        self._flushed_at = 0.0
        # HPO trial labeling (hpo.py run_hpo exports HYDRAGNN_TRIAL_ID per
        # trial): every record of a worker's stream carries its trial id,
        # so a parent study can attribute per-trial signals after the fact
        trial = envflags.env_str("HYDRAGNN_TRIAL_ID")
        self._trial: Optional[Any] = None
        if trial is not None:
            try:
                self._trial = int(trial)
            except ValueError:
                self._trial = trial
        if rank0:
            os.makedirs(run_dir, exist_ok=True)
            self._fh = open(self.path, "a")
            # abnormal-exit guarantee: an unhandled exception (or a signal
            # handler exiting via sys.exit) still flushes the buffered tail
            # of the stream — without this a crash truncates the final
            # telemetry window (the 1 Hz flush limiter keeps it in memory)
            import atexit

            atexit.register(self._atexit_flush)

    def _atexit_flush(self) -> None:
        try:
            if self._fh is not None:
                self._fh.flush()
        except Exception:
            pass

    def write(self, kind: str, record: Dict[str, Any]) -> None:
        if self._fh is None:
            return
        line = {"v": SCHEMA_VERSION, "ts": round(time.time(), 3),
                "kind": kind, "host": self._host, **record}
        if self._trial is not None:
            line["trial"] = self._trial
        try:
            self._fh.write(json.dumps(line) + "\n")
            # flush ~1/s, not per record: the file flush is one of the two
            # syscalls that dominate the per-step telemetry bill (the <=2%
            # overhead budget of run-scripts/telemetry_smoke.py);
            # non-window records (epoch/run) are rare and tailed live
            now = time.monotonic()
            if kind != "step_window" or now - self._flushed_at >= 1.0:
                self._fh.flush()
                self._flushed_at = now
        except (OSError, ValueError) as e:
            # a full disk / vanished run dir must not kill the training run
            # (the plane's contract: observability never takes the owner
            # down) — drop the stream and keep going
            self._fh = None
            warnings.warn(
                f"metrics.jsonl stream failed ({e}); telemetry records are "
                "dropped for the rest of this run",
                RuntimeWarning,
                stacklevel=2,
            )

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        try:
            import atexit

            atexit.unregister(self._atexit_flush)
        except Exception:
            pass


class ProfileTrigger:
    """On-demand xprof capture: arm via a touch file or ``SIGUSR1``; the
    next flush starts ``jax.profiler`` for the following ``steps`` steps.

    The touch file (``<run_dir>/profile_trigger``) is polled at most once
    a second (a ``stat`` costs ~100us on network filesystems — per-window
    polling alone would blow the <=2% overhead budget) and consumed
    (unlinked) when the capture starts; the signal flag is checked every
    step (one attribute read, so SIGUSR1 reacts within a window). Captures
    land in step-stamped subdirectories of ``<run_dir>/profile_on_demand``
    so repeated triggers never clobber."""

    def __init__(self, run_dir: str, steps: int = 5,
                 install_signal: bool = True):
        self.trigger_path = os.path.join(run_dir, "profile_trigger")
        self.out_dir = os.path.join(run_dir, "profile_on_demand")
        self.steps = max(int(steps), 1)
        self.captures = 0
        self._signaled = False
        self._polled_at = 0.0
        self._active_until: Optional[int] = None
        self._prev_handler = None
        if install_signal:
            try:
                self._prev_handler = signal.signal(
                    signal.SIGUSR1, self._on_signal
                )
            except ValueError:
                pass  # not the main thread: touch-file trigger only

    def _on_signal(self, signum, frame) -> None:
        self._signaled = True  # async-signal-safe: only a flag

    def _consume_trigger(self) -> bool:
        if self._signaled:
            self._signaled = False
            return True
        now = time.monotonic()
        if now - self._polled_at < 1.0:
            return False
        self._polled_at = now
        if os.path.exists(self.trigger_path):
            try:
                os.unlink(self.trigger_path)
            except OSError:
                pass
            return True
        return False

    @property
    def active(self) -> bool:
        return self._active_until is not None

    def poll(self, global_step: int) -> None:
        """Flush-cadence check: start a capture if armed."""
        if self.active or not self._consume_trigger():
            return
        try:
            import jax

            out = os.path.join(self.out_dir, f"step{global_step}")
            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out, create_perfetto_trace=True)
        except Exception as e:  # an epoch-profile may already be tracing
            warnings.warn(
                f"on-demand profile trigger could not start a capture: {e}",
                RuntimeWarning,
                stacklevel=2,
            )
            return
        self._active_until = int(global_step) + self.steps

    def step(self, global_step: int) -> None:
        """Per-step check: stop the capture once its window is done."""
        if self._active_until is not None and global_step >= self._active_until:
            self._stop()

    def _stop(self) -> None:
        self._active_until = None
        try:
            import jax

            jax.effects_barrier()
            jax.profiler.stop_trace()
            self.captures += 1
        except Exception:
            pass

    def close(self) -> None:
        if self.active:
            self._stop()
        if self._prev_handler is not None:
            try:
                signal.signal(signal.SIGUSR1, self._prev_handler)
            except ValueError:
                pass
            self._prev_handler = None


# whether mask readback should batch both masks into one device_get round
# trip: True on accelerator backends (a remote-tunneled TPU pays per-call
# LATENCY, so one round trip beats two) and False on the CPU backend
# (np.asarray is a ~1us zero-copy view there, device_get ~7x slower).
# Resolved once, at the first non-numpy batch.
_BATCH_MASK_READBACK: Optional[bool] = None


def _mask_arrays(nm, em):
    global _BATCH_MASK_READBACK
    if isinstance(nm, np.ndarray):
        return nm, np.asarray(em)
    if _BATCH_MASK_READBACK is None:
        import jax

        _BATCH_MASK_READBACK = jax.default_backend() != "cpu"
    if _BATCH_MASK_READBACK:
        import jax

        return jax.device_get((nm, em))
    return np.asarray(nm), np.asarray(em)


def _batch_census(batch, real_graphs: Optional[int] = None):
    """(real, padded) counts per axis for a (possibly device-stacked)
    ``GraphBatch``. Masks are loader-produced leaves, so reading them never
    waits on device compute (the same contract the epoch loop relies on),
    and the per-shard pad spec is recovered from the trailing axes of a
    stacked batch. Device-resident masks read back per ``_mask_arrays``
    (one batched round trip on accelerators); the graph mask is only
    materialized when the loop did not already pass its count —
    padded counts and stacking come from shapes, which are free."""
    gshape = tuple(batch.graph_mask.shape)
    nm, em = _mask_arrays(batch.node_mask, batch.edge_mask)
    real = {
        "graphs": (
            int(np.asarray(batch.graph_mask).sum())
            if real_graphs is None
            else int(real_graphs)
        ),
        "nodes": int(nm.sum()),
        "edges": int(em.sum()),
    }
    padded = {"graphs": int(np.prod(gshape)), "nodes": int(nm.size),
              "edges": int(em.size)}
    if len(gshape) == 2:  # stacked [num_shards, ...]
        spec_key = (int(nm.shape[1]), int(em.shape[1]))
    else:
        spec_key = (int(nm.size), int(em.size))
    return real, padded, spec_key


class StepTelemetry:
    """Per-step instrumentation layer of the training loop.

    Construct via ``from_config`` (returns None when the ``Telemetry``
    section is absent/disabled — the loop then skips every call site);
    drive with ``on_step(batch, dt, real_graphs)`` from the epoch loop,
    ``on_epoch`` at epoch boundaries, ``absorb_counters`` wherever the
    run-level totals are already host-synced, and ``close`` in the run's
    ``finally``."""

    @staticmethod
    def from_config(
        config: Dict[str, Any],
        log_name: str,
        writer=None,
        log_path: str = "./logs",
    ) -> Optional["StepTelemetry"]:
        settings = resolve_telemetry(config)
        if not settings["enabled"]:
            return None
        return StepTelemetry(settings, log_name, writer=writer,
                             log_path=log_path)

    def __init__(self, settings: Dict[str, Any], log_name: str, writer=None,
                 log_path: str = "./logs"):
        self.settings = settings
        self.log_name = log_name
        self.run_dir = os.path.join(log_path, log_name)
        self.writer = writer
        self.interval = int(settings["interval_steps"])
        self.want_mfu = bool(settings["mfu"])
        self.global_step = 0
        self._flops_for: Optional[Callable[[Tuple[int, int]], Optional[float]]] = None
        self._flops_cache: Dict[Tuple[int, int], Optional[float]] = {}
        # comm accounting source (train/compile_plane.py comm_by_spec):
        # (per-shard padded nodes, edges) -> per-spec collective table
        self._comm_for: Optional[
            Callable[[Tuple[int, int]], Optional[Dict[str, Any]]]
        ] = None
        self._device_kind: Optional[str] = None
        self._mem_refreshed_at = 0.0
        self._numerics_meta: Optional[Dict[str, Any]] = None
        self._g_num: Dict[str, Any] = {}
        self._reset_window()
        publish_build_info()

        # -- sinks / registry ------------------------------------------------
        self.stream = (
            MetricsStream(self.run_dir, fleet=bool(settings.get("fleet")))
            if settings["jsonl"]
            else None
        )
        self.trigger = (
            ProfileTrigger(self.run_dir, steps=int(settings["profile_steps"]))
            if settings["profile_trigger"]
            else None
        )
        self.http = None
        if settings["http_port"] is not None:
            from .prometheus import start_endpoint

            self.http = start_endpoint(
                int(settings["http_port"]),
                ready_fn=lambda: True,
                health_fn=lambda: (True, "training"),
                label=f"telemetry[{log_name}]",
                host=str(settings["http_host"]),
            )
        # fleet plane (obs/fleet.py): rank-0 collector + per-host pusher;
        # None when Telemetry.fleet is off — every call site then pays one
        # `is not None` check, nothing else
        self.fleet = None
        if settings.get("fleet"):
            from .fleet import FleetPlane

            self.fleet = FleetPlane.from_settings(settings, self.run_dir)
        reg = registry()
        self._h_step = reg.histogram(
            "hydragnn_step_time_seconds",
            "Optimizer-step wall time (host dispatch-to-dispatch)",
            labelnames=("phase",),
        )
        self._g_rate = reg.gauge(
            "hydragnn_goodput_per_second",
            "Real (mask-counted) items processed per second over the last "
            "telemetry window",
            labelnames=("axis",),
        )
        self._g_waste = reg.gauge(
            "hydragnn_padding_waste_fraction",
            "1 - real/padded slots over the last telemetry window",
            labelnames=("axis",),
        )
        self._g_waste_bucket = reg.gauge(
            "hydragnn_padding_waste_bucket_fraction",
            "Node-slot padding waste per pad-bucket specialization",
            labelnames=("bucket",),
        )
        self._g_mfu = reg.gauge(
            "hydragnn_mfu_estimate",
            "XLA-counted FLOPs / elapsed / chip peak over the last window",
        )
        self._g_devmem = reg.gauge(
            "hydragnn_device_memory_peak_bytes",
            "Per-device peak bytes in use",
            labelnames=("device",),
        )
        self._g_hostmem = reg.gauge(
            "hydragnn_host_memory_rss_bytes", "Host process resident set size"
        )
        self._g_epoch = reg.gauge(
            "hydragnn_epoch", "Last completed training epoch"
        )
        self._g_loss = reg.gauge(
            "hydragnn_loss", "Per-epoch loss", labelnames=("split",)
        )
        self._g_lr = reg.gauge(
            "hydragnn_learning_rate", "Current injected learning rate"
        )
        self._c_guard = reg.counter(
            "hydragnn_guard_skipped_steps_total",
            "Non-finite steps skipped by the in-graph guard",
        )
        self._c_data_skip = reg.counter(
            "hydragnn_data_skipped_samples_total",
            "Samples dropped by the data-plane validator",
            labelnames=("reason",),
        )
        self._c_retrace = reg.counter(
            "hydragnn_retrace_violations_total",
            "Trace-sentinel violations (silent recompiles) this process",
        )
        self._c_cache_hits = reg.counter(
            "hydragnn_compile_cache_hits_total",
            "Persistent compilation cache hits this process",
        )
        self._c_cache_misses = reg.counter(
            "hydragnn_compile_cache_misses_total",
            "Persistent compilation cache misses this process",
        )
        # materialize the always-expected series so a scrape is schema-
        # complete from the first window (counters appear at 0, not never)
        self._c_guard.set_total(0)
        self._c_retrace.set_total(0)
        self._c_cache_hits.set_total(0)
        self._c_cache_misses.set_total(0)

    def _reset_window(self) -> None:
        self._w_steps = 0
        self._w_dt = 0.0
        self._w_real = {"graphs": 0, "nodes": 0, "edges": 0}
        self._w_padded = {"graphs": 0, "nodes": 0, "edges": 0}
        self._w_buckets: Dict[Tuple[int, int], Dict[str, float]] = {}
        # device-resident numerics stacks ([P,5] act, [G,5] grad per step):
        # held un-synced until flush — by then the producing steps have
        # retired, so the readback copies ready buffers instead of stalling
        # the async dispatch pipeline
        self._w_numerics: List[Tuple[Any, Any]] = []

    # -- wiring --------------------------------------------------------------

    def attach_flops(
        self, flops_for: Callable[[Tuple[int, int]], Optional[float]]
    ) -> None:
        """Install the FLOPs source: (per-shard padded nodes, edges) ->
        XLA-counted FLOPs of that train-step specialization, or None while
        unknown (the compile plane fills its table as warm-up progresses)."""
        self._flops_for = flops_for

    def attach_numerics(self, meta: Dict[str, Any]) -> None:
        """Install the numerics name tables (the step builder's mutable
        meta cell — act_names/grad_names are written at trace time, so they
        are populated by the time the first window flushes)."""
        self._numerics_meta = meta

    def attach_comm(
        self,
        comm_for: Callable[[Tuple[int, int]], Optional[Dict[str, Any]]],
    ) -> None:
        """Install the comm-accounting source: (per-shard padded nodes,
        edges) -> that train-step specialization's collective table
        (train/compile_plane.py ``train_comm_for``), or None while warm-up
        has not walked its HLO yet. The flush windows then carry the
        per-step collective bytes + compute-vs-comm decomposition."""
        self._comm_for = comm_for

    def _flops_of(self, key: Tuple[int, int]) -> Optional[float]:
        got = self._flops_cache.get(key)
        if got is None and self._flops_for is not None:
            got = self._flops_for(key)
            if got is not None:
                self._flops_cache[key] = float(got)
        return got

    # -- per-step path -------------------------------------------------------

    def on_step(self, batch, dt: float, real_graphs: Optional[int] = None,
                numerics: Optional[Dict[str, Any]] = None) -> None:
        """Record one optimizer step: ``dt`` is the host wall time of the
        dispatch (see module docstring for why that converges to device
        step time), ``real_graphs`` the already-computed mask count the
        loop has anyway, ``numerics`` the step's in-graph stat bundle
        (obs/numerics.py) when ``Telemetry.numerics`` is on — held as
        device arrays until flush."""
        self.global_step += 1
        if numerics is not None:
            self._w_numerics.append(
                (numerics.get("act"), numerics.get("grad"))
            )
        self._h_step.observe(dt, phase="train")
        real, padded, key = _batch_census(batch, real_graphs)
        self._w_steps += 1
        self._w_dt += float(dt)
        for axis in ("graphs", "nodes", "edges"):
            self._w_real[axis] += real[axis]
            self._w_padded[axis] += padded[axis]
        b = self._w_buckets.setdefault(
            key, {"steps": 0, "real_nodes": 0, "padded_nodes": 0, "dt": 0.0}
        )
        b["steps"] += 1
        b["real_nodes"] += real["nodes"]
        b["padded_nodes"] += padded["nodes"]
        b["dt"] += float(dt)
        if self.trigger is not None:
            self.trigger.step(self.global_step)
        if self._w_steps >= self.interval:
            self.flush()

    def flush(self) -> None:
        """Close the current window: compute rates/waste/MFU, update the
        registry, emit one ``step_window`` record, poll the profile
        trigger, refresh the memory gauges."""
        if self._w_steps == 0:
            if self.trigger is not None:
                self.trigger.poll(self.global_step)
            return
        dt = max(self._w_dt, 1e-9)
        rates = {a: self._w_real[a] / dt for a in ("graphs", "nodes", "edges")}
        waste = {
            a: 1.0 - self._w_real[a] / max(self._w_padded[a], 1)
            for a in ("graphs", "nodes", "edges")
        }
        for a in ("graphs", "nodes", "edges"):
            self._g_rate.set(rates[a], axis=a)
            self._g_waste.set(waste[a], axis=a)
        buckets = {}
        flops = 0.0
        flops_known = self.want_mfu and self._flops_for is not None
        for key, b in self._w_buckets.items():
            label = f"{key[0]}n/{key[1]}e"
            bucket_waste = 1.0 - b["real_nodes"] / max(b["padded_nodes"], 1)
            self._g_waste_bucket.set(bucket_waste, bucket=label)
            buckets[label] = {
                "steps": b["steps"],
                "padding_waste": round(bucket_waste, 4),
            }
            if flops_known:
                f = self._flops_of(key)
                if f is None:
                    flops_known = False
                else:
                    flops += f * b["steps"]
        mfu = None
        if flops_known and flops > 0:
            mfu = mfu_estimate(flops, dt, self._device_kind_cached())
            self._g_mfu.set(mfu)
        # comm accounting (compile-plane HLO walk): window-weighted
        # collective bytes per step + the compute-vs-comm decomposition —
        # None until every visited spec's table is harvested
        comm_bytes = comm_frac = None
        if self._comm_for is not None:
            total_bytes = 0.0
            frac_weighted = 0.0
            steps_seen = 0
            known = frac_known = True
            for key, b in self._w_buckets.items():
                c = self._comm_for(key)
                if c is None:
                    known = False
                    break
                total_bytes += float(c.get("bytes_total", 0.0)) * b["steps"]
                frac = c.get("comm_fraction_est")
                if frac is None:
                    # a spec whose FLOPs never harvested has bytes but no
                    # decomposition — publishing a fraction diluted by
                    # zeros would underestimate (and could mask a
                    # fleet_collective_budget breach), so the whole
                    # window's fraction stays unknown instead
                    frac_known = False
                else:
                    frac_weighted += float(frac) * b["steps"]
                steps_seen += b["steps"]
            if known and steps_seen:
                comm_bytes = total_bytes / steps_seen
                if frac_known:
                    comm_frac = frac_weighted / steps_seen
        num_rec = None
        if self._w_numerics and self._numerics_meta is not None:
            try:  # observability never takes the owner down
                num_rec = self._flush_numerics()
            except Exception as e:
                warnings.warn(
                    f"numerics window flush failed ({type(e).__name__}: "
                    f"{e}); this window's layer statistics are dropped",
                    RuntimeWarning,
                    stacklevel=2,
                )
        self._update_memory_gauges()
        if self.stream is not None:
            self.stream.write(
                "step_window",
                {
                    "step": self.global_step,
                    "steps": self._w_steps,
                    "step_time_ms": round(dt / self._w_steps * 1e3, 3),
                    "graphs_per_sec": round(rates["graphs"], 2),
                    "nodes_per_sec": round(rates["nodes"], 1),
                    "edges_per_sec": round(rates["edges"], 1),
                    "padding_waste": round(waste["nodes"], 4),
                    "padding_waste_graphs": round(waste["graphs"], 4),
                    "padding_waste_edges": round(waste["edges"], 4),
                    # 9 decimals: a CPU-backend MFU is ~1e-7 against the
                    # TPU peak table and must not round to a dead 0.0
                    "mfu_est": round(mfu, 9) if mfu is not None else None,
                    # per-device collective bytes each step moves + the
                    # estimated fraction of step time inside collectives
                    # (compile-plane comm accounting; None until harvested)
                    "comm_bytes_per_step": (
                        round(comm_bytes, 1) if comm_bytes is not None
                        else None
                    ),
                    "comm_fraction_est": (
                        round(comm_frac, 6) if comm_frac is not None
                        else None
                    ),
                    "buckets": buckets,
                },
            )
            if num_rec is not None:
                self.stream.write(
                    "numerics", {"step": self.global_step, **num_rec}
                )
        if self.writer is not None:
            self.writer.add_scalars(
                {
                    "telemetry/step_time_ms": dt / self._w_steps * 1e3,
                    "telemetry/graphs_per_sec": rates["graphs"],
                    "telemetry/padding_waste": waste["nodes"],
                    **(
                        {"telemetry/mfu_est": mfu} if mfu is not None else {}
                    ),
                },
                self.global_step,
            )
        if self.trigger is not None:
            self.trigger.poll(self.global_step)
        if self.fleet is not None:
            # the flush IS the heartbeat: registry snapshot + step index +
            # window step time (+ collective fraction) push to the rank-0
            # collector on the fleet plane's background thread
            self.fleet.on_window(
                self.global_step,
                step_time_s=dt / max(self._w_steps, 1),
                comm_fraction_est=comm_frac,
            )
        self._reset_window()

    def _numerics_gauges(self):
        if not self._g_num:
            reg = registry()
            self._g_num = {
                "max_abs": reg.gauge(
                    "hydragnn_numerics_max_abs",
                    "Per-tensor max |x| over the last telemetry window "
                    "(obs/numerics.py probes)",
                    labelnames=("kind", "tensor"),
                ),
                "rms": reg.gauge(
                    "hydragnn_numerics_rms",
                    "Per-tensor rms over the last telemetry window",
                    labelnames=("kind", "tensor"),
                ),
                "underflow": reg.gauge(
                    "hydragnn_numerics_bf16_underflow_fraction",
                    "Fraction of (real) elements below the smallest normal "
                    "bf16 magnitude over the last window",
                    labelnames=("kind", "tensor"),
                ),
                "nonfinite": reg.counter(
                    "hydragnn_numerics_nonfinite_total",
                    "Non-finite elements seen per tensor (windows "
                    "accumulate)",
                    labelnames=("kind", "tensor"),
                ),
            }
        return self._g_num

    @staticmethod
    def _combine_numerics(stacks):
        """Merge per-step [P,5] stacks over the window: max-abs by max,
        the summed moments by sum. Returns a host [P,5] array or None."""
        arrs = [np.asarray(s) for s in stacks if s is not None and s.size]
        if not arrs:
            return None
        stacked = np.stack(arrs)  # [W, P, 5]
        out = np.empty(stacked.shape[1:], np.float64)
        out[:, 0] = stacked[:, :, 0].max(axis=0)
        out[:, 1:] = stacked[:, :, 1:].sum(axis=0)
        return out

    @staticmethod
    def _json_stat(v: float):
        # metrics.jsonl stays strict-JSON parseable: non-finite stats are
        # the SIGNAL here, so encode them as strings instead of bare NaN
        return float(v) if np.isfinite(v) else str(v)

    def _flush_numerics(self) -> Optional[Dict[str, Any]]:
        """Aggregate the window's numerics stacks, publish the per-tensor
        gauges, and return the metrics.jsonl ``numerics`` record body."""
        from .numerics import finalize_stats

        stacks, self._w_numerics = self._w_numerics, []
        acts = self._combine_numerics([a for a, _ in stacks])
        grads = self._combine_numerics([g for _, g in stacks])
        meta = self._numerics_meta or {}
        gauges = self._numerics_gauges()
        record: Dict[str, Any] = {}
        for kind, names, table in (
            ("activation", meta.get("act_names"), acts),
            ("gradient", meta.get("grad_names"), grads),
        ):
            if table is None:
                continue
            section: Dict[str, Any] = {}
            for i in range(table.shape[0]):
                name = (
                    names[i] if names and i < len(names) else f"{kind}{i}"
                )
                st = finalize_stats(table[i])
                gauges["max_abs"].set(st["max_abs"], kind=kind, tensor=name)
                gauges["rms"].set(st["rms"], kind=kind, tensor=name)
                gauges["underflow"].set(
                    st["bf16_underflow"], kind=kind, tensor=name
                )
                if st["nonfinite"] > 0:
                    gauges["nonfinite"].inc(
                        st["nonfinite"], kind=kind, tensor=name
                    )
                section[name] = {
                    "max_abs": self._json_stat(st["max_abs"]),
                    "rms": self._json_stat(st["rms"]),
                    "nonfinite": int(st["nonfinite"]),
                    "bf16_underflow": round(st["bf16_underflow"], 6),
                }
            record["activations" if kind == "activation" else "gradients"] = (
                section
            )
        return record or None

    def _device_kind_cached(self) -> str:
        if self._device_kind is None:
            try:
                import jax

                self._device_kind = jax.devices()[0].device_kind
            except Exception:
                self._device_kind = "unknown"
        return self._device_kind

    def _update_memory_gauges(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._mem_refreshed_at < _MEMORY_REFRESH_S:
            return
        self._mem_refreshed_at = now
        try:
            from ..utils.profile import peak_memory_stats

            for dev, peak in peak_memory_stats().items():
                self._g_devmem.set(peak, device=dev)
        except Exception:
            pass
        self._g_hostmem.set(host_memory_bytes())

    # -- epoch / run path ----------------------------------------------------

    def on_epoch(self, epoch: int, scalars: Dict[str, float],
                 filler: bool = False) -> None:
        """Epoch-boundary record. ``filler=True`` marks rows whose val/test
        entries are carried forward (mid-epoch preemption stop) rather than
        measured — consumers comparing validation curves (HPO early
        stopping) must skip them."""
        self.flush()
        self._g_epoch.set(int(epoch))
        for split, v in scalars.items():
            if split == "lr":
                self._g_lr.set(float(v))
            else:
                self._g_loss.set(float(v), split=split)
        if self.stream is not None:
            self.stream.write(
                "epoch",
                {
                    "epoch": int(epoch),
                    **{k: float(v) for k, v in scalars.items()},
                    "filler": bool(filler),
                },
            )

    def absorb_counters(
        self,
        guard_skipped: Optional[int] = None,
        data_skipped: Optional[Dict[str, int]] = None,
        retrace_violations: Optional[int] = None,
        compile_metrics: Optional[Dict[str, float]] = None,
    ) -> None:
        """Absorb externally maintained monotonic totals (idempotent:
        counters max-merge). Call wherever the owning subsystem is already
        host-synced — the epoch boundary, run end. ``guard_skipped`` must
        be a monotonic EVENT count: the raw TrainState counter can go DOWN
        on a rollback restore, so the loop accumulates positive deltas
        before absorbing (train/loop.py guard_events)."""
        if guard_skipped is not None:
            self._c_guard.set_total(int(guard_skipped))
        for reason, count in (data_skipped or {}).items():
            self._c_data_skip.set_total(int(count), reason=reason)
        if retrace_violations is not None:
            self._c_retrace.set_total(int(retrace_violations))
        if compile_metrics:
            self._c_cache_hits.set_total(int(compile_metrics["cache_hits"]))
            self._c_cache_misses.set_total(
                int(compile_metrics["cache_misses"])
            )

    def run_record(self, info: Dict[str, Any]) -> None:
        if self.stream is not None:
            self.stream.write("run", dict(info))

    def compile_record(self, rep: Dict[str, Any]) -> None:
        """Persist the compile plane's end-of-run report as a
        ``compile_report`` record (obs/schema.py) — the run doctor's
        source for HBM/comm/cache/retrace verdicts; until r14 this
        report was a stderr line only."""
        if self.stream is None:
            return
        body = {
            "mode": str(rep.get("mode", "off")),
            "precompiled": int(rep.get("precompiled") or 0),
            "specializations": int(rep.get("specializations") or 0),
            "cache_hits": int(rep.get("cache_hits") or 0),
            "cache_misses": int(rep.get("cache_misses") or 0),
            "violations": int(rep.get("violations") or 0),
            "time_to_first_step": rep.get("time_to_first_step"),
            "hbm_by_spec": dict(rep.get("hbm_by_spec") or {}),
            "hbm_peak_bytes": rep.get("hbm_peak_bytes"),
            "comm_by_spec": dict(rep.get("comm_by_spec") or {}),
            "comm_bytes_peak": rep.get("comm_bytes_peak"),
            "device_bytes_limit": rep.get("device_bytes_limit"),
            # JSON-safe extras (warmup errors may be exception objects)
            "warmup_errors": [str(e) for e in rep.get("warmup_errors") or []],
            "remat_policy": rep.get("remat_policy"),
        }
        self.stream.write("compile_report", body)

    @property
    def endpoint_port(self) -> Optional[int]:
        return self.http.port if self.http is not None else None

    def close(self) -> None:
        self.flush()
        if self.fleet is not None:
            # final synchronous push: the collector sees this host's
            # terminal step, and this host applies any last broadcast
            self.fleet.close(final_step=self.global_step)
            self.fleet = None
        if self.trigger is not None:
            self.trigger.close()
        if self.http is not None:
            self.http.close()
            self.http = None
        if self.stream is not None:
            self.stream.close()
