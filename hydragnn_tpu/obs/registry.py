"""Process-wide metrics registry — the single publication point of the
telemetry plane (docs/OBSERVABILITY.md).

Six PRs of perf/robustness/serving work each grew their own signal surface:
grep-able stdout report lines (compile plane), ``GraphServer.stats()``
dicts, epoch-boundary tallies (validator, guard), and several unrelated
JSONL formats. This registry absorbs them all into one typed, labeled
namespace that every sink (the versioned ``metrics.jsonl`` stream, the
TensorBoard writer, the Prometheus endpoint — obs/telemetry.py,
obs/prometheus.py) renders from.

Design points:

- **stdlib-only and lock-cheap**: publishing is a dict write under one
  process lock; subsystems publish unconditionally (the registry is the
  plane), sinks are opt-in (``Telemetry`` config / ``Serving.http_port``).
- **Prometheus-shaped**: three instrument types (counter / gauge /
  histogram with cumulative buckets), label sets as frozen key-value
  tuples, metric names validated against the exposition grammar at
  registration so a typo fails at wiring time, not scrape time.
- **absorbing counters**: much of this repo's accounting already exists as
  monotonic totals maintained elsewhere (guard ``skipped_steps`` rides the
  TrainState, the validator keeps per-reason counts, jax.monitoring feeds
  the compile-cache tallies). ``Counter.set_total`` publishes such an
  external total without double counting — it only ever moves the sample
  up (max-merge), so absorption at every epoch boundary is idempotent.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# label values as a canonical, hashable key
LabelKey = Tuple[Tuple[str, str], ...]

# default histogram buckets: latency-shaped, sub-ms to a wedged minute
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _label_key(labelnames: Sequence[str], labels: Dict[str, object]) -> LabelKey:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared labelnames "
            f"{sorted(labelnames)}"
        )
    return tuple((n, str(labels[n])) for n in labelnames)


class _Metric:
    """Shared bookkeeping of one named instrument (any type)."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock

    def samples(self) -> List[Tuple[str, LabelKey, float]]:
        """[(suffix, labels, value)] — suffix is "" for scalar instruments,
        "_bucket"/"_sum"/"_count" (+ an extra ``le`` label) for histograms."""
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic total. ``inc`` adds; ``set_total`` max-merges an externally
    maintained monotonic total (idempotent absorption)."""

    kind = "counter"

    def __init__(self, *args):
        super().__init__(*args)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def set_total(self, total: float, **labels) -> None:
        """Publish an external monotonic total: the sample only moves up,
        so absorbing the same total twice is a no-op."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = max(self._values.get(key, 0.0), float(total))

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            return [("", k, v) for k, v in self._values.items()]


class Gauge(_Metric):
    """Point-in-time value (queue depth, padding waste, MFU estimate)."""

    kind = "gauge"

    def __init__(self, *args):
        super().__init__(*args)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values[key] = float(value)

    def set_default(self, value: float, **labels) -> None:
        """Materialize the series at ``value`` only if it has no sample yet
        — constructors use this so a second publisher instance in the same
        process cannot clobber a live one's state just by existing."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values.setdefault(key, float(value))

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, math.nan)

    def remove(self, **labels) -> None:
        """Drop one label-set's series entirely (the fleet collector
        retires aggregates whose only contributors went stale — a frozen
        last value scraping forever is indistinguishable from a live
        reading). No-op when the series never existed."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._values.pop(key, None)

    def samples(self):
        with self._lock:
            return [("", k, v) for k, v in self._values.items()]


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics): per-label-set
    bucket counts, observation sum, and count. p50/p99 come out of the
    bucket CDF on the scrape side."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = bs
        # per label-set: [counts per finite bucket] + overflow, sum, count
        self._data: Dict[LabelKey, Tuple[List[int], List[float]]] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(self.labelnames, labels)
        v = float(value)
        with self._lock:
            counts, agg = self._data.setdefault(
                key, ([0] * (len(self.buckets) + 1), [0.0, 0.0])
            )
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            agg[0] += v
            agg[1] += 1.0

    def snapshot(self, **labels) -> Dict[str, float]:
        """{count, sum} plus cumulative counts keyed by upper bound."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            counts, agg = self._data.get(
                key, ([0] * (len(self.buckets) + 1), [0.0, 0.0])
            )
            out: Dict[str, float] = {"sum": agg[0], "count": agg[1]}
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                out[str(b)] = float(cum)
            out["+Inf"] = float(cum + counts[-1])
            return out

    def samples(self):
        out: List[Tuple[str, LabelKey, float]] = []
        with self._lock:
            for key, (counts, agg) in self._data.items():
                cum = 0
                for b, c in zip(self.buckets, counts):
                    cum += c
                    out.append(("_bucket", key + (("le", repr(float(b))),),
                                float(cum)))
                out.append(
                    ("_bucket", key + (("le", "+Inf"),),
                     float(cum + counts[-1]))
                )
                out.append(("_sum", key, agg[0]))
                out.append(("_count", key, agg[1]))
        return out


class MetricsRegistry:
    """Named instrument table. ``counter``/``gauge``/``histogram`` are
    get-or-create: re-declaring an existing name returns the existing
    instrument (so publishers in different modules can declare locally),
    but a type or label mismatch fails loudly — two subsystems silently
    disagreeing about a metric's shape is a catalog bug."""

    def __init__(self):
        # RLock, not Lock: publishers run from signal handlers too (the
        # serve drain hook flips the ready gauge) — a handler interrupting
        # its own thread mid-publish must be able to re-acquire. Every
        # guarded mutation is a single dict store/add, so re-entry cannot
        # observe torn state.
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> _Metric:
        with self._lock:
            have = self._metrics.get(name)
            if have is not None:
                if type(have) is not cls or have.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{have.kind}{list(have.labelnames)}; cannot "
                        f"re-declare as {cls.kind}{list(labelnames)}"
                    )
                want_buckets = kw.get("buckets")
                if want_buckets is not None and tuple(
                    sorted(float(b) for b in want_buckets)
                ) != have.buckets:
                    # same loud-mismatch contract as type/labels: bucket
                    # bounds silently inherited from an earlier declaration
                    # would make scrape-side p50/p99 lie about what the
                    # publisher chose
                    raise ValueError(
                        f"histogram {name!r} already registered with buckets "
                        f"{have.buckets}; cannot re-declare with "
                        f"{tuple(want_buckets)}"
                    )
                return have
            m = cls(name, help, tuple(labelnames), self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Drop every instrument (tests; a long-lived process keeps them)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every subsystem publishes into."""
    return _REGISTRY
