"""Public convenience API: ``run_training(config)`` / ``run_prediction(config)``.

Mirrors the reference's two-call surface (hydragnn/run_training.py:48-63,
hydragnn/run_prediction.py:34-49): accepts a config file path or dict, loads
and splits data, completes the config from it, builds the model, trains, and
checkpoints. The DDP/DeepSpeed wrapping steps of the reference are replaced by
mesh sharding (hydragnn_tpu/parallel) applied inside the jitted step.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, List, Optional, Tuple

from .config import (
    get_log_name_config,
    load_config,
    save_config,
    update_config,
    voi_from_config,
)
from .data.graph import Graph, PadSpec, SpecLadder
from .data.pipeline import (
    GraphLoader,
    MinMax,
    extract_variables,
    select_input_columns,
    split_dataset,
)
from .data.synthetic import deterministic_graph_dataset
from .data.transforms import apply_dataset_transforms, wants_transforms
from .models.create import create_model, init_model
from .train.checkpoint import (
    clear_loader_state,
    load_existing_model,
    load_loader_state,
    load_mixture_state,
    save_loader_state,
    save_mixture_state,
    save_model,
)
from .train.loop import test_model, train_validate_test
from .train.optimizer import make_optimizer
from .train.state import TrainState
from .utils import envflags


def _localize_loader(loader: GraphLoader) -> GraphLoader:
    """Unstacked single-host view of a (possibly device-stacked) loader —
    prediction/visualization run per host with the plain jitted eval step,
    which expects batches without the leading device axis."""
    if loader.num_shards == 1:
        return loader
    return GraphLoader(
        loader.graphs,
        loader.batch_size,
        shuffle=False,
        host_count=loader.host_count,
        host_index=loader.host_index,
        # the Pallas sorted-segment route is baked into the model when
        # use_sorted_aggregation is on — the localized loader must keep
        # feeding receiver-sorted batches or its sums are unspecified
        sort_edges=loader.sort_edges,
    )


def _load_raw_dataset(config: Dict[str, Any]) -> List[Graph]:
    """Dataset from config. Formats: 'synthetic' (deterministic BCC fixture,
    the analog of the reference's unit_test format) and 'pickle'
    (reference: dataset_loading_and_splitting, load_data.py:206-222)."""
    ds = config.get("Dataset", {})
    fmt = ds.get("format", "synthetic")
    if fmt in ("synthetic", "unit_test"):
        opts = ds.get("synthetic", {})
        return deterministic_graph_dataset(
            number_configurations=opts.get("number_configurations", 300),
            linear_only=opts.get("linear_only", False),
            radius=config["NeuralNetwork"]["Architecture"].get("radius", 2.0) or 2.0,
            max_neighbours=config["NeuralNetwork"]["Architecture"].get("max_neighbours")
            or 100,
            seed=opts.get("seed", 97),
        )
    if fmt == "lennard_jones":
        from .data.synthetic import lennard_jones_dataset

        opts = dict(ds.get("lennard_jones", {}))
        arch = config["NeuralNetwork"]["Architecture"]
        opts.setdefault("radius", arch.get("radius", 2.5) or 2.5)
        if arch.get("max_neighbours"):
            opts.setdefault("max_neighbours", arch["max_neighbours"])
        return lennard_jones_dataset(**opts)
    if fmt == "pickle":
        from .data.datasets import SimplePickleDataset

        return list(SimplePickleDataset(ds["path"]["total"], ds["name"]))
    if fmt == "columnar":
        from .data.columnar import ColumnarDataset

        # samples are materialized as host Graphs for the split/normalize
        # pipeline; mmap/shmem modes bound the *raw array* residency during
        # the read, not the materialized working set
        return list(
            ColumnarDataset(ds["path"]["total"], mode=ds.get("mode", "mmap"))
        )
    if fmt in ("LSMS", "XYZ", "CFG"):
        from .data.raw import finalize_graphs, load_raw_dataset

        arch = config["NeuralNetwork"]["Architecture"]
        kwargs = {}
        if fmt == "LSMS":
            nf = ds.get("node_features", {})
            gf = ds.get("graph_features", {})
            if "column_index" in nf:
                kwargs["node_feature_cols"] = nf["column_index"]
                kwargs["node_feature_dims"] = nf["dim"]
            if "column_index" in gf:
                kwargs["graph_feature_cols"] = gf["column_index"]
                kwargs["graph_feature_dims"] = gf["dim"]
            kwargs["charge_density_correction"] = ds.get(
                "charge_density_correction", False
            )
        # warn_skip/quarantine extend to the file level: a truncated or
        # garbled raw dump drops that file (counted + warned) instead of
        # killing the run; 'error' keeps the historical fail-fast parse
        kwargs["on_error"] = (
            "raise"
            if ds.get("bad_sample_policy", "warn_skip") == "error"
            else "skip"
        )
        raw = load_raw_dataset(ds["path"]["total"], fmt, **kwargs)
        return finalize_graphs(
            raw,
            radius=arch.get("radius", 5.0) or 5.0,
            max_neighbours=arch.get("max_neighbours"),
            periodic=arch.get("periodic_boundary_conditions", False),
        )
    raise ValueError(f"unknown Dataset.format {fmt!r}")


def _zero_stage(training: Dict[str, Any]) -> int:
    """ZeRO stage from the Optimizer block (reference: DeepSpeed ds_config
    zero stage, run_training.py:136-149). ``use_zero_redundancy`` alone
    means stage 1."""
    opt = training.get("Optimizer", {})
    use_zero = opt.get("use_zero_redundancy", False)
    return int(opt.get("zero_stage", 1 if use_zero else 0))


def _wants_zero2_mesh(training: Dict[str, Any]) -> bool:
    """Whether a single-host multi-device run must take the mesh step for
    ZeRO-2 (the gradient constraint lives inside the mesh step). ONE
    predicate shared by prepare_data's loader gate and run_training's
    step selection — they must agree or the mesh step sees unstacked
    batches."""
    import jax

    if _zero_stage(training) < 2:
        return False
    if bool(training.get("branch_parallel", False)):
        # no silent downgrade: the branch-parallel step has no ZeRO path
        raise ValueError(
            "Optimizer.zero_stage >= 2 is not supported together with "
            "Training.branch_parallel (the branch-parallel step shards "
            "decoders, not gradients/moments); drop one of the two"
        )
    return jax.process_count() == 1 and jax.local_device_count() > 1


def resolve_parallel(config: Dict[str, Any]):
    """Resolve the run's sharding rule table (parallel/rules.py) — the ONE
    placement decision every entry point shares (train / predict / serve,
    all via prepare_data, and run_training's step selection).

    ``Parallel.rules`` (preset name or inline table) wins; otherwise the
    table derives from the legacy ``Training`` keys. Validation is EAGER
    (bad regex / unknown axis / preset-vs-flag conflicts raise here, never
    from inside a trace), the resolved table is recorded under
    ``Parallel.resolved_rules`` so the saved run config replays the
    identical placement on restore, and the legacy gate keys are
    normalized to match the table so prepare_data's loader routing and
    run_training's step selection can never disagree:

    - a routed (branch/mp) table sets ``Training.branch_parallel``;
    - a non-routed table with grads/params/opt_state rules raises
      ``Optimizer.zero_stage`` to the implied stage (never lowers it).

    Idempotent — safe to call from prepare_data AND run_training."""
    from .parallel import rules as parallel_rules

    table = parallel_rules.resolve(config)
    section = config.setdefault("Parallel", {})
    section["resolved_rules"] = table.to_config()
    training = config.setdefault("NeuralNetwork", {}).setdefault(
        "Training", {}
    )
    if table.routed:
        training["branch_parallel"] = True
    else:
        implied = (
            3
            if table.shards("params")
            else 2
            if table.shards("grads")
            else 1
            if table.shards("opt_state")
            else 0
        )
        if implied > _zero_stage(training):
            training.setdefault("Optimizer", {})["zero_stage"] = implied
    return table


def _make_validator(config: Dict[str, Any]):
    """Run-level SampleValidator from ``Dataset.bad_sample_policy``
    (docs/ROBUSTNESS.md "Data plane"): one instance spans ingest filtering
    and every loader, so its per-reason tally is the run's complete
    skipped-sample record. Quarantine manifests land in the run dir."""
    from .data.validate import SampleValidator

    policy = str(
        config.get("Dataset", {}).get("bad_sample_policy", "warn_skip")
    )
    quarantine_dir = None
    if policy == "quarantine":
        quarantine_dir = os.path.join(
            "./logs", get_log_name_config(config), "quarantine"
        )
    return SampleValidator(policy, quarantine_dir=quarantine_dir)


def prepare_data(
    config: Dict[str, Any], datasets: Optional[Tuple[List[Graph], ...]] = None
):
    """Load -> normalize -> select variables -> split -> loaders; returns
    (completed config, loaders, minmax).

    Every sample passes the data-plane validation gate (data/validate.py)
    BEFORE normalization/splitting — one NaN feature reaching
    ``MinMax.fit`` would NaN the normalization of the whole dataset, so
    dirty samples are dropped (or raised on, per
    ``Dataset.bad_sample_policy``) at the door; the validator rides on the
    returned loaders so the epoch loop can log the tally."""
    # resolve + record the sharding rule table FIRST: it validates the
    # Parallel section eagerly and normalizes the Training gate keys the
    # loader-routing decisions below read (resolve_parallel)
    resolve_parallel(config)
    validator = _make_validator(config)
    from .utils import faultinject

    if datasets is None:
        raw = _load_raw_dataset(config)
        ds_cfg = config.get("Dataset", {})
        if wants_transforms(ds_cfg):
            # load-time geometric transforms (reference:
            # serialized_dataset_loader.py:130-180). Rotation is shift/cell
            # aware so applying it after edge construction is exact.
            (raw,) = apply_dataset_transforms(ds_cfg, raw)
        # chaos hook (exact no-op unarmed): NaN-poison armed sample indices
        # so the validation gate below is exercised end-to-end with a skip
        # tally that must match the injection plan
        raw = faultinject.poison_samples(raw)
        raw = validator.filter(raw, source="ingest")
        if config["NeuralNetwork"]["Training"].get("compute_grad_energy", False):
            # energy/forces ride on the graphs directly (no target extraction
            # or minmax scaling — physical units matter); input node-feature
            # column selection still applies
            mm = None
            voi = voi_from_config(config)
            ready = [select_input_columns(g, voi) for g in raw]
        else:
            mm = MinMax.fit(raw)
            if config.get("Dataset", {}).get("normalize", True):
                raw = mm.apply(raw)
            voi = voi_from_config(config)
            ready = [extract_variables(g, voi) for g in raw]
        arch = config["NeuralNetwork"]["Architecture"]
        if arch.get("global_attn_engine"):
            # Laplacian PE + relative edge PE feed GPS (reference:
            # serialized_dataset_loader.py:89-94,182-189)
            from .data.lappe import add_dataset_pe

            # eigendecomposition results ride a topology-keyed disk cache
            # (Dataset.lappe_cache, default on) so re-runs and resumes skip
            # the O(N^3) per-graph eigh sweep (data/lappe.py)
            ready = add_dataset_pe(
                ready,
                int(arch.get("pe_dim") or 1),
                cache=ds_cfg.get("lappe_cache", True),
            )
        trainset, valset, testset = split_dataset(
            ready,
            perc_train=config["NeuralNetwork"]["Training"].get("perc_train", 0.7),
            seed=0,
            stratified=config.get("Dataset", {}).get(
                "compositional_stratified_splitting", False
            ),
        )
    else:
        trainset, valset, testset = datasets
        mm = None
        ds_cfg = config.get("Dataset", {})
        if wants_transforms(ds_cfg):
            # explicit-datasets path gets the same transform chain, with one
            # edge-length max shared across the three splits
            trainset, valset, testset = apply_dataset_transforms(
                ds_cfg, trainset, valset, testset
            )
        # explicit datasets get the same validation gate, per split
        trainset = validator.filter(trainset, source="train")
        valset = validator.filter(valset, source="val")
        testset = validator.filter(testset, source="test")

    config = update_config(config, trainset, valset, testset)
    if validator.policy == "quarantine":
        # the run name is derived from COMPLETED config keys — retarget the
        # manifest to the real run dir (any ingest-time entries move along)
        validator.set_quarantine_dir(
            os.path.join("./logs", get_log_name_config(config), "quarantine")
        )
    training = config["NeuralNetwork"]["Training"]
    arch = config["NeuralNetwork"]["Architecture"]
    batch_size = training["batch_size"]
    # multi-host: each process loads a disjoint 1/host_count slice
    # (DistributedSampler semantics) and stacks one shard per local device
    # for the global-mesh DP step (docs/MULTIHOST.md)
    import jax

    from .parallel import local_host_info

    host_count, host_index = local_host_info()
    num_shards = jax.local_device_count() if jax.process_count() > 1 else 1
    # single-host branch-parallel still needs stacked, branch-routed rows
    from .models.create import num_branches_from

    num_branches = num_branches_from(arch)
    if (
        bool(training.get("branch_parallel", False))
        and num_branches > 1
        and jax.process_count() == 1
        and jax.local_device_count() > 1
    ):
        num_shards = jax.local_device_count()
    # single-host ZeRO-2 runs the mesh step (the gradient-sharding
    # constraint lives there), so its batches must be stacked too —
    # _wants_zero2_mesh is the SAME predicate run_training uses
    if _wants_zero2_mesh(training):
        num_shards = jax.local_device_count()
    if batch_size % num_shards != 0:
        raise ValueError(
            f"Training.batch_size {batch_size} must be divisible by the "
            f"{num_shards} local devices on multi-host runs"
        )
    # bucketed pad specs when graph sizes vary (SURVEY §5.7): a few jit
    # specializations instead of one worst-case padding for every batch
    # (default set by update_config)
    num_buckets = int(training["num_pad_buckets"])
    # opt-in size-homogeneous batch composition; measured on the OC20-shaped
    # distribution it LOSES to random batching at batch sizes >= 32 (CLT
    # already concentrates random batch totals; docs/PERFORMANCE.md), so the
    # default stays off — the ladder simulation must match the policy either
    # way, or bucketed small batches never fit a level
    size_bucketing = bool(training.get("size_bucketed_batching", False))
    # packed batching: greedy bin-packing into ONE fixed budget, variable
    # real-graph count per batch — a single jit specialization at ~95%
    # occupancy; multi-host epoch lengths agree communication-free via
    # simulated packing of every host's slice (docs/PERFORMANCE.md)
    pack = bool(training.get("pack_batches", False))
    if pack:
        # ONE budget over all three splits, so eval reuses the train step's
        # compilation (the whole point of pack mode; per-split auto budgets
        # would each be their own jit specialization)
        from .data.pipeline import _pack_spec

        spec = _pack_spec(
            trainset + valset + testset,
            max(batch_size // num_shards, 1),
            with_triplets=arch["mpnn_type"] == "DimeNet",
        )
    else:
        spec = SpecLadder.for_dataset(
            trainset + valset + testset,
            batch_size // num_shards,
            num_buckets=num_buckets,
            with_triplets=arch["mpnn_type"] == "DimeNet",
            size_bucketing=size_bucketing,
        )
    shard_kw = dict(
        spec=spec,
        pack=pack,
        host_count=host_count,
        host_index=host_index,
        num_shards=num_shards,
        size_bucketing=size_bucketing,
        # receiver-sorted edges feed the Pallas segment kernel (TPU). No
        # max_in_degree here: update_config already validated the dataset's
        # top in-degree against the bound (config.py:194-207); the loader
        # check exists for directly constructed loaders
        sort_edges=bool(arch.get("use_sorted_aggregation", False)),
        # data-plane fault tolerance: batch-time budget policing rides the
        # run's validator, and the prefetch watchdog turns a wedged producer
        # into an actionable LoaderStallError (docs/ROBUSTNESS.md)
        validator=validator,
        stall_timeout=float(training.get("loader_stall_timeout", 600.0) or 0.0),
    )
    # equal per-dataset step budget for GFM fleets: weighted draws with
    # replacement, the SPMD analog of the reference's uneven branch process
    # groups (examples/multibranch/train.py:166-213; data.branch_sample_weights)
    balance = bool(training.get("balance_branch_sampling", False))
    sample_weights = None
    if balance:
        from .data import branch_sample_weights

        ids = sorted({g.dataset_id for g in trainset})
        sample_weights = branch_sample_weights(
            trainset, {i: 1.0 for i in ids}
        )
    # GFM mixture plane (docs/GFM.md): a ``Mixture`` config section swaps
    # the train loader for the streaming temperature-sampled multi-source
    # scheduler; val/test stay plain ladder loaders over the merged splits
    # (deterministic eval), sharing the same spec ladder so every
    # specialization is reused across train and eval
    if config.get("Mixture"):
        if pack:
            raise ValueError(
                "the Mixture section is not supported with "
                "Training.pack_batches: mixture batches are drawn at a "
                "FIXED graph count and ladder-padded, while pack mode bins "
                "a variable graph count into one budget — the two batch "
                "composers are mutually exclusive by construction. Drop "
                "Training.pack_batches (use Training.num_pad_buckets for "
                "the few-specializations effect) or drop the Mixture "
                "section"
            )
        if balance:
            raise ValueError(
                "Training.balance_branch_sampling is subsumed by the "
                "Mixture section (Mixture.temperature/weights set the "
                "per-source draw shares); drop one of the two"
            )
        from .mix import MixturePlane, sources_from_graphs

        if (
            bool(training.get("branch_parallel", False))
            and num_branches > 1
            and num_shards > 1
        ):
            # routed rule tables need branch-routed shard rows: one
            # MixturePlane per served branch, rows stacked branch-major
            # (parallel/routing.py BranchRoutedMixture); per-branch
            # decoders are then placed by the branch rule preset
            # (parallel/rules.py -> parallel/engine.py)
            from .parallel.routing import (
                BranchRoutedLoader,
                BranchRoutedMixture,
            )

            route_kw = dict(
                branch_count=num_branches,
                num_shards=num_shards,
                host_count=host_count,
                host_index=host_index,
                sort_edges=shard_kw["sort_edges"],
                spec=spec,
            )
            train_loader = BranchRoutedMixture(
                sources_from_graphs(trainset),
                batch_size,
                settings=config["Mixture"],
                seed=int(training.get("seed", 0)),
                validator=validator,
                **route_kw,
            )
            val_loader = BranchRoutedLoader(
                valset, batch_size, shuffle=False, oversampling=False,
                **route_kw,
            )
            test_loader = BranchRoutedLoader(
                testset, batch_size, shuffle=False, oversampling=False,
                **route_kw,
            )
            train_loader.validator = validator
            return config, (train_loader, val_loader, test_loader), mm
        # flat (data-parallel) mixture: each host owns a disjoint draw
        # stripe of the SAME absolute draw sequence (mix/plane.py "host
        # loss"). Stripe identity comes from the fleet plane's view so a
        # simulated fleet (HYDRAGNN_FLEET_HOST_INDEX/_COUNT, one jax
        # process per child) stripes exactly like a real pod — on real
        # multi-host runs host_identity() equals local_host_info()
        from .obs.fleet import host_identity

        mix_host_index, mix_host_count = host_identity()
        train_loader = MixturePlane(
            sources_from_graphs(trainset),
            batch_size,
            settings=config["Mixture"],
            spec=spec,
            seed=int(training.get("seed", 0)),
            sort_edges=shard_kw["sort_edges"],
            validator=validator,
            num_shards=num_shards,
            host_count=mix_host_count,
            host_index=mix_host_index,
        )
        val_loader = GraphLoader(
            valset, batch_size, shuffle=False, source="val", **shard_kw
        )
        test_loader = GraphLoader(
            testset, batch_size, shuffle=False, source="test", **shard_kw
        )
        return config, (train_loader, val_loader, test_loader), mm
    if (
        bool(training.get("branch_parallel", False))
        and num_branches > 1
        and num_shards > 1
    ):
        if pack:
            raise ValueError(
                "Training.pack_batches is not supported with branch_parallel "
                "(branch-routed rows need fixed graph counts); use "
                "num_pad_buckets"
            )
        # routed rule tables need branch-routed shard rows
        # (parallel/routing.py BranchRoutedLoader); ONE ladder over all
        # splits so eval reuses the train step's compilations
        from .parallel.routing import BranchRoutedLoader

        route_kw = dict(
            branch_count=num_branches,
            num_shards=num_shards,
            host_count=host_count,
            host_index=host_index,
            sort_edges=shard_kw["sort_edges"],
            # the FULL ladder (shared across splits): each stacked batch
            # selects the smallest level fitting its largest row, and the
            # loader's per-branch template census warms every reachable
            # level (parallel/routing.py; multi-host collapses to worst-case
            # inside the loader — level choice cannot agree across hosts
            # without a collective)
            spec=spec,
        )
        train_loader = BranchRoutedLoader(
            trainset, batch_size, seed=0, shuffle=True, **route_kw
        )
        val_loader = BranchRoutedLoader(
            valset, batch_size, shuffle=False, oversampling=False, **route_kw
        )
        test_loader = BranchRoutedLoader(
            testset, batch_size, shuffle=False, oversampling=False, **route_kw
        )
        # branch-routed loaders did their validation at the ingest gate
        # above; carry the validator so the epoch loop still logs the tally
        train_loader.validator = validator
        return config, (train_loader, val_loader, test_loader), mm
    train_loader = GraphLoader(
        trainset,
        batch_size,
        shuffle=True,
        seed=0,
        # RandomSampler-with-replacement / fixed-draw modes
        # (reference: load_data.py:237-274)
        oversampling=bool(training.get("oversampling", False)) or balance,
        num_samples=training.get("num_samples"),
        sample_weights=sample_weights,
        # background batch building (HYDRAGNN_NUM_WORKERS=0 disables; the
        # reference's env of the same name sizes its thread-pool loader)
        prefetch=max(envflags.env_int("HYDRAGNN_NUM_WORKERS", 2), 0),
        # multi-host batches must stay full so every process steps in
        # lockstep with identical shard shapes
        drop_last=jax.process_count() > 1,
        source="train",
        **shard_kw,
    )
    val_loader = GraphLoader(
        valset, batch_size, shuffle=False, source="val", **shard_kw
    )
    test_loader = GraphLoader(
        testset, batch_size, shuffle=False, source="test", **shard_kw
    )
    return config, (train_loader, val_loader, test_loader), mm


@functools.singledispatch
def run_training(config, datasets=None, verbosity: Optional[int] = None):
    raise TypeError(f"config must be a dict or str path, got {type(config)}")


@run_training.register
def _(config: str, datasets=None, verbosity: Optional[int] = None):
    return run_training(load_config(config), datasets, verbosity)


@run_training.register
def _(config: dict, datasets=None, verbosity: Optional[int] = None):
    """(reference: run_training.py:62-182)"""
    from .parallel import setup_distributed
    from .utils import MetricsWriter, Timer, print_timers, setup_log
    from .utils import tracer as tr

    # multi-host rendezvous first — before anything touches the XLA backend
    # (reference: run_training.py:71 calls setup_ddp before load/model)
    setup_distributed()
    # fresh per-run accumulators (class/module-level state would otherwise
    # report cumulative totals across repeated runs in one process)
    Timer.reset()
    tr.reset()
    with Timer("load_data"):
        config, loaders, mm = prepare_data(config, datasets)
    train_loader, val_loader, test_loader = loaders
    verbosity = (
        verbosity if verbosity is not None else config["Verbosity"].get("level", 0)
    )
    import jax
    import numpy as np

    log_name = get_log_name_config(config)
    if verbosity > 0:
        setup_log(log_name)
    if jax.process_index() == 0:
        # rank-0 config dump (reference: save_config, config_utils.py:352-358)
        save_config(config, log_name)

    # persistent XLA compilation cache (train/compile_plane.py): activated
    # BEFORE the first jit touch (model init below compiles too), so
    # restarts/rollbacks/resumes deserialize executables instead of
    # recompiling. Training.compile_cache_dir / HYDRAGNN_COMPILE_CACHE.
    from .train.compile_plane import setup_compile_cache

    setup_compile_cache(config["NeuralNetwork"]["Training"], log_name)

    multihost = jax.process_count() > 1
    training = config["NeuralNetwork"]["Training"]
    arch = config["NeuralNetwork"]["Architecture"]
    # one seed drives init and the train rng stream (dropout etc.);
    # ``Training.seed`` pins runs for reproducibility studies
    run_seed = int(training.get("seed", 0))
    with Timer("create_model"):
        model = create_model(config)
        sample = next(iter(train_loader))
        if getattr(train_loader, "num_shards", 1) > 1:
            # loader emits stacked [local_shards, ...] batches: init on one
            sample = jax.tree_util.tree_map(lambda x: np.asarray(x)[0], sample)
        variables = init_model(model, sample, seed=run_seed)
    from .utils import print_model

    # parameter summary (reference: print_model, model.py:289-297)
    print_model(variables, verbosity=verbosity)
    tx = make_optimizer(
        training["Optimizer"],
        freeze_conv=bool(arch.get("freeze_conv_layers", False)),
    )
    state = TrainState.create(variables, tx)

    # resume mid-run (reference: "continue"/"startfrom" keys,
    # hydragnn/utils/model/model.py:118-125, run_training.py:114) — restore
    # before any device placement so the loaded host arrays get re-placed
    if training.get("continue"):
        import warnings as _warnings

        startfrom = training.get("startfrom") or log_name
        state = load_existing_model(state, startfrom)
        # mid-epoch resume (docs/ROBUSTNESS.md "Data plane"): a loader-state
        # sidecar beside the checkpoint means the save happened BETWEEN
        # steps — arm the train loader to replay the interrupted epoch's
        # remaining batches in the same order, after guarding that the data
        # recipe still matches (a changed seed/batch count would replay the
        # wrong stream — then epoch-granularity resume is the honest choice)
        ls = load_loader_state(startfrom)
        if ls is not None:
            recipe_ok = hasattr(train_loader, "resume") and ls.seed == int(
                getattr(train_loader, "seed", 0) or 0
            )
            if recipe_ok:
                train_loader.resume(ls.epoch, ls.next_batch)
                if ls.mixture and hasattr(train_loader, "restore_mixture"):
                    # mid-epoch mixture resume: cursors + draw index + the
                    # source topology AT the checkpointed batch — BEFORE the
                    # batch-count guard below, which must compare against
                    # the sidecar's (possibly churned/demoted) active set,
                    # not the fresh all-sources topology (mix/plane.py)
                    train_loader.restore_mixture(ls.mixture, mid_epoch=True)
                # batch-count guard AFTER arming: pack-mode batch counts are
                # epoch-dependent, so len() is only comparable once the
                # loader sits at the sidecar's epoch. EXCEPTION: a mixture
                # sidecar written under a different (host_count, host_index)
                # stripe layout legitimately changes the per-host batch
                # count — the elastic re-deal (mix/plane.py restore_mixture)
                # already re-armed the loader at the mapped position
                relayout = (
                    isinstance(ls.mixture, dict)
                    and (
                        int(ls.mixture.get("host_count", 1))
                        != int(getattr(train_loader, "host_count", 1) or 1)
                        or int(ls.mixture.get("host_index", 0))
                        != int(getattr(train_loader, "host_index", 0) or 0)
                    )
                )
                if (
                    ls.num_batches
                    and ls.num_batches != len(train_loader)
                    and not relayout
                ):
                    train_loader.resume(0, 0)  # disarm: fresh epoch 0 start
                    recipe_ok = False
                if relayout and recipe_ok:
                    # record the survivor's re-layout as a typed event (the
                    # doctor's elastic rules read exactly this record); the
                    # driver that relaunched us may hand over the measured
                    # progress loss (run-scripts/elastic_smoke.py)
                    from .train.elastic import note_relayout

                    lost = envflags.env_str("HYDRAGNN_ELASTIC_LOST_STEPS")
                    note_relayout(
                        {
                            "host_count": int(
                                ls.mixture.get("host_count", 1) or 1
                            ),
                            "host_index": int(
                                ls.mixture.get("host_index", 0) or 0
                            ),
                            "epoch": int(ls.epoch),
                            "next_batch": int(ls.next_batch),
                        },
                        {
                            "host_count": int(
                                getattr(train_loader, "host_count", 1) or 1
                            ),
                            "host_index": int(
                                getattr(train_loader, "host_index", 0) or 0
                            ),
                            "epoch": int(
                                getattr(train_loader, "epoch", ls.epoch)
                            ),
                            "next_batch": int(
                                getattr(
                                    train_loader, "start_batch", 0
                                )
                            ),
                        },
                        trigger="resume",
                        progress_lost_steps=int(lost) if lost else None,
                    )
            if recipe_ok:
                if verbosity > 0:
                    print(
                        f"[{log_name}] resuming mid-epoch: replaying epoch "
                        f"{ls.epoch} from batch {ls.next_batch}"
                    )
            else:
                _warnings.warn(
                    f"loader-state sidecar of run {startfrom!r} does not "
                    "match the current loader (seed/batch-count drift, or a "
                    "loader without resume support); resuming at epoch "
                    "granularity instead of mid-epoch",
                    stacklevel=2,
                )
        elif hasattr(train_loader, "restore_mixture"):
            # epoch-boundary (or SIGKILL) resume: no loader sidecar, but the
            # mixture snapshot beside the checkpoint still carries the source
            # topology + the absolute epoch sequence to continue
            ms = load_mixture_state(startfrom)
            if ms is not None:
                train_loader.restore_mixture(ms)
                if isinstance(ms, dict) and (
                    int(ms.get("host_count", 1) or 1)
                    != int(getattr(train_loader, "host_count", 1) or 1)
                    or int(ms.get("host_index", 0) or 0)
                    != int(getattr(train_loader, "host_index", 0) or 0)
                ):
                    # an epoch-boundary re-layout (elastic shrink survivor
                    # finishing, or a re-grown host rejoining): the new
                    # epoch re-deals the stripes from position 0 by purity
                    # alone, but the typed event must still be recorded —
                    # it is the doctor's evidence of the topology change
                    from .train.elastic import note_relayout

                    lost = envflags.env_str("HYDRAGNN_ELASTIC_LOST_STEPS")
                    note_relayout(
                        {
                            "host_count": int(ms.get("host_count", 1) or 1),
                            "host_index": int(ms.get("host_index", 0) or 0),
                            "epoch": int(ms.get("epoch", 0) or 0),
                        },
                        {
                            "host_count": int(
                                getattr(train_loader, "host_count", 1) or 1
                            ),
                            "host_index": int(
                                getattr(train_loader, "host_index", 0) or 0
                            ),
                            "epoch": int(
                                getattr(train_loader, "epoch", 0) or 0
                            ),
                        },
                        trigger="resume",
                        progress_lost_steps=int(lost) if lost else None,
                    )
                if verbosity > 0:
                    print(
                        f"[{log_name}] mixture topology restored: epoch "
                        f"sequence continues at {train_loader.epoch}"
                    )

    # every device-placement transform applied to the state below is also
    # recorded here, so the rollback restore path (non_finite_policy:
    # rollback) can replay the SAME placement on a freshly deserialized
    # host-array state — a restored state must be indistinguishable from a
    # resumed one (train/loop.py restore_fn)
    placement_fns: List[Any] = []

    # sharding rule table (parallel/rules.py): prepare_data already
    # resolved + recorded it; re-resolving here is idempotent and hands
    # this function the table object driving placement AND step building.
    # ZeRO stage selection (reference: ZeroRedundancyOptimizer / DeepSpeed
    # stages, hydragnn/utils/optimizer/optimizer.py:43-113): stage 1 =
    # moment sharding (placement only — tx.update runs under the outer
    # jit, so XLA partitions the update by the moments' sharding), stage
    # 2/3 add in-step gradient/param rules and need the mesh step.
    rule_table = resolve_parallel(config)
    zero_stage = _zero_stage(training)
    use_zero = zero_stage >= 1
    # stage >= 2 needs the mesh step — same predicate prepare_data used
    # for the loader num_shards gate (unstacked batches would break it);
    # resolve_parallel normalized zero_stage from the table, so inline
    # tables with grads/params rules take this gate too
    zero2_mesh = _wants_zero2_mesh(training) and not multihost
    if (
        use_zero
        and zero_stage < 2
        and not multihost
        and not training.get("branch_parallel", False)
        and len(jax.devices()) > 1
    ):
        # ZeRO-1 placement under the plain-jit loop step: moments sharded
        # P(data) by the table, everything else replicated
        from .parallel import make_mesh2d, place_state

        mesh = make_mesh2d()

        def _place_zero1(st, _mesh=mesh, _table=rule_table):
            return place_state(st, _table, _mesh)

        placement_fns.append(_place_zero1)
        state = _place_zero1(state)

    # mesh-step mode: multi-host DP (shard_map over the global (data,
    # model) mesh, grads psum over ICI/DCN) and/or routed decoder sharding
    # — single-host multi-device branch_parallel runs the same mesh steps
    # (promote_batch no-ops with one process)
    step_fn = eval_fn = None
    # routed decoder sharding (Training.branch_parallel / the branch-mp
    # rule presets): decoder banks sharded over the model axis, data
    # routed by branch — the MultiTaskModelMP analog (parallel/engine.py).
    # The predicate must MATCH prepare_data's loader-routing gate exactly
    # (resolve_parallel normalizes both from the same table): a routed
    # step on unrouted batches computes garbage.
    branch_parallel = bool(training.get("branch_parallel", False))
    if branch_parallel and (
        getattr(model.cfg, "num_branches", 1) < 2
        or jax.local_device_count() < 2
    ):
        raise ValueError(
            "Training.branch_parallel requires a multibranch model "
            f"(num_branches={getattr(model.cfg, 'num_branches', 1)}) and "
            f">=2 local devices (have {jax.local_device_count()}): "
            "prepare_data could not build branch-routed loaders"
        )
    if multihost or branch_parallel or zero2_mesh:
        # the ONE mesh-step path (parallel/engine.py): the rule table
        # decides placement, in-step constraints, and routing — dp /
        # ZeRO-2/3 / branch-parallel are presets, not code paths
        from .parallel import (
            Objective,
            make_mesh2d,
            make_mesh_eval_step,
            make_mesh_train_step,
            place_state,
            promote_batch,
        )

        cge = training.get("compute_grad_energy", False)
        mp = training.get("mixed_precision", False)
        # Telemetry.numerics changes the step program (in-graph probes ride
        # the outputs — obs/numerics.py), so the mesh builders must get the
        # same resolution the loop applies to its default builders
        from .obs.telemetry import resolve_telemetry as _resolve_telemetry

        numerics_on = bool(_resolve_telemetry(config)["numerics"])
        # 2D (data, model) mesh; model extent 1 unless the table routes
        # decoder banks over the model axis (branch/mp presets)
        mesh = make_mesh2d(
            model_size=rule_table.model_size if rule_table.routed else 1
        )

        def _place_rules(st, _mesh=mesh, _table=rule_table):
            # table-driven placement: moments/params/decoder banks land on
            # their rule's spec, unmatched non-scalar leaves replicate with
            # an audit finding (obs/sharding.py record_unmatched); restored
            # Adam moments are PLACED, never re-initialized
            return place_state(st, _table, _mesh)

        placement_fns.append(_place_rules)
        state = _place_rules(state)
        _obj = Objective(
            model=model,
            tx=tx,
            compute_grad_energy=cge,
            mixed_precision=mp,
            numerics=numerics_on,
        )
        _pstep = make_mesh_train_step(_obj, rule_table, mesh)
        _peval = make_mesh_eval_step(_obj, rule_table, mesh)
        # the wrappers hide the jit objects from the compile plane —
        # attach_lower_fn re-exposes them (same jit object + same batch
        # transform the loop uses) so warm-up lands the identical executable
        from .train.compile_plane import attach_lower_fn

        step_fn = attach_lower_fn(
            lambda s, b, r: _pstep(s, promote_batch(b, mesh), r),
            # a numerics-enabled builder returns a wrapper carrying the
            # true jit as _jitted (parallel/engine.py)
            getattr(_pstep, "_jitted", _pstep),
            lambda b: promote_batch(b, mesh),
        )
        for _attr in ("_numerics_meta", "_nan_diagnose"):
            # the numerics name tables + NaN drill-down travel with the
            # step function the loop receives (train/loop.py reads them)
            _val = getattr(_pstep, _attr, None)
            if _val is not None:
                setattr(step_fn, _attr, _val)
        # evaluate() expects (tot, tasks, aux) like make_eval_step
        eval_fn = attach_lower_fn(
            lambda s, b: _peval(s, promote_batch(b, mesh)) + (None,),
            _peval,
            lambda b: promote_batch(b, mesh),
        )

    # sharding-layout inspector (obs/sharding.py): whenever a placement
    # was applied (zero1/2/3, mesh DP, branch decoders), tabulate the
    # placed state's param/optimizer leaf shardings, run the replicated-
    # above-threshold audit, publish the hydragnn_sharding_* gauges, and
    # record the report so every flight dump carries sharding.json — the
    # before/after oracle for the planned rule-table sharding refactor
    if placement_fns:
        from .obs import sharding as obs_sharding
        from .obs.telemetry import resolve_telemetry as _rt

        try:
            import sys as _sys

            _shard_report = obs_sharding.inspect_state(
                state,
                threshold_bytes=int(
                    _rt(config)["fleet_sharding_audit_bytes"]
                ),
                label=log_name,
                mesh=mesh,
            )
            obs_sharding.record(_shard_report)
            if verbosity > 0:
                # summary + audit at verbosity 1 (one grep-able line per
                # run), the full per-leaf table at 2+
                print(
                    obs_sharding.format_report(
                        _shard_report, leaves=verbosity > 1
                    ),
                    file=_sys.stderr,
                )
        except Exception as _e:  # the inspector must never block training
            import warnings as _warnings

            _warnings.warn(
                f"sharding inspector failed ({type(_e).__name__}: {_e}); "
                "the placement report is unavailable for this run",
                RuntimeWarning,
                stacklevel=2,
            )

    writer = MetricsWriter(log_name)

    def log_fn(epoch, scalars):
        # per-epoch scalars (reference: train_validate_test.py:198-205)
        writer.add_scalars(
            {f"loss/{k}": v for k, v in scalars.items() if k != "lr"}, epoch
        )
        writer.add_scalar("lr", scalars.get("lr", 0.0), epoch)

    retention = int(training.get("checkpoint_retention", 0) or 0)
    if training.get("checkpoint_backend", "msgpack") == "orbax":
        from .train.checkpoint import save_model_orbax

        _save_model = lambda s, e=None: save_model_orbax(
            s, log_name, epoch=e, retention=retention
        )
    else:
        _save_model = lambda s, e=None: save_model(
            s, log_name, epoch=e, retention=retention
        )

    def save_fn(s, e=None):
        out = _save_model(s, e)
        # any committed save invalidates an older mid-epoch cursor; the
        # mid-epoch preemption path re-publishes its sidecar right after
        # this (loader_state_fn below), so a PRESENT sidecar always
        # describes the checkpoint it sits beside
        clear_loader_state(log_name)
        if hasattr(train_loader, "mixture_state_dict"):
            # mixture snapshot beside every checkpoint: active/demoted
            # sources, weights, absolute epoch — what a SIGKILL resume
            # needs to continue the exact draw sequence (docs/GFM.md)
            save_mixture_state(train_loader.mixture_state_dict(), log_name)
        return out

    def loader_state_fn(d):
        from .train.state import LoaderState

        save_loader_state(LoaderState.from_dict(d), log_name)

    def restore_fn(template):
        # rollback path (Training.non_finite_policy: rollback): restore the
        # last VERIFIED checkpoint of THIS run (digest-checked, walking back
        # on corruption — train/checkpoint.py), then replay the recorded
        # device placement so the restored state matches the step's contract
        st = load_existing_model(template, log_name)
        for place in placement_fns:
            st = place(st)
        return st

    try:
        with Timer("train_validate_test"):
            state, hist = train_validate_test(
                model,
                state,
                tx,
                train_loader,
                val_loader,
                test_loader,
                config,
                log_name=log_name,
                verbosity=verbosity,
                seed=run_seed,
                save_fn=save_fn,
                log_fn=log_fn,
                step_fn=step_fn,
                eval_fn=eval_fn,
                restore_fn=restore_fn,
                loader_state_fn=loader_state_fn,
                # the loop routes guard/data/compile health counters (and
                # the Telemetry layer's TB mirror) through the same writer
                # the epoch scalars use (obs/telemetry.py)
                writer=writer,
            )
    finally:
        writer.close()
    # final save with the GLOBAL (possibly sharded) state — orbax writes
    # shard-parallel; skipped when the preemption path already checkpointed
    # (re-serializing identical state would burn the SIGTERM grace window).
    # Gate on the loop's cross-host AGREED decision, not the local SIGTERM
    # flag: under orbax the save is a collective, and skewed signal delivery
    # would otherwise hang the non-preempted hosts in it.
    from .parallel.mesh import materialize_replicated
    from .utils import preemption

    do_final_save = not preemption.global_stop_noted()
    final_epoch = len(hist["train"]) - 1
    orbax_backend = training.get("checkpoint_backend", "msgpack") == "orbax"
    if multihost and not orbax_backend:
        # localize BEFORE the msgpack save: save_model gathers sharded
        # leaves anyway (checkpoint.py), so gathering once here serves both
        # the save and the downstream consumers (prediction, plotting)
        state = materialize_replicated(state)
    if do_final_save:
        save_fn(state, final_epoch if final_epoch >= 0 else None)
    if multihost and orbax_backend:
        # orbax writes shard-parallel — save the SHARDED state first, then
        # localize for downstream consumers
        state = materialize_replicated(state)
    if config.get("Visualization", {}).get("create_plots") and jax.process_index() == 0:
        # parity/error/history plots (reference: train_validate_test.py:100-126,
        # 268-313 drives postprocess/visualizer.py)
        from .postprocess import Visualizer

        _, _, preds, trues = test_model(
            model,
            state,
            _localize_loader(test_loader),
            compute_grad_energy=config["NeuralNetwork"]["Training"].get(
                "compute_grad_energy", False
            ),
            mixed_precision=config["NeuralNetwork"]["Training"].get(
                "mixed_precision", False
            ),
        )
        viz = Visualizer(log_name)
        viz.create_scatter_plots(trues, preds)
        viz.create_error_histograms(trues, preds)
        viz.plot_history(hist)
        viz.create_plot_global(trues, preds)
        viz.num_nodes_plot(
            [g.num_nodes for g in test_loader.graphs]
        )
        for name in trues:
            arr = np.asarray(trues[name])
            if name == "forces" or (arr.ndim == 2 and arr.shape[-1] == 3):
                viz.create_parity_plot_per_node_vector(name, trues[name], preds[name])
            else:
                viz.create_plot_global_analysis(name, trues[name], preds[name])
                viz.create_parity_plot_and_error_histogram_scalar(
                    name, trues[name], preds[name]
                )
    print_timers(verbosity)
    return model, state, hist, config, loaders, mm


@functools.singledispatch
def run_prediction(config, model_state=None, datasets=None):
    raise TypeError(f"config must be a dict or str path, got {type(config)}")


@run_prediction.register
def _(config: str, model_state=None, datasets=None):
    return run_prediction(load_config(config), model_state, datasets)


def _restore_for_inference(config, variables):
    """Restore the run's newest verified checkpoint for inference into the
    pre-initialized ``variables``: an optimizer-free ``InferenceState``
    template through the msgpack chain (no AdamW moments allocated — 2x
    params of dead memory on large models), falling back to the full
    ``TrainState`` template only for orbax-backed runs (their
    shard-parallel restore needs it). Returns ``(state, loaded_entry)`` —
    the entry ACTUALLY restored, which the verified walk-back chain may
    have taken PAST a corrupt ``latest``."""
    from .train.checkpoint import latest_checkpoint_entry, load_inference_state
    from .train.state import InferenceState

    log_name = get_log_name_config(config)
    entry = latest_checkpoint_entry(log_name)
    if entry and entry.startswith("orbax/"):
        tx = make_optimizer(config["NeuralNetwork"]["Training"]["Optimizer"])
        loaded: list = []
        state = load_existing_model(
            TrainState.create(variables, tx), log_name, loaded_entry=loaded
        )
        return state, (loaded[0] if loaded else entry)
    return load_inference_state(InferenceState.create(variables), log_name)


@run_prediction.register
def _(config: dict, model_state=None, datasets=None):
    """(reference: run_prediction.py:49-107): rebuild model, restore latest
    checkpoint, evaluate on the test split, optionally denormalize."""
    from .parallel import setup_distributed

    setup_distributed()  # (reference: run_prediction.py:56)
    config, loaders, mm = prepare_data(config, datasets)
    _, _, test_loader = loaders
    # prediction is per-host (plain jitted eval): drop any device stacking
    test_loader = _localize_loader(test_loader)
    # persistent compilation cache, same wiring as run_training: a serving/
    # prediction restart must deserialize its eval executables instead of
    # repaying the full compile bill (train/compile_plane.py)
    from .train.compile_plane import setup_compile_cache

    setup_compile_cache(
        config["NeuralNetwork"]["Training"], get_log_name_config(config)
    )
    model = create_model(config)
    if model_state is None:
        variables = init_model(model, next(iter(test_loader)), seed=0)
        model_state, _ = _restore_for_inference(config, variables)
    tot, tasks, preds, trues = test_model(
        model,
        model_state,
        test_loader,
        compute_grad_energy=config["NeuralNetwork"]["Training"].get(
            "compute_grad_energy", False
        ),
        mixed_precision=config["NeuralNetwork"]["Training"].get(
            "mixed_precision", False
        ),
    )
    # multi-host: every process returns the FULL prediction set and a
    # globally reduced loss (reference: padded all-gather of test samples
    # train_validate_test.py:410-448 + reduce_values_ranks :382-407)
    import jax as _jax

    from .parallel import gather_across_hosts

    if _jax.process_count() > 1:
        import numpy as _np

        # per-host weight = number of real graphs this host evaluated (the
        # same weighting _weighted_avg used inside test_model) — NOT the
        # element count of the first head, which for a node-level head
        # scales with node count and would skew the merged loss when hosts
        # hold different-sized graphs
        w = float(len(test_loader.graphs))
        packed = {
            "w": _np.asarray([w]),
            "tot": _np.asarray([tot * w]),
            **{f"task_{k}": _np.asarray([v * w]) for k, v in tasks.items()},
        }
        g = gather_across_hosts(packed)
        W = float(g["w"].sum()) or 1.0
        tot = float(g["tot"].sum() / W)
        tasks = {k: float(g[f"task_{k}"].sum() / W) for k in tasks}
    preds = gather_across_hosts(preds)
    trues = gather_across_hosts(trues)
    var = config["NeuralNetwork"]["Variables_of_interest"]
    if var.get("denormalize_output") and mm is not None:
        # every head is denormalized, node-level included (reference:
        # output_denormalize, hydragnn/postprocess/postprocess.py:13-26)
        voi = voi_from_config(config)
        for name, t, idx in zip(var["output_names"], var["type"], var["output_index"]):
            if name not in preds:
                continue  # e.g. autograd-forces head replaces the node head
            if t == "graph":
                sl = voi.graph_feature_slice(idx)
                preds[name] = mm.denormalize_graph(preds[name], sl)
                trues[name] = mm.denormalize_graph(trues[name], sl)
            else:
                sl = voi.node_feature_slice(idx)
                preds[name] = mm.denormalize_node(preds[name], sl)
                trues[name] = mm.denormalize_node(trues[name], sl)
    return tot, tasks, preds, trues


@functools.singledispatch
def run_server(config, datasets=None, install_sigterm: bool = False):
    raise TypeError(f"config must be a dict or str path, got {type(config)}")


@run_server.register
def _(config: str, datasets=None, install_sigterm: bool = False):
    return run_server(load_config(config), datasets, install_sigterm)


@run_server.register
def _(config: dict, datasets=None, install_sigterm: bool = False):
    """Config-driven serving entry point (docs/SERVING.md): complete the
    config from data, restore the run's newest verified checkpoint into an
    optimizer-free inference state, and start a ``GraphServer`` whose
    micro-batcher packs requests into the run's SpecLadder pad buckets —
    every servable shape AOT-warmed before readiness flips, the retrace
    sentinel armed per ``Serving.retrace_policy`` (default ``error``).

    Returns the STARTED server; callers submit requests and ``close()`` it
    (it is also a context manager). ``install_sigterm=True`` wires SIGTERM
    to a graceful drain. With no checkpoint on disk the server serves the
    fresh initialization (warned — useful for smokes only).
    """
    import warnings as _warnings

    from .parallel import setup_distributed
    from .serve import CheckpointWatcher, GraphServer, ServeConfig
    from .train.state import InferenceState

    setup_distributed()
    config, loaders, mm = prepare_data(config, datasets)
    _, _, test_loader = loaders
    test_loader = _localize_loader(test_loader)
    log_name = get_log_name_config(config)
    # persistent compilation cache BEFORE any jit touch, like run_training:
    # a server restart deserializes the warmed ladder instead of recompiling
    from .train.compile_plane import setup_compile_cache

    setup_compile_cache(config["NeuralNetwork"]["Training"], log_name)
    model = create_model(config)
    variables = init_model(model, next(iter(test_loader)), seed=0)
    try:
        state, entry = _restore_for_inference(config, variables)
    except FileNotFoundError:
        _warnings.warn(
            f"run {log_name!r} has no checkpoint on disk; serving the fresh "
            "model initialization (train first for real predictions)",
            stacklevel=2,
        )
        state = InferenceState.create(variables)
        entry = None
    training = config["NeuralNetwork"]["Training"]
    arch = config["NeuralNetwork"]["Architecture"]
    serve_cfg = ServeConfig.from_config(config)
    # tracing plane (obs/trace.py, obs/flightrec.py; docs/OBSERVABILITY.md):
    # Telemetry.trace arms head-sampled request traces (trace_sample) to
    # logs/<run>/trace.jsonl; the flight recorder arms the serve-wedge /
    # unhandled-exception / SIGUSR2 black box. The server owns both and
    # tears them down at close().
    from .obs.telemetry import resolve_telemetry

    obs_settings = resolve_telemetry(config)
    run_dir = os.path.join("./logs", log_name)
    tracer = None
    if obs_settings["trace"]:
        from .obs import trace as obs_trace

        tracer = obs_trace.Tracer(
            run_dir, sample=float(obs_settings["trace_sample"])
        )
        obs_trace.install(tracer)
    flight = None
    if obs_settings["flight_recorder"] and (
        obs_settings["trace"] or obs_settings["enabled"]
    ):
        from .obs.flightrec import FlightRecorder

        flight = FlightRecorder(run_dir, tracer=tracer).install()
    if obs_settings["trace"] or obs_settings["enabled"]:
        # persistent incident stream (obs/events.py): shed/queue-full/
        # wedge/reload events land in logs/<run>/events.jsonl so the run
        # doctor (obs/doctor.py) can diagnose a serving deployment
        # post-hoc; last attach wins, matching the tracer install contract
        from .obs.events import attach_stream as _attach_events

        _attach_events(run_dir)
    # kernel autotuning plane (tune/; docs/TUNING.md): install the run's
    # tuned table BEFORE the server's ladder warm-up, so the serve-side
    # Pallas routes consult it (same wiring as run_training's warm-up)
    from .tune.runtime import setup_autotune

    setup_autotune(config, test_loader, log_name)
    server = GraphServer(
        model,
        state,
        test_loader.ladder,
        serve_cfg,
        template_graphs=test_loader.graphs,
        mixed_precision=bool(training.get("mixed_precision", False)),
        sort_edges=bool(arch.get("use_sorted_aggregation", False)),
        log_name=log_name,
        checkpoint_label=entry,
        # int8 plane: locates pre-quantized snapshot artifacts beside the
        # checkpoints (serve/quantize.py) — a replica that finds one skips
        # re-quantization and calibration entirely
        checkpoint_dir="./logs",
        tracer=tracer,
        flight_recorder=flight,
    )
    server.start(install_sigterm=install_sigterm)
    if serve_cfg.hot_reload:
        watcher = CheckpointWatcher(
            server,
            log_name,
            poll_s=serve_cfg.reload_poll_s,
            initial_entry=entry,
        ).start()
        server.attach_watcher(watcher)
    return server


def run_server_fleet(
    config,
    replicas: int = None,
    path: str = "./logs",
    per_replica_env=None,
    wait_ready_s: float = None,
):
    """Config-driven serving FLEET (docs/SERVING.md "Fleet"): spawn
    ``Serving.fleet_replicas`` (or ``replicas=``) worker processes, each a
    full ``run_server`` deployment on its own ephemeral port and device
    set, supervised by a ``ReplicaManager`` — crash restart with backoff,
    flap benching, wedge detection, rolling hot-reload with rollback —
    and fronted by its ``router()`` (retries, hedging, circuit breakers,
    optional prediction cache).

    ``config`` is a config dict or JSON path. ``per_replica_env`` maps a
    1-based replica index to extra environment for that worker (the hook
    for pinning device sets). ``wait_ready_s`` blocks until every replica
    passes /readyz (warm-up included) or raises; None returns immediately
    with replicas still warming. Returns the STARTED ``ReplicaManager``
    — call ``.router().predict(graph)`` to serve and ``.close()`` (or use
    it as a context manager) to drain the fleet.
    """
    from .serve.fleet import ReplicaManager

    manager = ReplicaManager(
        config, path=path, per_replica_env=per_replica_env,
        replicas=replicas,
    ).start()
    if wait_ready_s is not None:
        if not manager.wait_ready(timeout=float(wait_ready_s)):
            state = manager.replica_state()
            manager.close()
            raise RuntimeError(
                f"serving fleet failed to become ready within "
                f"{wait_ready_s}s: {state}"
            )
    return manager
