// Cell-list radius-graph builder (open boundary conditions).
//
// Native analog of the C-accelerated neighbor search the reference leans on
// (ASE neighborlist, hydragnn/preprocess/graph_samples_checks_and_updates.py
// :141-343 — SURVEY §2.3 item 10). The numpy/scipy path in
// data/neighbors.py is fine for molecules; at OC20-catalog scale (millions
// of samples, hundreds of atoms each) host-side preprocessing becomes the
// bottleneck and the O(27 * n * density) cell list wins.
//
// Contract (mirrors data/neighbors.radius_graph before the neighbor cap):
// all DIRECTED edges (sender j -> receiver i, i != j) with
// ||pos_i - pos_j|| <= radius. Edges are emitted receiver-major and
// sender-sorted within a receiver, a canonical order.
//
// Returns the edge count, or -(needed) when the caller's buffer is too
// small (caller retries with a bigger buffer).

#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

long rg_open(const double* pos, long n, double radius,
             int32_t* senders, int32_t* receivers, long cap) {
    if (n <= 0 || radius <= 0.0) return 0;
    const double r2 = radius * radius;

    // bounding box
    double lo[3], hi[3];
    for (int d = 0; d < 3; ++d) { lo[d] = pos[d]; hi[d] = pos[d]; }
    for (long i = 1; i < n; ++i)
        for (int d = 0; d < 3; ++d) {
            const double v = pos[3 * i + d];
            if (v < lo[d]) lo[d] = v;
            if (v > hi[d]) hi[d] = v;
        }

    // grid of cells with side >= radius
    long nc[3];
    for (int d = 0; d < 3; ++d) {
        nc[d] = (long)std::floor((hi[d] - lo[d]) / radius) + 1;
        if (nc[d] < 1) nc[d] = 1;
    }
    const long ncells = nc[0] * nc[1] * nc[2];

    auto cell_of = [&](long i, long out[3]) {
        for (int d = 0; d < 3; ++d) {
            long c = (long)std::floor((pos[3 * i + d] - lo[d]) / radius);
            if (c < 0) c = 0;
            if (c >= nc[d]) c = nc[d] - 1;
            out[d] = c;
        }
    };
    auto flat = [&](const long c[3]) {
        return (c[0] * nc[1] + c[1]) * nc[2] + c[2];
    };

    // counting sort of atoms into cells
    std::vector<long> count(ncells + 1, 0);
    std::vector<long> acell(n);
    for (long i = 0; i < n; ++i) {
        long c[3];
        cell_of(i, c);
        acell[i] = flat(c);
        count[acell[i] + 1]++;
    }
    for (long c = 0; c < ncells; ++c) count[c + 1] += count[c];
    std::vector<long> order(n);
    {
        std::vector<long> cursor(count.begin(), count.end() - 1);
        for (long i = 0; i < n; ++i) order[cursor[acell[i]]++] = i;
    }

    long m = 0;
    std::vector<int32_t> nbr;  // senders of receiver i, gathered then sorted
    nbr.reserve(64);
    for (long i = 0; i < n; ++i) {
        long c[3];
        cell_of(i, c);
        nbr.clear();
        for (long dx = -1; dx <= 1; ++dx) {
            const long cx = c[0] + dx;
            if (cx < 0 || cx >= nc[0]) continue;
            for (long dy = -1; dy <= 1; ++dy) {
                const long cy = c[1] + dy;
                if (cy < 0 || cy >= nc[1]) continue;
                for (long dz = -1; dz <= 1; ++dz) {
                    const long cz = c[2] + dz;
                    if (cz < 0 || cz >= nc[2]) continue;
                    const long cc[3] = {cx, cy, cz};
                    const long f = flat(cc);
                    for (long k = count[f]; k < count[f + 1]; ++k) {
                        const long j = order[k];
                        if (j == i) continue;
                        double d2 = 0.0;
                        for (int d = 0; d < 3; ++d) {
                            const double diff = pos[3 * i + d] - pos[3 * j + d];
                            d2 += diff * diff;
                        }
                        if (d2 <= r2) nbr.push_back((int32_t)j);
                    }
                }
            }
        }
        // canonical order: senders ascending within each receiver
        for (size_t a = 1; a < nbr.size(); ++a) {  // insertion sort, small lists
            int32_t v = nbr[a];
            size_t b = a;
            while (b > 0 && nbr[b - 1] > v) { nbr[b] = nbr[b - 1]; --b; }
            nbr[b] = v;
        }
        if (m + (long)nbr.size() > cap) {
            // count the rest so the caller can size the retry buffer
            long needed = m + (long)nbr.size();
            for (long i2 = i + 1; i2 < n; ++i2) {
                long c2[3];
                cell_of(i2, c2);
                for (long dx = -1; dx <= 1; ++dx) {
                    const long cx = c2[0] + dx;
                    if (cx < 0 || cx >= nc[0]) continue;
                    for (long dy = -1; dy <= 1; ++dy) {
                        const long cy = c2[1] + dy;
                        if (cy < 0 || cy >= nc[1]) continue;
                        for (long dz = -1; dz <= 1; ++dz) {
                            const long cz = c2[2] + dz;
                            if (cz < 0 || cz >= nc[2]) continue;
                            const long cc[3] = {cx, cy, cz};
                            const long f = flat(cc);
                            for (long k = count[f]; k < count[f + 1]; ++k) {
                                const long j = order[k];
                                if (j == i2) continue;
                                double d2 = 0.0;
                                for (int d = 0; d < 3; ++d) {
                                    const double diff =
                                        pos[3 * i2 + d] - pos[3 * j + d];
                                    d2 += diff * diff;
                                }
                                if (d2 <= r2) ++needed;
                            }
                        }
                    }
                }
            }
            return -needed;
        }
        for (int32_t s : nbr) {
            senders[m] = s;
            receivers[m] = (int32_t)i;
            ++m;
        }
    }
    return m;
}

}  // extern "C"
