// Shared-memory sample store — the TPU-host analog of ORNL's DDStore
// (reference: pyddstore used by hydragnn/utils/datasets/distdataset.py:1-183;
// a C++/MPI one-sided remote-memory object store holding datasets larger
// than a single process can). On TPU pods every host feeds only its own
// devices and datasets are sharded per host (data/columnar.py), so the
// cross-node MPI RMA plane collapses to an intra-host concern: many loader
// processes sharing one pinned copy of the samples. This store provides
// that: a POSIX shared-memory arena with a slot table indexed directly by
// sample id (ids are dense dataset indices, so lookup is O(1)), atomic
// space reservation with no partial-failure leaks, and epoch_begin/end
// fences kept API-compatible with DDStore's windowed access
// (train loop brackets: train_validate_test.py:480-563).
//
// Build: g++ -O3 -shared -fPIC -o _ddstore.so ddstore.cpp -lrt
// (driven by hydragnn_tpu/native/build.py; loaded via ctypes).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x44445354'2d545055ULL;  // "DDST-TPU"

struct Header {
  // Cross-process readiness flag: written last by the creator with release
  // ordering, checked by attachers with acquire — guarantees capacity /
  // max_items / slot states are visible once magic reads valid, even on
  // weakly-ordered CPUs.
  std::atomic<uint64_t> magic;
  int64_t capacity;    // payload bytes
  int64_t max_items;   // slot-table size; valid ids are [0, max_items)
  std::atomic<int64_t> bump;       // next free payload offset
  std::atomic<int64_t> num_items;  // successfully published items
  std::atomic<int64_t> epoch;      // epoch_begin/end counter
};

struct Slot {
  std::atomic<int64_t> state;  // 0 = empty, 1 = published (set last)
  int64_t offset;
  int64_t length;
};

struct Store {
  Header* hdr;
  Slot* slots;
  char* payload;
  size_t mapped;
  int fd;
  char name[256];
};

size_t total_bytes(int64_t capacity, int64_t max_items) {
  return sizeof(Header) + sizeof(Slot) * (size_t)max_items + (size_t)capacity;
}

}  // namespace

extern "C" {

// Remove a named store (explicit cleanup of stale segments from crashed
// runs). Returns 0 on success.
int dds_unlink(const char* name) { return shm_unlink(name); }

// Create (create=1, fails with nullptr when the name already exists — the
// caller decides whether to dds_unlink a stale segment first) or attach
// (create=0) a named store. Returns nullptr on failure.
void* dds_open(const char* name, int64_t capacity, int64_t max_items,
               int create) {
  int fd;
  size_t bytes = 0;
  if (create) {
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return nullptr;  // EEXIST: never clobber silently
    bytes = total_bytes(capacity, max_items);
    if (ftruncate(fd, (off_t)bytes) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    bytes = (size_t)st.st_size;
  }
  void* base = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store;
  s->hdr = (Header*)base;
  s->mapped = bytes;
  s->fd = fd;
  strncpy(s->name, name, sizeof(s->name) - 1);
  s->name[sizeof(s->name) - 1] = 0;
  if (create) {
    s->hdr->capacity = capacity;
    s->hdr->max_items = max_items;
    s->hdr->bump.store(0);
    s->hdr->num_items.store(0);
    s->hdr->epoch.store(0);
  } else if (s->hdr->magic.load(std::memory_order_acquire) != kMagic) {
    munmap(base, bytes);
    close(fd);
    delete s;
    return nullptr;
  }
  s->slots = (Slot*)((char*)base + sizeof(Header));
  s->payload =
      (char*)base + sizeof(Header) + sizeof(Slot) * (size_t)s->hdr->max_items;
  if (create) {
    for (int64_t i = 0; i < max_items; ++i) s->slots[i].state.store(0);
    // publish header last: attachers acquire-check magic
    s->hdr->magic.store(kMagic, std::memory_order_release);
  }
  return s;
}

// Store a blob under id in [0, max_items). Returns 0 on success, -1 when the
// payload arena is full, -2 when id is out of range, -3 when id is already
// published. Space is reserved with a CAS loop so failed puts leak nothing.
int dds_put(void* h, int64_t id, const void* buf, int64_t nbytes) {
  Store* s = (Store*)h;
  if (id < 0 || id >= s->hdr->max_items) return -2;
  if (s->slots[id].state.load()) return -3;
  int64_t off = s->hdr->bump.load();
  do {
    if (off + nbytes > s->hdr->capacity) return -1;
  } while (!s->hdr->bump.compare_exchange_weak(off, off + nbytes));
  memcpy(s->payload + off, buf, (size_t)nbytes);
  s->slots[id].offset = off;
  s->slots[id].length = nbytes;
  s->slots[id].state.store(1);  // publish last
  s->hdr->num_items.fetch_add(1);
  return 0;
}

// Size of blob id, or -1 when absent.
int64_t dds_get_size(void* h, int64_t id) {
  Store* s = (Store*)h;
  if (id < 0 || id >= s->hdr->max_items || !s->slots[id].state.load())
    return -1;
  return s->slots[id].length;
}

// One-sided fetch (the DDStore get analog, distdataset.py:159-183).
// Copies at most nbytes into out; returns bytes copied or -1 when absent.
int64_t dds_get(void* h, int64_t id, void* out, int64_t nbytes) {
  Store* s = (Store*)h;
  if (id < 0 || id >= s->hdr->max_items || !s->slots[id].state.load())
    return -1;
  int64_t len = s->slots[id].length < nbytes ? s->slots[id].length : nbytes;
  memcpy(out, s->payload + s->slots[id].offset, (size_t)len);
  return len;
}

int64_t dds_count(void* h) { return ((Store*)h)->hdr->num_items.load(); }

int64_t dds_max_items(void* h) { return ((Store*)h)->hdr->max_items; }

int64_t dds_used_bytes(void* h) { return ((Store*)h)->hdr->bump.load(); }

// Epoch window fences (DDStore epoch_begin/end semantics; here the store is
// always resident so these only bump a counter readers can observe).
void dds_epoch_begin(void* h) { ((Store*)h)->hdr->epoch.fetch_add(1); }
void dds_epoch_end(void* h) {}

int64_t dds_epoch(void* h) { return ((Store*)h)->hdr->epoch.load(); }

void dds_close(void* h, int unlink_shm) {
  Store* s = (Store*)h;
  char name[256];
  strncpy(name, s->name, sizeof(name));
  munmap((void*)s->hdr, s->mapped);
  close(s->fd);
  if (unlink_shm) shm_unlink(name);
  delete s;
}

// ---------------------------------------------------------------------------
// Cross-host fetch plane (DCN). The reference DDStore serves datasets across
// nodes with MPI one-sided gets (distdataset.py:159-183); TPU pods have no
// MPI plane, so the remote path here is a tiny length-prefixed TCP protocol:
//   request  : int64 global_id
//   response : int64 nbytes (-1 when absent), then payload
// Each host serves its shm arena read-only (published slots only, acquire
// loads) and fetches other hosts' samples through persistent connections.
// ---------------------------------------------------------------------------

namespace {

bool read_full(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t r = write(fd, p, n);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

struct Server {
  Store* store;
  int64_t id_offset;  // global id of local slot 0
  int listen_fd;
  std::atomic<bool> stop;
  std::thread accept_thread;
  // live connection bookkeeping: dds_serve_stop shuts these sockets down
  // and waits for every connection thread to exit BEFORE the caller can
  // munmap the arena — no use-after-free on shutdown with in-flight peers
  std::mutex mu;
  std::vector<int> conns;
  std::atomic<int> live{0};
};

void serve_conn(Server* sv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int64_t gid;
  while (!sv->stop.load() && read_full(fd, &gid, sizeof(gid))) {
    Store* s = sv->store;
    int64_t id = gid - sv->id_offset;
    int64_t len = -1;
    const char* src = nullptr;
    if (id >= 0 && id < s->hdr->max_items &&
        s->slots[id].state.load(std::memory_order_acquire)) {
      len = s->slots[id].length;
      src = s->payload + s->slots[id].offset;
    }
    if (!write_full(fd, &len, sizeof(len))) break;
    if (len > 0 && !write_full(fd, src, (size_t)len)) break;
  }
  // deregister BEFORE close: once the fd number is released the kernel can
  // recycle it, and the stop sweep must never shutdown() a stranger's fd
  {
    std::lock_guard<std::mutex> lock(sv->mu);
    for (auto it = sv->conns.begin(); it != sv->conns.end(); ++it) {
      if (*it == fd) {
        sv->conns.erase(it);
        break;
      }
    }
  }
  close(fd);
  sv->live.fetch_sub(1);
}

struct Conn {
  int fd;
  std::vector<char> buf;
};

}  // namespace

// Serve this store's published slots on 0.0.0.0:port; ids received on the
// wire are global (local slot = id - id_offset). Returns an opaque server
// handle, or nullptr on bind failure.
void* dds_serve_start(void* h, int port, int64_t id_offset) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  Server* sv = new Server;
  sv->store = (Store*)h;
  sv->id_offset = id_offset;
  sv->listen_fd = fd;
  sv->stop.store(false);
  sv->accept_thread = std::thread([sv]() {
    while (!sv->stop.load()) {
      int c = accept(sv->listen_fd, nullptr, nullptr);
      if (c < 0) {
        if (errno == EINTR) continue;
        break;  // listen socket closed by dds_serve_stop
      }
      if (sv->stop.load()) {
        close(c);
        break;
      }
      {
        std::lock_guard<std::mutex> lock(sv->mu);
        sv->conns.push_back(c);
      }
      sv->live.fetch_add(1);
      std::thread(serve_conn, sv, c).detach();
    }
  });
  return sv;
}

// Blocks until every connection thread has exited, so the caller may
// safely dds_close (munmap) the store afterwards.
void dds_serve_stop(void* server) {
  Server* sv = (Server*)server;
  sv->stop.store(true);
  // shutdown unblocks accept(); close only after the accept thread exits,
  // so it can never accept() on a recycled fd number
  shutdown(sv->listen_fd, SHUT_RDWR);
  if (sv->accept_thread.joinable()) sv->accept_thread.join();
  close(sv->listen_fd);
  while (sv->live.load() > 0) {
    {
      std::lock_guard<std::mutex> lock(sv->mu);
      for (int fd : sv->conns) shutdown(fd, SHUT_RDWR);
    }
    usleep(1000);
  }
  delete sv;
}

namespace {

void set_fd_timeout(int fd, int timeout_ms) {
  // SO_RCVTIMEO/SO_SNDTIMEO make a blocked read/write (and, on Linux, a
  // blocked connect via SNDTIMEO) fail with EAGAIN after the deadline;
  // read_full/write_full then report a broken stream and the Python client
  // reconnects — a server that accepts but never responds can no longer
  // wedge the loader forever. 0 disables (historical blocking behavior).
  if (timeout_ms <= 0) return;
  timeval tv{};
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

}  // namespace

// Apply send/receive timeouts (milliseconds; <= 0 leaves the socket
// blocking) to an existing client connection.
void dds_set_timeout(void* conn, int timeout_ms) {
  set_fd_timeout(((Conn*)conn)->fd, timeout_ms);
}

// Persistent client connection to a serving host, with an optional
// connect/IO timeout applied to the socket AT CREATION (timeout_ms <= 0 =
// blocking, the historical behavior). Returns nullptr on connect failure.
void* dds_connect_t(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return nullptr;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    freeaddrinfo(res);
    return nullptr;
  }
  set_fd_timeout(fd, timeout_ms);  // bounds connect() too (SO_SNDTIMEO)
  if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    close(fd);
    freeaddrinfo(res);
    return nullptr;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  Conn* c = new Conn;
  c->fd = fd;
  return c;
}

void* dds_connect(const char* host, int port) {
  return dds_connect_t(host, port, 0);
}

// Fetch global id into the connection's scratch buffer. Returns the blob
// length, -1 when the server does not hold the id, -2 on a broken
// connection.
int64_t dds_fetch(void* conn, int64_t gid) {
  // sanity cap on the wire length: a desynced/corrupt stream must surface
  // as a recoverable broken-connection error, not a std::bad_alloc
  // terminating the process through the ctypes boundary
  constexpr int64_t kMaxFetchBytes = int64_t(1) << 33;  // 8 GiB
  Conn* c = (Conn*)conn;
  if (!write_full(c->fd, &gid, sizeof(gid))) return -2;
  int64_t len;
  if (!read_full(c->fd, &len, sizeof(len))) return -2;
  if (len == -1) return -1;
  if (len < 0 || len > kMaxFetchBytes) return -2;
  c->buf.resize((size_t)len);
  if (len > 0 && !read_full(c->fd, c->buf.data(), (size_t)len)) return -2;
  return len;
}

// Copy the last fetched payload out (up to nbytes); returns bytes copied.
int64_t dds_fetch_read(void* conn, void* out, int64_t nbytes) {
  Conn* c = (Conn*)conn;
  int64_t len =
      (int64_t)c->buf.size() < nbytes ? (int64_t)c->buf.size() : nbytes;
  memcpy(out, c->buf.data(), (size_t)len);
  return len;
}

void dds_disconnect(void* conn) {
  Conn* c = (Conn*)conn;
  close(c->fd);
  delete c;
}

}  // extern "C"
