// Shared-memory sample store — the TPU-host analog of ORNL's DDStore
// (reference: pyddstore used by hydragnn/utils/datasets/distdataset.py:1-183;
// a C++/MPI one-sided remote-memory object store holding datasets larger
// than a single process can). On TPU pods every host feeds only its own
// devices and datasets are sharded per host (data/columnar.py), so the
// cross-node MPI RMA plane collapses to an intra-host concern: many loader
// processes sharing one pinned copy of the samples. This store provides
// that: a POSIX shared-memory arena with a slot table indexed directly by
// sample id (ids are dense dataset indices, so lookup is O(1)), atomic
// space reservation with no partial-failure leaks, and epoch_begin/end
// fences kept API-compatible with DDStore's windowed access
// (train loop brackets: train_validate_test.py:480-563).
//
// Build: g++ -O3 -shared -fPIC -o _ddstore.so ddstore.cpp -lrt
// (driven by hydragnn_tpu/native/build.py; loaded via ctypes).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x44445354'2d545055ULL;  // "DDST-TPU"

struct Header {
  // Cross-process readiness flag: written last by the creator with release
  // ordering, checked by attachers with acquire — guarantees capacity /
  // max_items / slot states are visible once magic reads valid, even on
  // weakly-ordered CPUs.
  std::atomic<uint64_t> magic;
  int64_t capacity;    // payload bytes
  int64_t max_items;   // slot-table size; valid ids are [0, max_items)
  std::atomic<int64_t> bump;       // next free payload offset
  std::atomic<int64_t> num_items;  // successfully published items
  std::atomic<int64_t> epoch;      // epoch_begin/end counter
};

struct Slot {
  std::atomic<int64_t> state;  // 0 = empty, 1 = published (set last)
  int64_t offset;
  int64_t length;
};

struct Store {
  Header* hdr;
  Slot* slots;
  char* payload;
  size_t mapped;
  int fd;
  char name[256];
};

size_t total_bytes(int64_t capacity, int64_t max_items) {
  return sizeof(Header) + sizeof(Slot) * (size_t)max_items + (size_t)capacity;
}

}  // namespace

extern "C" {

// Remove a named store (explicit cleanup of stale segments from crashed
// runs). Returns 0 on success.
int dds_unlink(const char* name) { return shm_unlink(name); }

// Create (create=1, fails with nullptr when the name already exists — the
// caller decides whether to dds_unlink a stale segment first) or attach
// (create=0) a named store. Returns nullptr on failure.
void* dds_open(const char* name, int64_t capacity, int64_t max_items,
               int create) {
  int fd;
  size_t bytes = 0;
  if (create) {
    fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return nullptr;  // EEXIST: never clobber silently
    bytes = total_bytes(capacity, max_items);
    if (ftruncate(fd, (off_t)bytes) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return nullptr;
    struct stat st;
    if (fstat(fd, &st) != 0) {
      close(fd);
      return nullptr;
    }
    bytes = (size_t)st.st_size;
  }
  void* base = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store;
  s->hdr = (Header*)base;
  s->mapped = bytes;
  s->fd = fd;
  strncpy(s->name, name, sizeof(s->name) - 1);
  s->name[sizeof(s->name) - 1] = 0;
  if (create) {
    s->hdr->capacity = capacity;
    s->hdr->max_items = max_items;
    s->hdr->bump.store(0);
    s->hdr->num_items.store(0);
    s->hdr->epoch.store(0);
  } else if (s->hdr->magic.load(std::memory_order_acquire) != kMagic) {
    munmap(base, bytes);
    close(fd);
    delete s;
    return nullptr;
  }
  s->slots = (Slot*)((char*)base + sizeof(Header));
  s->payload =
      (char*)base + sizeof(Header) + sizeof(Slot) * (size_t)s->hdr->max_items;
  if (create) {
    for (int64_t i = 0; i < max_items; ++i) s->slots[i].state.store(0);
    // publish header last: attachers acquire-check magic
    s->hdr->magic.store(kMagic, std::memory_order_release);
  }
  return s;
}

// Store a blob under id in [0, max_items). Returns 0 on success, -1 when the
// payload arena is full, -2 when id is out of range, -3 when id is already
// published. Space is reserved with a CAS loop so failed puts leak nothing.
int dds_put(void* h, int64_t id, const void* buf, int64_t nbytes) {
  Store* s = (Store*)h;
  if (id < 0 || id >= s->hdr->max_items) return -2;
  if (s->slots[id].state.load()) return -3;
  int64_t off = s->hdr->bump.load();
  do {
    if (off + nbytes > s->hdr->capacity) return -1;
  } while (!s->hdr->bump.compare_exchange_weak(off, off + nbytes));
  memcpy(s->payload + off, buf, (size_t)nbytes);
  s->slots[id].offset = off;
  s->slots[id].length = nbytes;
  s->slots[id].state.store(1);  // publish last
  s->hdr->num_items.fetch_add(1);
  return 0;
}

// Size of blob id, or -1 when absent.
int64_t dds_get_size(void* h, int64_t id) {
  Store* s = (Store*)h;
  if (id < 0 || id >= s->hdr->max_items || !s->slots[id].state.load())
    return -1;
  return s->slots[id].length;
}

// One-sided fetch (the DDStore get analog, distdataset.py:159-183).
// Copies at most nbytes into out; returns bytes copied or -1 when absent.
int64_t dds_get(void* h, int64_t id, void* out, int64_t nbytes) {
  Store* s = (Store*)h;
  if (id < 0 || id >= s->hdr->max_items || !s->slots[id].state.load())
    return -1;
  int64_t len = s->slots[id].length < nbytes ? s->slots[id].length : nbytes;
  memcpy(out, s->payload + s->slots[id].offset, (size_t)len);
  return len;
}

int64_t dds_count(void* h) { return ((Store*)h)->hdr->num_items.load(); }

int64_t dds_max_items(void* h) { return ((Store*)h)->hdr->max_items; }

int64_t dds_used_bytes(void* h) { return ((Store*)h)->hdr->bump.load(); }

// Epoch window fences (DDStore epoch_begin/end semantics; here the store is
// always resident so these only bump a counter readers can observe).
void dds_epoch_begin(void* h) { ((Store*)h)->hdr->epoch.fetch_add(1); }
void dds_epoch_end(void* h) {}

int64_t dds_epoch(void* h) { return ((Store*)h)->hdr->epoch.load(); }

void dds_close(void* h, int unlink_shm) {
  Store* s = (Store*)h;
  char name[256];
  strncpy(name, s->name, sizeof(name));
  munmap((void*)s->hdr, s->mapped);
  close(s->fd);
  if (unlink_shm) shm_unlink(name);
  delete s;
}

}  // extern "C"
