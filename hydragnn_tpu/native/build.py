"""Build the native C++ components with g++ (cached .so next to the source).

The reference links against prebuilt C++ libraries (ADIOS2, pyddstore,
GPTL — SURVEY §2.3); here the native runtime pieces are compiled on first
use from the sources in this directory. Rebuilds are keyed on a hash of the
source content (stored in ``_<name>.so.hash``), not file mtimes — git does
not preserve mtimes on checkout, so an mtime check could skip a needed
rebuild or trust a foreign binary after a fresh clone.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_lock = threading.Lock()
_HERE = os.path.dirname(os.path.abspath(__file__))


def _source_digest(src: str) -> str:
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def build_library(name: str = "ddstore") -> str:
    """Compile ``<name>.cpp`` -> ``_<name>.so`` if missing/stale; return path."""
    src = os.path.join(_HERE, f"{name}.cpp")
    out = os.path.join(_HERE, f"_{name}.so")
    stamp = out + ".hash"
    digest = _source_digest(src)
    with _lock:
        if os.path.exists(out) and os.path.exists(stamp):
            with open(stamp) as f:
                if f.read().strip() == digest:
                    return out
        cmd = [
            "g++",
            "-O3",
            "-std=c++17",
            "-shared",
            "-fPIC",
            "-o",
            out,
            src,
            "-lrt",
            "-pthread",
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except FileNotFoundError as e:
            raise RuntimeError("g++ not available to build native library") from e
        except subprocess.CalledProcessError as e:
            raise RuntimeError(f"native build failed:\n{e.stderr}") from e
        with open(stamp, "w") as f:
            f.write(digest)
    return out


def build_executable(name: str = "launcher") -> str:
    """Compile ``<name>.cpp`` -> a standalone binary (e.g. the
    ``hydragnn-launch`` multi-host bootstrap); return its path. Same
    content-hash staleness rule as ``build_library``."""
    src = os.path.join(_HERE, f"{name}.cpp")
    out = os.path.join(
        _HERE, "hydragnn-launch" if name == "launcher" else f"_{name}"
    )
    stamp = out + ".hash"
    digest = _source_digest(src)
    with _lock:
        if os.path.exists(out) and os.path.exists(stamp):
            with open(stamp) as f:
                if f.read().strip() == digest:
                    return out
        cmd = ["g++", "-O3", "-std=c++17", "-o", out, src]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except FileNotFoundError as e:
            raise RuntimeError("g++ not available to build native launcher") from e
        except subprocess.CalledProcessError as e:
            raise RuntimeError(f"native build failed:\n{e.stderr}") from e
        with open(stamp, "w") as f:
            f.write(digest)
    return out
