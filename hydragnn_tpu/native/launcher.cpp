// hydragnn-launch — native multi-host bootstrap for the JAX runtime.
//
// The reference boots its distributed runtime in Python: setup_ddp reads
// OMPI/SLURM world envs, discovers the master address from LSB_HOSTS or a
// SLURM nodelist regex, and calls dist.init_process_group
// (hydragnn/utils/distributed/distributed.py:52-198). SURVEY §2.3 item 1
// marks that host-side bootstrap as the piece to implement natively. This
// is that piece for the TPU stack: it resolves (world_size, rank,
// coordinator) BEFORE any Python starts, exports the contract
// hydragnn_tpu.parallel.setup_distributed() consumes
// (HYDRAGNN_COORDINATOR + WORLD_SIZE/RANK), and execs the training
// command — so every interpreter boots already knowing its place, with no
// Python-side scheduler sniffing in the hot path.
//
// Two modes:
//   scheduler mode (default): world info comes from SLURM_*/OMPI_*/
//     WORLD_SIZE envs (one launcher per scheduler-spawned task, like
//     `srun hydragnn-launch -- python train.py ...`); the coordinator is
//     --coordinator/HYDRAGNN_COORDINATOR, or the first host of
//     SLURM_JOB_NODELIST (bracket ranges expanded, the distributed.py
//     master-addr discovery), port HYDRAGNN_MASTER_PORT or 12355.
//   local fan-out (--nprocs N): fork/exec N ranks on this host with a
//     free loopback port as coordinator (the torchrun analog; also how CI
//     exercises multi-process rendezvous without a scheduler). Signals
//     forward to the children; exit code is the first nonzero child rc.
//
// Build: hydragnn_tpu.native.build.build_executable("launcher") or
//   g++ -O3 -std=c++17 -o hydragnn-launch launcher.cpp
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

const char* getenv_or(const char* key, const char* fallback) {
  const char* v = std::getenv(key);
  return (v && *v) ? v : fallback;
}

// Expand the FIRST hostname of a SLURM nodelist: "frontier[0007-0010,0act12]"
// -> "frontier0007"; "nid001,nid002" -> "nid001"; bare names pass through.
// Only the first host matters (it hosts the coordinator), so a full
// expansion is unnecessary.
std::string first_host(const std::string& nodelist) {
  std::string prefix, body;
  size_t lb = nodelist.find('[');
  size_t comma = nodelist.find(',');
  if (lb == std::string::npos || (comma != std::string::npos && comma < lb)) {
    // no bracket in the first item: take up to the first top-level comma
    return nodelist.substr(0, comma == std::string::npos ? nodelist.size()
                                                         : comma);
  }
  prefix = nodelist.substr(0, lb);
  size_t rb = nodelist.find(']', lb);
  body = nodelist.substr(lb + 1, rb == std::string::npos
                                     ? std::string::npos
                                     : rb - lb - 1);
  // first range item, left endpoint, zero padding preserved
  size_t end = body.find_first_of(",-");
  return prefix + body.substr(0, end);
}

// (world_size, rank) from scheduler envs — same precedence as
// hydragnn_tpu.parallel.mesh._scheduler_host_info.
bool world_from_env(int* size, int* rank) {
  struct {
    const char *size_key, *rank_key;
  } pairs[] = {
      {"SLURM_NTASKS", "SLURM_PROCID"},
      {"OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK"},
      {"WORLD_SIZE", "RANK"},
  };
  for (auto& p : pairs) {
    const char* s = std::getenv(p.size_key);
    if (s && *s) {
      *size = std::atoi(s);
      *rank = std::atoi(getenv_or(p.rank_key, "0"));
      return true;
    }
  }
  return false;
}

int free_loopback_port() {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 12355;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  socklen_t len = sizeof(addr);
  int port = 12355;
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0 &&
      getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len) == 0) {
    port = ntohs(addr.sin_port);
  }
  close(fd);  // freed port may race another bind; jax retries rendezvous
  return port;
}

std::vector<pid_t> g_children;

void forward_signal(int sig) {
  // reaped entries are set to -1; skip them (a recycled PID could belong to
  // an unrelated process, and kill(0/-1, ...) would signal the whole group)
  for (pid_t pid : g_children) {
    if (pid > 0) kill(pid, sig);
  }
}

int run_local_fanout(int nprocs, char** cmd) {
  int port = free_loopback_port();
  char coord[64];
  std::snprintf(coord, sizeof(coord), "127.0.0.1:%d", port);
  for (int rank = 0; rank < nprocs; ++rank) {
    pid_t pid = fork();
    if (pid < 0) {
      std::perror("hydragnn-launch: fork");
      forward_signal(SIGTERM);
      return 1;
    }
    if (pid == 0) {
      char ws[16], rk[16];
      std::snprintf(ws, sizeof(ws), "%d", nprocs);
      std::snprintf(rk, sizeof(rk), "%d", rank);
      setenv("HYDRAGNN_COORDINATOR", coord, 1);
      setenv("WORLD_SIZE", ws, 1);
      setenv("RANK", rk, 1);
      execvp(cmd[0], cmd);
      std::perror("hydragnn-launch: execvp");
      _exit(127);
    }
    g_children.push_back(pid);
  }
  std::signal(SIGINT, forward_signal);
  std::signal(SIGTERM, forward_signal);
  // Reap in COMPLETION order, not rank order: if rank k>0 crashes while
  // rank 0 hangs in a collective waiting for it, a rank-ordered
  // waitpid(pid_0) would block forever and never fire the group SIGTERM.
  int first_fail = 0;
  size_t reaped = 0;
  while (reaped < g_children.size()) {
    int status = 0;
    pid_t pid = waitpid(-1, &status, 0);
    if (pid < 0) {
      if (errno == EINTR) continue;
      break;  // ECHILD: no children left to reap
    }
    int rank = -1;
    for (size_t k = 0; k < g_children.size(); ++k) {
      if (g_children[k] == pid) { rank = static_cast<int>(k); break; }
    }
    if (rank < 0) continue;  // not one of ours (shouldn't happen)
    g_children[rank] = -1;  // dead: never signal this (recyclable) PID again
    ++reaped;
    int rc = WIFEXITED(status) ? WEXITSTATUS(status)
                               : 128 + WTERMSIG(status);
    if (rc != 0 && first_fail == 0) {
      first_fail = rc;
      std::fprintf(stderr,
                   "hydragnn-launch: rank %d exited rc=%d; "
                   "terminating remaining ranks\n", rank, rc);
      // one failed rank dooms the rendezvous group: take the rest down
      // instead of letting them hang in collectives
      forward_signal(SIGTERM);
    }
  }
  return first_fail;
}

int run_scheduler_mode(const char* coordinator, char** cmd) {
  int size = 1, rank = 0;
  if (!world_from_env(&size, &rank)) {
    std::fprintf(stderr,
                 "hydragnn-launch: no scheduler world envs "
                 "(SLURM_NTASKS/OMPI_COMM_WORLD_SIZE/WORLD_SIZE); "
                 "running single-process\n");
  }
  std::string coord;
  if (coordinator && *coordinator) {
    coord = coordinator;
  } else if (const char* env = std::getenv("HYDRAGNN_COORDINATOR");
             env && *env) {
    coord = env;
  } else if (const char* nodes = std::getenv("SLURM_JOB_NODELIST");
             nodes && *nodes) {
    coord = first_host(nodes) + ":" + getenv_or("HYDRAGNN_MASTER_PORT",
                                                "12355");
  }
  if (!coord.empty()) setenv("HYDRAGNN_COORDINATOR", coord.c_str(), 1);
  char ws[16], rk[16];
  std::snprintf(ws, sizeof(ws), "%d", size);
  std::snprintf(rk, sizeof(rk), "%d", rank);
  setenv("WORLD_SIZE", ws, 1);
  setenv("RANK", rk, 1);
  execvp(cmd[0], cmd);
  std::perror("hydragnn-launch: execvp");
  return 127;
}

}  // namespace

int main(int argc, char** argv) {
  int nprocs = 0;
  const char* coordinator = nullptr;
  int i = 1;
  for (; i < argc; ++i) {
    if (std::strcmp(argv[i], "--") == 0) {
      ++i;
      break;
    }
    if (std::strcmp(argv[i], "--nprocs") == 0 && i + 1 < argc) {
      nprocs = std::atoi(argv[++i]);
    } else if (std::strcmp(argv[i], "--coordinator") == 0 && i + 1 < argc) {
      coordinator = argv[++i];
    } else if (std::strcmp(argv[i], "--help") == 0) {
      std::fprintf(
          stderr,
          "usage: hydragnn-launch [--nprocs N] [--coordinator HOST:PORT] "
          "-- cmd args...\n"
          "  --nprocs N   fork N local ranks with a loopback coordinator\n"
          "  (default)    scheduler mode: world from SLURM/OMPI/WORLD_SIZE "
          "envs, coordinator from --coordinator/HYDRAGNN_COORDINATOR/"
          "SLURM_JOB_NODELIST\n");
      return 2;
    } else {
      break;  // first non-flag token starts the command
    }
  }
  if (i >= argc) {
    std::fprintf(stderr, "hydragnn-launch: no command given (see --help)\n");
    return 2;
  }
  char** cmd = argv + i;
  if (nprocs > 0) return run_local_fanout(nprocs, cmd);
  return run_scheduler_mode(coordinator, cmd);
}
