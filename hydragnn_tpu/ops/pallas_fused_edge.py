"""Pallas TPU kernel: fused gather -> edge dense -> sorted-segment sum.

The EGNN edge hot path (models/egnn.py EGCL, via layers.hoisted_pair_dense)
is three HBM round-trips today even with the sorted-segment MXU kernel:

    pre  = Dense_r(x)[recv] + Dense_s(x)[send] + edge terms   # [E, C] write
    msg  = relu(Dense_2(relu(pre)))                           # [E, C] rw
    agg  = sorted_segment_sum(msg, recv)                      # [E, C] read

At the SC25 production shape ([E, 866] ~ 12.8 MB per intermediate at batch
32) the r5 trace shows ~78% of the step stalled on non-dot time around
exactly these arrays (docs/PERFORMANCE.md). This kernel keeps the whole
chain VMEM-resident: per-edge messages never touch HBM.

It extends the ``sorted_segment_sum`` grid/blocking scheme
(ops/pallas_segment.py — ``row_starts``/scalar-prefetch ``estart`` windows
over receiver-sorted edges) with a weights operand and in-kernel dots:

- grid ``(C_blocks, row_blocks j, K)``; for output row-block ``j`` the K
  inner steps stream the edge windows that can touch its rows (bounded by
  ``Nb * max_degree``), revisiting the output block as a reduction
  accumulator — unchanged from the segment-sum kernel;
- the *receiver gather runs in-kernel*: the same in-register one-hot
  ``mine = (ids == j*Nb + iota)`` that scatters messages also GATHERS the
  receiver-projected node rows, as ``mine @ node_recv_block`` on the MXU
  (one-hot rows copy exactly one node row per edge, exact in any dtype).
  Edges owned by other row blocks get a zero gather row — harmless, since
  the same one-hot zeroes their contribution on the way out;
- senders are NOT sorted, so the sender-side gather (plus the small
  edge-local projections: length, edge_attr) stays an XLA gather fused
  into ONE edge-aligned operand ``edge_in`` — XLA gathers are fast on TPU
  and this is the only [E, C] array the fused path ever materializes;
- per step: ``pre = mine @ nrecv + ein``; ``msg = relu(relu(pre) @ W + b)``
  ([Eb, Ci] x [Ci, Cb] on the MXU); ``acc += mine.T @ msg``. The edge
  dense is recomputed for every row block whose windows cover the edge
  block — a ``K*Eb/(Nb*avg_degree)`` redundancy factor (~1.3x at the
  production shape), paid in MXU FLOPs that were previously stalled on
  HBM anyway.

Differentiation: ``jax.custom_jvp`` whose tangent rule is PLAIN jnp (the
dense reference implementation pushed through ``jax.jvp``). Only the
primal ever runs the Pallas kernel, so reverse-mode falls out by
transposing jnp ops (segment-sum VJP is a gather; dense VJP is two
matmuls) and the op composes under ``jax.grad`` to ANY order — unlike
``jax.custom_vjp``, which is first-order only and forced the grad-energy
guard the r5 round shipped (config/config.py). Call sites should wrap the
op in ``jax.checkpoint`` (models/layers.py does) so the tangent-rule
residuals are recomputed in the backward instead of re-materialized in
the forward, keeping the training forward VMEM-resident too.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_segment import _pad_to


def reference_edge_message_sum(
    node_recv, edge_in, weights, bias, segment_ids, num_segments
):
    """Dense (plain-jnp) statement of the fused computation — the off-TPU
    fallback, the tangent rule, and the identity oracle for tests:

        segment_sum(relu(relu(node_recv[ids] + edge_in) @ weights + bias))
    """
    pre = node_recv[segment_ids] + edge_in
    msg = jax.nn.relu(jnp.dot(jax.nn.relu(pre), weights) + bias)
    return jax.ops.segment_sum(msg, segment_ids, num_segments=num_segments)


def _kernel(estart_ref, ids_ref, nrecv_ref, ein_ref, w_ref, b_ref, out_ref):
    j = pl.program_id(1)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    nb = out_ref.shape[0]
    dtype = ein_ref.dtype
    # in-register one-hot: edge e belongs to local row r iff its receiver id
    # equals j*Nb + r; padding edges carry id -1 and never match
    rows = j * nb + jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1)
    mine = (ids_ref[:] == rows).astype(dtype)  # [Eb, Nb]
    # in-kernel receiver gather: each one-hot row copies exactly one row of
    # the receiver-projected node block (exact in any dtype — the f32
    # accumulation sums a single product 1.0 * x). Unowned/padding edges get
    # a zero row; their messages are zeroed by the same one-hot below.
    pre = jax.lax.dot_general(
        mine,
        nrecv_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dtype) + ein_ref[:]
    h = jnp.maximum(pre, jnp.zeros((), dtype))
    lin = jax.lax.dot_general(
        h,
        w_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + b_ref[:].astype(jnp.float32)
    # round the message to the streaming dtype before accumulating, matching
    # the dense route (flax Dense emits operand-dtype outputs; the segment
    # accumulation stays f32 via preferred_element_type)
    msg = jnp.maximum(lin, 0.0).astype(dtype)
    out_ref[:] += jax.lax.dot_general(
        mine,
        msg,
        (((0,), (0,)), ((), ())),  # contract over the edge axis
        preferred_element_type=jnp.float32,
    )


# tuned-table key component (tune/table.py): bump on any change to the
# kernel's schedule, block layout, or semantics — stale tuned entries must
# miss, not steer a different program
KERNEL_VERSION = 1


def normalize_tiles(
    ci, co, dtype,
    block_rows=128, block_edges=512, block_cols=512,
):
    """Clamp a candidate tile plan to what ``_forward`` will actually run —
    the one clamp site, shared by the kernel, the routing layer (so nondiff
    specialization args are pre-clamped) and the tune plane's table keys
    (tune/plans.py).

    Channel padding: input width streams whole (the dense contracts over
    it). Output width: ONE block when it fits a lane-aligned <=1024 tile
    (the production hidden 866 -> 896, no pad waste and no re-streaming
    of the edge operand per output block); otherwise block_cols-blocks.
    """
    nb, eb = block_rows, block_edges
    ci_pad = ci + (-ci) % 128
    co128 = co + (-co) % 128
    cb = co128 if co128 <= 1024 else min(block_cols, co128)

    # VMEM fit: shrink the edge window until the resident working set —
    # double-buffered streams, weights, f32 accumulator, and the dense
    # intermediates (pre/h/msg live in VMEM scratch) — fits comfortably.
    # Redundant-recompute cost is eb-invariant (K ~ Nb*max_degree/eb, so
    # K*Eb is ~constant), which makes shrinking eb nearly free.
    itemsize = jnp.dtype(dtype).itemsize

    def _vmem_estimate(eb_):
        return (
            2 * eb_ * ci_pad * itemsize      # edge_in stream
            + 2 * nb * ci_pad * itemsize     # node_recv block
            + 2 * ci_pad * cb * itemsize     # weights block
            + nb * cb * 4                    # f32 accumulator
            + eb_ * ci_pad * 4               # pre (f32 dot output)
            + eb_ * ci_pad * itemsize        # h
            + 2 * eb_ * cb * 4               # lin + msg
        )

    while eb > 128 and _vmem_estimate(eb) > 12 * 1024 * 1024:
        eb //= 2
    return nb, eb, cb


def _forward(
    node_recv, edge_in, weights, bias, segment_ids, num_segments, max_degree,
    block_rows, block_edges, block_cols, interpret,
):
    e, ci = edge_in.shape
    ci_w, co = weights.shape
    assert ci_w == ci, (ci_w, ci)
    assert node_recv.shape[1] == ci, (node_recv.shape, ci)
    dtype = edge_in.dtype
    ci_pad = ci + (-ci) % 128
    nb, eb, cb = normalize_tiles(
        ci, co, dtype, block_rows, block_edges, block_cols,
    )
    ids = segment_ids.astype(jnp.int32)
    ein = _pad_to(_pad_to(edge_in, eb, 0), 128, 1)
    nrecv = _pad_to(_pad_to(node_recv, nb, 0), 128, 1)
    w = _pad_to(_pad_to(weights, 128, 0), cb, 1)
    b = _pad_to(bias.reshape(1, -1), cb, 1)
    assert ein.shape[1] == ci_pad and w.shape[0] == ci_pad
    n_pad = nrecv.shape[0]
    co_pad = w.shape[1]

    # K inner windows cover the worst legal row block (degree-capped), +1
    # for edge-block misalignment; trailing zero blocks so estart[j] + k is
    # always in range (same scheme as pallas_segment._forward)
    k_windows = (nb * max_degree + eb - 1) // eb + 1
    k_windows = min(k_windows, ein.shape[0] // eb)
    k_windows = max(k_windows, 1)
    ein = jnp.pad(ein, ((0, k_windows * eb), (0, 0)))
    e_pad = ein.shape[0]

    ids_col = jnp.full((e_pad, 1), -1, jnp.int32).at[:e, 0].set(ids)

    j_blocks = n_pad // nb
    row_starts = jnp.searchsorted(
        ids, jnp.arange(j_blocks, dtype=jnp.int32) * nb, side="left"
    ).astype(jnp.int32)
    estart_block = row_starts // eb

    def ids_index(c_i, j, k, estart):
        return (estart[j] + k, 0)

    def nrecv_index(c_i, j, k, estart):
        return (j, 0)

    def ein_index(c_i, j, k, estart):
        return (estart[j] + k, 0)

    def w_index(c_i, j, k, estart):
        return (0, c_i)

    def b_index(c_i, j, k, estart):
        return (0, c_i)

    def out_index(c_i, j, k, estart):
        return (j, c_i)

    grid = (co_pad // cb, j_blocks, k_windows)
    out = pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((eb, 1), ids_index),
                pl.BlockSpec((nb, nrecv.shape[1]), nrecv_index),
                pl.BlockSpec((eb, ein.shape[1]), ein_index),
                pl.BlockSpec((w.shape[0], cb), w_index),
                pl.BlockSpec((1, cb), b_index),
            ],
            out_specs=pl.BlockSpec((nb, cb), out_index),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, co_pad), jnp.float32),
        interpret=interpret,
    )(estart_block, ids_col, nrecv, ein, w, b)
    return out[:num_segments, :co].astype(dtype)


@functools.partial(jax.custom_jvp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def fused_edge_message_sum(
    node_recv,
    edge_in,
    weights,
    bias,
    segment_ids,
    num_segments: int,
    max_degree: int = 32,
    block_rows: int = 128,
    block_edges: int = 512,
    block_cols: int = 512,
    interpret: bool = False,
):
    """Fused ``segment_sum(relu(relu(node_recv[ids] + edge_in) @ W + b))``
    for receiver-sorted edges, VMEM-resident end to end.

    ``segment_ids`` MUST be ascending and ``node_recv`` must span exactly
    the ``num_segments`` nodes the ids index. Segments holding more than
    ``max_degree`` edges get an UNSPECIFIED value, exactly like
    ``sorted_segment_sum`` — and, same as there, the spill can also starve
    LATER segments inside the same ``block_rows`` row block (their edges
    get pushed past the K streamed windows; subsequent row blocks are
    unaffected, since each gets its own ``estart``). The framework's
    batches satisfy this by construction: real in-degrees are capped, and
    the only over-cap segment is the FINAL dummy node, with no rows after
    it (data/graph.py padding docs). NOTE the dummy node's row is garbage
    rather than zero here (padding-edge messages are relu(bias)-shaped,
    not maskable pre-kernel) — same "mask downstream" contract, asserted
    at the model level by tests/test_fused_edge.py.

    Returns ``[num_segments, co]`` in the operand dtype; accumulation is
    f32 throughout. Differentiable to arbitrary order (custom-JVP with a
    plain-jnp tangent), so energy-force (grad-of-grad) training composes.
    """
    return _forward(
        node_recv, edge_in, weights, bias, segment_ids, num_segments,
        max_degree, block_rows, block_edges, block_cols, interpret,
    )


@fused_edge_message_sum.defjvp
def _fused_jvp(
    num_segments, max_degree, block_rows, block_edges, block_cols, interpret,
    primals, tangents,
):
    node_recv, edge_in, weights, bias, segment_ids = primals
    t_nr, t_ei, t_w, t_b, _ = tangents
    out = fused_edge_message_sum(
        node_recv, edge_in, weights, bias, segment_ids, num_segments,
        max_degree, block_rows, block_edges, block_cols, interpret,
    )
    # tangent in PLAIN jnp: linear in the tangents, built from transposable
    # primitives, differentiable to any order — reverse mode transposes it
    # into the gather + two-matmul VJP, and grad-of-grad just differentiates
    # this rule again. The primal-dependent residuals (relu masks, pre) are
    # what jax.checkpoint at the call site pushes into the backward.
    fn = lambda nr, ei, w, b: reference_edge_message_sum(
        nr, ei, w, b, segment_ids, num_segments
    )
    _, t_out = jax.jvp(
        fn, (node_recv, edge_in, weights, bias), (t_nr, t_ei, t_w, t_b)
    )
    return out, t_out
