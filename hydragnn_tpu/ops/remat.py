"""Tuned rematerialization policies (``Training.remat_policy``).

Through PR 10 every remat decision was a scattered bare ``jax.checkpoint``:
the fused-edge kernel call (models/layers.py ``_FusedEdgeDense``), the GPS
flash-attention call (models/gps.py), and — when
``Training.conv_checkpointing`` is on — the whole loss function
(train/loop.py, parallel/dp.py, parallel/branch.py). Bare checkpoint is the
maximal policy: recompute EVERYTHING inside the wrapped region during the
backward. That is the right default for the kernel call sites (their whole
point is keeping [E, C] tangent residuals out of the forward), but it is a
blunt instrument for the whole-loss wrap: recomputing the Pallas kernels
themselves in the backward re-pays their launch + redundant-revisit cost
when saving just their (node-sized, already-HBM-resident) outputs would do.

``Training.remat_policy`` names the policy once and applies it everywhere a
remat wrap happens:

- ``full`` (default — today's per-call behavior): bare ``jax.checkpoint``,
  recompute everything;
- ``dots``: ``jax.checkpoint_policies.checkpoint_dots`` — save matmul
  outputs, recompute the elementwise chains between them;
- ``names``: ``jax.checkpoint_policies.save_only_these_names`` over the
  kernel outputs tagged below — the Pallas kernels run ONCE (forward),
  their node-sized outputs are saved, and everything else inside the wrap
  is recomputed. The tuned point for kernel-heavy message paths;
- ``none``: kernel call sites are left unwrapped (save everything); the
  whole-loss ``conv_checkpointing`` wrap degrades to ``full`` (asking for
  conv checkpointing and no-remat at once is a contradiction — the
  checkpoint must exist for the flag to mean anything).

The policy is surfaced in the compile plane's report next to the flops/MFU
accounting (train/compile_plane.py) so a banked bench cell always records
which recompute schedule its FLOP count was measured under — remat changes
XLA's counted FLOPs, and an A/B across policies is meaningless without it.
"""

from __future__ import annotations

import jax

REMAT_POLICIES = ("none", "dots", "names", "full")

# checkpoint_name tags planted on the Pallas kernel outputs at their call
# sites — the save set of the ``names`` policy. One tuple so the policy and
# the tags can never drift apart.
KERNEL_OUTPUT_NAMES = (
    "fused_edge_sum",      # models/layers.py _FusedEdgeDense
    "multi_agg_moments",   # models/pna.py pna_aggregate (multi-agg route)
    "flash_attention_out", # models/gps.py flash attention
)


def tag(x, name: str):
    """Tag a kernel output (array or pytree) for ``save_only_these_names``.
    A no-op unless the surrounding ``jax.checkpoint`` runs the ``names``
    policy, so call sites tag unconditionally."""
    from jax.ad_checkpoint import checkpoint_name

    return jax.tree_util.tree_map(lambda v: checkpoint_name(v, name), x)


def _policy_of(policy: str):
    if policy not in REMAT_POLICIES:
        raise ValueError(
            f"remat_policy {policy!r} must be one of {REMAT_POLICIES}"
        )
    if policy == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if policy == "names":
        return jax.checkpoint_policies.save_only_these_names(
            *KERNEL_OUTPUT_NAMES
        )
    return None  # none / full: no policy object


def kernel_remat(fn, policy: str = "full"):
    """Remat wrap for a Pallas-kernel call site. ``none`` leaves the call
    unwrapped (store residuals); every other policy checkpoints with the
    corresponding save rule."""
    if policy == "none":
        return fn
    pol = _policy_of(policy)
    return jax.checkpoint(fn, policy=pol) if pol is not None else jax.checkpoint(fn)


def loss_remat(fn, policy: str = "full"):
    """Remat wrap for the whole-loss ``conv_checkpointing`` sites. ``none``
    and ``full`` keep today's bare checkpoint (the flag asked for a
    checkpoint; ``none`` only relaxes the kernel call sites)."""
    pol = _policy_of(policy)
    return jax.checkpoint(fn, policy=pol) if pol is not None else jax.checkpoint(fn)
