"""Pallas TPU kernel: fused gather -> edge message -> multi-moment reduction.

The PNA family's message path was the largest piece of MFU headroom left
behind by the r6 fused edge kernel: PNA/PNAPlus/PNAEq aggregate every edge
message FOUR ways (mean/min/max/std, models/pna.py pna_aggregate), and the
r6 decision record argued fusion was pointless because "min/max/std need
the full [E, C] message array in HBM regardless". That premise only holds
for single-output kernels. This kernel is multi-output: one launch over the
receiver-sorted edge windows emits per-node

    (sum, min, max, sum-of-squares)

in a single pass — the same online-statistics trick the flash-attention
kernel uses for its softmax (m, l) running stats, applied to the PNA
moments — so the per-edge messages never round-trip HBM at all. mean and
std derive in plain jnp outside (std via the zero-clamped E[x²]−E[x]²
form; the count is a [E]-read segment count, negligible traffic).

It extends the sorted-edge grid/``estart`` scheme of
``ops/pallas_fused_edge.py``:

- grid ``(C_blocks, row_blocks j, K)``; for output row-block ``j`` the K
  inner steps stream the edge windows that can touch its rows (bounded by
  ``Nb * max_degree``), revisiting all four output blocks as reduction
  accumulators (sum/sumsq init 0, min/max init +/-FLT_MAX at k==0);
- the *receiver gather runs in-kernel*: the one-hot
  ``mine = (ids == j*Nb + iota)`` that scatters the moments also GATHERS
  the receiver-projected node rows as ``mine @ node_recv_block`` on the
  MXU (PNA's pre-MLP is pre_layers=1, already distributed over the concat
  by ``hoisted_pair_dense`` — so the whole message is
  ``node_recv[recv] + edge_in`` (optionally ``* gate`` for PNAPlus's
  Hadamard rbf gate), no weights operand needed);
- senders are unsorted, so the sender projection plus edge-local terms
  stay ONE XLA-gathered edge-aligned operand ``edge_in`` — the only
  [E, C] array the fused path materializes (PNAPlus adds the [E, C]
  ``gate``; PNAEq passes its post-MLP message as ``edge_in`` directly and
  skips the in-kernel gather);
- sum and sumsq accumulate as ``mine.T @ msg`` MXU contractions
  (f32 accumulation); min/max have no matmul form, so they reduce on the
  VPU in ``chunk_edges``-sized sub-windows via a masked 3D where
  ([chunk, Nb, Cb] resident in VMEM) — VPU cycles that were previously
  stalled on the four separate [E, C] HBM traversals.

Differentiation: ``jax.custom_jvp`` whose tangent rule is the PLAIN-jnp
dense reference pushed through ``jax.jvp`` — the recompute schedule
ROADMAP item 4 asked for: the backward re-derives the edge messages from
the gathered inputs (a gather + elementwise + segment ops, all
XLA-native) instead of loading stored [E, C] residuals, and because no
Pallas call appears on a tangent path the op composes under ``jax.grad``
to ANY order (energy-force grad-of-grad included). Call sites wrap the op
per ``Training.remat_policy`` (ops/remat.py) so the tangent residuals are
recomputed in the backward rather than materialized in the forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_segment import _pad_to

# min/max accumulator sentinel: large enough that no real message reaches
# it, small enough that +/-_BIG survives an f32 round-trip exactly
_BIG = 3.0e38


def reference_multi_agg(node_recv, edge_in, gate, segment_ids, num_segments,
                        mask=None):
    """Dense (plain-jnp) statement of the fused computation — the off-TPU
    fallback, the custom-JVP tangent rule, and the identity oracle for
    tests. Per-edge message ``m = (node_recv[ids] + edge_in) * gate`` with
    ``node_recv``/``gate`` optional (None); returns the five f32 moments

        (sum, count, min, max, sumsq)

    each ``[num_segments, C]`` (count ``[num_segments]``), with empty
    segments fixed to 0 in min/max (the torch_scatter convention the
    dense ``segment_min``/``segment_max`` already follow). All moments
    accumulate in f32 regardless of the message dtype — bf16 sumsq would
    otherwise lose exactly the low bits the std's E[x²]−E[x]² subtraction
    needs (ops/segment.py segment_std carries the same guard)."""
    msg = edge_in if node_recv is None else node_recv[segment_ids] + edge_in
    if gate is not None:
        msg = msg * gate
    msg = msg.astype(jnp.float32)
    ones = jnp.ones(segment_ids.shape[:1], jnp.float32)
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (msg.ndim - mask.ndim))
        msg_0 = jnp.where(m, msg, 0.0)
        msg_lo = jnp.where(m, msg, _BIG)
        msg_hi = jnp.where(m, msg, -_BIG)
        ones = jnp.where(mask, ones, 0.0)
    else:
        msg_0 = msg_lo = msg_hi = msg
    s = jax.ops.segment_sum(msg_0, segment_ids, num_segments=num_segments)
    cnt = jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)
    mn = jax.ops.segment_min(msg_lo, segment_ids, num_segments=num_segments)
    mx = jax.ops.segment_max(msg_hi, segment_ids, num_segments=num_segments)
    ssq = jax.ops.segment_sum(
        msg_0 * msg_0, segment_ids, num_segments=num_segments
    )
    nonempty = (cnt > 0.0)[:, None]
    mn = jnp.where(nonempty, mn, 0.0)
    mx = jnp.where(nonempty, mx, 0.0)
    return s, cnt, mn, mx, ssq


def _make_kernel(has_recv: bool, has_gate: bool, chunk: int):
    def kernel(estart_ref, *refs):
        i = 1
        ids_ref = refs[0]
        nrecv_ref = refs[i] if has_recv else None
        i += int(has_recv)
        ein_ref = refs[i]
        i += 1
        gate_ref = refs[i] if has_gate else None
        i += int(has_gate)
        s_ref, mn_ref, mx_ref, ssq_ref = refs[i:i + 4]

        @pl.when(pl.program_id(2) == 0)
        def _init():
            s_ref[:] = jnp.zeros_like(s_ref)
            ssq_ref[:] = jnp.zeros_like(ssq_ref)
            mn_ref[:] = jnp.full_like(mn_ref, _BIG)
            mx_ref[:] = jnp.full_like(mx_ref, -_BIG)

        j = pl.program_id(1)
        nb = s_ref.shape[0]
        dtype = ein_ref.dtype
        # in-register one-hot: edge e belongs to local row r iff its
        # receiver id equals j*Nb + r; padding edges carry id -1 and never
        # match, so they are excluded from every moment
        rows = j * nb + jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1)
        mine = ids_ref[:] == rows  # [Eb, Nb] bool
        minef = mine.astype(dtype)
        msg = ein_ref[:]
        if has_recv:
            # in-kernel receiver gather: each one-hot row copies exactly one
            # row of the receiver-projected node block (exact in any dtype).
            # Edges owned by other row blocks get a zero gather row — their
            # (wrong) message is zeroed by the same one-hot in the sum dots
            # and masked out of the min/max by `mine` below.
            msg = jax.lax.dot_general(
                minef,
                nrecv_ref[:],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(dtype) + msg
        if has_gate:
            msg = msg * gate_ref[:]
        msg32 = msg.astype(jnp.float32)
        # sum / sumsq: MXU one-hot contractions over the edge axis, f32
        # accumulation (sumsq squares in f32 — see reference_multi_agg)
        s_ref[:] += jax.lax.dot_general(
            minef,
            msg,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ssq_ref[:] += jax.lax.dot_general(
            mine.astype(jnp.float32),
            msg32 * msg32,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # min / max: no matmul form — masked VPU reduction over the edge
        # window in `chunk`-sized sub-windows ([chunk, Nb, Cb] resident)
        mn = mn_ref[:]
        mx = mx_ref[:]
        eb = msg.shape[0]
        for c0 in range(0, eb, chunk):
            m3 = mine[c0:c0 + chunk][:, :, None]   # [chunk, Nb, 1]
            v3 = msg32[c0:c0 + chunk][:, None, :]  # [chunk, 1, Cb]
            mn = jnp.minimum(mn, jnp.min(jnp.where(m3, v3, _BIG), axis=0))
            mx = jnp.maximum(mx, jnp.max(jnp.where(m3, v3, -_BIG), axis=0))
        mn_ref[:] = mn
        mx_ref[:] = mx

    return kernel


# tuned-table key component (tune/table.py): bump on any change to the
# kernel's schedule, block layout, or semantics — stale tuned entries must
# miss, not steer a different program
KERNEL_VERSION = 1


def normalize_tiles(
    c, dtype, has_recv, has_gate,
    block_rows=128, block_edges=512, block_cols=128, chunk_edges=32,
):
    """Clamp a candidate tile plan to what ``_forward`` will actually run:
    ``block_cols`` to the lane-padded channel width, ``block_edges`` by the
    VMEM-fit shrink loop, ``chunk_edges`` to the surviving edge window.

    This is the one clamp site — ``_forward`` consumes its result, and the
    routing layer (ops/segment.py) normalizes BEFORE the values become
    ``custom_jvp`` nondiff args, so equivalent plans share one jit
    specialization instead of keying the executable cache on the unclamped
    request (tune/plans.py builds tuned-table keys from the same values).
    """
    nb, eb = block_rows, block_edges
    c128 = c + (-c) % 128
    cb = min(block_cols, c128)
    chunk = min(chunk_edges, eb)

    # VMEM fit: shrink the edge window until the resident working set —
    # double-buffered streams, the four f32 accumulators, msg32, and the
    # [chunk, Nb, Cb] min/max temporary — fits comfortably. As in the
    # fused edge kernel, the redundant-revisit cost is eb-invariant
    # (K ~ Nb*max_degree/eb), so shrinking eb is nearly free.
    itemsize = jnp.dtype(dtype).itemsize

    def _vmem_estimate(eb_):
        return (
            2 * eb_ * cb * itemsize * (1 + int(has_gate))  # edge streams
            + 2 * nb * cb * itemsize * int(has_recv)       # node_recv block
            + 4 * nb * cb * 4                              # accumulators
            + 2 * eb_ * cb * 4                             # msg + msg32
            + min(chunk, eb_) * nb * cb * 4                # min/max select
        )

    while eb > 128 and _vmem_estimate(eb) > 12 * 1024 * 1024:
        eb //= 2
    chunk = min(chunk, eb)
    return nb, eb, cb, chunk


def _forward(
    node_recv, edge_in, gate, segment_ids, num_segments, max_degree,
    block_rows, block_edges, block_cols, chunk_edges, interpret,
):
    e, c = edge_in.shape
    dtype = edge_in.dtype
    has_recv = node_recv is not None
    has_gate = gate is not None
    if has_recv:
        assert node_recv.shape[1] == c, (node_recv.shape, c)
    if has_gate:
        assert gate.shape == edge_in.shape, (gate.shape, edge_in.shape)

    nb, eb, cb, chunk = normalize_tiles(
        c, dtype, has_recv, has_gate,
        block_rows, block_edges, block_cols, chunk_edges,
    )

    ids = segment_ids.astype(jnp.int32)
    ein = _pad_to(_pad_to(edge_in, eb, 0), cb, 1)
    c_pad = ein.shape[1]
    operands = []
    if has_recv:
        nrecv = _pad_to(_pad_to(node_recv.astype(dtype), nb, 0), cb, 1)
        n_pad = nrecv.shape[0]
    else:
        n_pad = num_segments + (-num_segments) % nb

    # K inner windows cover the worst legal row block (degree-capped), +1
    # for edge-block misalignment; trailing zero blocks so estart[j] + k is
    # always in range (same scheme as pallas_segment._forward)
    k_windows = (nb * max_degree + eb - 1) // eb + 1
    k_windows = min(k_windows, ein.shape[0] // eb)
    k_windows = max(k_windows, 1)
    ein = jnp.pad(ein, ((0, k_windows * eb), (0, 0)))
    e_pad = ein.shape[0]
    if has_gate:
        g = _pad_to(_pad_to(gate.astype(dtype), eb, 0), cb, 1)
        g = jnp.pad(g, ((0, k_windows * eb), (0, 0)))

    ids_col = jnp.full((e_pad, 1), -1, jnp.int32).at[:e, 0].set(ids)

    j_blocks = n_pad // nb
    row_starts = jnp.searchsorted(
        ids, jnp.arange(j_blocks, dtype=jnp.int32) * nb, side="left"
    ).astype(jnp.int32)
    estart_block = row_starts // eb

    def edge_index(c_i, j, k, estart):
        return (estart[j] + k, c_i)

    def ids_index(c_i, j, k, estart):
        return (estart[j] + k, 0)

    def nrecv_index(c_i, j, k, estart):
        return (j, c_i)

    def out_index(c_i, j, k, estart):
        return (j, c_i)

    in_specs = [pl.BlockSpec((eb, 1), ids_index)]
    operands = [ids_col]
    if has_recv:
        in_specs.append(pl.BlockSpec((nb, cb), nrecv_index))
        operands.append(nrecv)
    in_specs.append(pl.BlockSpec((eb, cb), edge_index))
    operands.append(ein)
    if has_gate:
        in_specs.append(pl.BlockSpec((eb, cb), edge_index))
        operands.append(g)

    grid = (c_pad // cb, j_blocks, k_windows)
    moment = jax.ShapeDtypeStruct((n_pad, c_pad), jnp.float32)
    s, mn, mx, ssq = pl.pallas_call(
        _make_kernel(has_recv, has_gate, chunk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=[pl.BlockSpec((nb, cb), out_index)] * 4,
        ),
        out_shape=[moment] * 4,
        interpret=interpret,
    )(estart_block, *operands)

    # count is a [E]-read / [N]-write segment sum — negligible traffic next
    # to the [E, C] streams, and it drives the empty-segment fixup that the
    # dense segment_min/segment_max already apply (empty -> 0, not +/-BIG)
    cnt = jax.ops.segment_sum(
        jnp.ones((e,), jnp.float32), ids, num_segments=num_segments
    )
    nonempty = (cnt > 0.0)[:, None]
    s = s[:num_segments, :c]
    mn = jnp.where(nonempty, mn[:num_segments, :c], 0.0)
    mx = jnp.where(nonempty, mx[:num_segments, :c], 0.0)
    ssq = ssq[:num_segments, :c]
    return s, cnt, mn, mx, ssq


@functools.partial(jax.custom_jvp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def fused_multi_agg(
    node_recv,
    edge_in,
    gate,
    segment_ids,
    num_segments: int,
    max_degree: int = 32,
    block_rows: int = 128,
    block_edges: int = 512,
    block_cols: int = 128,
    chunk_edges: int = 32,
    interpret: bool = False,
):
    """Fused multi-moment aggregation of ``(node_recv[ids] + edge_in) *
    gate`` for receiver-sorted edges — (sum, count, min, max, sumsq), each
    f32, messages never materialized in HBM. ``node_recv`` and ``gate``
    are optional (None): PNA passes (node_recv, edge_in, None), PNAPlus
    adds its rbf Hadamard ``gate``, PNAEq passes its post-MLP message as
    ``edge_in`` alone.

    ``segment_ids`` MUST be ascending and segments holding more than
    ``max_degree`` edges get UNSPECIFIED moments — same contract and same
    blast-radius containment as ``sorted_segment_sum`` (the spill can also
    starve LATER segments inside the same row block; the framework routes
    every padding edge to the FINAL dummy node, so real segments stay
    exact — data/graph.py). The dummy-node row is garbage, masked
    downstream like every other kernel output here.

    Differentiable to arbitrary order: custom-JVP with the plain-jnp dense
    reference as tangent rule, so reverse mode recomputes the edge
    messages from the gathered inputs instead of storing [E, C] residuals.
    """
    return _forward(
        node_recv, edge_in, gate, segment_ids, num_segments, max_degree,
        block_rows, block_edges, block_cols, chunk_edges, interpret,
    )


@fused_multi_agg.defjvp
def _jvp(num_segments, max_degree, block_rows, block_edges, block_cols,
         chunk_edges, interpret, primals, tangents):
    node_recv, edge_in, gate, segment_ids = primals
    t_nr, t_ei, t_g, _ = tangents
    out = fused_multi_agg(
        node_recv, edge_in, gate, segment_ids, num_segments, max_degree,
        block_rows, block_edges, block_cols, chunk_edges, interpret,
    )
    # tangent in PLAIN jnp: the dense reference pushed through jax.jvp.
    # Reverse mode transposes it into a gather + elementwise + segment-op
    # backward that RECOMPUTES the messages from the (node-sized) gathered
    # inputs — the recompute schedule, not a stored-residual one — and
    # grad-of-grad just differentiates this rule again (energy-force).
    fn = lambda nr, ei, g: reference_multi_agg(
        nr, ei, g, segment_ids, num_segments
    )
    _, t_out = jax.jvp(fn, (node_recv, edge_in, gate), (t_nr, t_ei, t_g))
    return out, t_out
