"""Radial bases, cutoff envelopes, and distance transforms.

TPU-native equivalents of the geometric primitives the reference spreads over
its model stacks (reference: hydragnn/models/SCFStack.py Gaussian smearing,
hydragnn/models/PNAPlusStack.py Bessel basis + envelope,
hydragnn/models/PAINNStack.py:322-343 sinc expansion + cosine cutoff,
hydragnn/utils/model/mace_utils/modules/radial.py Bessel/Chebyshev/Gaussian
bases, polynomial cutoff, Agnesi/Soft transforms).

Everything here is a pure jnp function or tiny flax module over fixed-shape
arrays: XLA fuses all of it into the surrounding conv, which is exactly what
the MXU/HBM balance wants (these are elementwise ops feeding matmuls).

Distances are computed PBC-aware: ``edge_vectors`` honors per-edge cartesian
shift vectors (reference: get_edge_vectors_and_lengths usage in EGCLStack).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

# Covalent radii in Angstrom indexed by atomic number 0..96 (element 0 is a
# placeholder). Public physical constants (Cordero et al. 2008), the same table
# ase.data.covalent_radii exposes in the reference's Agnesi/Soft transforms.
COVALENT_RADII = np.array(
    [
        0.2, 0.31, 0.28, 1.28, 0.96, 0.84, 0.76, 0.71, 0.66, 0.57, 0.58,
        1.66, 1.41, 1.21, 1.11, 1.07, 1.05, 1.02, 1.06, 2.03, 1.76,
        1.70, 1.60, 1.53, 1.39, 1.39, 1.32, 1.26, 1.24, 1.32, 1.22,
        1.22, 1.20, 1.19, 1.20, 1.20, 1.16, 2.20, 1.95, 1.90, 1.75,
        1.64, 1.54, 1.47, 1.46, 1.42, 1.39, 1.45, 1.44, 1.42, 1.39,
        1.39, 1.38, 1.39, 1.40, 2.44, 2.15, 2.07, 2.04, 2.03, 2.01,
        1.99, 1.98, 1.98, 1.96, 1.94, 1.92, 1.92, 1.89, 1.90, 1.87,
        1.87, 1.75, 1.70, 1.62, 1.51, 1.44, 1.41, 1.36, 1.36, 1.32,
        1.45, 1.46, 1.48, 1.40, 1.50, 1.50, 2.60, 2.21, 2.15, 2.06,
        2.00, 1.96, 1.90, 1.87, 1.80, 1.69, 1.68,
    ],
    dtype=np.float32,
)


def edge_vectors(
    pos: jnp.ndarray,
    senders: jnp.ndarray,
    receivers: jnp.ndarray,
    edge_shifts: Optional[jnp.ndarray] = None,
    eps: float = 1e-12,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-edge displacement r_j - r_i (+ PBC shift) and its length.

    Lengths are clamped away from 0 so padding self-edges (sender==receiver)
    stay differentiable; mask downstream with ``edge_mask``.
    """
    vec = pos[senders] - pos[receivers]
    if edge_shifts is not None:
        vec = vec + edge_shifts
    d2 = jnp.sum(vec * vec, axis=-1, keepdims=True)
    length = jnp.sqrt(jnp.maximum(d2, eps))
    return vec, length


# ---------------------------------------------------------------------------
# bases
# ---------------------------------------------------------------------------


def bessel_basis(r: jnp.ndarray, r_max: float, num_basis: int) -> jnp.ndarray:
    """Spherical-Bessel radial basis sqrt(2/c) sin(n pi r / c)/r
    (reference: mace radial.py BesselBasis eq. (7); PNAPlusStack rbf)."""
    n = jnp.arange(1, num_basis + 1, dtype=r.dtype) * (math.pi / r_max)
    r = r.reshape(-1, 1)
    return math.sqrt(2.0 / r_max) * jnp.sin(n * r) / jnp.maximum(r, 1e-9)


def gaussian_basis(r: jnp.ndarray, r_max: float, num_basis: int, start: float = 0.0):
    """Gaussian-smeared distances (reference: SCFStack GaussianSmearing;
    mace radial.py GaussianBasis)."""
    centers = jnp.linspace(start, r_max, num_basis, dtype=r.dtype)
    width = (r_max - start) / max(num_basis - 1, 1)
    coeff = -0.5 / (width * width)
    diff = r.reshape(-1, 1) - centers
    return jnp.exp(coeff * diff * diff)


def chebyshev_basis(r: jnp.ndarray, num_basis: int) -> jnp.ndarray:
    """Chebyshev polynomials T_1..T_num_basis of the (pre-scaled) input
    (reference: mace radial.py ChebychevBasis). Input expected in [-1, 1]."""
    x = r.reshape(-1, 1)
    t_prev = jnp.ones_like(x)  # T_0
    t_cur = x  # T_1
    cols = [t_cur]
    for _ in range(num_basis - 1):
        t_next = 2.0 * x * t_cur - t_prev
        t_prev, t_cur = t_cur, t_next
        cols.append(t_cur)
    return jnp.concatenate(cols, axis=-1)


def sinc_expansion(r: jnp.ndarray, r_max: float, num_basis: int) -> jnp.ndarray:
    """sin(n pi r / r_max) / r expansion used by PaiNN
    (reference: PAINNStack.py:322-332)."""
    n = jnp.arange(1, num_basis + 1, dtype=r.dtype) * (math.pi / r_max)
    r = r.reshape(-1, 1)
    return jnp.sin(n * r) / jnp.maximum(r, 1e-9)


# ---------------------------------------------------------------------------
# cutoffs
# ---------------------------------------------------------------------------


def cosine_cutoff(r: jnp.ndarray, r_max: float) -> jnp.ndarray:
    """0.5 (cos(pi r / r_max) + 1) for r < r_max else 0
    (reference: PAINNStack.py:335-343; SCFStack CFConv cutoff)."""
    return jnp.where(r < r_max, 0.5 * (jnp.cos(math.pi * r / r_max) + 1.0), 0.0)


def polynomial_cutoff(r: jnp.ndarray, r_max: float, p: int = 6) -> jnp.ndarray:
    """MACE/DimeNet smooth polynomial envelope, eq. (8) of MACE
    (reference: mace radial.py PolynomialCutoff)."""
    x = r / r_max
    env = (
        1.0
        - ((p + 1.0) * (p + 2.0) / 2.0) * x**p
        + p * (p + 2.0) * x ** (p + 1)
        - (p * (p + 1.0) / 2.0) * x ** (p + 2)
    )
    return env * (r < r_max)


def dimenet_envelope(r_scaled: jnp.ndarray, exponent: int = 5) -> jnp.ndarray:
    """DimeNet envelope u(d) = 1/d + a d^(p-1) + b d^p + c d^(p+1), smooth to
    zero at d=1 (reference: PNAPlusStack.py Envelope; DIMEStack via PyG).
    Input is d = r/cutoff; combined with 1/d-weighted bases."""
    p = exponent + 1
    a = -(p + 1) * (p + 2) / 2.0
    b = p * (p + 2.0)
    c = -p * (p + 1) / 2.0
    x = r_scaled
    val = 1.0 / jnp.maximum(x, 1e-9) + a * x ** (p - 1) + b * x**p + c * x ** (p + 1)
    return val * (x < 1.0)


def bessel_basis_enveloped(r: jnp.ndarray, r_max: float, num_basis: int,
                           envelope_exponent: int = 5) -> jnp.ndarray:
    """DimeNet-style enveloped Bessel rbf: env(d) * sin(n pi d)  with
    d = r/r_max (reference: PNAPlusStack BesselBasisLayer)."""
    d = (r / r_max).reshape(-1, 1)
    n = jnp.arange(1, num_basis + 1, dtype=r.dtype) * math.pi
    return dimenet_envelope(d, envelope_exponent) * jnp.sin(n * d)


# ---------------------------------------------------------------------------
# distance transforms (MACE)
# ---------------------------------------------------------------------------


def _pair_r0(z: jnp.ndarray, senders, receivers, scale: float) -> jnp.ndarray:
    radii = jnp.asarray(COVALENT_RADII)
    zi = jnp.clip(z, 0, radii.shape[0] - 1)
    r = radii[zi]
    return scale * (r[senders] + r[receivers]).reshape(-1, 1)


def agnesi_transform(
    r: jnp.ndarray, z: jnp.ndarray, senders, receivers,
    q: float = 0.9183, p: float = 4.5791, a: float = 1.0805,
) -> jnp.ndarray:
    """Agnesi distance transform (ACEpotentials.jl; reference: mace
    radial.py AgnesiTransform). r0 = (rc_i + rc_j)/2 from covalent radii."""
    r0 = _pair_r0(z, senders, receivers, 0.5)
    x = r.reshape(-1, 1) / r0
    return 1.0 / (1.0 + a * x**q / (1.0 + x ** (q - p)))


def soft_transform(
    r: jnp.ndarray, z: jnp.ndarray, senders, receivers,
    a: float = 0.2, b: float = 3.0,
) -> jnp.ndarray:
    """Soft distance transform (reference: mace radial.py SoftTransform);
    r0 = (rc_i + rc_j)/4."""
    r0 = _pair_r0(z, senders, receivers, 0.25)
    x = r.reshape(-1, 1) / r0
    return r.reshape(-1, 1) + 0.5 * jnp.tanh(-x - a * x**b) + 0.5


class RadialEmbedding(nn.Module):
    """Distance -> radial feature row, combining basis x cutoff (+transform).

    The MACE radial embedding block (reference: mace radial.py:23-100 analog):
    ``radial_type`` in {bessel, gaussian, chebyshev}, polynomial cutoff, and
    optional Agnesi/Soft distance transform applied before the basis.
    """

    r_max: float
    num_basis: int = 8
    radial_type: str = "bessel"
    envelope_exponent: int = 6  # polynomial cutoff p
    distance_transform: Optional[str] = None

    @nn.compact
    def __call__(self, lengths, z=None, senders=None, receivers=None):
        r = lengths.reshape(-1)
        cutoff = polynomial_cutoff(r, self.r_max, self.envelope_exponent)[:, None]
        if self.distance_transform in ("Agnesi", "agnesi"):
            r = agnesi_transform(r, z, senders, receivers).reshape(-1)
        elif self.distance_transform in ("Soft", "soft"):
            r = soft_transform(r, z, senders, receivers).reshape(-1)
        if self.radial_type == "bessel":
            feats = bessel_basis(r, self.r_max, self.num_basis)
        elif self.radial_type == "gaussian":
            feats = gaussian_basis(r, self.r_max, self.num_basis)
        elif self.radial_type == "chebyshev":
            feats = chebyshev_basis(2.0 * r / self.r_max - 1.0, self.num_basis)
        else:
            raise ValueError(f"unknown radial_type {self.radial_type!r}")
        return feats * cutoff
