"""Pallas TPU kernel: sorted-segment sum of edge messages.

The scatter-add ``out[i] = sum_{e: recv[e]==i} msg[e]`` sits on the hot path
of every message-passing model here (ops/segment.py -> jax.ops.segment_sum,
the torch_scatter analog, SURVEY §2.3 item 2). XLA lowers it to a serialized
scatter; with receivers *sorted* (free at batching time — edge order is
semantically irrelevant) the reduction becomes CSR-contiguous and maps onto
the MXU as a block-diagonal one-hot matmul:

- grid ``(C_blocks, row_blocks, K)``: for output row-block ``j``, the K
  inner steps stream the edge windows that can touch its rows (degree-capped
  graphs bound edges-per-row-block by ``Nb * max_degree``), and the output
  block is revisited across K as a standard reduction accumulator;
- the edge->local-row map is precomputed as an owner-encoded one-hot
  ``oh[e, recv[e] % Nb] = owner(e) + 1`` so one streamed operand carries
  both the scatter pattern and the this-block mask (exact float compares,
  values < 2^24);
- per step: ``acc[Nb, Cb] += onehot_masked.T @ msg_window`` — an
  [Nb, Eb] x [Eb, Cb] MXU contraction instead of a scatter.

The backward pass of a segment sum is a gather, which XLA already does
well, so the custom VJP uses ``dout[recv]`` directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(estart_ref, oh_ref, msg_ref, out_ref):
    c, j, k = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    del c, k  # block selection happened in the index maps

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # owner-encoded one-hot: entries equal to j+1 belong to this row block
    mine = (oh_ref[:] == (j + 1).astype(oh_ref.dtype)).astype(msg_ref.dtype)
    out_ref[:] += jax.lax.dot_general(
        mine,
        msg_ref[:],
        (((0,), (0,)), ((), ())),  # contract over the edge axis
        preferred_element_type=jnp.float32,
    )


def _pad_to(x, multiple, axis):
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7)
)
def sorted_segment_sum(
    messages,
    segment_ids,
    num_segments: int,
    max_degree: int = 32,
    block_rows: int = 128,
    block_edges: int = 512,
    block_cols: int = 512,
    interpret: bool = False,
):
    """``segment_sum`` for receiver-sorted edges via the Pallas kernel.

    ``segment_ids`` MUST be ascending (sorted receivers), and any segment
    holding more than ``max_degree`` edges gets an UNSPECIFIED value (its
    trailing edges fall outside the K streamed windows). Real nodes of this
    framework's batches satisfy the cap (data/neighbors.py caps in-degree;
    ``GraphLoader(sort_edges=True)`` sorts receivers) — but the final
    *padding* node receives every padding edge and will exceed it: its slot
    must be masked downstream, which every consumer of the dummy-node
    convention already does (data/graph.py padding docs).
    Messages are [E, C] float; returns [num_segments, C].
    """
    return _forward(
        messages, segment_ids, num_segments, max_degree, block_rows,
        block_edges, block_cols, interpret,
    )


def _forward(
    messages, segment_ids, num_segments, max_degree, block_rows, block_edges,
    block_cols, interpret,
):
    e, c = messages.shape
    nb, eb, cb = block_rows, block_edges, block_cols
    cb = min(cb, max(c, 128))
    dtype = messages.dtype

    ids = segment_ids.astype(jnp.int32)
    # messages stream in their own dtype (bf16 stays bf16 — half the HBM
    # traffic under mixed precision); the kernel's dot_general accumulates
    # in f32 via preferred_element_type either way. The one-hot operand must
    # stay f32: owner encodings are exact-compared and bf16's 8 mantissa
    # bits would corrupt owners > 256.
    msg = _pad_to(messages, eb, 0)
    msg = _pad_to(msg, cb, 1)
    n_pad = num_segments + (-num_segments) % nb

    # K inner windows cover the worst legal row block (degree-capped), +1
    # for edge-block misalignment
    k_windows = (nb * max_degree + eb - 1) // eb + 1
    k_windows = min(k_windows, msg.shape[0] // eb)
    k_windows = max(k_windows, 1)
    # trailing zero blocks so estart[j] + k is always in range — never clamp
    # (a clamp would re-read one block for several k and double-count edges).
    # k_windows blocks of slack: estart can point one block past the data
    # when a trailing row block owns no edges.
    msg = jnp.pad(msg, ((0, k_windows * eb), (0, 0)))
    e_pad = msg.shape[0]

    # owner-encoded one-hot [E_pad, Nb]; padding edges stay all-zero so the
    # (oh == j+1 >= 1) comparison never selects them
    owner = ids // nb + 1
    local = ids % nb
    oh = jnp.zeros((e_pad, nb), jnp.float32)
    oh = oh.at[jnp.arange(e), local].set(owner.astype(jnp.float32))

    # first edge-block index each row block may need (receivers sorted)
    j_blocks = n_pad // nb
    row_starts = jnp.searchsorted(
        ids, jnp.arange(j_blocks, dtype=jnp.int32) * nb, side="left"
    ).astype(jnp.int32)
    estart_block = row_starts // eb

    def msg_index(c_i, j, k, estart):
        return (estart[j] + k, c_i)

    def oh_index(c_i, j, k, estart):
        return (estart[j] + k, 0)

    def out_index(c_i, j, k, estart):
        return (j, c_i)

    grid = (msg.shape[1] // cb, j_blocks, k_windows)
    out = pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((eb, nb), oh_index),
                pl.BlockSpec((eb, cb), msg_index),
            ],
            out_specs=pl.BlockSpec((nb, cb), out_index),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, msg.shape[1]), jnp.float32),
        interpret=interpret,
    )(estart_block, oh, msg)
    return out[:num_segments, :c].astype(dtype)


def _fwd(messages, segment_ids, *static):
    return _forward(messages, segment_ids, *static), segment_ids


def _bwd(num_segments, max_degree, block_rows, block_edges, block_cols,
         interpret, segment_ids, g):
    # d/d msg of a segment sum is a gather of the cotangent (XLA-fast);
    # integer ids get no gradient
    return g[segment_ids], None


sorted_segment_sum.defvjp(_fwd, _bwd)
