"""Pallas TPU kernel: sorted-segment sum of edge messages.

The scatter-add ``out[i] = sum_{e: recv[e]==i} msg[e]`` sits on the hot path
of every message-passing model here (ops/segment.py -> jax.ops.segment_sum,
the torch_scatter analog, SURVEY §2.3 item 2). XLA lowers it to a serialized
scatter; with receivers *sorted* (free at batching time — edge order is
semantically irrelevant) the reduction becomes CSR-contiguous and maps onto
the MXU as a block-diagonal one-hot matmul:

- grid ``(C_blocks, row_blocks, K)``: for output row-block ``j``, the K
  inner steps stream the edge windows that can touch its rows (degree-capped
  graphs bound edges-per-row-block by ``Nb * max_degree``), and the output
  block is revisited across K as a standard reduction accumulator;
- the raw receiver ids stream beside the messages (4 bytes/edge) and the
  kernel builds the one-hot selector in-register with an iota compare
  ``ids == j*Nb + iota(Nb)`` — nothing but the payload ever touches HBM
  (an earlier revision materialized an [E, Nb] f32 one-hot operand: 128x
  the bandwidth of the ids and an extra scatter to build it);
- per step: ``acc[Nb, Cb] += onehot.T @ msg_window`` — an [Nb, Eb] x
  [Eb, Cb] MXU contraction instead of a scatter.

The backward pass of a segment sum is a gather, which XLA already does
well. Differentiation is a ``jax.custom_jvp`` whose tangent rule is the
PLAIN ``jax.ops.segment_sum`` of the tangent (a segment sum is linear):
reverse mode transposes that jnp tangent into the ``dout[recv]`` gather —
identical backward cost to the r5 custom-VJP — and, because no Pallas call
ever appears on a tangent path, the op composes under ``jax.grad`` to ANY
order. That second-order capability is what lets energy-force training
(forces = -dE/dpos inside the loss, differentiated again by the training
grad) use this kernel; the r5 custom_vjp was first-order only and raised
pallas_call's missing-JVP NotImplementedError on exactly that workload
(the since-dropped grad-energy guard in config/config.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(estart_ref, ids_ref, msg_ref, out_ref):
    j = pl.program_id(1)

    @pl.when(pl.program_id(2) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # in-register one-hot: edge e belongs to local row r iff its receiver id
    # equals j*Nb + r; padding edges carry id -1 and never match
    nb = out_ref.shape[0]
    rows = j * nb + jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1)
    mine = (ids_ref[:] == rows).astype(msg_ref.dtype)  # [Eb, Nb]
    out_ref[:] += jax.lax.dot_general(
        mine,
        msg_ref[:],
        (((0,), (0,)), ((), ())),  # contract over the edge axis
        preferred_element_type=jnp.float32,
    )


def _pad_to(x, multiple, axis):
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.custom_jvp, nondiff_argnums=(2, 3, 4, 5, 6, 7)
)
def sorted_segment_sum(
    messages,
    segment_ids,
    num_segments: int,
    max_degree: int = 32,
    block_rows: int = 128,
    block_edges: int = 512,
    block_cols: int = 512,
    interpret: bool = False,
):
    """``segment_sum`` for receiver-sorted edges via the Pallas kernel.

    ``segment_ids`` MUST be ascending (sorted receivers), and any segment
    holding more than ``max_degree`` edges gets an UNSPECIFIED value (its
    trailing edges fall outside the K streamed windows) — and can starve
    LATER segments inside the same ``block_rows`` row block, whose edges
    get pushed past those windows (subsequent row blocks are unaffected:
    each gets its own ``estart``). Real nodes of this
    framework's batches satisfy the cap (data/neighbors.py caps in-degree;
    ``GraphLoader(sort_edges=True)`` sorts receivers; the loader validates
    real in-degrees against the bound) — but the final *padding* node
    receives every padding edge and will exceed it: its slot must be masked
    downstream, which every consumer of the dummy-node convention already
    does (data/graph.py padding docs).
    Messages are [E, C] float; returns [num_segments, C].
    """
    return _forward(
        messages, segment_ids, num_segments, max_degree, block_rows,
        block_edges, block_cols, interpret,
    )


# tuned-table key component (tune/table.py): bump on any change to the
# kernel's schedule, block layout, or semantics — stale tuned entries must
# miss, not steer a different program
KERNEL_VERSION = 1


def normalize_tiles(c, block_rows=128, block_edges=512, block_cols=512):
    """Clamp a candidate tile plan to what ``_forward`` will actually run
    (``block_cols`` never exceeds the lane-padded channel width) — the one
    clamp site, shared by the kernel, the routing layer (so nondiff
    specialization args are pre-clamped) and the tune plane's table keys
    (tune/plans.py)."""
    return block_rows, block_edges, min(block_cols, max(c, 128))


def _forward(
    messages, segment_ids, num_segments, max_degree, block_rows, block_edges,
    block_cols, interpret,
):
    e, c = messages.shape
    nb, eb, cb = normalize_tiles(c, block_rows, block_edges, block_cols)
    dtype = messages.dtype

    ids = segment_ids.astype(jnp.int32)
    # messages stream in their own dtype (bf16 stays bf16 — half the HBM
    # traffic under mixed precision); the kernel's dot_general accumulates
    # in f32 via preferred_element_type either way.
    msg = _pad_to(messages, eb, 0)
    msg = _pad_to(msg, cb, 1)
    n_pad = num_segments + (-num_segments) % nb

    # K inner windows cover the worst legal row block (degree-capped), +1
    # for edge-block misalignment
    k_windows = (nb * max_degree + eb - 1) // eb + 1
    k_windows = min(k_windows, msg.shape[0] // eb)
    k_windows = max(k_windows, 1)
    # trailing zero blocks so estart[j] + k is always in range — never clamp
    # (a clamp would re-read one block for several k and double-count edges).
    # k_windows blocks of slack: estart can point one block past the data
    # when a trailing row block owns no edges.
    msg = jnp.pad(msg, ((0, k_windows * eb), (0, 0)))
    e_pad = msg.shape[0]

    # receiver ids stream beside the messages; padding edges get id -1 so
    # the in-kernel iota compare never selects them
    ids_col = jnp.full((e_pad, 1), -1, jnp.int32).at[:e, 0].set(ids)

    # first edge-block index each row block may need (receivers sorted)
    j_blocks = n_pad // nb
    row_starts = jnp.searchsorted(
        ids, jnp.arange(j_blocks, dtype=jnp.int32) * nb, side="left"
    ).astype(jnp.int32)
    estart_block = row_starts // eb

    def msg_index(c_i, j, k, estart):
        return (estart[j] + k, c_i)

    def ids_index(c_i, j, k, estart):
        return (estart[j] + k, 0)

    def out_index(c_i, j, k, estart):
        return (j, c_i)

    grid = (msg.shape[1] // cb, j_blocks, k_windows)
    out = pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((eb, 1), ids_index),
                pl.BlockSpec((eb, cb), msg_index),
            ],
            out_specs=pl.BlockSpec((nb, cb), out_index),
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, msg.shape[1]), jnp.float32),
        interpret=interpret,
    )(estart_block, ids_col, msg)
    return out[:num_segments, :c].astype(dtype)


@sorted_segment_sum.defjvp
def _jvp(num_segments, max_degree, block_rows, block_edges, block_cols,
         interpret, primals, tangents):
    messages, segment_ids = primals
    t_msg, _ = tangents  # integer ids get a float0 tangent — no gradient
    out = sorted_segment_sum(
        messages, segment_ids, num_segments, max_degree, block_rows,
        block_edges, block_cols, interpret,
    )
    # tangent in PLAIN jnp (a segment sum is linear in the messages): its
    # transpose is the ``dout[recv]`` gather — the same XLA-fast backward
    # as the r5 custom_vjp — and it is differentiable to any order, so
    # grad-of-grad (energy-force training) composes instead of hitting
    # pallas_call's missing JVP rule.
    t_out = jax.ops.segment_sum(
        t_msg, segment_ids, num_segments=num_segments
    ).astype(out.dtype)
    return out, t_out
