"""O(3) representation algebra for higher-order equivariant message passing.

TPU-native replacement for the e3nn machinery the reference's MACE stack
wraps (reference: hydragnn/models/MACEStack.py:146-150 uses
``o3.SphericalHarmonics``; hydragnn/utils/model/mace_utils/tools/cg.py:94-136
builds Wigner/CG contraction tensors through e3nn). Everything here is either
a host-side numpy precomputation (CG tensors, cached per (l1,l2,l3)) or a
closed-form jax function (real spherical harmonics), so the device program is
pure einsum/MXU work with no codegen.

Conventions (self-consistent across this module, verified by
tests/test_o3.py):
- real spherical harmonics with "component" normalization
  (mean_{unit sphere} Y_lm^2 = 1, i.e. sqrt(4*pi) times the orthonormal
  basis), component order m = -l..l;
- features with uniform channel multiplicity are stored dense as
  [N, C, (L+1)^2] with irrep l occupying slice l^2:(l+1)^2 of the last axis.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# real spherical harmonics (closed form, l <= 3)
# ---------------------------------------------------------------------------

_SQRT_4PI = math.sqrt(4.0 * math.pi)


def sh_dim(lmax: int) -> int:
    return (lmax + 1) ** 2


def irrep_slice(l: int) -> slice:
    """Slice of irrep ``l`` inside a stacked [..., (L+1)^2] axis."""
    return slice(l * l, (l + 1) * (l + 1))


def _double_fact(n: int) -> float:
    out = 1.0
    while n > 1:
        out *= n
        n -= 2
    return out


def _real_sph_harm_general(u: jnp.ndarray, lmax: int) -> jnp.ndarray:
    """Arbitrary-``lmax`` component-normalized real spherical harmonics of
    unit vectors via the reduced associated-Legendre recurrence.

    Everything is a POLYNOMIAL in (x, y, z): the azimuthal factors
    ``c_m = Re[(x+iy)^m]`` / ``s_m = Im[(x+iy)^m]`` absorb the sin^m(theta)
    of P_l^m, and the reduced ``Q_l^m(z) = P_l^m / sin^m`` follows
    ``(l-m) Q_l^m = (2l-1) z Q_{l-1}^m - (l+m-1) Q_{l-2}^m`` with
    ``Q_m^m = (2m-1)!!`` — so there is no pole sqrt and autograd forces
    stay smooth everywhere (the closed forms below are the same
    polynomials, hand-expanded for l <= 3)."""
    x, y, z = u[..., 0], u[..., 1], u[..., 2]
    cs = [(jnp.ones_like(x), jnp.zeros_like(x))]
    for m in range(1, lmax + 1):
        cp, sp = cs[-1]
        cs.append((cp * x - sp * y, cp * y + sp * x))
    q: Dict[Tuple[int, int], jnp.ndarray] = {}
    for m in range(0, lmax + 1):
        q[(m, m)] = jnp.full_like(z, _double_fact(2 * m - 1))
        if m + 1 <= lmax:
            q[(m + 1, m)] = (2 * m + 1) * z * q[(m, m)]
        for l in range(m + 2, lmax + 1):
            q[(l, m)] = (
                (2 * l - 1) * z * q[(l - 1, m)]
                - (l + m - 1) * q[(l - 2, m)]
            ) / (l - m)
    out = []
    for l in range(lmax + 1):
        for m in range(-l, l + 1):
            am = abs(m)
            norm = math.sqrt((2 * l + 1) * _fact(l - am) / _fact(l + am))
            if m != 0:
                norm *= math.sqrt(2.0)
            base = norm * q[(l, am)]
            if m < 0:
                out.append(base * cs[am][1])
            elif m == 0:
                out.append(base)
            else:
                out.append(base * cs[am][0])
    return jnp.stack(out, axis=-1)


def real_sph_harm(vec: jnp.ndarray, lmax: int, eps: float = 1e-12) -> jnp.ndarray:
    """Component-normalized real spherical harmonics of (auto-normalized)
    3-vectors. vec: [..., 3] -> [..., (lmax+1)^2].

    Replaces e3nn ``o3.SphericalHarmonics(normalize=True,
    normalization="component")`` (reference: MACEStack.py:146-150) at
    arbitrary ``lmax``: hand-expanded closed forms for l <= 3 (the MACE
    default max_ell range), the Legendre-recurrence path beyond. The two
    paths are the same polynomials (tests pin them to 2e-5), but NOT
    bitwise: the closed forms stay the l <= 3 default so existing
    fixed-seed training results (the accuracy matrix, pinned example
    seeds) are not perturbed by a float-associativity change.
    """
    n = jnp.sqrt(jnp.sum(vec * vec, axis=-1, keepdims=True) + eps)
    u = vec / n
    if lmax > 3:
        return _real_sph_harm_general(u, lmax)
    x, y, z = u[..., 0], u[..., 1], u[..., 2]
    out = [jnp.ones_like(x)]
    if lmax >= 1:
        c1 = math.sqrt(3.0)
        out += [c1 * y, c1 * z, c1 * x]
    if lmax >= 2:
        c2a = math.sqrt(15.0)
        c2b = math.sqrt(5.0) / 2.0
        c2c = math.sqrt(15.0) / 2.0
        out += [
            c2a * x * y,
            c2a * y * z,
            c2b * (3.0 * z * z - 1.0),
            c2a * x * z,
            c2c * (x * x - y * y),
        ]
    if lmax >= 3:
        c3a = math.sqrt(35.0 / 8.0)
        c3b = math.sqrt(105.0)
        c3c = math.sqrt(21.0 / 8.0)
        c3d = math.sqrt(7.0) / 2.0
        c3e = math.sqrt(105.0) / 2.0
        out += [
            c3a * y * (3.0 * x * x - y * y),
            c3b * x * y * z,
            c3c * y * (5.0 * z * z - 1.0),
            c3d * z * (5.0 * z * z - 3.0),
            c3c * x * (5.0 * z * z - 1.0),
            c3e * z * (x * x - y * y),
            c3a * x * (x * x - 3.0 * y * y),
        ]
    return jnp.stack(out, axis=-1)


# ---------------------------------------------------------------------------
# Clebsch-Gordan coefficients (complex, Racah formula) -> real basis
# ---------------------------------------------------------------------------


def _fact(n: float) -> float:
    return math.gamma(n + 1.0)


def _cg_complex_element(j1, m1, j2, m2, j3, m3) -> float:
    """<j1 m1 j2 m2 | j3 m3> via the Racah closed form (Condon-Shortley)."""
    if m3 != m1 + m2:
        return 0.0
    if not (abs(j1 - j2) <= j3 <= j1 + j2):
        return 0.0
    pref = math.sqrt(
        (2 * j3 + 1)
        * _fact(j3 + j1 - j2)
        * _fact(j3 - j1 + j2)
        * _fact(j1 + j2 - j3)
        / _fact(j1 + j2 + j3 + 1)
    )
    pref *= math.sqrt(
        _fact(j3 + m3)
        * _fact(j3 - m3)
        * _fact(j1 - m1)
        * _fact(j1 + m1)
        * _fact(j2 - m2)
        * _fact(j2 + m2)
    )
    s = 0.0
    kmin = max(0, int(j2 - j3 - m1), int(j1 - j3 + m2))
    kmax = min(int(j1 + j2 - j3), int(j1 - m1), int(j2 + m2))
    for k in range(kmin, kmax + 1):
        s += (-1.0) ** k / (
            _fact(k)
            * _fact(j1 + j2 - j3 - k)
            * _fact(j1 - m1 - k)
            * _fact(j2 + m2 - k)
            * _fact(j3 - j2 + m1 + k)
            * _fact(j3 - j1 - m2 + k)
        )
    return pref * s


@lru_cache(maxsize=None)
def _cg_complex(l1: int, l2: int, l3: int) -> np.ndarray:
    out = np.zeros((2 * l1 + 1, 2 * l2 + 1, 2 * l3 + 1))
    for i1, m1 in enumerate(range(-l1, l1 + 1)):
        for i2, m2 in enumerate(range(-l2, l2 + 1)):
            for i3, m3 in enumerate(range(-l3, l3 + 1)):
                out[i1, i2, i3] = _cg_complex_element(l1, m1, l2, m2, l3, m3)
    return out


@lru_cache(maxsize=None)
def _real_to_complex(l: int) -> np.ndarray:
    """U with Y_real = U @ Y_complex for the real convention above
    (rows: real m = -l..l; cols: complex m = -l..l)."""
    U = np.zeros((2 * l + 1, 2 * l + 1), complex)
    for m in range(-l, l + 1):
        r = m + l  # row index of real component m
        if m == 0:
            U[r, l] = 1.0
        elif m > 0:
            U[r, l + m] = (-1.0) ** m / math.sqrt(2.0)
            U[r, l - m] = 1.0 / math.sqrt(2.0)
        else:
            a = -m
            U[r, l + a] = -1j * (-1.0) ** a / math.sqrt(2.0)
            U[r, l - a] = 1j / math.sqrt(2.0)
    return U


@lru_cache(maxsize=None)
def real_cg(l1: int, l2: int, l3: int) -> np.ndarray:
    """Real-basis Clebsch-Gordan tensor [2l1+1, 2l2+1, 2l3+1], normalized to
    unit Frobenius norm (learned path weights absorb overall scale; the
    reference's e3nn TensorProduct normalizes per path similarly)."""
    C = _cg_complex(l1, l2, l3)
    U1 = _real_to_complex(l1)
    U2 = _real_to_complex(l2)
    U3 = _real_to_complex(l3)
    M = np.einsum("am,bn,co,mno->abc", U1, U2, np.conj(U3), C)
    re, im = np.real(M), np.imag(M)
    if np.linalg.norm(im) > 1e-9 * max(np.linalg.norm(re), 1e-30):
        assert np.linalg.norm(re) < 1e-9 * np.linalg.norm(im), (
            f"real CG ({l1},{l2},{l3}) is neither purely real nor imaginary"
        )
        out = im
    else:
        out = re
    norm = np.linalg.norm(out)
    if norm < 1e-12:
        return np.zeros_like(out)
    return (out / norm).astype(np.float64)


def tp_paths(
    lmax_in1: int, lmax_in2: int, lmax_out: int
) -> List[Tuple[int, int, int]]:
    """All coupling paths (l1, l2, l3) with |l1-l2| <= l3 <= l1+l2 and a
    nonvanishing real CG tensor."""
    paths = []
    for l1 in range(lmax_in1 + 1):
        for l2 in range(lmax_in2 + 1):
            for l3 in range(abs(l1 - l2), min(l1 + l2, lmax_out) + 1):
                if np.linalg.norm(real_cg(l1, l2, l3)) > 1e-8:
                    paths.append((l1, l2, l3))
    return paths


def couple(
    a: jnp.ndarray, b: jnp.ndarray, l1: int, l2: int, l3: int
) -> jnp.ndarray:
    """Channelwise CG coupling: a[..., 2l1+1] x b[..., 2l2+1] -> [..., 2l3+1]."""
    cg = jnp.asarray(real_cg(l1, l2, l3), a.dtype)
    return jnp.einsum("...a,...b,abc->...c", a, b, cg)


@lru_cache(maxsize=None)
def combined_cg(
    lmax1: int, lmax2: int, lmax_out: int
) -> Tuple[np.ndarray, Tuple[Tuple[int, int, int], ...], Tuple[int, ...]]:
    """Block CG tensor for a FUSED tensor product: ``G[d1, d2, Q]`` with one
    (2*l3+1)-wide output block per coupling path of ``tp_paths(lmax1, lmax2,
    lmax_out)``, plus the path list and per-path block offsets.

    Contracting once with G computes every ``couple(a_l1, b_l2, l3)`` of the
    per-path chain in a single dense einsum (one or two dot_generals instead
    of ~len(paths) tiny bandwidth-bound kernels); callers slice the Q axis
    by offset to apply per-path weights. Zeros fill the blocks a path does
    not touch, so the dense contraction is algebraically identical to the
    path loop."""
    paths = tp_paths(lmax1, lmax2, lmax_out)
    d1, d2 = sh_dim(lmax1), sh_dim(lmax2)
    q_tot = sum(2 * l3 + 1 for _, _, l3 in paths)
    G = np.zeros((d1, d2, q_tot), np.float32)
    offsets = []
    q = 0
    for l1, l2, l3 in paths:
        G[irrep_slice(l1), irrep_slice(l2), q : q + 2 * l3 + 1] = real_cg(
            l1, l2, l3
        )
        offsets.append(q)
        q += 2 * l3 + 1
    return G, tuple(paths), tuple(offsets)


@lru_cache(maxsize=None)
def summed_cg(lmax1: int, lmax2: int, lmax_out: int) -> np.ndarray:
    """``G[d1, d2, d_out]`` with every coupling path ACCUMULATED into its
    ``irrep_slice(l3)`` output block — the fused form of an unweighted
    path-sum tensor product (SymmetricProduct's recursion, where no
    per-path weights exist): ``einsum('...m,...n,mnk->...k', a, b, G)``
    equals the full couple-and-add chain exactly."""
    d1, d2 = sh_dim(lmax1), sh_dim(lmax2)
    G = np.zeros((d1, d2, sh_dim(lmax_out)), np.float32)
    for l1, l2, l3 in tp_paths(lmax1, lmax2, lmax_out):
        G[irrep_slice(l1), irrep_slice(l2), irrep_slice(l3)] += real_cg(
            l1, l2, l3
        )
    return G
