"""DimeNet spherical basis: spherical Bessel x Legendre angular functions.

The reference relies on sympy-codegen'd basis functions inside PyG's
``SphericalBasisLayer`` (reference: hydragnn/models/DIMEStack.py:70-73 via
torch_geometric.nn.models.dimenet). Here the same math is built TPU-natively:

- zeros of the spherical Bessel functions j_l are found once on host with a
  numpy bisection (no scipy needed),
- on device, j_l is evaluated by upward recurrence and Y_l0 by the Legendre
  recurrence — pure elementwise jnp that XLA fuses into the conv.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .radial import dimenet_envelope


def _sph_jl_np(l: int, x: np.ndarray) -> np.ndarray:
    """Spherical Bessel j_l on host (float64) for zero-finding."""
    x = np.asarray(x, np.float64)
    small = np.abs(x) < 1e-8
    xs = np.where(small, 1.0, x)
    j0 = np.sin(xs) / xs
    if l == 0:
        return np.where(small, 1.0, j0)
    j1 = np.sin(xs) / xs**2 - np.cos(xs) / xs
    jm, jc = j0, j1
    for n in range(1, l):
        jm, jc = jc, (2 * n + 1) / xs * jc - jm
    return np.where(small, 0.0, jc)


@functools.lru_cache(maxsize=None)
def spherical_bessel_zeros(num_spherical: int, num_radial: int) -> Tuple[Tuple[float, ...], ...]:
    """First ``num_radial`` positive zeros of j_l for l = 0..num_spherical-1.

    j_0 zeros are n*pi; zeros of j_{l} interlace those of j_{l-1}, so each is
    bracketed and bisected. Cached per (L, N)."""
    zeros = [tuple(np.pi * np.arange(1, num_radial + num_spherical + 1))]
    for l in range(1, num_spherical):
        prev = zeros[-1]
        row = []
        for i in range(len(prev) - 1):
            lo, hi = prev[i], prev[i + 1]
            flo = _sph_jl_np(l, np.array(lo))
            for _ in range(80):
                mid = 0.5 * (lo + hi)
                fmid = _sph_jl_np(l, np.array(mid))
                if np.sign(fmid) == np.sign(flo):
                    lo, flo = mid, fmid
                else:
                    hi = mid
            row.append(0.5 * (lo + hi))
        zeros.append(tuple(row))
    return tuple(tuple(z[:num_radial]) for z in zeros)


@functools.lru_cache(maxsize=None)
def _sbf_normalizers(num_spherical: int, num_radial: int) -> Tuple[Tuple[float, ...], ...]:
    """N_ln = sqrt(2 / j_{l+1}(z_ln)^2) so each radial mode has unit norm on
    the unit interval (DimeNet eq. 10 normalization, cutoff factored out)."""
    zeros = spherical_bessel_zeros(num_spherical, num_radial)
    out = []
    for l in range(num_spherical):
        zs = np.array(zeros[l])
        out.append(tuple(np.sqrt(2.0) / np.abs(_sph_jl_np(l + 1, zs))))
    return tuple(out)


def _sph_jl_jnp(l_max: int, x: jnp.ndarray) -> jnp.ndarray:
    """j_0..j_{l_max} stacked on the last axis, via upward recurrence."""
    xs = jnp.maximum(jnp.abs(x), 1e-8)
    j0 = jnp.sin(xs) / xs
    cols = [j0]
    if l_max >= 1:
        j1 = jnp.sin(xs) / xs**2 - jnp.cos(xs) / xs
        cols.append(j1)
        jm, jc = j0, j1
        for n in range(1, l_max):
            jm, jc = jc, (2 * n + 1) / xs * jc - jm
            cols.append(jc)
    return jnp.stack(cols, axis=-1)


def legendre_cos(l_max: int, angle: jnp.ndarray) -> jnp.ndarray:
    """P_0..P_{l_max}(cos angle) stacked on the last axis (Bonnet recurrence)."""
    c = jnp.cos(angle)
    cols = [jnp.ones_like(c)]
    if l_max >= 1:
        cols.append(c)
        pm, pc = cols[0], c
        for n in range(1, l_max):
            pm, pc = pc, ((2 * n + 1) * c * pc - n * pm) / (n + 1)
            cols.append(pc)
    return jnp.stack(cols, axis=-1)


def spherical_basis(
    dist: jnp.ndarray,
    angle: jnp.ndarray,
    idx_kj: jnp.ndarray,
    r_max: float,
    num_spherical: int,
    num_radial: int,
    envelope_exponent: int = 5,
    edge_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """[T, num_spherical * num_radial] directional basis a_SBF(d_kj, angle_kji).

    ``dist`` is per-edge [E]; the radial part is evaluated per edge, enveloped,
    then gathered to triplets via ``idx_kj`` and modulated by Y_l0(angle)
    (same contraction as PyG SphericalBasisLayer.forward).

    ``edge_mask`` marks the real edges. Padding edges carry an eps-clamped
    near-zero length (ops/radial.py edge_vectors), and the upward j_l
    recurrence at x ~ 1e-6 amplifies rounding error by ~(2l+1)/x per level —
    to ~1e38 garbage by l=6, one fused op away from inf. Padding triplets
    gather exactly those rows (data/graph.py compute_triplets_np pads with
    the last edge slot): eagerly the downstream masks keep that garbage out
    of the loss, but under jit XLA's fusion of the select/multiply patterns
    produces 0*inf = NaN in the backward (measured: eager grads finite,
    jitted grads 53 NaN leaves; first observed as the r5 live-TPU DimeNet
    mixed-precision cell training to NaN, logs/ab_matrix.jsonl). With the
    mask, padding rows are evaluated at a safe mid-range distance and zeroed
    — no huge intermediate ever exists, in forward or backward.
    """
    if edge_mask is not None:
        dist = jnp.where(edge_mask, dist, 0.5 * r_max)
    d = dist / r_max
    zeros = jnp.asarray(spherical_bessel_zeros(num_spherical, num_radial))  # [L, N]
    norms = jnp.asarray(_sbf_normalizers(num_spherical, num_radial))  # [L, N]
    # j_l(z_ln * d): evaluate recurrence at each of the L*N scaled arguments
    x = d[:, None, None] * zeros[None, :, :]  # [E, L, N]
    jl_all = _sph_jl_jnp(num_spherical - 1, x)  # [E, L, N, L']
    l_idx = jnp.arange(num_spherical)
    rad = jl_all[:, l_idx, :, l_idx]  # [L, E, N] (advanced indexing moves axis)
    rad = jnp.moveaxis(rad, 0, 1) * norms[None, :, :]  # [E, L, N]
    rad = rad * dimenet_envelope(d, envelope_exponent)[:, None, None]
    if edge_mask is not None:
        rad = jnp.where(edge_mask[:, None, None], rad, 0.0)
    # angular part per triplet
    y_l0 = legendre_cos(num_spherical - 1, angle)  # [T, L]
    scale = jnp.sqrt((2.0 * jnp.arange(num_spherical) + 1.0) / (4.0 * math.pi))
    y_l0 = y_l0 * scale[None, :]
    out = rad[idx_kj] * y_l0[:, :, None]  # [T, L, N]
    return out.reshape(out.shape[0], num_spherical * num_radial)
