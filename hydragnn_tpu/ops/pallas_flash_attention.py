"""Pallas TPU kernel: segment-masked flash attention for GPS global attention.

GPS global attention (models/gps.py, reference hydragnn/globalAtt/gps.py:
125-141) is block-diagonal over graphs: node i attends node j iff both are
real and share a graph. The incumbent TPU paths materialize the score
matrix in HBM — ``[G, H, Nmax, Nmax]`` for the per-graph gathered layout,
``[H, N, N]`` for the flat masked fallback — and the masked fallback also
*computes* every cross-graph pair just to throw it away.

This kernel is FlashAttention-style online-softmax tiling (PAPERS.md: Dao
et al.; Rabe & Staats) specialized to the sorted block-diagonal layout the
batcher already produces (graphs contiguous along the flat node axis,
data/graph.py):

- grid ``(H, q_blocks, K)``: for query block ``j`` the K inner steps
  stream only the key/value blocks its graphs can touch. The window is
  scheduled like the sorted-segment kernels' ``estart`` scheme
  (ops/pallas_segment.py): ``node_graph`` ascends along the flat layout,
  so a searchsorted over it gives each q-block's first/last k-block as
  scalar-prefetch arrays. Cross-graph tiles are never visited — the block
  index map CLAMPS to the window's last block and ``pl.when`` skips the
  recompute, so an out-of-window step is a zero-cost revisit of an
  already-resident block, not a DMA;
- per visited tile: ``s = q @ k.T`` on the MXU (f32 accumulation),
  same-graph masking by an in-register compare of the streamed per-node
  graph-id column/row (padding nodes carry id -1 and never match), and
  the standard running-max/denominator update in f32 VMEM scratch. The
  ``[*, N, N]`` logits never exist in HBM — only q/k/v tiles and the
  final ``[N, H, d]`` output move;
- inputs stream in their own dtype (bf16 halves the traffic under mixed
  precision); probabilities are cast back to the streaming dtype for the
  ``p @ v`` MXU dot, accumulation stays f32 (the same contract as
  ops/pallas_segment.py).

The kernel also emits the running (max, denominator) statistics, which is
what makes the single-graph regime reusable: ``flash_block_summary``
returns the UN-normalized online-softmax partial ``(m, l, acc)`` of local
queries against one K/V block, and ``parallel/ring_attention.py`` merges
those partials across ring steps in plain jnp — the per-chip block of
ring attention rides the same inner loop instead of a dense einsum.

Differentiation is the house custom-JVP: only the primal runs Pallas; the
tangent rule is the plain-jnp per-graph gathered reference pushed through
``jax.jvp`` (G·Nmax² work, not N²), so reverse mode transposes to the
dense-recompute backward and the op composes under ``jax.grad`` to ANY
order — energy-force (grad-of-grad) training works. Call sites wrap the
op in ``jax.checkpoint`` (models/gps.py) so the tangent residuals (the
per-graph probability blocks) are recomputed in the backward instead of
stored by the forward: the training forward keeps the flash memory
profile, the backward pays the gathered-dense recompute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from ..utils import envflags
from jax.experimental.pallas import tpu as pltpu

from .pallas_segment import _pad_to

# masking constant: large-negative instead of finfo.min so the f32
# running-max arithmetic (exp of differences) never overflows; shared by
# the kernel and the jnp references so their masked maxima agree exactly
_NEG = -1.0e30

# tuned-table key component (tune/table.py): bump on any change to the
# kernel's schedule, block layout, or semantics — stale tuned entries must
# miss, not steer a different program
KERNEL_VERSION = 1


def normalize_tiles(block_q=128, block_k=128):
    """Snap a candidate tile plan to the kernel's alignment contract —
    ``block_q`` to the 16-row sublane tile (covers bf16), ``block_k`` to
    the 128-lane tile. The one clamp site shared by the routing layer and
    the tune plane's table keys (tune/plans.py); the kernel itself requires
    already-aligned blocks."""
    bq = max(16, block_q - block_q % 16)
    bk = max(128, block_k - block_k % 128)
    return bq, bk


def _flash_route_enabled() -> bool:
    """Whether GPS attention routes to the Pallas flash kernel.

    Same trace-time contract as ``ops.segment._pallas_route_enabled``:
    ``HYDRAGNN_PALLAS_FLASH=0/1`` overrides; otherwise the default backend
    decides. Off-TPU forcing runs the kernel in interpret mode (the CPU
    dryrun / CI smoke route).
    """
    pref = envflags.env_force("HYDRAGNN_PALLAS_FLASH")
    if pref is not None:
        return pref
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# plain-jnp references: the flat-masked oracle (tests), the per-graph
# gathered tangent rule, and the one-block summary (ring attention)
# ---------------------------------------------------------------------------


def reference_masked_attention(q, k, v, node_graph, node_mask):
    """Flat ``[N, N]``-masked softmax attention — the dense oracle, stated
    exactly like the ``max_nodes_per_graph == 0`` fallback in models/gps.py
    (rows with no valid key are zeroed rather than left as softmax garbage,
    matching the kernel's empty-row convention)."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)).astype(q.dtype)
    same = (node_graph[:, None] == node_graph[None, :]) & (
        node_mask[:, None] & node_mask[None, :]
    )
    logits = jnp.einsum("ihd,jhd->hij", q, k) * scale
    logits = jnp.where(same[None], logits, jnp.asarray(_NEG, logits.dtype))
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("hij,jhd->ihd", probs, v)
    has_key = jnp.any(same, axis=1)
    return jnp.where(has_key[:, None, None], out, 0.0)


def reference_gathered_attention(q, k, v, node_graph, node_mask, num_graphs,
                                 max_nodes_per_graph):
    """Per-graph gathered dense attention — the ``[G, Nmax]`` layout of
    models/gps.py restated over ``[N, H, d]`` operands. Same function as
    the masked oracle on real rows (graphs within the static bound); this
    is the kernel's TANGENT rule: G·Nmax² work instead of N²."""
    n, _, d = q.shape
    nmax = max_nodes_per_graph
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)).astype(q.dtype)
    counts = jnp.zeros((num_graphs,), jnp.int32).at[node_graph].add(
        node_mask.astype(jnp.int32)
    )
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)]
    )
    slot = jnp.arange(nmax, dtype=jnp.int32)
    valid = slot[None, :] < counts[:, None]
    idx = jnp.where(valid, starts[:, None] + slot[None, :], n - 1)
    qg, kg, vg = q[idx], k[idx], v[idx]  # [G, Nmax, H, d]
    logits = jnp.einsum("gihd,gjhd->ghij", qg, kg) * scale
    logits = jnp.where(
        valid[:, None, None, :], logits, jnp.asarray(_NEG, logits.dtype)
    )
    probs = jax.nn.softmax(logits, axis=-1)
    og = jnp.einsum("ghij,gjhd->gihd", probs, vg)
    out = jnp.zeros_like(q).at[idx.reshape(-1)].add(
        og.reshape(idx.size, *q.shape[1:])
        * valid.reshape(-1, 1, 1).astype(q.dtype)
    )
    return out


def reference_block_summary(q, k, v, key_mask):
    """One online-softmax partial of all queries against ONE key/value
    block, in plain jnp: ``m = rowmax``, ``l = sum exp(s - m)``,
    ``acc = exp(s - m) @ v`` — the quantity ring attention merges across
    steps. Fully-masked rows return ``(_NEG, 0, 0)``."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)).astype(q.dtype)
    logits = jnp.einsum("qhd,khd->qhk", q, k) * scale
    logits = jnp.where(
        key_mask[None, None, :], logits, jnp.asarray(_NEG, logits.dtype)
    )
    m = jnp.max(logits, axis=-1)  # [n_q, H]
    p = jnp.where(
        key_mask[None, None, :], jnp.exp(logits - m[..., None]), 0.0
    )
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("qhk,khd->qhd", p, v)
    return m, l, acc


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _kernel(kstart_ref, klast_ref, gidq_ref, gidk_ref, q_ref, k_ref, v_ref,
            *refs, scale, emit_stats):
    # stats outputs exist only for the block-summary (ring) launch: the
    # self-attention launch would have to WRITE two [H, N, 128] f32 arrays
    # to HBM just to discard them (pallas outputs cannot be DCE'd)
    if emit_stats:
        o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # out-of-window steps clamp their block index to the window's last
    # block (no DMA — the block is already resident) and skip the update
    @pl.when(kstart_ref[j] + kk <= klast_ref[j])
    def _step():
        q = q_ref[0]  # [Bq, d_pad]
        s = jax.lax.dot_general(
            q,
            k_ref[0],
            (((1,), (1,)), ((), ())),  # contract the head dim: q @ k.T
            preferred_element_type=jnp.float32,
        ) * scale  # [Bq, Bk] f32
        # same-graph mask from the streamed graph-id column/row; padding
        # nodes carry id -1 on the KEY side and never match
        mask = (gidq_ref[:] == gidk_ref[:]) & (gidk_ref[:] >= 0)
        s = jnp.where(mask, s, _NEG)
        m_prev = m_scr[:, 0:1]
        l_prev = l_scr[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        # fully-masked tiles keep m_new == m_prev == _NEG: exp(0) == 1 on
        # the correction, so the explicit where() is what zeroes them
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_scr[:] = jnp.broadcast_to(
            l_prev * corr + jnp.sum(p, axis=1, keepdims=True), l_scr.shape
        )
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype),  # bf16 streams hit the MXU fast path
            v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(kk == pl.num_programs(2) - 1)
    def _finalize():
        l = l_scr[:, 0:1]
        # rows with no valid key (padding queries): l == 0, acc == 0 -> 0
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if emit_stats:
            m_ref[0] = m_scr[:]
            l_ref[0] = l_scr[:]


def _forward(q, k, v, gid_q, gid_k, kstart, klast, k_windows,
             block_q, block_k, interpret, emit_stats=False):
    """Shared launch: q ``[Nq, H, d]`` against k/v ``[Nk, H, d]`` with
    per-q-block key-window schedule (kstart/klast in k-block units) and
    per-node graph ids (-1 = never a valid key). Returns the normalized
    ``o [Nq, H, d]`` (operand dtype); with ``emit_stats`` also the f32
    running statistics ``(m [Nq, H], l [Nq, H])`` as extra HBM outputs —
    only the block-summary launch pays for them."""
    nq, h, d = q.shape
    nk = k.shape[0]
    bq, bk = block_q, block_k
    d_pad = d + (-d) % 128
    scale = 1.0 / float(d) ** 0.5

    def _prep(x, blk):
        x = _pad_to(_pad_to(x, blk, 0), 128, 2)
        return jnp.transpose(x, (1, 0, 2))  # [H, N_pad, d_pad]

    qt = _prep(q, bq)
    kt = _prep(k, bk)
    vt = _prep(v, bk)
    nq_pad, nk_pad = qt.shape[1], kt.shape[1]
    j_blocks = nq_pad // bq
    k_blocks = nk_pad // bk
    k_windows = max(1, min(k_windows, k_blocks))

    gq = jnp.full((nq_pad, 1), -1, jnp.int32).at[:nq, 0].set(
        gid_q.astype(jnp.int32)
    )
    gk = jnp.full((1, nk_pad), -1, jnp.int32).at[0, :nk].set(
        gid_k.astype(jnp.int32)
    )
    kstart = jnp.clip(kstart.astype(jnp.int32), 0, k_blocks - 1)
    klast = jnp.clip(klast.astype(jnp.int32), 0, k_blocks - 1)

    def q_index(h_i, j, kk, ks, kl):
        return (h_i, j, 0)

    def kv_index(h_i, j, kk, ks, kl):
        return (h_i, jnp.minimum(ks[j] + kk, kl[j]), 0)

    def gidq_index(h_i, j, kk, ks, kl):
        return (j, 0)

    def gidk_index(h_i, j, kk, ks, kl):
        return (0, jnp.minimum(ks[j] + kk, kl[j]))

    def out_index(h_i, j, kk, ks, kl):
        return (h_i, j, 0)

    grid = (h, j_blocks, k_windows)
    out_specs = [pl.BlockSpec((1, bq, d_pad), out_index)]
    out_shape = [jax.ShapeDtypeStruct((h, nq_pad, d_pad), q.dtype)]
    if emit_stats:
        out_specs += [pl.BlockSpec((1, bq, 128), out_index)] * 2
        out_shape += [jax.ShapeDtypeStruct((h, nq_pad, 128), jnp.float32)] * 2
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, emit_stats=emit_stats),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((bq, 1), gidq_index),
                pl.BlockSpec((1, bk), gidk_index),
                pl.BlockSpec((1, bq, d_pad), q_index),
                pl.BlockSpec((1, bk, d_pad), kv_index),
                pl.BlockSpec((1, bk, d_pad), kv_index),
            ],
            out_specs=out_specs,
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, d_pad), jnp.float32),
            ],
        ),
        out_shape=out_shape,
        interpret=interpret,
    )(kstart, klast, gq, gk, qt, kt, vt)
    o = jnp.transpose(out[0], (1, 0, 2))[:nq, :, :d]
    if not emit_stats:
        return o
    m = jnp.transpose(out[1][:, :, 0])[:nq]  # [Nq, H]
    l = jnp.transpose(out[2][:, :, 0])[:nq]
    return o, m, l


def _block_windows(node_graph, n, block_q, block_k, max_nodes_per_graph):
    """Per-q-block key-window schedule over the flat node layout.

    ``node_graph`` ascends (graphs contiguous, padding nodes in the final
    slot — data/graph.py), so the window of q-block ``j`` spans from the
    first node of the graph owning its first row to the last node of the
    graph owning its last row. The static inner-step count covers the
    worst legal window: a q block can touch at most
    ``block_q + 2·(Nmax - 1)`` nodes.
    """
    ng = node_graph.astype(jnp.int32)
    j_blocks = (n + block_q - 1) // block_q
    row0 = jnp.minimum(
        jnp.arange(j_blocks, dtype=jnp.int32) * block_q, n - 1
    )
    row1 = jnp.minimum(row0 + block_q - 1, n - 1)
    first = jnp.searchsorted(ng, ng[row0], side="left").astype(jnp.int32)
    last = jnp.searchsorted(ng, ng[row1], side="right").astype(jnp.int32) - 1
    k_windows = (block_q + 2 * max(max_nodes_per_graph - 1, 0)
                 + block_k - 1) // block_k + 1
    return first // block_k, last // block_k, k_windows


@functools.partial(jax.custom_jvp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_self_attention(
    q,
    k,
    v,
    node_graph,
    node_mask,
    num_graphs: int,
    max_nodes_per_graph: int,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Segment-masked flash self-attention over the flat node array.

    ``q``/``k``/``v``: ``[N, H, d]``; attention is restricted to same-graph
    real-node pairs (``node_graph``/``node_mask``), exactly the semantics
    of both dense paths in models/gps.py. Requires the batcher's layout:
    graphs CONTIGUOUS along the node axis (``node_graph`` non-decreasing,
    padding nodes in the final slot) — the block schedule derives from it.
    A real graph larger than the static ``max_nodes_per_graph`` bound gets
    an UNSPECIFIED value (its key window is under-covered); the model
    layer poisons that case to NaN, same as the gathered-dense path.
    Padding rows come out 0 (the dense oracle leaves softmax garbage
    there; both are masked downstream).

    ``block_q`` must be a multiple of the sublane tile (16 covers bf16),
    ``block_k`` of the 128-lane tile. Returns ``[N, H, d]`` in the operand
    dtype; logits/softmax accumulate in f32 and never touch HBM.
    Differentiable to arbitrary order (custom-JVP whose tangent is the
    plain-jnp gathered-dense reference), so energy-force training
    composes; wrap call sites in ``jax.checkpoint`` to keep the tangent
    residuals out of the training forward.
    """
    n = q.shape[0]
    gid = jnp.where(node_mask, node_graph.astype(jnp.int32), -1)
    kstart, klast, k_windows = _block_windows(
        node_graph, n, block_q, block_k, max_nodes_per_graph
    )
    return _forward(
        q, k, v, gid, gid, kstart, klast, k_windows, block_q, block_k,
        interpret,
    )


@flash_self_attention.defjvp
def _flash_jvp(num_graphs, max_nodes_per_graph, block_q, block_k, interpret,
               primals, tangents):
    q, k, v, node_graph, node_mask = primals
    t_q, t_k, t_v, _, _ = tangents
    out = flash_self_attention(
        q, k, v, node_graph, node_mask, num_graphs, max_nodes_per_graph,
        block_q, block_k, interpret,
    )
    # tangent in PLAIN jnp — the per-graph gathered reference (G·Nmax²,
    # not N²) pushed through jax.jvp: linear in the tangents, built from
    # transposable primitives, differentiable to any order. Reverse mode
    # transposes it into the dense-recompute backward; jax.checkpoint at
    # the call site pushes its residuals (the per-graph probability
    # blocks) into the backward pass.
    fn = lambda q_, k_, v_: reference_gathered_attention(
        q_, k_, v_, node_graph, node_mask, num_graphs, max_nodes_per_graph
    )
    _, t_out = jax.jvp(fn, (q, k, v), (t_q, t_k, t_v))
    return out, t_out


@functools.partial(jax.custom_jvp, nondiff_argnums=(4, 5, 6))
def flash_block_summary(
    q,
    k,
    v,
    key_mask,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Online-softmax partial of local queries against ONE key/value block
    — the single-graph regime of the flash kernel, reusing its inner loop.

    ``q [n_q, H, d]`` against ``k/v [n_k, H, d]`` with ``key_mask [n_k]``;
    returns ``(m [n_q, H], l [n_q, H], acc [n_q, H, d])`` such that the
    normalized attention over several blocks is the standard running-max
    merge of their partials (parallel/ring_attention.py does the merging
    in plain jnp between ``ppermute`` rotations). Fully-masked rows give
    ``(-1e30, 0, 0)``. Statistics are f32 inside the kernel and cast to
    the operand dtype on return (the ring carries match the dense route's
    dtypes either way).
    """
    nq, nk = q.shape[0], k.shape[0]
    gid_q = jnp.zeros((nq,), jnp.int32)
    gid_k = jnp.where(key_mask, 0, -1).astype(jnp.int32)
    k_blocks = (nk + block_k - 1) // block_k
    kstart = jnp.zeros((max(1, (nq + block_q - 1) // block_q),), jnp.int32)
    klast = jnp.full_like(kstart, k_blocks - 1)
    o, m, l = _forward(
        q, k, v, gid_q, gid_k, kstart, klast, k_blocks, block_q, block_k,
        interpret, emit_stats=True,
    )
    dt = q.dtype
    # un-normalize: acc = o * l (exact where l > 0; both zero where l == 0)
    return m.astype(dt), l.astype(dt), o * l[..., None].astype(dt)


@flash_block_summary.defjvp
def _summary_jvp(block_q, block_k, interpret, primals, tangents):
    q, k, v, key_mask = primals
    t_q, t_k, t_v, _ = tangents
    out = flash_block_summary(q, k, v, key_mask, block_q, block_k, interpret)
    fn = lambda q_, k_, v_: jax.tree_util.tree_map(
        lambda x: x.astype(q.dtype),
        reference_block_summary(q_, k_, v_, key_mask),
    )
    _, t_out = jax.jvp(fn, (q, k, v), (t_q, t_k, t_v))
    return out, t_out
