"""Int8 quantization primitives (docs/SERVING.md "Quantization").

Per-channel symmetric int8 for inference weights: each output channel of a
dense kernel gets its own fp32 scale (``amax / 127`` over the input axis),
so the quantization error of one wide-ranged channel never bleeds into its
neighbors — the standard post-training recipe (Jacob et al. 2018). Symmetric
(no zero point) keeps the integer matmul a plain ``lax.dot_general`` with an
int32 accumulator and the dequant a single fused multiply.

Two consumers (serve/quantize.py):

- weight-only: kernels live in HBM as int8 + a ``[1, out]`` scale;
  ``dequantize`` runs inside the jitted predict, where XLA fuses the
  convert+scale into the matmul's operand read — activations stay f32;
- w8a8: activations are quantized against a *static* calibrated scale
  (max-abs over template batches / 127 — no per-batch reduction in the
  serving path), then ``int8_matmul`` accumulates int8 x int8 in int32 and
  one ``a_scale * w_scale`` multiply rescales the product.

The block-plan surface (``normalize_tiles`` + the ``int8_dot`` entry in
tune/plans.py) keys int8 executions as their own axis of the tuned table:
an int8 plan can never be confused with (or silently reuse) an f32/bf16
entry for the same shapes.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
from jax import lax

#: bumping this invalidates tuned-table entries for the int8_dot plan
#: (tune/plans.py kernel_version contract)
KERNEL_VERSION = 1

#: symmetric int8 range: +-127 (the -128 slot is unused so negation is
#: closed and the scale math stays symmetric)
INT8_MAX = 127.0


def normalize_tiles(rows: int, cols: int, k: int, block_m: int,
                    block_n: int, block_k: int) -> Tuple[int, int, int]:
    """Clamp an int8_dot block plan to the operand extents (lane-padded to
    the 128 MXU lane width), the same normalize-before-key contract as the
    Pallas kernels: equivalent plans collapse to one tuned-table entry."""

    def _clamp(block: int, extent: int) -> int:
        block = max(int(block), 8)
        if extent > 0:
            block = min(block, max(-(-int(extent) // 128) * 128, 8))
        return block

    return (_clamp(block_m, rows), _clamp(block_n, cols), _clamp(block_k, k))


def quantize_per_channel(w) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-output-channel int8 quantization of a dense kernel.

    ``w`` is ``[in, out]`` (or branch-banked ``[B, in, out]``); the scale
    reduces over the input axis (``-2``) with keepdims, giving ``[1, out]``
    (``[B, 1, out]``) so ``q * scale`` broadcasts back to the kernel shape.
    All-zero channels get scale 1.0 — they quantize to 0 and dequantize to
    0 exactly, without a 0/0 in the round."""
    w = jnp.asarray(w)
    amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True).astype(jnp.float32)
    scale = jnp.where(amax > 0.0, amax / INT8_MAX, 1.0)
    q = jnp.clip(
        jnp.round(w.astype(jnp.float32) / scale), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)
    return q, scale


def dequantize(q, scale, dtype=jnp.float32):
    """``q * scale`` in ``dtype`` — inside a jitted predict XLA keeps the
    int8 array resident and fuses the convert into the consuming matmul."""
    return q.astype(dtype) * scale.astype(dtype)


def quantize_activations(x, act_scale):
    """Quantize activations against a static calibrated scale (w8a8).
    Out-of-range activations saturate at +-127 — the max-abs calibration
    over the warmed template batches makes saturation the tail case."""
    return jnp.clip(
        jnp.round(x / act_scale), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)


def int8_matmul(x_q, w_q) -> jnp.ndarray:
    """int8 x int8 contraction with an int32 accumulator: contracts the
    last axis of ``x_q`` against the first of ``w_q`` (the dense-layer
    layout). ``preferred_element_type=int32`` is the whole point — an int8
    accumulator would overflow at K > ~2, and f32 accumulation would
    forfeit the integer MXU path this mode exists for.

    Consults the ``int8_dot`` tile plan (tune/runtime.py) at trace time so
    int8 executions are announced and tuned under their own dtype axis;
    the plan is advisory for the XLA lowering but is the tuned-table key
    a Pallas int8 kernel will consume verbatim."""
    try:  # keying/announcement only — never allowed to fail the matmul
        from ..tune.runtime import tile_plan

        tile_plan(
            "int8_dot",
            {
                "rows": int(x_q.shape[0]) if x_q.ndim > 1 else 1,
                "cols": int(w_q.shape[-1]),
                "k": int(w_q.shape[0]),
            },
            dtype="int8",
        )
    except Exception:  # noqa: BLE001 — advisory plane
        pass
    return lax.dot_general(
        x_q,
        w_q,
        (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
