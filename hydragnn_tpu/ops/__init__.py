from .segment import (
    fused_edge_message_sum,
    masked_global_mean_pool,
    masked_global_sum_pool,
    multi_moment_agg,
    segment_count,
    segment_max,
    segment_mean,
    segment_min,
    segment_softmax,
    segment_std,
    segment_sum,
)

__all__ = [
    "fused_edge_message_sum",
    "multi_moment_agg",
    "masked_global_mean_pool",
    "masked_global_sum_pool",
    "segment_count",
    "segment_max",
    "segment_mean",
    "segment_min",
    "segment_softmax",
    "segment_std",
    "segment_sum",
]
