"""Masked segment reductions — the TPU replacement for torch_scatter.

The reference's message passing relies on torch_scatter/PyG CUDA scatter
kernels (SURVEY §2.3 item 2). On TPU the idiomatic lowering is
``jax.ops.segment_sum`` over statically shaped arrays: XLA turns sorted
segment reductions into efficient one-pass kernels and fuses the surrounding
elementwise math. Padding edges/nodes are neutralized by masks rather than by
dynamic shapes.

All functions take ``num_segments`` statically so shapes stay fixed under jit.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from ..utils import envflags


def _pallas_route_enabled() -> bool:
    """Whether ``sorted_ids`` segment sums route to the Pallas MXU kernel.

    ``jax.default_backend()`` is evaluated at trace time, which is correct
    for the supported configurations (the framework jits for the default
    backend); ``HYDRAGNN_PALLAS_SEGMENT=0/1`` overrides for a jit that
    targets a non-default device.
    """
    pref = envflags.env_force("HYDRAGNN_PALLAS_SEGMENT")
    if pref is not None:
        return pref
    return jax.default_backend() == "tpu"


def _debug_check_sorted(segment_ids) -> None:
    """Opt-in (HYDRAGNN_DEBUG_SORTED=1) runtime check that segment_ids is
    non-decreasing — ``sorted_ids=True`` is otherwise an unchecked caller
    promise, and an unsorted batch (e.g. hand-built at inference, bypassing
    GraphLoader's sort_edges) would silently produce wrong sums."""

    def _host_assert(ids):
        import numpy as np

        ids = np.asarray(ids)
        if ids.size and (np.diff(ids) < 0).any():
            raise AssertionError(
                "segment_sum(sorted_ids=True) received unsorted segment_ids; "
                "build batches with GraphLoader(sort_edges=True) or disable "
                "use_sorted_aggregation"
            )

    jax.debug.callback(_host_assert, segment_ids)


def _mask_messages(messages: jnp.ndarray, mask: Optional[jnp.ndarray], fill: float = 0.0):
    if mask is None:
        return messages
    m = mask.reshape(mask.shape + (1,) * (messages.ndim - mask.ndim))
    return jnp.where(m, messages, fill)


def segment_sum(
    messages,
    segment_ids,
    num_segments,
    mask=None,
    sorted_ids: bool = False,
    max_degree: Optional[int] = None,
):
    """Scatter-add of edge messages.

    With ``sorted_ids=True`` (receiver-sorted edge arrays, built by
    ``GraphLoader(sort_edges=True)``) and a static in-degree bound
    ``max_degree`` (config ``max_in_degree``, measured over the dataset),
    the TPU backend routes through the Pallas MXU kernel
    (ops/pallas_segment.py) instead of XLA's serialized scatter. Any other
    backend, or 1-D messages, falls back to ``jax.ops.segment_sum``.
    """
    msg = _mask_messages(messages, mask)
    if sorted_ids and envflags.env_force("HYDRAGNN_DEBUG_SORTED"):
        _debug_check_sorted(segment_ids)
    if sorted_ids and max_degree and msg.ndim == 2 and _pallas_route_enabled():
        from .pallas_segment import sorted_segment_sum
        from ..tune.runtime import tile_plan

        # block constants come from the tuned-table lookup (tuned entry for
        # this (kernel, device, shape, dtype) if one exists, else the pinned
        # defaults, normalized either way so equivalent plans share one jit
        # specialization — tune/runtime.py)
        plan = tile_plan("segment_sum", {
            "edges": msg.shape[0], "channels": msg.shape[1],
            "num_segments": num_segments, "max_degree": max_degree,
        }, msg.dtype)
        # forcing the route on a non-TPU backend (HYDRAGNN_PALLAS_SEGMENT=1,
        # e.g. the CPU-mesh dryrun) runs the kernel in interpret mode —
        # same program, Python-evaluated blocks
        return sorted_segment_sum(
            msg, segment_ids, num_segments, max_degree,
            block_rows=plan["block_rows"], block_edges=plan["block_edges"],
            block_cols=plan["block_cols"],
            interpret=jax.default_backend() != "tpu",
        )
    return jax.ops.segment_sum(msg, segment_ids, num_segments=num_segments)


def fused_edge_message_sum(
    node_recv,
    edge_in,
    weights,
    bias,
    segment_ids,
    num_segments,
    max_degree: int,
):
    """Fused gather -> edge dense -> segment sum of the edge hot path:

        segment_sum(relu(relu(node_recv[ids] + edge_in) @ weights + bias))

    Routing mirrors ``segment_sum``: receiver-sorted ids + a static
    in-degree bound on a TPU jit target go through the VMEM-resident Pallas
    kernel (ops/pallas_fused_edge.py) — per-edge messages never touch HBM;
    ``HYDRAGNN_PALLAS_SEGMENT=1`` forces the route off-TPU in interpret
    mode (the CPU-mesh dryrun / CI smoke); any other backend falls back to
    the dense plain-jnp reference, which is the same function. Both routes
    differentiate to arbitrary order (the kernel's tangent rule is plain
    jnp), so energy-force training composes.
    """
    if envflags.env_force("HYDRAGNN_DEBUG_SORTED"):
        _debug_check_sorted(segment_ids)
    if max_degree and _pallas_route_enabled():
        from .pallas_fused_edge import fused_edge_message_sum as _pallas_fused
        from ..tune.runtime import tile_plan

        plan = tile_plan("fused_edge", {
            "edges": edge_in.shape[0], "ci": edge_in.shape[1],
            "co": weights.shape[1], "num_segments": num_segments,
            "max_degree": max_degree, "dtype": str(edge_in.dtype),
        }, edge_in.dtype)
        return _pallas_fused(
            node_recv, edge_in, weights, bias, segment_ids, num_segments,
            max_degree, block_rows=plan["block_rows"],
            block_edges=plan["block_edges"], block_cols=plan["block_cols"],
            interpret=jax.default_backend() != "tpu",
        )
    from .pallas_fused_edge import reference_edge_message_sum

    return reference_edge_message_sum(
        node_recv, edge_in, weights, bias, segment_ids, num_segments
    )


def _multiagg_route_enabled() -> bool:
    """Whether ``multi_moment_agg`` routes to the fused multi-moment Pallas
    kernel. ``HYDRAGNN_PALLAS_MULTIAGG=0/1`` is the dedicated override;
    unset, the decision falls through to ``HYDRAGNN_PALLAS_SEGMENT`` /
    the TPU-backend default, so one env flip drives every sorted kernel
    in an A/B (the multichip dryrun relies on that)."""
    pref = envflags.env_force("HYDRAGNN_PALLAS_MULTIAGG")
    if pref is not None:
        return pref
    return _pallas_route_enabled()


def multi_moment_agg(
    edge_in,
    segment_ids,
    num_segments,
    node_recv=None,
    gate=None,
    mask=None,
    sorted_ids: bool = False,
    max_degree: int = 0,
):
    """Multi-moment aggregation of ``(node_recv[ids] + edge_in) * gate``:
    the five moments ``(sum, count, min, max, sumsq)`` every PNA-family
    aggregate-and-scale derives from, in ONE pass — f32 each,
    ``node_recv``/``gate`` optional (None).

    Routing mirrors ``segment_sum``: receiver-sorted ids + a static
    in-degree bound on a TPU jit target go through the multi-output
    Pallas kernel (ops/pallas_multi_agg.py) — the [E, C] messages never
    round-trip HBM; ``HYDRAGNN_PALLAS_MULTIAGG=1`` (or the shared
    ``HYDRAGNN_PALLAS_SEGMENT=1``) forces the route off-TPU in interpret
    mode; any other backend falls back to the dense plain-jnp reference,
    which is the same function. Both routes differentiate to arbitrary
    order (the kernel's tangent rule is plain jnp), so energy-force
    training composes. ``mask`` is honored only on the dense route — the
    sorted layout neutralizes padding edges by construction (they all
    land on the final dummy node, masked downstream)."""
    if sorted_ids and envflags.env_force("HYDRAGNN_DEBUG_SORTED"):
        _debug_check_sorted(segment_ids)
    from .pallas_multi_agg import fused_multi_agg, reference_multi_agg

    if (sorted_ids and max_degree and edge_in.ndim == 2
            and _multiagg_route_enabled()):
        from ..tune.runtime import tile_plan

        # normalizing HERE (tile_plan always returns a clamped plan) is
        # also the fix for the specialization-key bug: the kernel clamps
        # block_cols to the lane-padded channel width internally, but the
        # custom_jvp nondiff args — and hence the jit executable cache —
        # used to key on the caller's unclamped value
        plan = tile_plan("multi_agg", {
            "edges": edge_in.shape[0], "channels": edge_in.shape[1],
            "num_segments": num_segments, "max_degree": max_degree,
            "has_recv": node_recv is not None, "has_gate": gate is not None,
            "dtype": str(edge_in.dtype),
        }, edge_in.dtype)
        return fused_multi_agg(
            node_recv, edge_in, gate, segment_ids, num_segments, max_degree,
            block_rows=plan["block_rows"], block_edges=plan["block_edges"],
            block_cols=plan["block_cols"], chunk_edges=plan["chunk_edges"],
            interpret=jax.default_backend() != "tpu",
        )
    return reference_multi_agg(
        node_recv, edge_in, gate, segment_ids, num_segments, mask=mask
    )


def segment_count(segment_ids, num_segments, mask=None):
    ones = jnp.ones(segment_ids.shape[:1], jnp.float32)
    if mask is not None:
        ones = jnp.where(mask, ones, 0.0)
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)


def segment_mean(
    messages,
    segment_ids,
    num_segments,
    mask=None,
    eps: float = 0.0,
    sorted_ids: bool = False,
    max_degree: Optional[int] = None,
):
    s = segment_sum(
        messages, segment_ids, num_segments, mask,
        sorted_ids=sorted_ids, max_degree=max_degree,
    )
    n = segment_count(segment_ids, num_segments, mask)
    n = jnp.maximum(n, 1.0) if eps == 0.0 else n + eps
    return s / n.reshape(n.shape + (1,) * (s.ndim - 1))


def segment_max(messages, segment_ids, num_segments, mask=None):
    neg = jnp.finfo(messages.dtype).min
    m = jax.ops.segment_max(
        _mask_messages(messages, mask, neg), segment_ids, num_segments=num_segments
    )
    # segments with no (real) incoming messages -> 0, like torch_scatter 'max'
    return jnp.where(m <= neg / 2, 0.0, m)


def segment_min(messages, segment_ids, num_segments, mask=None):
    pos = jnp.finfo(messages.dtype).max
    m = jax.ops.segment_min(
        _mask_messages(messages, mask, pos), segment_ids, num_segments=num_segments
    )
    return jnp.where(m >= pos / 2, 0.0, m)


def segment_std(messages, segment_ids, num_segments, mask=None, eps: float = 1e-5):
    """Population std per segment (PNA 'std' aggregator semantics).

    Guarded against catastrophic cancellation: the moments accumulate in
    f32 regardless of the message dtype, and the E[x²]−E[x]² variance is
    clamped at zero BEFORE the sqrt — a bf16 near-constant segment
    otherwise yields a small negative variance (E[x²] and E[x]² agree to
    ~8 bits and the subtraction is pure rounding noise) and a NaN std
    that poisons the whole PNA step."""
    m = messages.astype(jnp.float32)
    mean = segment_mean(m, segment_ids, num_segments, mask)
    mean_sq = segment_mean(m * m, segment_ids, num_segments, mask)
    var = jnp.maximum(mean_sq - mean**2, 0.0)
    return jnp.sqrt(var + eps).astype(messages.dtype)


def segment_softmax(logits, segment_ids, num_segments, mask=None):
    """Numerically stable softmax within each segment (GAT attention)."""
    neg = jnp.finfo(logits.dtype).min
    masked = _mask_messages(logits, mask, neg)
    seg_max = jax.ops.segment_max(masked, segment_ids, num_segments=num_segments)
    seg_max = jnp.where(seg_max <= neg / 2, 0.0, seg_max)
    shifted = masked - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if mask is not None:
        exp = _mask_messages(exp, mask, 0.0)
    denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
    return exp / jnp.maximum(denom[segment_ids], 1e-16)


def gather(values, index):
    """Row gather: values[index] — spelled out for symmetry with scatter."""
    return jnp.take(values, index, axis=0)


def masked_global_mean_pool(x, node_graph, num_graphs, node_mask):
    """Per-graph mean over real nodes (reference: global_mean_pool, Base.py:478)."""
    return segment_mean(x, node_graph, num_graphs, node_mask)


def masked_global_sum_pool(x, node_graph, num_graphs, node_mask):
    return segment_sum(x, node_graph, num_graphs, node_mask)
