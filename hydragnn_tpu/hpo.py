"""Hyperparameter optimization glue
(reference: hydragnn/utils/hpo/deephyper.py:5-177, which carves SLURM node
lists into per-trial srun launch commands for DeepHyper/Optuna studies).

TPU-native equivalents:
- ``parse_slurm_nodelist`` — generic SLURM nodelist expansion (the
  reference hardcodes frontier/perlmutter name shapes; this parses any
  ``prefix[a-b,c,...]`` pattern);
- ``suggest_config`` / ``run_hpo`` — an in-process search driver over the
  JSON config (random search by default, Optuna TPE when importable) whose
  objective is the best validation loss from ``run_training``. Each TPU
  trial runs on the local chips; multi-host studies launch one driver per
  pod slice with a distinct ``trial_offset``.

Search-space spec: a dict mapping a "/"-separated config path to either a
list of categorical choices or a ("loguniform"|"uniform", low, high) tuple,
e.g. ``{"NeuralNetwork/Architecture/hidden_dim": [32, 64, 128],
"NeuralNetwork/Training/Optimizer/learning_rate":
("loguniform", 1e-4, 1e-1)}``.
"""

from __future__ import annotations

import copy
import json
import math
import os
import re
import subprocess
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
from .utils import envflags


def parse_slurm_nodelist(node_list: str) -> List[str]:
    """Expand 'prefix[0001-0003,0007]' (possibly mixed with bare hostnames)
    to explicit host names (reference: read_node_list, deephyper.py:13-45)."""
    # split on top-level commas only (commas inside [...] separate ranges)
    items: List[str] = []
    depth = 0
    cur = ""
    for ch in node_list:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            items.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        items.append(cur)

    out: List[str] = []
    for item in items:
        item = item.strip()
        if not item:
            continue
        m = re.fullmatch(r"([^\[\]]+)\[([^\]]+)\]", item)
        if m is None:
            out.append(item)
            continue
        prefix, body = m.group(1), m.group(2)
        for part in body.split(","):
            if "-" in part:
                a, b = part.split("-")
                width = len(a)
                for i in range(int(a), int(b) + 1):
                    out.append(f"{prefix}{str(i).zfill(width)}")
            else:
                out.append(f"{prefix}{part}")
    return out


def _set_path(config: Dict[str, Any], path: str, value: Any) -> None:
    keys = path.split("/")
    node = config
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def suggest_config(
    base_config: Dict[str, Any],
    search_space: Dict[str, Any],
    rng: np.random.Generator,
) -> Dict[str, Any]:
    """One random draw from the search space applied to a config copy."""
    config = copy.deepcopy(base_config)
    for path, spec in search_space.items():
        if isinstance(spec, (list, tuple)) and spec and spec[0] in (
            "uniform",
            "loguniform",
        ):
            kind, lo, hi = spec
            if kind == "loguniform":
                value = float(math.exp(rng.uniform(math.log(lo), math.log(hi))))
            else:
                value = float(rng.uniform(lo, hi))
        else:
            value = spec[int(rng.integers(len(spec)))]
        _set_path(config, path, value)
    return config


def _surface_trial_metrics(
    run_dir: str,
    trial_id,
    study_dir: str,
    offsets: Optional[Dict[str, int]] = None,
) -> Optional[str]:
    """Copy one trial run's telemetry files (``metrics.jsonl``,
    ``scalars.jsonl``, ``trace.jsonl`` — whichever exist) into
    ``<study_dir>/trials/trial_<id>/``, so the parent study sees every
    worker's per-trial signal stream without digging through per-run log
    dirs.

    ``offsets`` (a per-source byte-cursor map the caller keeps across
    trials) makes the copy *incremental*: the streams are append-mode, so
    two trials whose configs resolve to the same log name share one
    physical file — without the cursor, trial N's surfaced copy would
    contain trials 0..N-1's records too. Returns the surfaced directory,
    or None when the trial appended no telemetry. Best-effort: surfacing
    failure never fails the trial."""
    out = os.path.join(study_dir, "trials", f"trial_{trial_id}")
    copied = False
    for fname in ("metrics.jsonl", "scalars.jsonl", "trace.jsonl"):
        src = os.path.join(run_dir, fname)
        try:
            if not os.path.exists(src):
                continue
            start = (offsets or {}).get(src, 0)
            with open(src, "rb") as fh:
                fh.seek(start)
                data = fh.read()
            if offsets is not None:
                offsets[src] = start + len(data)
            if not data:
                continue
            os.makedirs(out, exist_ok=True)
            with open(os.path.join(out, fname), "wb") as fh:
                fh.write(data)
            copied = True
        except OSError:
            pass
    return out if copied else None


def run_hpo(
    base_config: Dict[str, Any],
    search_space: Dict[str, Any],
    num_trials: int = 10,
    seed: int = 0,
    trial_offset: int = 0,
    objective: Optional[Callable[[Dict[str, Any]], float]] = None,
    use_optuna: Optional[bool] = None,
    study_dir: Optional[str] = None,
) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Run an HPO study; returns (best_config, trial records).

    ``objective(config) -> loss`` defaults to training the config with the
    public API and reporting the best validation loss. With Optuna available
    (and not disabled), the sampler is TPE; otherwise pure random search.

    Every trial runs under a ``HYDRAGNN_TRIAL_ID`` label: the telemetry
    plane stamps it into each ``metrics.jsonl`` record (obs/telemetry.py)
    and the registry publishes it as ``hydragnn_hpo_trial``, so a worker's
    signals are attributable per trial instead of hiding behind the run
    name. ``study_dir`` (default: the ``HYDRAGNN_HPO_STUDY_DIR`` env, which
    ``launch_hpo_workers`` exports as the parent workdir) makes the default
    objective additionally surface each trial's metric files into
    ``<study_dir>/trials/trial_<id>/`` (docs/OBSERVABILITY.md "HPO").
    """
    if study_dir is None:
        study_dir = envflags.env_str("HYDRAGNN_HPO_STUDY_DIR") or None
    # worker-qualified labels: launch_hpo_workers gives every worker an
    # overlapping trial_offset range (offset+i seeds the sampler stream),
    # so bare numeric ids would collide across workers — two workers'
    # trials/trial_3/ dirs silently overwriting each other. The exported
    # HYDRAGNN_HPO_WORKER index disambiguates both the surfaced dirs and
    # the "trial" labels in metrics.jsonl.
    worker = envflags.env_str("HYDRAGNN_HPO_WORKER")
    surf_offsets: Dict[str, int] = {}

    if objective is None:

        def objective(config: Dict[str, Any]) -> float:
            from .api import run_training
            from .config import get_log_name_config

            _, _, hist, cfg_out, *_ = run_training(config)
            if study_dir:
                _surface_trial_metrics(
                    os.path.join("./logs", get_log_name_config(cfg_out)),
                    envflags.env_str("HYDRAGNN_TRIAL_ID", "unknown"),
                    study_dir,
                    offsets=surf_offsets,
                )
            return float(np.min(hist["val"]))

    # trial-id labeling wraps WHATEVER objective runs (default or custom):
    # the env label scopes exactly the trial's lifetime, and the registry
    # gauge makes the active trial scrapeable on a worker's endpoint
    inner_objective = objective
    trial_counter = iter(range(trial_offset, trial_offset + max(num_trials, 1)))

    def objective(config: Dict[str, Any]) -> float:
        tid = next(trial_counter, trial_offset + num_trials)
        prev = envflags.env_str("HYDRAGNN_TRIAL_ID")
        os.environ["HYDRAGNN_TRIAL_ID"] = (
            f"w{worker}.{tid}" if worker is not None else str(tid)
        )
        try:
            from .obs.registry import registry

            registry().gauge(
                "hydragnn_hpo_trial",
                "Trial id currently running in this HPO worker",
            ).set(tid)
        except Exception:
            pass
        try:
            return inner_objective(config)
        finally:
            if prev is None:
                os.environ.pop("HYDRAGNN_TRIAL_ID", None)
            else:
                os.environ["HYDRAGNN_TRIAL_ID"] = prev

    if use_optuna is None:
        try:
            import optuna  # noqa: F401

            use_optuna = True
        except ImportError:
            use_optuna = False

    trials: List[Dict[str, Any]] = []

    if use_optuna:
        import optuna

        def optuna_objective(trial):
            config = copy.deepcopy(base_config)
            for path, spec in search_space.items():
                name = path.replace("/", ".")
                if isinstance(spec, (list, tuple)) and spec and spec[0] in (
                    "uniform",
                    "loguniform",
                ):
                    kind, lo, hi = spec
                    value = trial.suggest_float(name, lo, hi, log=kind == "loguniform")
                else:
                    value = trial.suggest_categorical(name, list(spec))
                _set_path(config, path, value)
            loss = objective(config)
            trials.append({"config": config, "loss": loss})
            return loss

        study = optuna.create_study(
            sampler=optuna.samplers.TPESampler(seed=seed + trial_offset)
        )
        study.optimize(optuna_objective, n_trials=num_trials)
        best = min(trials, key=lambda t: t["loss"])
        return best["config"], trials

    rng = np.random.default_rng(seed + trial_offset)
    for _ in range(num_trials):
        config = suggest_config(base_config, search_space, rng)
        loss = objective(config)
        trials.append({"config": config, "loss": loss})
    best = min(trials, key=lambda t: t["loss"])
    return best["config"], trials


def append_trial_records(path: str, trials: Sequence[Dict[str, Any]]) -> None:
    """Append trial records as JSONL (one ``{"loss", "config"}`` per line) —
    the worker side of a parallel study."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "a") as fh:
        for t in trials:
            fh.write(json.dumps({"loss": t["loss"], "config": t["config"]}) + "\n")


def merge_hpo_results(paths: Sequence[str]) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Merge per-worker JSONL trial records -> (best_config, all trials)."""
    trials: List[Dict[str, Any]] = []
    for p in paths:
        if not os.path.exists(p):
            continue
        with open(p) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    trials.append(json.loads(line))
    if not trials:
        raise RuntimeError(f"no HPO trial records found in {list(paths)}")
    best = min(trials, key=lambda t: t["loss"])
    return best["config"], trials


def launch_hpo_workers(
    argv_template: Sequence[str],
    num_workers: int,
    num_trials: int,
    workdir: str,
    hosts: Optional[Sequence[str]] = None,
    env: Optional[Dict[str, str]] = None,
    timeout: Optional[float] = None,
    trial_offset: int = 0,
) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Async multi-worker HPO orchestration (the DeepHyper analog: the
    reference carves a SLURM node list into per-trial srun launch commands,
    deephyper.py:47-177; here each worker is a subprocess — optionally
    ssh-prefixed onto a carved host — drawing an INDEPENDENT sampler stream
    and appending JSONL records the parent merges).

    ``argv_template`` tokens may contain ``{worker}``, ``{num_trials}``,
    ``{trial_offset}``, ``{results}`` placeholders. Trials are split as
    evenly as possible; worker ``i`` gets ``trial_offset=trial_offset+i``,
    which seeds its sampler as ``seed + offset`` (run_hpo). That makes the
    streams independent, NOT disjoint: in a small categorical space two
    workers can draw the same config (the study simply spends a duplicate
    trial, as the reference's parallel DeepHyper evaluator also can).
    ``env`` entries are applied to each worker: exported into the local
    subprocess environment (merged over ``os.environ``), and in ``hosts``
    mode additionally prefixed onto the remote command as ``env K=V ...``
    so they reach the remote process, not just the local ssh client.
    ``timeout`` bounds the WHOLE study; on timeout or a failed worker every
    remaining subprocess is terminated. ``hosts`` round-robins workers over
    ssh (tokens are shell-quoted for the remote side; ``workdir`` must live
    on a filesystem shared with the hosts — on clusters without one, point
    it at the shared scratch the scheduler provides, as the reference's
    per-node DeepHyper launches do). Returns the merged
    ``(best_config, trials)``.
    """
    import time as _time

    if num_workers < 1:
        raise ValueError("num_workers must be >= 1")
    os.makedirs(workdir, exist_ok=True)
    # the parent workdir IS the study dir: each worker's run_hpo surfaces
    # per-trial metric files into <workdir>/trials/trial_w<i>.<id>/
    # (run_hpo study_dir + worker-label resolution), closing the "HPO
    # workers hide their signals" gap — the parent reads one directory,
    # not N per-run log trees. The worker index rides the env too: the
    # per-worker trial_offset ranges overlap by design (sampler seeding),
    # so the index is what keeps surfaced dirs and trial labels disjoint.
    base_env = {**(env or {}), "HYDRAGNN_HPO_STUDY_DIR": os.path.abspath(workdir)}
    shares = [
        num_trials // num_workers + (1 if i < num_trials % num_workers else 0)
        for i in range(num_workers)
    ]
    procs: List[Tuple[int, subprocess.Popen, str]] = []
    results: List[str] = []
    logs: List[Any] = []
    failures: List[Tuple[int, Any]] = []
    try:
        for i, share in enumerate(shares):
            if share == 0:
                continue
            res = os.path.join(workdir, f"trials_worker{i}.jsonl")
            if os.path.exists(res):
                os.remove(res)
            results.append(res)
            worker_env = {**base_env, "HYDRAGNN_HPO_WORKER": str(i)}
            argv = [
                tok.format(
                    worker=i, num_trials=share,
                    trial_offset=trial_offset + i, results=res,
                )
                for tok in argv_template
            ]
            if hosts:
                # ssh concatenates the remote argv into one shell line —
                # quote each token or paths with spaces/metachars re-split.
                # env entries must ride the REMOTE command (Popen(env=...)
                # would only configure the local ssh client).
                import shlex

                argv = ["env"] + [
                    f"{k}={v}" for k, v in worker_env.items()
                ] + argv
                argv = ["ssh", hosts[i % len(hosts)]] + [
                    shlex.quote(t) for t in argv
                ]
            log = open(os.path.join(workdir, f"worker{i}.log"), "w")
            logs.append(log)
            procs.append(
                (
                    i,
                    subprocess.Popen(
                        argv, stdout=log, stderr=subprocess.STDOUT,
                        env={**os.environ, **worker_env},
                    ),
                    res,
                )
            )
        deadline = None if timeout is None else _time.monotonic() + timeout
        for i, proc, res in procs:
            remain = (
                None if deadline is None
                else max(deadline - _time.monotonic(), 0.0)
            )
            try:
                rc = proc.wait(timeout=remain)
            except subprocess.TimeoutExpired:
                failures.append((i, "timeout"))
                continue
            if rc != 0:
                failures.append((i, rc))
    finally:
        # never leave detached workers training unsupervised: on any
        # failure/timeout/exception, terminate whatever still runs
        for _, proc, _ in procs:
            if proc.poll() is None:
                proc.terminate()
        for _, proc, _ in procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        for log in logs:
            log.close()
    if failures:
        # surface the failure, not just the fact of it: each failed worker's
        # log tail rides the exception, so the jax.distributed/env class of
        # errors that previously hid in hpo_workers/*.log (a file the parent
        # never read) is in the parent's traceback directly
        detail = []
        for i, reason in failures:
            log_path = os.path.join(workdir, f"worker{i}.log")
            try:
                with open(log_path, encoding="utf-8", errors="replace") as fh:
                    tail = fh.read()[-2000:].strip()
            except OSError as e:
                tail = f"<log unreadable: {e}>"
            detail.append(
                f"--- worker {i} (reason: {reason}; log: {log_path}) ---\n"
                + (tail or "<empty log>")
            )
        raise RuntimeError(
            f"HPO workers failed (worker, reason): {failures}; log tails:\n"
            + "\n".join(detail)
        )
    return merge_hpo_results(results)
