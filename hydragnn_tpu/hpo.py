"""Hyperparameter optimization glue
(reference: hydragnn/utils/hpo/deephyper.py:5-177, which carves SLURM node
lists into per-trial srun launch commands for DeepHyper/Optuna studies).

TPU-native equivalents:
- ``parse_slurm_nodelist`` — generic SLURM nodelist expansion (the
  reference hardcodes frontier/perlmutter name shapes; this parses any
  ``prefix[a-b,c,...]`` pattern);
- ``suggest_config`` / ``run_hpo`` — an in-process search driver over the
  JSON config (random search by default, Optuna TPE when importable) whose
  objective is the best validation loss from ``run_training``. Each TPU
  trial runs on the local chips; multi-host studies launch one driver per
  pod slice with a distinct ``trial_offset``.

Search-space spec: a dict mapping a "/"-separated config path to either a
list of categorical choices or a ("loguniform"|"uniform", low, high) tuple,
e.g. ``{"NeuralNetwork/Architecture/hidden_dim": [32, 64, 128],
"NeuralNetwork/Training/Optimizer/learning_rate":
("loguniform", 1e-4, 1e-1)}``.
"""

from __future__ import annotations

import copy
import math
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


def parse_slurm_nodelist(node_list: str) -> List[str]:
    """Expand 'prefix[0001-0003,0007]' (possibly mixed with bare hostnames)
    to explicit host names (reference: read_node_list, deephyper.py:13-45)."""
    # split on top-level commas only (commas inside [...] separate ranges)
    items: List[str] = []
    depth = 0
    cur = ""
    for ch in node_list:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            items.append(cur)
            cur = ""
        else:
            cur += ch
    if cur:
        items.append(cur)

    out: List[str] = []
    for item in items:
        item = item.strip()
        if not item:
            continue
        m = re.fullmatch(r"([^\[\]]+)\[([^\]]+)\]", item)
        if m is None:
            out.append(item)
            continue
        prefix, body = m.group(1), m.group(2)
        for part in body.split(","):
            if "-" in part:
                a, b = part.split("-")
                width = len(a)
                for i in range(int(a), int(b) + 1):
                    out.append(f"{prefix}{str(i).zfill(width)}")
            else:
                out.append(f"{prefix}{part}")
    return out


def _set_path(config: Dict[str, Any], path: str, value: Any) -> None:
    keys = path.split("/")
    node = config
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def suggest_config(
    base_config: Dict[str, Any],
    search_space: Dict[str, Any],
    rng: np.random.Generator,
) -> Dict[str, Any]:
    """One random draw from the search space applied to a config copy."""
    config = copy.deepcopy(base_config)
    for path, spec in search_space.items():
        if isinstance(spec, (list, tuple)) and spec and spec[0] in (
            "uniform",
            "loguniform",
        ):
            kind, lo, hi = spec
            if kind == "loguniform":
                value = float(math.exp(rng.uniform(math.log(lo), math.log(hi))))
            else:
                value = float(rng.uniform(lo, hi))
        else:
            value = spec[int(rng.integers(len(spec)))]
        _set_path(config, path, value)
    return config


def run_hpo(
    base_config: Dict[str, Any],
    search_space: Dict[str, Any],
    num_trials: int = 10,
    seed: int = 0,
    trial_offset: int = 0,
    objective: Optional[Callable[[Dict[str, Any]], float]] = None,
    use_optuna: Optional[bool] = None,
) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Run an HPO study; returns (best_config, trial records).

    ``objective(config) -> loss`` defaults to training the config with the
    public API and reporting the best validation loss. With Optuna available
    (and not disabled), the sampler is TPE; otherwise pure random search.
    """
    if objective is None:

        def objective(config: Dict[str, Any]) -> float:
            from .api import run_training

            _, _, hist, *_ = run_training(config)
            return float(np.min(hist["val"]))

    if use_optuna is None:
        try:
            import optuna  # noqa: F401

            use_optuna = True
        except ImportError:
            use_optuna = False

    trials: List[Dict[str, Any]] = []

    if use_optuna:
        import optuna

        def optuna_objective(trial):
            config = copy.deepcopy(base_config)
            for path, spec in search_space.items():
                name = path.replace("/", ".")
                if isinstance(spec, (list, tuple)) and spec and spec[0] in (
                    "uniform",
                    "loguniform",
                ):
                    kind, lo, hi = spec
                    value = trial.suggest_float(name, lo, hi, log=kind == "loguniform")
                else:
                    value = trial.suggest_categorical(name, list(spec))
                _set_path(config, path, value)
            loss = objective(config)
            trials.append({"config": config, "loss": loss})
            return loss

        study = optuna.create_study(
            sampler=optuna.samplers.TPESampler(seed=seed + trial_offset)
        )
        study.optimize(optuna_objective, n_trials=num_trials)
        best = min(trials, key=lambda t: t["loss"])
        return best["config"], trials

    rng = np.random.default_rng(seed + trial_offset)
    for _ in range(num_trials):
        config = suggest_config(base_config, search_space, rng)
        loss = objective(config)
        trials.append({"config": config, "loss": loss})
    best = min(trials, key=lambda t: t["loss"])
    return best["config"], trials
