"""hydragnn_tpu: a TPU-native (JAX/XLA/Pallas) multi-headed graph neural
network framework with the capability surface of HydraGNN (+GPS support).

Public API mirrors the reference (hydragnn/__init__.py:1-3):
``run_training(config)`` / ``run_prediction(config)`` plus model IO helpers,
and ``run_server(config)`` — the fault-tolerant micro-batched serving plane
built on top of ``run_prediction``'s machinery (docs/SERVING.md).
"""

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy imports keep `import hydragnn_tpu` light (no jax init on import).
    if name in ("run_training", "run_prediction", "run_server",
                "run_server_fleet"):
        from . import api

        return getattr(api, name)
    if name in ("save_model", "load_existing_model"):
        from .train import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(name)
