"""Config migration lint: audit a (reference-style) JSON config against this
framework's config surface.

The JSON surface is intentionally the reference's (SURVEY §2 row 2;
config completion in config/config.py), so most reference configs run
unchanged. This tool makes the remainder explicit instead of silent: for
every key it reports whether it is HANDLED here, NOT-APPLICABLE by design
on TPU (with the equivalent to use instead), a LEGACY reference key with a
direct replacement, or UNKNOWN (likely a typo — unknown keys are otherwise
ignored by config completion, which is how the reference behaves too).

Usage:
    python -m hydragnn_tpu.config.lint path/to/config.json
    >>> from hydragnn_tpu.config.lint import lint_config
    >>> findings = lint_config(json.load(open("config.json")))

Reference key census: union of /root/reference/examples/*/*.json and
tests/inputs/*.json key paths (see docs/MIGRATION.md).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List

# sub-dicts whose members are schema'd elsewhere (heads/optimizer/features)
# or are free-form — lint stops descending at these paths
_OPAQUE = {
    "NeuralNetwork.Architecture.output_heads",
    "NeuralNetwork.Training.Optimizer",
    "NeuralNetwork.Training.Checkpoint",
    # elastic-fleet sub-dict (enabled/min_hosts/grace_s; train/elastic.py)
    "NeuralNetwork.Training.elastic",
    "Dataset.node_features",
    "Dataset.graph_features",
    "Dataset.path",
    "Dataset.synthetic",
    "Dataset.lennard_jones",
    "Dataset.Descriptors",
    "Mixture.weights",
    "Mixture.branch_loss_weights",
    # the resolved rule table api.py records for restore replay
    # (parallel/rules.py table_from_recorded)
    "Parallel.resolved_rules",
    # int8 quantization sub-dict — schema'd strictly by
    # serve/config.py QuantizationSpec.resolve (unknown keys FAIL there)
    "Serving.quantization",
}

# exact key paths this framework consumes (config/config.py completion,
# models/create.py, api.py, train/loop.py, docs/CONFIG.md)
_HANDLED = {
    "Verbosity.level",
    "Dataset.name",
    "Dataset.format",
    "Dataset.path",
    "Dataset.node_features",
    "Dataset.graph_features",
    "Dataset.compositional_stratified_splitting",
    "Dataset.rotational_invariance",
    "Dataset.normalize",
    "Dataset.synthetic",
    "Dataset.lennard_jones",
    "Dataset.bad_sample_policy",
    "Dataset.lappe_cache",
    "Dataset.edge_features",
    "Dataset.Descriptors",
    "Dataset.charge_density_correction",
    "Dataset.mode",
    "NeuralNetwork.Profile",
    "NeuralNetwork.Profile.enable",
    "NeuralNetwork.Profile.target_epoch",
    "NeuralNetwork.Architecture.mpnn_type",
    "NeuralNetwork.Architecture.activation_function",
    "NeuralNetwork.Architecture.equivariance",
    "NeuralNetwork.Architecture.radius",
    "NeuralNetwork.Architecture.max_neighbours",
    "NeuralNetwork.Architecture.periodic_boundary_conditions",
    "NeuralNetwork.Architecture.hidden_dim",
    "NeuralNetwork.Architecture.num_conv_layers",
    "NeuralNetwork.Architecture.output_heads",
    "NeuralNetwork.Architecture.task_weights",
    "NeuralNetwork.Architecture.output_dim",
    "NeuralNetwork.Architecture.output_type",
    "NeuralNetwork.Architecture.input_dim",
    "NeuralNetwork.Architecture.edge_dim",
    "NeuralNetwork.Architecture.edge_features",
    "NeuralNetwork.Architecture.num_nodes",
    "NeuralNetwork.Architecture.pna_deg",
    "NeuralNetwork.Architecture.num_gaussians",
    "NeuralNetwork.Architecture.num_filters",
    "NeuralNetwork.Architecture.num_radial",
    "NeuralNetwork.Architecture.num_spherical",
    "NeuralNetwork.Architecture.envelope_exponent",
    "NeuralNetwork.Architecture.radial_type",
    "NeuralNetwork.Architecture.distance_transform",
    "NeuralNetwork.Architecture.basis_emb_size",
    "NeuralNetwork.Architecture.int_emb_size",
    "NeuralNetwork.Architecture.out_emb_size",
    "NeuralNetwork.Architecture.num_before_skip",
    "NeuralNetwork.Architecture.num_after_skip",
    "NeuralNetwork.Architecture.max_ell",
    "NeuralNetwork.Architecture.node_max_ell",
    "NeuralNetwork.Architecture.correlation",
    "NeuralNetwork.Architecture.avg_num_neighbors",
    "NeuralNetwork.Architecture.global_attn_engine",
    "NeuralNetwork.Architecture.global_attn_type",
    "NeuralNetwork.Architecture.global_attn_heads",
    "NeuralNetwork.Architecture.pe_dim",
    "NeuralNetwork.Architecture.max_nodes_per_graph",
    "NeuralNetwork.Architecture.freeze_conv_layers",
    "NeuralNetwork.Architecture.initial_bias",
    "NeuralNetwork.Architecture.use_sorted_aggregation",
    "NeuralNetwork.Architecture.max_in_degree",
    "NeuralNetwork.Architecture.use_fused_edge_kernel",
    "NeuralNetwork.Architecture.use_flash_attention",
    "NeuralNetwork.Architecture.branch_loss_weights",
    "NeuralNetwork.Architecture.branch_loss_metrics",
    "NeuralNetwork.Architecture.dropout",
    "NeuralNetwork.Architecture.decoder_mirror_init",
    "NeuralNetwork.Architecture.decoder_recovery_slope",
    "NeuralNetwork.Variables_of_interest.input_node_features",
    "NeuralNetwork.Variables_of_interest.output_names",
    "NeuralNetwork.Variables_of_interest.output_index",
    "NeuralNetwork.Variables_of_interest.output_dim",
    "NeuralNetwork.Variables_of_interest.type",
    "NeuralNetwork.Variables_of_interest.denormalize_output",
    "NeuralNetwork.Variables_of_interest.graph_feature_names",
    "NeuralNetwork.Variables_of_interest.graph_feature_dims",
    "NeuralNetwork.Variables_of_interest.node_feature_names",
    "NeuralNetwork.Variables_of_interest.node_feature_dims",
    "NeuralNetwork.Training.num_epoch",
    "NeuralNetwork.Training.batch_size",
    "NeuralNetwork.Training.perc_train",
    "NeuralNetwork.Training.loss_function_type",
    "NeuralNetwork.Training.EarlyStopping",
    "NeuralNetwork.Training.patience",
    "NeuralNetwork.Training.seed",
    "NeuralNetwork.Training.continue",
    "NeuralNetwork.Training.startfrom",
    "NeuralNetwork.Training.Checkpoint",
    "NeuralNetwork.Training.checkpoint_warmup",
    "NeuralNetwork.Training.checkpoint_backend",
    "NeuralNetwork.Training.checkpoint_retention",
    "NeuralNetwork.Training.non_finite_policy",
    "NeuralNetwork.Training.non_finite_rollback_after",
    "NeuralNetwork.Training.non_finite_lr_backoff",
    "NeuralNetwork.Training.non_finite_max_rollbacks",
    "NeuralNetwork.Training.loader_stall_timeout",
    "NeuralNetwork.Training.compile_cache_dir",
    "NeuralNetwork.Training.precompile",
    "NeuralNetwork.Training.retrace_policy",
    "NeuralNetwork.Training.autotune",
    "NeuralNetwork.Training.autotune_budget",
    "NeuralNetwork.Training.autotune_cache_dir",
    "NeuralNetwork.Training.compute_grad_energy",
    "NeuralNetwork.Training.conv_checkpointing",
    "NeuralNetwork.Training.remat_policy",
    "NeuralNetwork.Training.Optimizer",
    "NeuralNetwork.Training.elastic",
    "NeuralNetwork.Training.mixed_precision",
    "NeuralNetwork.Training.pack_batches",
    "NeuralNetwork.Training.num_pad_buckets",
    "NeuralNetwork.Training.size_bucketed_batching",
    "NeuralNetwork.Training.branch_parallel",
    "NeuralNetwork.Training.double_buffer",
    "NeuralNetwork.Training.warmup_epochs",
    "NeuralNetwork.Training.walltime_minutes",
    "NeuralNetwork.Training.return_best",
    "NeuralNetwork.Training.oversampling",
    "NeuralNetwork.Training.num_samples",
    "NeuralNetwork.Training.balance_branch_sampling",
    "NeuralNetwork.Training.CheckRemainingTime",
    "Visualization.create_plots",
    "Serving.max_queue_requests",
    "Serving.micro_batch_graphs",
    "Serving.batch_window_s",
    "Serving.default_deadline_s",
    "Serving.slo_p99_s",
    "Serving.expected_latency_per_graph_s",
    "Serving.step_timeout_s",
    "Serving.retrace_policy",
    "Serving.hot_reload",
    "Serving.reload_poll_s",
    "Serving.drain_timeout_s",
    "Serving.http_port",
    "Serving.http_host",
    "Serving.weights_dtype",
    "Serving.drain_grace_s",
    "Serving.fleet_replicas",
    "Serving.fleet_restart_backoff_s",
    "Serving.fleet_restart_backoff_max_s",
    "Serving.fleet_flap_window_s",
    "Serving.fleet_flap_max_restarts",
    "Serving.fleet_ready_floor",
    "Serving.router_timeout_s",
    "Serving.router_retries",
    "Serving.router_backoff_s",
    "Serving.router_hedge_factor",
    "Serving.router_hedge_min_s",
    "Serving.breaker_failures",
    "Serving.breaker_cooldown_s",
    "Serving.prediction_cache",
    "Serving.quantization",
    "Serving.reload_error_spike",
    "Serving.reload_probe_requests",
    "Telemetry.enabled",
    "Telemetry.interval_steps",
    "Telemetry.http_port",
    "Telemetry.http_host",
    "Telemetry.mfu",
    "Telemetry.jsonl",
    "Telemetry.profile_trigger",
    "Telemetry.profile_steps",
    "Telemetry.trace",
    "Telemetry.trace_sample",
    "Telemetry.trace_interval_steps",
    "Telemetry.flight_recorder",
    "Telemetry.numerics",
    "Telemetry.fleet",
    "Telemetry.fleet_collector",
    "Telemetry.fleet_collector_port",
    "Telemetry.fleet_collector_host",
    "Telemetry.fleet_straggler_factor",
    "Telemetry.fleet_max_step_lag",
    "Telemetry.fleet_stale_after_s",
    "Telemetry.fleet_collective_budget",
    "Telemetry.fleet_sharding_audit_bytes",
    "Mixture.temperature",
    "Mixture.weights",
    "Mixture.draws_per_epoch",
    "Mixture.balance",
    "Mixture.branch_loss_weights",
    "Mixture.drift_ema_decay",
    "Mixture.drift_threshold",
    "Mixture.demote_after",
    "Mixture.seed",
    # sharding rule engine (parallel/rules.py resolve; docs/PARALLELISM.md)
    "Parallel.rules",
    "Parallel.min_size",
    "Parallel.model_size",
    "Parallel.routed",
    "Parallel.name",
    "Parallel.resolved_rules",
}

# reference keys that are intentionally NOT consumed here, with the
# TPU-native answer a migrating user needs
_NOT_APPLICABLE = {
    "NeuralNetwork.Architecture.SyncBatchNorm": (
        "no DDP process groups to sync: batch-norm statistics are computed "
        "over the (masked) global batch inside the jitted step "
        "(models/layers.py MaskedBatchNorm); multi-device runs reduce via "
        "the mesh, so the torch SyncBatchNorm wrapper has no analog to "
        "enable"
    ),
}

# a couple of reference tests/inputs configs predate the NeuralNetwork
# nesting and put Architecture at the top level — one uniform rename
_LEGACY_TOPLEVEL_ARCH = (
    "legacy top-level 'Architecture' section (pre-NeuralNetwork layout, "
    "reference tests/inputs/ci_periodic.json) — nest the keys under "
    "NeuralNetwork.Architecture ('periodic' becomes "
    "'periodic_boundary_conditions'; 'predicted_value_option' is "
    "superseded by Variables_of_interest.output_index/type)"
)

# legacy/renamed reference keys -> what to use here
_LEGACY = {
    "NeuralNetwork.Training.early_stopping": (
        "use 'EarlyStopping' (capitalized, the reference's current key)"
    ),
    "NeuralNetwork.Training.epoch_start": (
        "resume is 'Training.continue: 1' (+ optional 'startfrom'); the "
        "epoch counter restores from the checkpoint"
    ),
    "NeuralNetwork.Architecture.predicted_value_option": (
        "superseded by Variables_of_interest.output_index/type (the "
        "reference itself migrated off this key)"
    ),
    "Visualization.plot_init_solution": (
        "visualizer plot families are selected by the postprocess API "
        "(postprocess/visualizer.py); 'create_plots' gates them all"
    ),
    "Visualization.plot_hist_solution": (
        "visualizer plot families are selected by the postprocess API "
        "(postprocess/visualizer.py); 'create_plots' gates them all"
    ),
}

# top-level Dataset/Architecture synonyms appearing in some reference
# example configs at non-standard paths ("Serving", "Telemetry", "Mixture"
# and "Parallel" are this framework's own sections — no reference analog;
# docs/SERVING.md, docs/OBSERVABILITY.md, docs/GFM.md, docs/PARALLELISM.md)
_TOPLEVEL_SECTIONS = (
    "Verbosity", "Dataset", "NeuralNetwork", "Visualization", "Serving",
    "Telemetry", "Mixture", "Parallel",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    status: str  # handled | not-applicable | legacy | unknown
    path: str
    message: str = ""


def _walk(d: Dict[str, Any], prefix: str = "") -> List[str]:
    # never descends into _OPAQUE subtrees, so no yielded path has an
    # opaque entry as a proper prefix (their children are schema'd elsewhere)
    out = []
    for k, v in d.items():
        p = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        out.append(p)
        if isinstance(v, dict) and p not in _OPAQUE:
            out.extend(_walk(v, p))
    return out


def lint_config(config: Dict[str, Any]) -> List[Finding]:
    findings: List[Finding] = []
    for path in _walk(config):
        if path in _NOT_APPLICABLE:
            findings.append(Finding("not-applicable", path, _NOT_APPLICABLE[path]))
        elif path == "Architecture" or path.startswith("Architecture."):
            findings.append(Finding("legacy", path, _LEGACY_TOPLEVEL_ARCH))
        elif path in _LEGACY:
            findings.append(Finding("legacy", path, _LEGACY[path]))
        elif path in _HANDLED or path in _TOPLEVEL_SECTIONS:
            findings.append(Finding("handled", path))
        elif path in (
            "NeuralNetwork.Architecture",
            "NeuralNetwork.Variables_of_interest",
            "NeuralNetwork.Training",
            "NeuralNetwork.Profile",
        ):
            findings.append(Finding("handled", path))
        else:
            findings.append(
                Finding(
                    "unknown",
                    path,
                    "not consumed by this framework (config completion "
                    "ignores unknown keys, matching the reference's "
                    "behavior) — check for a typo or see docs/CONFIG.md",
                )
            )
    return findings


def format_report(findings: List[Finding]) -> str:
    order = {"unknown": 0, "legacy": 1, "not-applicable": 2, "handled": 3}
    lines = []
    counts: Dict[str, int] = {}
    for f in sorted(findings, key=lambda f: (order[f.status], f.path)):
        counts[f.status] = counts.get(f.status, 0) + 1
        if f.status == "handled":
            continue
        lines.append(f"[{f.status}] {f.path}: {f.message}")
    lines.append(
        "summary: "
        + ", ".join(f"{counts.get(s, 0)} {s}" for s in order)
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    import sys

    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m hydragnn_tpu.config.lint config.json")
        return 2
    # exit codes: 0 = clean, 1 = unknown keys found, 2 = could not lint —
    # migration scripts branch on 1 vs 2
    try:
        with open(argv[0]) as fh:
            config = json.load(fh)
    except OSError as e:
        print(f"hydragnn_tpu.config.lint: cannot read {argv[0]}: {e}")
        return 2
    except json.JSONDecodeError as e:
        print(f"hydragnn_tpu.config.lint: {argv[0]} is not valid JSON: {e}")
        return 2
    if not isinstance(config, dict):
        print(
            f"hydragnn_tpu.config.lint: {argv[0]} is a JSON "
            f"{type(config).__name__}, expected an object"
        )
        return 2
    findings = lint_config(config)
    print(format_report(findings))
    return 1 if any(f.status == "unknown" for f in findings) else 0


if __name__ == "__main__":
    raise SystemExit(main())
