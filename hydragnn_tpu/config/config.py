"""JSON config system: defaults, data-derived completion, merge, save.

Same JSON surface as the reference (four top sections ``Verbosity``,
``Dataset``, ``NeuralNetwork`` {Architecture, Variables_of_interest, Training},
``Visualization``) and the same "config is completed from data" behavior
(reference: hydragnn/utils/input_config_parsing/config_utils.py:25-161).
"""

from __future__ import annotations

import copy
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..data.graph import Graph
from ..data.pipeline import VariablesOfInterest
from ..utils import envflags


def _jit_target_inference() -> tuple:
    """(is_tpu, source): whether jitted steps will target a TPU — WITHOUT
    initializing the backend — plus WHICH heuristic decided, so the
    decision can be logged when it flips a default (ADVICE r5 #1: the
    libtpu fallback can guess TPU before backend init; if runtime init
    later fails and jax lands on CPU, the log line is what makes the
    persisted ``use_sorted_aggregation: true`` diagnosable). Config
    completion may run before the multi-host rendezvous
    (jax.distributed.initialize must precede the first backend touch, or
    setup_distributed silently degrades to single-host — parallel/mesh.py),
    so ``jax.default_backend()`` may only be consulted if the backend
    already exists."""
    plats = os.environ.get("JAX_PLATFORMS", "").lower()
    if plats:
        # explicit platform list: jax uses the first entry ("axon" is the
        # tunneled-TPU plugin platform used by this image's test rig)
        first = plats.split(",")[0].strip()
        return first in ("tpu", "axon"), f"JAX_PLATFORMS={plats!r}"
    try:
        import jax._src.xla_bridge as xb

        if getattr(xb, "_backends", None):
            import jax

            backend = jax.default_backend()
            return backend == "tpu", f"initialized backend {backend!r}"
    except Exception:  # pragma: no cover - private-API drift tolerance
        pass
    # backend uninitialized and no explicit platform: jax will pick a TPU
    # runtime iff one is importable (highest platform priority)
    import importlib.util

    has_libtpu = importlib.util.find_spec("libtpu") is not None
    return has_libtpu, (
        "libtpu importable (backend uninitialized)" if has_libtpu
        else "no libtpu, backend uninitialized"
    )

# Architecture keys defaulted to None when absent
# (reference: config_utils.py:98-156 one-by-one ifs).
_ARCH_NONE_DEFAULTS = (
    "radius",
    "radial_type",
    "distance_transform",
    "num_gaussians",
    "num_filters",
    "envelope_exponent",
    "num_after_skip",
    "num_before_skip",
    "basis_emb_size",
    "int_emb_size",
    "out_emb_size",
    "num_radial",
    "num_spherical",
    "correlation",
    "max_ell",
    "node_max_ell",
    "initial_bias",
)

EQUIVARIANT_MODELS = ("EGNN", "SchNet", "PNAEq", "PAINN", "MACE")
PNA_MODELS = ("PNA", "PNAPlus", "PNAEq")


def merge_config(base: Dict[str, Any], overlay: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive deep-merge; overlay wins (reference: config_utils.py:380-388)."""
    out = copy.deepcopy(base)
    for k, v in overlay.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = merge_config(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def degree_histogram(graphs: Sequence[Graph], max_deg: int = 64) -> List[int]:
    """In-degree histogram over all nodes of the dataset, used by PNA scalers
    (reference: gather_deg, graph_samples_checks_and_updates.py:433-490)."""
    hist = np.zeros(max_deg + 1, np.int64)
    top = 0
    for g in graphs:
        deg = np.bincount(g.receivers, minlength=1)
        deg = np.concatenate([deg, np.zeros(g.num_nodes - deg.shape[0], np.int64)])
        h = np.bincount(deg.astype(np.int64), minlength=max_deg + 1)
        if h.shape[0] > hist.shape[0]:
            hist = np.concatenate([hist, np.zeros(h.shape[0] - hist.shape[0], np.int64)])
        hist[: h.shape[0]] += h
        top = max(top, int(deg.max(initial=0)))
    return hist[: top + 1].tolist()


def average_degree(graphs: Sequence[Graph]) -> float:
    """Average in-degree (MACE avg_num_neighbors, reference: model.py:253-276)."""
    e = sum(g.num_edges for g in graphs)
    n = sum(g.num_nodes for g in graphs)
    return float(e) / max(n, 1)


def check_if_graph_size_variable(*datasets: Sequence[Graph]) -> bool:
    """(reference: graph_samples_checks_and_updates.py:32-87)"""
    env = envflags.env_flag("HYDRAGNN_USE_VARIABLE_GRAPH_SIZE")
    if env is not None:
        return env
    sizes = {g.num_nodes for ds in datasets for g in ds}
    return len(sizes) > 1


def voi_from_config(config: Dict[str, Any]) -> VariablesOfInterest:
    """Build the VariablesOfInterest selector from a (completed) config."""
    var = config["NeuralNetwork"]["Variables_of_interest"]
    ds = config.get("Dataset", {})
    node_dims = ds.get("node_features", {}).get("dim", [1])
    graph_dims = ds.get("graph_features", {}).get("dim", [])
    return VariablesOfInterest(
        input_node_features=var["input_node_features"],
        output_names=var["output_names"],
        output_types=var["type"],
        output_index=var["output_index"],
        node_feature_dims=node_dims,
        graph_feature_dims=graph_dims,
    )


def update_config(
    config: Dict[str, Any],
    trainset: Sequence[Graph],
    valset: Sequence[Graph],
    testset: Sequence[Graph],
) -> Dict[str, Any]:
    """Complete a user config from the data, in place of the reference's
    ``update_config`` (config_utils.py:25-161). Returns a new dict.

    Derived fields: input_dim, per-head output dims/types, PNA degree
    histogram, MACE avg_num_neighbors, GPS defaults, edge_dim, ~20 optional
    keys, equivariance checks.
    """
    config = copy.deepcopy(config)
    arch = config["NeuralNetwork"]["Architecture"]
    training = config["NeuralNetwork"]["Training"]
    var = config["NeuralNetwork"]["Variables_of_interest"]

    # one pass over the datasets: size variability + the static per-graph
    # node bound (the latter lets GPS attention run per-graph dense
    # [B, Nmax, C] instead of batch-wide [N, N] — reference semantics:
    # to_dense_batch in hydragnn/globalAtt/gps.py:125-141)
    sizes = {g.num_nodes for ds in (trainset, valset, testset) for g in ds}
    env = envflags.env_flag("HYDRAGNN_USE_VARIABLE_GRAPH_SIZE")
    graph_size_variable = env if env is not None else len(sizes) > 1
    arch["graph_size_variable"] = graph_size_variable
    arch["max_nodes_per_graph"] = max(sizes, default=0)

    # GPS defaults (reference: config_utils.py:40-47)
    arch.setdefault("global_attn_engine", None)
    arch.setdefault("global_attn_type", None)
    arch.setdefault("global_attn_heads", 0)
    arch.setdefault("pe_dim", 0)

    training.setdefault("compute_grad_energy", False)
    # pad-spec bucketing (SURVEY §5.7): >1 builds a SpecLadder in the loaders
    training.setdefault("num_pad_buckets", 4 if graph_size_variable else 1)

    # ---- outputs (reference: update_config_NN_outputs, config_utils.py:219-260)
    voi = voi_from_config(config)
    sample = trainset[0]
    output_dim: List[int] = []
    if training["compute_grad_energy"]:
        # energy-force training: dims taken verbatim from the config
        # (reference: config_utils.py:223-224)
        if "output_dim" not in var:
            raise KeyError(
                "Training.compute_grad_energy requires "
                "Variables_of_interest.output_dim (the nodal-energy head "
                "dims, usually [1]) since they cannot be derived from data"
            )
        output_dim = [int(d) for d in var["output_dim"]]
    else:
        for t, idx in zip(voi.output_types, voi.output_index):
            if t == "graph":
                output_dim.append(int(voi.graph_feature_dims[idx]))
            elif t == "node":
                dim = int(voi.node_feature_dims[idx])
                node_head = arch["output_heads"].get("node", {})
                if isinstance(node_head, list):  # multibranch list form
                    node_head = node_head[0].get("architecture", {}) if node_head else {}
                if not graph_size_variable and node_head.get("type") == "mlp_per_node":
                    dim *= sample.num_nodes
                output_dim.append(dim)
            else:
                raise ValueError(f"output type {t!r} not graph or node")
    arch["output_dim"] = output_dim
    arch["output_type"] = list(voi.output_types)
    arch["num_nodes"] = sample.num_nodes
    var.setdefault("denormalize_output", False)

    arch["input_dim"] = voi.input_dim

    # ---- PNA degree histogram / MACE average degree
    if arch["mpnn_type"] in PNA_MODELS:
        deg = degree_histogram(trainset)
        arch["pna_deg"] = deg
        arch["max_neighbours"] = len(deg) - 1
    else:
        arch["pna_deg"] = None
    if arch["mpnn_type"] == "MACE":
        arch["avg_num_neighbors"] = average_degree(trainset)
    else:
        arch["avg_num_neighbors"] = None

    # ---- Pallas sorted-segment aggregation: static in-degree bound over
    # EVERY split (eval batches must satisfy the cap too; the kernel gives
    # unspecified sums for real segments past it — ops/pallas_segment.py)
    #
    # r5 default: ON when jitting for TPU — the first live A/B matrix
    # measured the Pallas MXU route +16.5% over XLA's scatter at the SC25
    # production shape (883.1 vs 757.9 graphs/sec/chip, mp on;
    # logs/ab_matrix.jsonl r5) with loss agreement to 3 decimals and an
    # exact interpret==dense dryrun check. Non-TPU backends keep the
    # default off: the Pallas route never activates there
    # (ops/segment.py:_pallas_route_enabled) and leaving the edge order
    # unsorted keeps CPU batches byte-stable with earlier rounds.
    # Explicit true/false in the config always wins.
    #
    # Grad-energy configs are INCLUDED since r6: the kernels differentiate
    # through a custom-JVP whose tangent rule is plain jnp
    # (ops/pallas_segment.py, ops/pallas_fused_edge.py), so the
    # energy-force objective's grad-of-grad composes; the r5 first-order
    # custom-VJP guard (which raised here) is gone. fused==dense on the
    # energy+force loss is asserted by tests/test_fused_edge.py and the
    # multichip dryrun (__graft_entry__._dryrun_sorted_agg).
    if "use_sorted_aggregation" not in arch or arch["use_sorted_aggregation"] is None:
        on, source = _jit_target_inference()
        arch["use_sorted_aggregation"] = on
        if on:
            # the libtpu heuristic can decide before backend init; print the
            # inference source so a later CPU fallback is diagnosable from
            # the log even though the persisted config says sorted=true
            # (ADVICE r5 #1). stderr: never mixes into stdout protocols.
            print(
                "[hydragnn_tpu.config] use_sorted_aggregation auto-enabled: "
                f"jit target inferred as TPU from {source}",
                file=sys.stderr,
            )
    if arch.get("use_sorted_aggregation"):
        top = 1
        for g in (*trainset, *valset, *testset):
            if g.num_edges:
                top = max(top, int(np.bincount(np.asarray(g.receivers)).max()))
        supplied = arch.get("max_in_degree")
        if supplied and int(supplied) < top:
            # a stale bound copied from another run would make the kernel
            # silently drop messages — fail loudly instead
            raise ValueError(
                f"max_in_degree={supplied} is below the dataset's actual "
                f"max in-degree {top}; remove the key to auto-measure"
            )
        arch["max_in_degree"] = int(supplied or top)
    arch.setdefault("max_in_degree", 0)

    # ---- fused edge hot path: auto-on wherever sorted aggregation is on —
    # it shares the sorted-receivers + max_in_degree contract and falls
    # back to the identical dense computation off-TPU (ops/segment.py
    # routing), so the flag is safe to carry on any backend. ONE knob, two
    # kernels: EGNN's single-consumer messages ride the gather -> dense ->
    # segment-sum kernel (ops/pallas_fused_edge.py, models/egnn.py); the
    # PNA family's multi-consumer messages ride the multi-output moment
    # kernel (ops/pallas_multi_agg.py, models/pna*.py — one pass emits
    # sum/count/min/max/sumsq, HYDRAGNN_PALLAS_MULTIAGG overrides).
    # Explicit true/false wins for A/B (bench.py BENCH_FUSED / BENCH_PNA).
    if ("use_fused_edge_kernel" not in arch
            or arch["use_fused_edge_kernel"] is None):
        arch["use_fused_edge_kernel"] = bool(arch["use_sorted_aggregation"])
    elif arch["use_fused_edge_kernel"] and not arch["use_sorted_aggregation"]:
        # without receiver-sorted batches + the degree bound the fused path
        # can never engage (models/egnn.py) — a silent no-op here would let
        # an A/B "measure" the fused kernel against itself; fail loudly,
        # mirroring the stale-max_in_degree treatment above
        raise ValueError(
            "use_fused_edge_kernel requires use_sorted_aggregation: the "
            "fused edge kernel rides the sorted-receivers + max_in_degree "
            "contract. Enable use_sorted_aggregation (or drop the explicit "
            "use_fused_edge_kernel, which then follows it automatically)."
        )

    # ---- GPS flash attention (ops/pallas_flash_attention.py): the
    # segment-masked online-softmax kernel for global attention. Auto-on
    # when jitting for TPU and GPS global attention is configured — same
    # inference + logging contract as use_sorted_aggregation above; the
    # dense layouts remain the oracle and the route on every other
    # backend (the model falls back automatically when the kernel cannot
    # engage). NOTE flash configs carry attention-PROB dropout 0 on every
    # backend (the probabilities never exist to mask — models/gps.py);
    # GPSConv's output dropout is unchanged. Explicit true/false wins
    # (bench.py BENCH_GPS A/B cells pin it).
    if "use_flash_attention" not in arch or arch["use_flash_attention"] is None:
        if arch.get("global_attn_engine"):
            on, source = _jit_target_inference()
            arch["use_flash_attention"] = on
            if on:
                # unlike the aggregation kernels this auto-flip is NOT
                # numerics-neutral under training (prob-dropout goes to 0)
                # — say so, so a changed-regularization run is diagnosable
                # from the log
                print(
                    "[hydragnn_tpu.config] use_flash_attention auto-enabled:"
                    f" jit target inferred as TPU from {source}; NOTE GPS"
                    " attention-prob dropout runs at 0 under this flag"
                    " (Architecture.dropout still drives the module-output"
                    " dropout; set use_flash_attention: false for reference"
                    " prob-dropout semantics)",
                    file=sys.stderr,
                )
        else:
            arch["use_flash_attention"] = False

    # CGCNN keeps hidden dim = input dim without global attention
    # (reference: config_utils.py:80-87)
    if arch["mpnn_type"] == "CGCNN" and not arch["global_attn_engine"]:
        arch["hidden_dim"] = arch["input_dim"]

    for key in _ARCH_NONE_DEFAULTS:
        arch.setdefault(key, None)

    # ---- edge dim (reference: update_config_edge_dim, config_utils.py:190-216)
    # (reference: config_utils.py:190-192 — GAT/PNA included)
    edge_models = (
        "GAT", "PNA", "PNAPlus", "PNAEq", "PAINN", "GPS",
        "CGCNN", "SchNet", "EGNN", "DimeNet", "MACE",
    )
    from ..data.transforms import descriptor_edge_dim

    _edge_dim = descriptor_edge_dim(config.get("Dataset", {}))
    if _edge_dim:
        assert (
            arch["mpnn_type"] in edge_models or arch["global_attn_engine"]
        ), "edge features can only be used with edge-aware models"
        # edge_features columns + Descriptors columns (Spherical: 3, PPF: 4)
        arch["edge_dim"] = _edge_dim
    elif arch["mpnn_type"] == "CGCNN":
        arch["edge_dim"] = 0
    else:
        arch.setdefault("edge_dim", None)

    # ---- equivariance (reference: update_config_equivariance, :164-177)
    if arch.get("equivariance"):
        assert arch["mpnn_type"] in EQUIVARIANT_MODELS, (
            "E(3) equivariance can only be ensured for "
            + ", ".join(EQUIVARIANT_MODELS)
        )
    arch.setdefault("equivariance", False)

    arch.setdefault("freeze_conv_layers", False)
    arch.setdefault("activation_function", "relu")
    arch.setdefault("SyncBatchNorm", False)
    arch.setdefault("periodic_boundary_conditions", False)
    arch.setdefault("max_neighbours", None)
    arch.setdefault("num_conv_layers", 1)
    training.setdefault("conv_checkpointing", False)
    # ---- rematerialization policy (docs/PERFORMANCE.md "Multi-aggregate
    # kernel"): which save rule every remat wrap uses — the kernel call
    # sites (fused edge / multi-agg / flash attention) and the whole-loss
    # conv_checkpointing wrap. Default 'full' preserves the historical
    # bare-jax.checkpoint behavior at every site.
    training.setdefault("remat_policy", "full")
    from ..ops.remat import REMAT_POLICIES

    if training["remat_policy"] not in REMAT_POLICIES:
        raise ValueError(
            f"Training.remat_policy {training['remat_policy']!r} must be "
            f"one of {REMAT_POLICIES}"
        )
    training.setdefault("loss_function_type", "mse")
    training.setdefault("batch_size", 32)
    training.setdefault("num_epoch", 1)
    training.setdefault("perc_train", 0.7)
    training.setdefault("patience", 10)
    training.setdefault("EarlyStopping", False)
    training.setdefault("Checkpoint", False)
    training.setdefault("checkpoint_warmup", 0)
    # ---- fault tolerance (docs/ROBUSTNESS.md): the in-graph non-finite
    # step guard's policy + the verified-checkpoint retention chain
    training.setdefault("non_finite_policy", "warn_skip")
    if training["non_finite_policy"] not in ("error", "warn_skip", "rollback"):
        raise ValueError(
            f"Training.non_finite_policy {training['non_finite_policy']!r} "
            "must be 'error', 'warn_skip' or 'rollback'"
        )
    training.setdefault("non_finite_rollback_after", 3)
    training.setdefault("non_finite_lr_backoff", 0.5)
    training.setdefault("non_finite_max_rollbacks", 3)
    # 0 = keep every per-epoch checkpoint (historical behavior); N > 0
    # prunes to the newest N, bounding disk and the corruption-fallback walk
    training.setdefault("checkpoint_retention", 0)
    # ---- compile plane (docs/PERFORMANCE.md "Compile plane"): persistent
    # XLA compilation cache (None = ./logs/<run>/xla_cache; false disables;
    # HYDRAGNN_COMPILE_CACHE overrides), AOT warm-up of the pad-bucket
    # ladder, and the retrace sentinel's response to a trace outside the
    # warmed specialization budget
    training.setdefault("compile_cache_dir", None)
    training.setdefault("precompile", "background")
    from ..train.compile_plane import PRECOMPILE_MODES, RETRACE_POLICIES

    if training["precompile"] not in PRECOMPILE_MODES:
        raise ValueError(
            f"Training.precompile {training['precompile']!r} must be one of "
            f"{PRECOMPILE_MODES}"
        )
    training.setdefault("retrace_policy", "warn")
    if training["retrace_policy"] not in RETRACE_POLICIES:
        raise ValueError(
            f"Training.retrace_policy {training['retrace_policy']!r} must "
            f"be one of {RETRACE_POLICIES}"
        )
    # ---- kernel autotuning plane (docs/TUNING.md): whether warm-up
    # consults the tuned tile table (cached), fills it first (sweep), or
    # rides pinned defaults (off); the per-kernel candidate budget; and the
    # table directory (None = next to the compile cache under the run's log
    # dir; false disables; HYDRAGNN_TUNE_CACHE overrides)
    training.setdefault("autotune", "cached")
    from ..tune.runtime import MODES as AUTOTUNE_MODES

    if training["autotune"] not in AUTOTUNE_MODES:
        raise ValueError(
            f"Training.autotune {training['autotune']!r} must be one of "
            f"{AUTOTUNE_MODES}"
        )
    training.setdefault("autotune_budget", 32)
    if int(training["autotune_budget"] or 0) < 0:
        raise ValueError(
            "Training.autotune_budget must be >= 0 (candidate plans per "
            f"kernel slot; 0 = defaults only), got {training['autotune_budget']!r}"
        )
    training.setdefault("autotune_cache_dir", None)
    # ---- data plane (docs/ROBUSTNESS.md "Data plane"): what a sample that
    # fails validation (non-finite features, degenerate edges, budget
    # overflow, corrupt bytes) means, and how long the loader's prefetch
    # consumer waits on a silent producer before raising LoaderStallError
    # (0 disables the stall clock; producer DEATH is always detected)
    ds_cfg = config.setdefault("Dataset", {})
    ds_cfg.setdefault("bad_sample_policy", "warn_skip")
    # LapPE eigendecomposition disk cache (data/lappe.py): true (default,
    # ./logs/lappe_cache), false, or an explicit directory;
    # HYDRAGNN_LAPPE_CACHE overrides
    ds_cfg.setdefault("lappe_cache", True)
    from ..data.validate import POLICIES

    if ds_cfg["bad_sample_policy"] not in POLICIES:
        raise ValueError(
            f"Dataset.bad_sample_policy {ds_cfg['bad_sample_policy']!r} "
            f"must be one of {POLICIES}"
        )
    training.setdefault("loader_stall_timeout", 600.0)
    if float(training["loader_stall_timeout"] or 0) < 0:
        raise ValueError(
            "Training.loader_stall_timeout must be >= 0 (seconds; 0 "
            f"disables), got {training['loader_stall_timeout']!r}"
        )
    # ---- double-buffered device staging (ROADMAP #3 H2D overlap): true
    # (default) = a 2-deep background device_put queue, false = inline
    # transfers, an int = that queue depth; HYDRAGNN_DEVICE_PREFETCH wins
    training.setdefault("double_buffer", True)
    db = training["double_buffer"]
    if not isinstance(db, (bool, int)) or (not isinstance(db, bool) and int(db) < 0):
        raise ValueError(
            "Training.double_buffer must be true/false or a queue depth "
            f">= 0, got {db!r}"
        )
    # ---- elastic fleet operation (docs/GFM.md "Multi-host and elastic
    # operation", train/elastic.py): ``enabled`` arms the driver-side
    # coordinator that turns watchdog detections / SIGTERM notices into
    # shrink-grow plans, ``min_hosts`` is the floor below which a shrink is
    # refused (fail the run instead of overloading survivors), ``grace_s``
    # bounds how long a preempted host may checkpoint before it counts as
    # dead. Checkpoint-restart semantics: progress since the coordinated
    # checkpoint is lost, never silently recomputed under a stale layout.
    el = training.setdefault("elastic", {})
    if not isinstance(el, dict):
        raise ValueError(
            f"Training.elastic must be a dict of elastic-fleet keys, got {el!r}"
        )
    el.setdefault("enabled", False)
    el.setdefault("min_hosts", 1)
    el.setdefault("grace_s", 30.0)
    if int(el["min_hosts"]) < 1:
        raise ValueError(
            f"Training.elastic.min_hosts must be >= 1, got {el['min_hosts']!r}"
        )
    if float(el["grace_s"]) < 0:
        raise ValueError(
            "Training.elastic.grace_s must be >= 0 (seconds), got "
            f"{el['grace_s']!r}"
        )
    if training["non_finite_policy"] == "rollback" and not training["Checkpoint"]:
        # rollback restores the last verified checkpoint — without best-val
        # checkpointing only the preemption/end-of-run saves exist, so the
        # first rollback of a fresh run would find nothing to restore
        print(
            "[hydragnn_tpu.config] non_finite_policy=rollback without "
            "Training.Checkpoint: enable checkpointing or the first "
            "rollback of a fresh run will fail with no checkpoint to "
            "restore",
            file=sys.stderr,
        )
    training.setdefault("Optimizer", {"type": "AdamW", "learning_rate": 1e-3})
    training["Optimizer"].setdefault("type", "AdamW")
    training["Optimizer"].setdefault("learning_rate", 1e-3)
    arch.setdefault("task_weights", [1.0] * len(output_dim))
    assert len(arch["task_weights"]) == len(output_dim), (
        f"task_weights {arch['task_weights']} must match number of heads {len(output_dim)}"
    )

    # ---- serving plane (docs/SERVING.md): validate the ``Serving`` section
    # eagerly when present so a typo'd policy fails at load time, not when
    # the server comes up under traffic. The section is optional — absent
    # means "all defaults" and nothing is added to the saved config.
    if config.get("Serving"):
        from ..serve.config import ServeConfig

        ServeConfig.from_config(config)

    # ---- telemetry plane (docs/OBSERVABILITY.md): same eager-validation
    # contract as ``Serving`` — a typo'd Telemetry key/value fails at load
    # time, not after the first epoch has already run unmeasured. Optional:
    # absent means disabled and nothing is added to the saved config; a
    # PRESENT section is completed to its resolved form (defaults filled,
    # unknown keys warned-and-dropped here, ONCE — the loop's later
    # resolve of the completed section is then warning-free).
    if config.get("Telemetry"):
        from ..obs.telemetry import resolve_telemetry

        config["Telemetry"] = resolve_telemetry(config)

    # ---- mixture plane (docs/GFM.md): same eager-validation contract as
    # the sections above; the completed section additionally plants the
    # static per-branch loss-balancing weights into the Architecture so
    # the jitted multibranch step sees them (train/loss.py)
    if config.get("Mixture"):
        from ..mix import branch_loss_weights_from, resolve_mixture
        from ..models.create import num_branches_from

        config["Mixture"] = resolve_mixture(config)
        nb = num_branches_from(arch)
        if nb > 1:
            blw = branch_loss_weights_from(config["Mixture"], nb)
            if blw is not None:
                arch["branch_loss_weights"] = list(blw)
                arch["branch_loss_metrics"] = True

    config.setdefault("Verbosity", {"level": 0})
    config.setdefault("Visualization", {})
    return config


def get_log_name_config(config: Dict[str, Any]) -> str:
    """Human-readable run name from key hyperparameters
    (reference: config_utils.py:314-349, abbreviated)."""
    arch = config["NeuralNetwork"]["Architecture"]
    training = config["NeuralNetwork"]["Training"]
    return (
        f"{arch['mpnn_type']}"
        f"-r-{arch.get('radius')}"
        f"-ncl-{arch.get('num_conv_layers')}"
        f"-hd-{arch.get('hidden_dim')}"
        f"-ne-{training.get('num_epoch')}"
        f"-lr-{training.get('Optimizer', {}).get('learning_rate')}"
        f"-bs-{training.get('batch_size')}"
    )


def save_config(config: Dict[str, Any], log_name: str, path: str = "./logs") -> str:
    """Dump the completed config next to the run logs
    (reference: config_utils.py:352-358; rank-0 gating is the caller's job)."""
    run_dir = os.path.join(path, log_name)
    os.makedirs(run_dir, exist_ok=True)
    fname = os.path.join(run_dir, "config.json")
    with open(fname, "w") as f:
        json.dump(config, f, indent=2)
    return fname


def load_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
