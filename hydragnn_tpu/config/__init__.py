from .config import (
    average_degree,
    check_if_graph_size_variable,
    degree_histogram,
    get_log_name_config,
    load_config,
    merge_config,
    save_config,
    update_config,
    voi_from_config,
)

__all__ = [
    "average_degree",
    "check_if_graph_size_variable",
    "degree_histogram",
    "get_log_name_config",
    "load_config",
    "merge_config",
    "save_config",
    "update_config",
    "voi_from_config",
]
