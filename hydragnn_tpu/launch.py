"""``python -m hydragnn_tpu.launch`` — build (once) and exec the native
multi-host launcher.

The C++ binary (native/launcher.cpp) is the torchrun/setup_ddp analog
(reference: hydragnn/utils/distributed/distributed.py:52-198): it resolves
(world_size, rank, coordinator) from scheduler envs or fans out ``--nprocs``
local ranks, exports the ``HYDRAGNN_COORDINATOR``/``WORLD_SIZE``/``RANK``
contract that ``hydragnn_tpu.parallel.setup_distributed`` consumes, and
execs the training command. The same exported envs give each rank its
fleet identity (``obs/fleet.py host_identity`` falls back to them before
the JAX runtime is up), so every launched process self-identifies in the
fleet observability plane from its very first record; set
``HYDRAGNN_FLEET_COLLECTOR=host:port`` alongside to point every rank's
telemetry push at rank 0 (docs/OBSERVABILITY.md "Fleet")::

    python -m hydragnn_tpu.launch --nprocs 2 -- python train.py config.json
    srun python -m hydragnn_tpu.launch -- python train.py config.json
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> None:
    from .native.build import build_executable

    binary = build_executable("launcher")
    args = list(sys.argv[1:] if argv is None else argv)
    os.execv(binary, [binary] + args)


if __name__ == "__main__":
    main()
