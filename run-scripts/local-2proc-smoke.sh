#!/usr/bin/env bash
# 2-process CPU validation of the multihost path on one machine — the
# launchable twin of tests/test_multihost.py (the analog of the reference
# CI's `mpirun -n 2 --oversubscribe pytest --with-mpi` tier,
# reference: .github/workflows/CI.yml:63).
#
#   ./run-scripts/local-2proc-smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu python -m pytest tests/test_multihost.py -q
