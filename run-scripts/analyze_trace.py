#!/usr/bin/env python
"""DEPRECATED shim — use ``python -m hydragnn_tpu.obs.doctor`` instead.

This script predates the PR 8 tracing plane: it parsed raw
``jax.profiler`` perfetto dumps. The span-decomposition report now lives
in the run doctor (per-stage count/p50/p99/total over ``trace.jsonl``):

    python -m hydragnn_tpu.obs.doctor trace logs/<run>/trace.jsonl
    python -m hydragnn_tpu.obs.doctor <run_dir>      # full diagnosis

For raw device-op rollups of a perfetto capture, load the trace in
Perfetto UI (ui.perfetto.dev) — the xprof capture directories written by
``BENCH_PROFILE=1`` / the on-demand trigger open there directly.
"""

import os
import sys

if __name__ == "__main__":
    # run-scripts/ is sys.path[0] when invoked directly; the package
    # lives one level up
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    print(
        "analyze_trace.py is deprecated: use "
        "`python -m hydragnn_tpu.obs.doctor trace <trace.jsonl>` for the "
        "span-decomposition report, or `python -m hydragnn_tpu.obs.doctor "
        "<run_dir>` for the full diagnosis (docs/OBSERVABILITY.md "
        "'Run doctor').",
        file=sys.stderr,
    )
    if len(sys.argv) > 1 and sys.argv[1].endswith(".jsonl"):
        # forward the one still-meaningful invocation shape
        from hydragnn_tpu.obs.doctor import main

        raise SystemExit(main(["trace", sys.argv[1]]))
    raise SystemExit(2)
