#!/usr/bin/env python
"""Summarize a jax.profiler perfetto trace: top device ops + MXU share.

Usage: python run-scripts/analyze_trace.py [trace_dir_or_file]

Default search root is logs/bench_profile (written by BENCH_PROFILE=1 —
bench.py captures 8 steady-state steps with create_perfetto_trace=True).
Prints the top ops by device self-time, the matmul vs non-matmul split,
and per-category totals — the working input for the MFU push (VERDICT r2
next-steps #3: "attack the top non-matmul cost with a profile in hand").

Pure stdlib: the perfetto JSON is a Chrome trace — complete events
("ph":"X") with microsecond durations on named tracks; device tracks are
the process/thread names containing "TPU"/"device" (field layout per the
Chrome Trace Event format).
"""

import gzip
import json
import os
import re
import sys
from collections import defaultdict


def find_trace(root: str) -> str:
    if os.path.isfile(root):
        return root
    hits = []
    for dirpath, _, files in os.walk(root):
        for f in files:
            if f.endswith((".perfetto-trace", "perfetto_trace.json.gz",
                           ".trace.json.gz")):
                hits.append(os.path.join(dirpath, f))
    if not hits:
        raise SystemExit(f"no perfetto/chrome trace under {root!r} — run "
                         "BENCH_PROFILE=1 python bench.py first")
    return max(hits, key=os.path.getmtime)


def load_events(path: str):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


# op-name buckets for the category rollup
_CATEGORIES = (
    ("matmul", re.compile(r"dot|conv|matmul|gemm", re.I)),
    ("fusion", re.compile(r"^(loop_)?fusion", re.I)),
    ("scatter/segment", re.compile(r"scatter|segment", re.I)),
    ("gather", re.compile(r"gather|dynamic-slice", re.I)),
    ("pallas", re.compile(r"pallas|custom-call", re.I)),
    ("copy/transpose", re.compile(r"copy|transpose|bitcast|reshape", re.I)),
    ("allreduce/collective", re.compile(r"all-reduce|all-gather|collective|"
                                        r"reduce-scatter|permute", re.I)),
    ("infeed/outfeed", re.compile(r"infeed|outfeed|transfer", re.I)),
)


def categorize(name: str) -> str:
    for cat, pat in _CATEGORIES:
        if pat.search(name):
            return cat
    return "other"


def main():
    root = sys.argv[1] if len(sys.argv) > 1 else "logs/bench_profile"
    path = find_trace(root)
    events = load_events(path)

    # map (pid, tid) -> track name; device tracks mention TPU / device / XLA
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") in ("process_name",
                                                    "thread_name"):
            key = (e.get("pid"), e.get("tid"), e["name"])
            names[key] = e.get("args", {}).get("name", "")
    def track(pid, tid):
        proc = names.get((pid, 0, "process_name")) or names.get(
            (pid, None, "process_name"), "")
        thr = names.get((pid, tid, "thread_name"), "")
        return f"{proc}/{thr}"

    device_pat = re.compile(r"tpu|device|/device|xla", re.I)
    per_op = defaultdict(float)
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if not device_pat.search(track(e.get("pid"), e.get("tid"))):
            continue
        per_op[e["name"]] += float(e["dur"])
        total += float(e["dur"])
    if not per_op:
        raise SystemExit(f"no device complete-events found in {path!r}")

    per_cat = defaultdict(float)
    for name, dur in per_op.items():
        per_cat[categorize(name)] += dur

    print(f"trace: {path}")
    print(f"total device op time: {total/1e3:.2f} ms\n")
    print("category rollup:")
    for cat, dur in sorted(per_cat.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:<22} {dur/1e3:10.2f} ms  {100*dur/total:5.1f}%")
    mxu = per_cat.get("matmul", 0.0)
    print(f"\nMXU (matmul-like) share: {100*mxu/total:.1f}% — everything "
          "else is the optimization surface\n")
    print("top 20 ops by device self-time:")
    for name, dur in sorted(per_op.items(), key=lambda kv: -kv[1])[:20]:
        print(f"  {100*dur/total:5.1f}%  {dur/1e3:9.2f} ms  {name[:90]}")


if __name__ == "__main__":
    main()
