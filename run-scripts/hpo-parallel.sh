#!/usr/bin/env bash
# Parallel HPO study (DeepHyper analog — the reference carves a SLURM node
# list into per-trial srun launches, hydragnn/utils/hpo/deephyper.py:47-177).
# Each worker explores a disjoint trial_offset shard of the study and
# appends JSONL records; the driver process merges them and reports the
# best config. On a SLURM allocation, export HPO_HOSTS="$(scontrol show
# hostnames)" to carve one worker per node via ssh (hpo.launch_hpo_workers
# hosts=); locally the workers share the host's CPU devices.
#
#   WORKERS=4 TRIALS=16 run-scripts/hpo-parallel.sh [extra gfm.py args]
set -euo pipefail
cd "$(dirname "$0")/.."
WORKERS="${WORKERS:-2}"
TRIALS="${TRIALS:-4}"
exec python examples/multidataset_hpo/gfm.py \
  --workers "$WORKERS" --num_trials "$TRIALS" "$@"
