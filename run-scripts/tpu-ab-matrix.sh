#!/usr/bin/env bash
# 4-cell perf A/B on the real chip: mixed_precision x sorted_aggregation.
# Appends one JSON line per cell to logs/ab_matrix.jsonl; run on a host with
# the TPU reachable (bench.py probes first and records an outage as data).
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p logs
for MP in 1 0; do
  for SORTED in 0 1; do
    echo "== BENCH_MP=$MP BENCH_SORTED=$SORTED ==" >&2
    BENCH_MP=$MP BENCH_SORTED=$SORTED python bench.py \
      | tee -a logs/ab_matrix.jsonl
  done
done
echo "A/B matrix done -> logs/ab_matrix.jsonl" >&2
