#!/usr/bin/env bash
# 4-cell perf A/B on the real chip: mixed_precision x sorted_aggregation.
# Runs ALL cells in ONE python process (BENCH_AB=1): every new process is a
# fresh PJRT client, and the axon pool has wedged mid-round on client
# reconnect churn (BASELINE.md round-3 notes) — a single client avoids the
# trigger. Cells append to logs/ab_matrix.jsonl as they finish, so a wedge
# mid-matrix still keeps the completed cells.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p logs
BENCH_AB=1 BENCH_PROFILE="${BENCH_PROFILE:-1}" python bench.py
echo "A/B matrix done -> logs/ab_matrix.jsonl" >&2
