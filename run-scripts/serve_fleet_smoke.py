#!/usr/bin/env python
"""CI serving-fleet chaos smoke (docs/SERVING.md "Fleet"). ONE child
process (scrubbed CPU-JAX, the chaos_smoke.py recipe) trains a real
checkpoint, brings up a 2-replica ``api.run_server_fleet`` deployment, and
drives the fleet's whole failure model through the router front door with
the deterministic replica drills of utils/faultinject.py:

1. BREAKER: replica 1's first three /predict calls are wedged
   (HYDRAGNN_FAULT_REPLICA_WEDGE="1:0,1,2:15") — every client call still
   succeeds (tail hedging + retry on the mate), the per-replica circuit
   breaker opens on the timeout failures, and after the cooldown a
   half-open probe against the now-healthy replica recloses it.
2. CACHE: the same graph predicted twice is served the second time from
   the content-addressed prediction cache, bit-identical, without
   touching the fleet.
3. KILL: replica 2 is SIGKILLed mid-load at a precise request index
   (HYDRAGNN_FAULT_REPLICA_KILL="2:400", reached by padding) while four
   concurrent clients stream requests — ZERO client-visible failures
   (the router retries on replica 1), and the supervisor restarts the
   dead worker back to ready. Replica 2 also runs the slow-replica drill
   (HYDRAGNN_FAULT_REPLICA_SLOW="2:0.01") for the whole run.
4. RELOAD: a new (scaled) checkpoint is published and
   ``manager.rolling_reload`` swaps the fleet one replica at a time
   UNDER concurrent load — ready capacity never dips below the floor,
   zero dropped requests, and predictions visibly move.
5. Teardown: the manager's aggregated ``fleet_serve`` metrics records and
   the typed replica_exit/replica_restart/breaker events are on disk for
   the run doctor, and the fleet drains cleanly.
6. QUANT: a second fleet comes up with ``Serving.weights_dtype: int8`` on
   a pre-quantized snapshot (both replicas report ``source=snapshot`` —
   no per-replica re-calibration) and agrees with the fp32 fleet's
   predictions; a clean rolling reload re-quantizes + canaries + swaps a
   new checkpoint fleet-wide; a drifted candidate (scales inflated by
   HYDRAGNN_FAULT_QUANT_DRIFT) is refused by the accuracy gate on every
   replica — ``installed == 0``, the fleet stays on the certified
   checkpoint, and the typed ``quant_drift`` event is on disk.

Exit 0 = fleet healthy; nonzero with a diagnostic otherwise.
"""

import os
import re
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "run-scripts"))

from smoke_env import child_env  # noqa: E402 — shared child-spawn recipe

_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import jax
if not hasattr(jax.distributed, "is_initialized"):
    # older jax (this CPU image): the fleet is N single-process servers
    jax.distributed.is_initialized = lambda: False

import dataclasses
import itertools
import json
import threading
import urllib.request

import numpy as np

import hydragnn_tpu
from hydragnn_tpu.config import update_config, voi_from_config
from hydragnn_tpu.data import deterministic_graph_dataset, split_dataset
from hydragnn_tpu.data.pipeline import extract_variables
from hydragnn_tpu.serve import HTTPReplicaClient

cfg = {{
    "Verbosity": {{"level": 1}},
    "Dataset": {{
        "name": "serve_fleet",
        "format": "synthetic",
        "synthetic": {{"number_configurations": 80}},
        "node_features": {{"name": ["x", "x2", "x3"], "dim": [1, 1, 1]}},
        "graph_features": {{"name": ["s"], "dim": [1]}},
    }},
    "NeuralNetwork": {{
        "Architecture": {{
            "mpnn_type": "GIN", "radius": 2.0, "max_neighbours": 100,
            "hidden_dim": 8, "num_conv_layers": 2, "task_weights": [1.0],
            "output_heads": {{"graph": {{"num_sharedlayers": 1,
                                        "dim_sharedlayers": 8,
                                        "num_headlayers": 2,
                                        "dim_headlayers": [8, 8]}}}},
        }},
        "Variables_of_interest": {{
            "input_node_features": [0],
            "output_names": ["s"], "output_index": [0],
            "type": ["graph"], "denormalize_output": False,
        }},
        "Training": {{
            "num_epoch": 2, "batch_size": 4, "seed": 7,
            "Optimizer": {{"type": "AdamW", "learning_rate": 0.01}},
        }},
    }},
    "Serving": {{
        "micro_batch_graphs": 4,
        "batch_window_s": 0.005,
        "step_timeout_s": 5.0,
        "hot_reload": True,
        "fleet_replicas": 2,
        "prediction_cache": True,
        "breaker_failures": 2,
        "breaker_cooldown_s": 1.0,
        "router_retries": 3,
        "router_backoff_s": 0.05,
        "router_hedge_min_s": 0.05,
        "router_timeout_s": 30.0,
        "fleet_restart_backoff_s": 1.0,
        "fleet_flap_window_s": 30.0,
        "fleet_flap_max_restarts": 5,
        "fleet_ready_floor": 0.5,
        "reload_probe_requests": 4,
        "reload_error_spike": 0.75,
    }},
}}

# ---- train 2 epochs: the fleet must come up on a REAL verified checkpoint
hydragnn_tpu.run_training(cfg)

# graphs matching the deployment's admission signature (serve_world recipe)
raw = deterministic_graph_dataset(60, seed=7, radius=2.0, max_neighbours=100)
tr, va, te = split_dataset(raw, 0.7, seed=0)
done = update_config(json.loads(json.dumps(cfg)), tr, va, te)
voi = voi_from_config(done)
ready_graphs = [extract_variables(g, voi) for g in raw]

_seq = itertools.count()

def ug():
    # unique graph per call: repeats would be served from the prediction
    # cache and never reach the fleet (the phases below need fleet traffic)
    i = next(_seq)
    g = ready_graphs[i % len(ready_graphs)]
    bump = np.float32(1e-6 * (i // len(ready_graphs) + 1))
    return dataclasses.replace(g, x=g.x + bump)

# ---- arm the replica chaos drills BEFORE spawn (children inherit environ):
# replica 1 wedges its first three /predict calls for 15 s (socket timeouts
# at the router -> breaker opens, then the unarmed 4th call recloses it);
# replica 2 runs 10 ms slower on every call and SIGKILLs itself at its
# 400th /predict — an index the KILL phase reaches deliberately by padding
import os
os.environ["HYDRAGNN_FAULT_REPLICA_WEDGE"] = "1:0,1,2:15"
os.environ["HYDRAGNN_FAULT_REPLICA_KILL"] = "2:400"
os.environ["HYDRAGNN_FAULT_REPLICA_SLOW"] = "2:0.01"

manager = hydragnn_tpu.run_server_fleet(cfg, wait_ready_s=600)
try:
    router = manager.router()
    assert sorted(router.replicas()) == ["replica1", "replica2"], (
        router.replicas())
    print("FLEET_READY replicas=%d" % len(router.replicas()), flush=True)

    def rstats(idx):
        port = manager.replica_state()[idx]["port"]
        req = urllib.request.Request(
            "http://127.0.0.1:%d/stats" % port, data=b"{{}}",
            headers={{"Content-Type": "application/json"}}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return json.loads(resp.read().decode("utf-8"))

    # ---- 1. wedged replica: calls succeed, breaker opens then recloses -
    br = router.breaker("replica1")
    for _ in range(100):
        out = router.predict(ug(), timeout_s=2.5)
        assert isinstance(out, dict), out
        if br.opens >= 1:
            break
        time.sleep(0.1)
    assert br.opens >= 1, "breaker never opened: state=%s" % br.state
    for _ in range(100):
        if br.state == "closed" and br.closes >= 1:
            break
        out = router.predict(ug(), timeout_s=2.5)
        assert isinstance(out, dict), out
        time.sleep(0.1)
    assert br.state == "closed" and br.closes >= 1, (
        "breaker never reclosed: state=%s closes=%d" % (br.state, br.closes))
    assert router.stats()["hedges"] >= 1, router.stats()
    print("BREAKER_OK opens=%d closes=%d hedges=%d"
          % (br.opens, br.closes, router.stats()["hedges"]), flush=True)

    # ---- 2. prediction cache: second identical request is a bit-identical
    # hit served without touching the fleet --------------------------------
    g0 = ug()
    first = router.predict(g0, timeout_s=30.0)
    hits0 = router.stats()["cache_hits"]
    second = router.predict(g0, timeout_s=30.0)
    assert router.stats()["cache_hits"] == hits0 + 1, router.stats()
    assert sorted(first) == sorted(second), (first.keys(), second.keys())
    for k in first:
        a, b = np.asarray(first[k]), np.asarray(second[k])
        assert a.dtype == b.dtype and a.shape == b.shape, (k, a.dtype, b.dtype)
        assert a.tobytes() == b.tobytes(), "cache hit not bit-identical: %s" % k
    print("CACHE_OK hits=%d" % router.stats()["cache_hits"], flush=True)

    # ---- 3. SIGKILL mid-load: zero client-visible failures + restart ----
    s2 = rstats(2)["submitted"]
    assert s2 < 380, "kill index margin exhausted: replica2 served %d" % s2
    port2 = manager.replica_state()[2]["port"]
    pad = HTTPReplicaClient("http://127.0.0.1:%d" % port2, name="replica2")
    while rstats(2)["submitted"] < 400:
        pad.predict(ug(), timeout_s=30.0)  # next /predict is the kill
    errors, okays = [], []

    def pump(n):
        for _ in range(n):
            try:
                okays.append(router.predict(ug(), timeout_s=30.0))
            except Exception as e:  # noqa: BLE001 — any escape is the bug
                errors.append(e)

    workers = [threading.Thread(target=pump, args=(15,)) for _ in range(4)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    assert not errors, "client-visible failures under SIGKILL: %r" % errors[:3]
    assert len(okays) == 60, len(okays)
    deadline = time.time() + 60
    while time.time() < deadline:
        if manager.replica_state()[2]["restarts"] >= 1:
            break
        time.sleep(0.1)
    assert manager.replica_state()[2]["restarts"] >= 1, manager.replica_state()
    deadline = time.time() + 420
    while time.time() < deadline and manager.ready_count() < 2:
        time.sleep(0.5)
    assert manager.ready_count() == 2, manager.replica_state()
    print("KILL_OK served=%d errors=0 restarts=%d"
          % (len(okays), manager.replica_state()[2]["restarts"]), flush=True)

    # ---- 4. rolling reload under load: floor held, predictions move ----
    from flax import serialization
    from hydragnn_tpu.train.checkpoint import (
        latest_checkpoint_entry, save_model,
    )
    from hydragnn_tpu.train.optimizer import make_optimizer
    from hydragnn_tpu.train.state import TrainState

    run = manager.log_name
    entry = latest_checkpoint_entry(run)
    ep = int(re.search(r"_epoch(\\d+)\\.msgpack$", entry).group(1))
    with open(os.path.join("./logs", run, entry), "rb") as f:
        rawckpt = serialization.msgpack_restore(f.read())
    scaled = jax.tree_util.tree_map(
        lambda p: np.asarray(p) * 2.0, rawckpt["params"]
    )
    ts = TrainState.create(
        {{"params": scaled, "batch_stats": rawckpt.get("batch_stats", {{}})}},
        make_optimizer({{"type": "AdamW", "learning_rate": 0.01}}),
    )
    save_model(ts, run, epoch=ep + 1)

    port1 = manager.replica_state()[1]["port"]
    c1 = HTTPReplicaClient("http://127.0.0.1:%d" % port1, name="replica1")
    gq = ug()
    ref = c1.predict(gq, timeout_s=30.0)["s"]
    workers = [threading.Thread(target=pump, args=(20,)) for _ in range(2)]
    for w in workers:
        w.start()
    res = manager.rolling_reload(ready_graphs[:4], timeout_s=180.0)
    for w in workers:
        w.join()
    assert not errors, "dropped requests during rolling reload: %r" % errors[:3]
    assert res["status"] == "done", res
    assert res["installed"] == 2, res
    assert res["min_ready_seen"] >= res["floor"], res
    new = c1.predict(gq, timeout_s=30.0)["s"]
    assert not np.allclose(ref, new), "weights did not move after reload"
    want = "%s_epoch%d.msgpack" % (run, ep + 1)
    assert rstats(1)["current_checkpoint"] == want, rstats(1)
    print("RELOAD_OK installed=%d min_ready=%d floor=%d"
          % (res["installed"], res["min_ready_seen"], res["floor"]),
          flush=True)

    # ---- 5. fleet observability on disk for the run doctor --------------
    mpath = os.path.join("./logs", run, "metrics.jsonl")
    with open(mpath) as f:
        fleet_recs = [ln for ln in f if '"fleet_serve"' in ln]
    assert fleet_recs, "no aggregated fleet_serve metrics records"
    with open(os.path.join("./logs", run, "events.jsonl")) as f:
        evs = f.read()
    for needed in ("replica_exit", "replica_restart", "breaker_open",
                   "breaker_close"):
        assert needed in evs, "missing typed event %r" % needed
finally:
    manager.close()
print("FLEET_CLEAN_EXIT", flush=True)

# ---- 6. QUANT: int8 fleet from a pre-quantized snapshot, canary-gated
# rolling reload, and a fault-injected drifted candidate refused ---------
import glob

from hydragnn_tpu.data.graph import SpecLadder
from hydragnn_tpu.data.pipeline import spec_template_batches
from hydragnn_tpu.models import create_model, init_model
from hydragnn_tpu.serve import quantize as qz
from hydragnn_tpu.train.checkpoint import load_inference_entry
from hydragnn_tpu.train.state import InferenceState

# pre-quantize the latest entry beside the checkpoint (what a producing
# server would have published) so BOTH replicas take the snapshot fast
# path — serving int8 without re-quantizing or re-calibrating
entry_q = latest_checkpoint_entry(run)
model = create_model(done)
ladder = SpecLadder.for_dataset(ready_graphs, 4, num_buckets=2)
tmpl = spec_template_batches(ready_graphs, ladder)[0][1]
fpstate = load_inference_entry(
    InferenceState.create(init_model(model, tmpl, seed=0)), run, entry_q
)
qbatches = [b for _, b in spec_template_batches(ready_graphs, ladder)][:2]
qstate = qz.quantize_state(model, fpstate, qbatches, mode="weight_only")
qreport = qz.gate_or_raise(
    model, fpstate, qstate, qbatches, 0.05, run=run, entry=entry_q
)
qz.save_snapshot(
    qstate, dict(qreport, source="calibrated"), run, entry_q, "./logs"
)
print("QUANT_SNAPSHOT_OK entry=%s max_error=%.6f"
      % (entry_q, qreport["max_error"]), flush=True)

# disarm the replica chaos drills; arm the quantization-drift fault for
# the FUTURE epoch+3 entry only (children inherit environ at spawn, so
# this must be set before the int8 fleet comes up)
for k in ("HYDRAGNN_FAULT_REPLICA_WEDGE", "HYDRAGNN_FAULT_REPLICA_KILL",
          "HYDRAGNN_FAULT_REPLICA_SLOW"):
    os.environ.pop(k, None)
os.environ["HYDRAGNN_FAULT_QUANT_DRIFT"] = "epoch%d.:6.0" % (ep + 3)

cfg_q = json.loads(json.dumps(cfg))
cfg_q["Serving"]["weights_dtype"] = "int8"
cfg_q["Serving"]["quantization"] = {{
    "mode": "weight_only", "calibration_batches": 2, "max_error": 0.05,
}}
# replica-side event streams (events-h<i>.jsonl): the gate's quant_drift
# events fire inside the replica processes
cfg_q["Telemetry"] = {{"enabled": True}}

manager2 = hydragnn_tpu.run_server_fleet(cfg_q, wait_ready_s=600)
try:
    router2 = manager2.router()

    def rstats2(idx):
        port = manager2.replica_state()[idx]["port"]
        req = urllib.request.Request(
            "http://127.0.0.1:%d/stats" % port, data=b"{{}}",
            headers={{"Content-Type": "application/json"}}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            return json.loads(resp.read().decode("utf-8"))

    for i in (1, 2):
        st = rstats2(i)
        assert st.get("weights_dtype") == "int8", st
        q = st.get("quantization") or {{}}
        assert q.get("source") == "snapshot", (
            "replica %d did not load the pre-quantized snapshot: %r"
            % (i, q))
    # int8 predictions agree with the fp32 fleet's on the same graph
    out_q = np.asarray(router2.predict(gq, timeout_s=30.0)["s"])
    denom = float(np.max(np.abs(np.asarray(new)))) + 1e-8
    rel = float(np.max(np.abs(out_q - np.asarray(new)))) / denom
    assert rel <= 0.05, "int8 fleet drifted from fp32: rel=%.5f" % rel
    print("QUANT_FLEET_OK source=snapshot rel_err=%.5f" % rel, flush=True)

    # clean rolling reload: a NEW checkpoint is re-quantized, canaried,
    # and swapped fleet-wide (gate green)
    scaled3 = jax.tree_util.tree_map(
        lambda p: np.asarray(p) * 3.0, rawckpt["params"]
    )
    ts2 = TrainState.create(
        {{"params": scaled3, "batch_stats": rawckpt.get("batch_stats", {{}})}},
        make_optimizer({{"type": "AdamW", "learning_rate": 0.01}}),
    )
    save_model(ts2, run, epoch=ep + 2)
    res2 = manager2.rolling_reload(ready_graphs[:4], timeout_s=300.0)
    assert res2["status"] == "done" and res2["installed"] == 2, res2
    want2 = "%s_epoch%d.msgpack" % (run, ep + 2)
    st1 = rstats2(1)
    assert st1["current_checkpoint"] == want2, st1
    assert (st1.get("quantization") or {{}}).get("source") in (
        "calibrated", "snapshot"), st1
    moved = np.asarray(router2.predict(gq, timeout_s=30.0)["s"])
    assert not np.allclose(out_q, moved), "int8 reload did not move preds"
    print("QUANT_RELOAD_OK installed=%d source=%s"
          % (res2["installed"], st1["quantization"]["source"]), flush=True)

    # drifted candidate: the armed fault inflates the scales of the
    # epoch+3 entry after calibration — the gate must refuse it on every
    # replica and the fleet must stay on the prior checkpoint
    ts3 = TrainState.create(
        {{"params": scaled3, "batch_stats": rawckpt.get("batch_stats", {{}})}},
        make_optimizer({{"type": "AdamW", "learning_rate": 0.01}}),
    )
    save_model(ts3, run, epoch=ep + 3)
    res3 = manager2.rolling_reload(ready_graphs[:4], timeout_s=300.0)
    assert res3["status"] == "done" and res3["installed"] == 0, res3
    for i in (1, 2):
        st = rstats2(i)
        assert st["current_checkpoint"] == want2, (
            "replica %d left the certified checkpoint: %r" % (i, st))
    ev_text = ""
    for p in glob.glob(os.path.join("./logs", run, "events*.jsonl")):
        with open(p) as f:
            ev_text += f.read()
    assert "quant_drift" in ev_text, "no quant_drift event on disk"
    print("QUANT_GATE_OK refused installed=0", flush=True)
finally:
    manager2.close()
print("QUANT_CLEAN_EXIT", flush=True)
"""


_MARKERS = (
    "FLEET_READY",
    "BREAKER_OK",
    "CACHE_OK",
    "KILL_OK",
    "RELOAD_OK",
    "FLEET_CLEAN_EXIT",
    "QUANT_SNAPSHOT_OK",
    "QUANT_FLEET_OK",
    "QUANT_RELOAD_OK",
    "QUANT_GATE_OK",
    "QUANT_CLEAN_EXIT",
)


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="serve_fleet_")
    script = os.path.join(workdir, "serve_fleet_child.py")
    with open(script, "w") as f:
        f.write("import re, time\n" + _CHILD.format(repo=_REPO))
    proc = subprocess.Popen(
        [sys.executable, script], cwd=workdir,
        env=child_env({"HYDRAGNN_VALTEST": "0"}),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    lines = []
    deadline = time.time() + 1800
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line == "" and proc.poll() is not None:
            break
        lines.append(line)
    else:
        proc.kill()
        print("serve_fleet FAIL: timed out\n" + "".join(lines)[-4000:])
        return 1
    out = "".join(lines)
    if proc.returncode != 0:
        print(f"serve_fleet FAIL: child rc={proc.returncode}:\n{out[-4000:]}")
        return 1
    missing = [m for m in _MARKERS if m not in out]
    if missing:
        print(f"serve_fleet FAIL: phases missing {missing}:\n{out[-4000:]}")
        return 1
    if not re.search(r"KILL_OK served=\d+ errors=0", out):
        print(f"serve_fleet FAIL: SIGKILL leaked client-visible failures:"
              f"\n{out[-4000:]}")
        return 1
    print(
        "serve_fleet OK: wedged replica absorbed (breaker opened + reclosed, "
        "hedges won), prediction cache hit bit-identical, SIGKILL mid-load "
        "retried to zero client-visible failures with supervisor restart, "
        "rolling reload under load held the ready floor and moved "
        "predictions, int8 fleet served from the pre-quantized snapshot and "
        "the accuracy gate refused the drifted candidate"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
