#!/usr/bin/env python
"""CI compile-plane smoke (docs/PERFORMANCE.md "Compile plane").

Two subprocess legs over one shared persistent compilation cache:

1. **cold**: a short CPU training run with ``Training.precompile:
   background`` and the retrace sentinel in ``error`` mode — the run must
   finish cleanly (zero post-warm-up retraces, or the sentinel raises) and
   the report must show every ladder specialization precompiled.
2. **warm**: the identical run again — every XLA compile must now be served
   from the cache (``cache_hits > 0``) with a time-to-first-step bounded by
   the cold leg's.

Invoked from run-scripts/ci.sh. Self-contained: fresh interpreters, CPU
JAX, scrubbed env, temp workdir (same recipe as chaos_smoke.py).
Exit 0 = compile plane healthy; nonzero with a diagnostic otherwise.
"""

import json
import os
import re
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import sys
sys.path.insert(0, {repo!r})
import jax
if not hasattr(jax.distributed, "is_initialized"):
    # older jax (this CPU image): run_training only uses it as an
    # already-initialized guard, and this smoke is strictly single-process
    jax.distributed.is_initialized = lambda: False
import hydragnn_tpu

cfg = {{
    "Verbosity": {{"level": 1}},
    "Dataset": {{
        "name": "compile_smoke",
        "format": "synthetic",
        "synthetic": {{"number_configurations": 48}},
        "node_features": {{"name": ["x", "x2", "x3"], "dim": [1, 1, 1]}},
        "graph_features": {{"name": ["s"], "dim": [1]}},
    }},
    "NeuralNetwork": {{
        "Architecture": {{
            "mpnn_type": "GIN", "radius": 2.0, "max_neighbours": 100,
            "hidden_dim": 8, "num_conv_layers": 2, "task_weights": [1.0],
            "output_heads": {{"graph": {{"num_sharedlayers": 1,
                                        "dim_sharedlayers": 8,
                                        "num_headlayers": 2,
                                        "dim_headlayers": [8, 8]}}}},
        }},
        "Variables_of_interest": {{
            "input_node_features": [0],
            "output_names": ["s"], "output_index": [0],
            "type": ["graph"], "denormalize_output": False,
        }},
        "Training": {{
            "num_epoch": 3, "batch_size": 8, "seed": 11,
            "num_pad_buckets": 3,
            "precompile": "background",
            "retrace_policy": "error",
            "Optimizer": {{"type": "AdamW", "learning_rate": 0.01}},
        }},
    }},
}}
model, state, hist, *_ = hydragnn_tpu.run_training(cfg)
print("CLEAN_EXIT epochs=%d" % len(hist["train"]), flush=True)
"""

_PLANE_RE = re.compile(
    # remat= (r11) and hbm_peak= (r12) are optional: the parsed fields keep
    # their group numbers across report-line growth
    r"compile plane: mode=(\S+) (?:remat=\S+ )?precompiled=(\d+)/(\d+) "
    r"compile_time_s=([0-9.]+) cache_hits=(\d+) cache_misses=(\d+) "
    r"time_to_first_step=([0-9.]+|n/a)s traces=(\d+) violations=(\d+)"
)


def _env(workdir):
    env = {
        k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ":".join(
        p
        for p in [_REPO] + env.get("PYTHONPATH", "").split(":")
        if p and ".axon_site" not in p
    )
    env["HYDRAGNN_COMPILE_CACHE"] = os.path.join(workdir, "xla_cache")
    # CPU-sized compiles beat jax's default 1s cache-write floor
    env["HYDRAGNN_COMPILE_CACHE_MIN_SECS"] = "0"
    return env


def _run_leg(workdir, name):
    script = os.path.join(workdir, f"{name}.py")
    with open(script, "w") as f:
        f.write(_CHILD.format(repo=_REPO))
    proc = subprocess.run(
        [sys.executable, script], cwd=workdir, env=_env(workdir),
        capture_output=True, text=True, timeout=600,
    )
    out = proc.stdout + proc.stderr
    if proc.returncode != 0 or "CLEAN_EXIT" not in out:
        print(f"compile_smoke FAIL: {name} leg crashed "
              f"(rc={proc.returncode}) — a retrace-sentinel error here "
              f"means a silent recompile slipped in:\n{out[-3000:]}")
        return None
    m = None
    for m in _PLANE_RE.finditer(out):
        pass
    if m is None:
        print(f"compile_smoke FAIL: {name} leg printed no compile-plane "
              f"report:\n{out[-3000:]}")
        return None
    return {
        "mode": m.group(1),
        "precompiled": int(m.group(2)),
        "specializations": int(m.group(3)),
        "compile_time_s": float(m.group(4)),
        "cache_hits": int(m.group(5)),
        "cache_misses": int(m.group(6)),
        "time_to_first_step": (
            None if m.group(7) == "n/a" else float(m.group(7))
        ),
        "violations": int(m.group(9)),
    }


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="compile_smoke_")
    cold = _run_leg(workdir, "cold")
    if cold is None:
        return 1
    if cold["mode"] != "background":
        print(f"compile_smoke FAIL: cold leg mode {cold['mode']!r} — the "
              "default background precompile did not engage (no cache dir?)")
        return 1
    if cold["precompiled"] == 0 or (
        cold["precompiled"] != cold["specializations"]
    ):
        print("compile_smoke FAIL: background warm-up did not cover the "
              f"ladder: {cold['precompiled']}/{cold['specializations']}")
        return 1
    if cold["violations"] != 0:
        print("compile_smoke FAIL: retrace sentinel reported "
              f"{cold['violations']} violations on the cold leg")
        return 1

    warm = _run_leg(workdir, "warm")
    if warm is None:
        return 1
    ok_hits = warm["cache_hits"] > 0
    ok_viol = warm["violations"] == 0
    ok_ttfs = (
        warm["time_to_first_step"] is not None
        and cold["time_to_first_step"] is not None
        and warm["time_to_first_step"]
        <= cold["time_to_first_step"] * 1.25 + 1.0
    )
    verdict = {
        "metric": "compile-plane smoke (background precompile + error "
                  "sentinel; cold -> warm cache)",
        "cold": cold,
        "warm": warm,
        "ok": bool(ok_hits and ok_viol and ok_ttfs),
    }
    print(json.dumps(verdict))
    if not ok_hits:
        print("compile_smoke FAIL: warm leg reported zero cache hits — the "
              "persistent compilation cache did not survive the restart")
        return 1
    if not ok_viol:
        print("compile_smoke FAIL: retrace sentinel reported "
              f"{warm['violations']} violations on the warm leg")
        return 1
    if not ok_ttfs:
        print("compile_smoke FAIL: warm time-to-first-step "
              f"{warm['time_to_first_step']}s not bounded by cold "
              f"{cold['time_to_first_step']}s")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
