#!/usr/bin/env python
"""CI data-plane chaos smoke (docs/ROBUSTNESS.md "Data plane"). Four legs,
each in a fresh scrubbed CPU-JAX subprocess (the chaos_smoke.py recipe):

1. warn_skip: a training run over a dataset seeded with injected NaN
   samples (HYDRAGNN_FAULT_SAMPLE_NAN) completes, the per-reason skip tally
   matches the injection plan EXACTLY, and the loss decreases.
2. error: the same injection under ``Dataset.bad_sample_policy: error``
   fails fast with an actionable error naming the sample.
3. socket drop: a RemoteStoreClient fetch plane with injected connection
   drops (HYDRAGNN_FAULT_SOCKET_DROP) delivers every blob intact — bounded
   retries, zero sample loss.
4. kill-and-resume: SIGTERM BETWEEN STEPS checkpoints mid-epoch (state +
   loader cursor); ``Training.continue`` replays the remaining batches of
   the interrupted epoch in exactly the order an unkilled run produces
   (batch fingerprints compared against an unkilled reference leg).

Exit 0 = data plane healthy; nonzero with a diagnostic otherwise.
"""

import os
import re
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = """
import sys
sys.path.insert(0, {repo!r})
import jax
if not hasattr(jax.distributed, "is_initialized"):
    # older jax (this CPU image): run_training only uses it as an
    # already-initialized guard, and this smoke is strictly single-process
    jax.distributed.is_initialized = lambda: False
"""

_TRAIN_CHILD = _PRELUDE + """
import hydragnn_tpu

# per-STEP batch fingerprints, printed in step order by wrapping
# train_epoch's step_fn: the resume-order assertion compares these across
# legs (build-time tracing would also catch prefetch lookahead and the
# model-init probe batch — step order is the ground truth)
import numpy as _np
import hydragnn_tpu.train.loop as _L
_orig_epoch = _L.train_epoch
def _traced_epoch(loader, step_fn, state, rng, start_batch=0, **kw):
    # forward the loop's keyword surface (telemetry, tracer, ...) untouched
    def stepped(s, b, r):
        print("BATCH %.4f" % float(_np.asarray(b.x).sum()), flush=True)
        return step_fn(s, b, r)
    return _orig_epoch(loader, stepped, state, rng, start_batch, **kw)
_L.train_epoch = _traced_epoch

cfg = {{
    "Verbosity": {{"level": 1}},
    "Dataset": {{
        "name": "data_chaos",
        "format": "synthetic",
        "synthetic": {{"number_configurations": 120}},
        "bad_sample_policy": {policy!r},
        "node_features": {{"name": ["x", "x2", "x3"], "dim": [1, 1, 1]}},
        "graph_features": {{"name": ["s"], "dim": [1]}},
    }},
    "NeuralNetwork": {{
        "Architecture": {{
            "mpnn_type": "GIN", "radius": 2.0, "max_neighbours": 100,
            "hidden_dim": 8, "num_conv_layers": 2, "task_weights": [1.0],
            "output_heads": {{"graph": {{"num_sharedlayers": 1,
                                        "dim_sharedlayers": 8,
                                        "num_headlayers": 2,
                                        "dim_headlayers": [8, 8]}}}},
        }},
        "Variables_of_interest": {{
            "input_node_features": [0],
            "output_names": ["s"], "output_index": [0],
            "type": ["graph"], "denormalize_output": False,
        }},
        "Training": {{
            "num_epoch": {num_epoch}, "batch_size": 4,
            "seed": 7,
            {extra}
            "Optimizer": {{"type": "AdamW", "learning_rate": 0.01}},
        }},
    }},
}}
print("CHILD_READY", flush=True)
model, state, hist, *_ = hydragnn_tpu.run_training(cfg)
print("CLEAN_EXIT epochs=%d" % len(hist["train"]), flush=True)
"""

_SOCKET_CHILD = _PRELUDE + """
import socket
from hydragnn_tpu.data import DDStore, RemoteStoreClient
from hydragnn_tpu.utils import faultinject

with socket.socket() as s:
    s.bind(("127.0.0.1", 0)); port = s.getsockname()[1]
store = DDStore("/data_chaos_smoke", max_items=16, create=True, overwrite=True)
try:
    blobs = [bytes([i]) * (500 * (i + 1)) for i in range(8)]
    for i, b in enumerate(blobs):
        store.put(i, b)
    store.serve(port)
    client = RemoteStoreClient("127.0.0.1", port, retry_base=0.0, timeout_s=10)
    faultinject.configure(socket_drop="2,5,9")  # three mid-run drops
    got = [client.get(i) for i in range(8)]
    assert got == blobs, "sample loss through injected socket drops"
    client.close()
    print("SOCKET_OK drops_absorbed=3 samples=8", flush=True)
finally:
    store.close(unlink=True)
"""

_LOSS_RE = re.compile(r"epoch (\d+): train ([0-9.eE+-]+)")
_BATCH_RE = re.compile(r"^BATCH (\S+)$", re.M)
_MIDKILL_RE = re.compile(r"SIGTERM: checkpointed mid-epoch (\d+) at batch (\d+)")


def _env(**extra):
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["HYDRAGNN_VALTEST"] = "0"
    env["PYTHONPATH"] = ":".join(
        p
        for p in [_REPO] + env.get("PYTHONPATH", "").split(":")
        if p and ".axon_site" not in p
    )
    env.update(extra)
    return env


def _run(workdir, name, code, env, timeout=300):
    script = os.path.join(workdir, f"{name}.py")
    with open(script, "w") as f:
        f.write(code)
    return subprocess.run(
        [sys.executable, script], cwd=workdir, env=env,
        capture_output=True, text=True, timeout=timeout,
    )


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="data_chaos_")
    train_code = lambda policy, num_epoch, extra="": _TRAIN_CHILD.format(
        repo=_REPO, policy=policy, num_epoch=num_epoch, extra=extra
    )

    # ---- leg 1: injected NaN samples under warn_skip -> exact tally + a
    # loss that still learns
    p = _run(workdir, "leg1", train_code("warn_skip", 3),
             _env(HYDRAGNN_FAULT_SAMPLE_NAN="3,7"))
    out = p.stdout + p.stderr
    if p.returncode != 0 or "CLEAN_EXIT" not in p.stdout:
        print(f"data_chaos FAIL leg1: run crashed (rc={p.returncode}):\n"
              f"{out[-2500:]}")
        return 1
    if "data-plane skips: 2 skipped [nonfinite_features=2]" not in out:
        print("data_chaos FAIL leg1: skip tally does not match the "
              f"injection plan (expected nonfinite_features=2):\n{out[-2500:]}")
        return 1
    losses = [float(m.group(2)) for m in _LOSS_RE.finditer(out)]
    if len(losses) < 3 or losses[-1] >= losses[0]:
        print(f"data_chaos FAIL leg1: loss did not decrease: {losses}")
        return 1

    # ---- leg 2: the same injection under `error` fails fast, actionably
    p = _run(workdir, "leg2", train_code("error", 3),
             _env(HYDRAGNN_FAULT_SAMPLE_NAN="3,7"))
    out = p.stdout + p.stderr
    if p.returncode == 0:
        print(f"data_chaos FAIL leg2: error policy did not fail:\n{out[-2000:]}")
        return 1
    if "rejected: nonfinite_features" not in out or "sample 3" not in out:
        print("data_chaos FAIL leg2: error is not actionable (no sample "
              f"index/reason):\n{out[-2000:]}")
        return 1

    # ---- leg 3: socket drops absorbed with zero sample loss
    p = _run(workdir, "leg3", _SOCKET_CHILD.format(repo=_REPO), _env())
    if p.returncode != 0 or "SOCKET_OK" not in p.stdout:
        print(f"data_chaos FAIL leg3: socket-drop leg failed "
              f"(rc={p.returncode}):\n{(p.stdout + p.stderr)[-2500:]}")
        return 1

    # ---- leg 4: kill-and-resume mid-epoch, same batch order as unkilled
    # 4a: unkilled reference epoch-0 fingerprints (same config/seed)
    p = _run(workdir, "leg4_ref", train_code("warn_skip", 1), _env())
    if p.returncode != 0:
        print(f"data_chaos FAIL leg4 ref: {(p.stdout + p.stderr)[-2000:]}")
        return 1
    ref = _BATCH_RE.findall(p.stdout)
    if len(ref) < 5:
        print(f"data_chaos FAIL leg4 ref: too few batches ({len(ref)})")
        return 1

    # 4b: SIGTERM between steps of epoch 0
    script = os.path.join(workdir, "leg4_kill.py")
    with open(script, "w") as f:
        f.write(train_code("warn_skip", 10000))
    proc = subprocess.Popen(
        [sys.executable, script], cwd=workdir, env=_env(),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    lines, batches_seen, deadline = [], 0, time.time() + 300
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line == "" and proc.poll() is not None:
            break
        lines.append(line)
        if line.startswith("BATCH "):
            batches_seen += 1
            if batches_seen == 2:  # mid-epoch 0, builds are ahead of steps
                proc.send_signal(signal.SIGTERM)
                break
    else:
        proc.kill()
        print("data_chaos FAIL leg4: never saw 2 batches:\n"
              + "".join(lines)[-2000:])
        return 1
    out, _ = proc.communicate(timeout=300)
    leg4 = "".join(lines) + out
    m = _MIDKILL_RE.search(leg4)
    if proc.returncode != 0 or m is None:
        print("data_chaos FAIL leg4: no mid-epoch checkpoint on SIGTERM "
              f"(rc={proc.returncode}):\n{leg4[-2500:]}")
        return 1
    cursor = int(m.group(2))

    # 4c: resume replays epoch 0 from the cursor, same order
    run_name = "GIN-r-2.0-ncl-2-hd-8-ne-10000-lr-0.01-bs-4"
    p = _run(
        workdir, "leg4_resume",
        train_code("warn_skip", 1,
                   extra=f'"continue": 1, "startfrom": {run_name!r},'),
        _env(),
    )
    out = p.stdout + p.stderr
    if p.returncode != 0 or "resuming mid-epoch" not in out:
        print(f"data_chaos FAIL leg4: resume leg did not arm mid-epoch "
              f"(rc={p.returncode}):\n{out[-2500:]}")
        return 1
    resumed = _BATCH_RE.findall(p.stdout)
    want = ref[cursor:]
    if resumed[: len(want)] != want:
        print("data_chaos FAIL leg4: resumed batch order diverges from the "
              f"unkilled run\n  cursor={cursor}\n  want={want}\n  "
              f"got={resumed[: len(want)]}")
        return 1

    print(
        "data_chaos OK: tally-exact warn_skip, actionable error policy, "
        f"{3} socket drops absorbed, mid-epoch resume replayed "
        f"{len(want)} batches in order from cursor {cursor}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
