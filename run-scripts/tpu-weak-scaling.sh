#!/usr/bin/env bash
# SC25 weak-scaling protocol on a TPU pod slice: per-host batch size FIXED,
# total work grows with the slice (reference: run-scripts/SC25-job-weak.sh —
# the dual of the strong-scaling script; per-rank batch constant, global
# batch = bs * ranks). Timed batches capped, val/test disabled.
#
#   ./run-scripts/tpu-weak-scaling.sh TPU_NAME ZONE DRIVER [ARGS...]
set -euo pipefail

TPU_NAME=${1:?tpu name}
ZONE=${2:?gce zone}
DRIVER=${3:?training driver .py}
shift 3

PER_HOST_BS=${PER_HOST_BS:-160}
REPO_DIR=${REPO_DIR:-\$HOME/hydragnn_tpu}

echo "weak scaling: per-host bs=${PER_HOST_BS} (global batch grows with the slice)"

ARGS=""
if [ "$#" -gt 0 ]; then
  ARGS=$(printf '%q ' "$@")
fi

gcloud compute tpus tpu-vm ssh "${TPU_NAME}" \
  --zone "${ZONE}" \
  --worker=all \
  --command "cd ${REPO_DIR} && \
    ${HYDRAGNN_COORDINATOR:+HYDRAGNN_COORDINATOR=${HYDRAGNN_COORDINATOR}} \
    HYDRAGNN_VALTEST=0 \
    HYDRAGNN_MAX_NUM_BATCH=${HYDRAGNN_MAX_NUM_BATCH:-5} \
    HYDRAGNN_TRACE_LEVEL=${HYDRAGNN_TRACE_LEVEL:-1} \
    python ${DRIVER} --batch_size ${PER_HOST_BS} ${ARGS}"
